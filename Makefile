# Build/run glue — the reference's client/Makefile targets
# (import_contracts / run / run_with_scraper / run_scraper,
# client/Makefile:1-13) mapped onto this framework, plus the
# framework-native targets (tests, bench, native runtime).

PY ?= python

.PHONY: run run_with_scraper run_scraper web lint test test_fast test_all verify presnapshot bench bench-serving bench-shard bench-hotpath bench-coldstart bench-cluster campaign native metrics-smoke chaos-smoke robustness-smoke robustness-cert obs-smoke obs-cost-smoke fabric-smoke serving-smoke crash-smoke chaos-fuzz-smoke shard-smoke hotpath-smoke coldstart-smoke cluster-smoke reconfig-smoke fleet-obs-smoke pallas-parity clean

# The stdin console client (reference: `make run` -> python3 main.py).
run:
	$(PY) -m svoc_tpu.apps.cli

# Console + background ingest loop (reference: `make run_with_scraper`).
run_with_scraper:
	$(PY) -m svoc_tpu.apps.cli --scraper

# Ingest loop alone (reference: `make run_scraper` -> scraper.py);
# SVOC_SCRAPER_RATE seconds between scrapes (reference default 600).
run_scraper:
	mkdir -p data
	$(PY) -c "import os; \
	from svoc_tpu.io.comment_store import CommentStore; \
	from svoc_tpu.io.scraper import SyntheticSource, run_scraper; \
	run_scraper(CommentStore('data/comments.db'), SyntheticSource(), \
	rate_s=float(os.environ.get('SVOC_SCRAPER_RATE', '600')))"

# The web UI (reference: eel window; here a stdlib server on :8100).
web:
	$(PY) -m svoc_tpu.apps.web

# Static analysis (docs/STATIC_ANALYSIS.md): the AST-based JAX hazard
# gate — trace purity, host-sync, recompile, donation, fixed-point and
# shared-state rules, plus the interprocedural SVOC008–012 pass
# (call-graph + lock model: replay pinning, leaf-lock discipline,
# durability ordering).  Imports no JAX; warm runs reuse the
# content-hash findings cache (.svoclint_cache.json, gitignored) and
# parse nothing.  Exits non-zero on any non-baselined finding or stale
# baseline entry.  `python tools/svoclint.py --changed` is the
# sub-second pre-commit loop.
lint:
	$(PY) tools/svoclint.py svoc_tpu tools

# Hermetic suite on the 8-device virtual CPU mesh — the tier-1 lane
# (heavyweight Monte-Carlo / interpret-mode-Pallas / trainer tests are
# marked @pytest.mark.slow and run in test_all; VERDICT r5 item 6).
test:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/ -q -m 'not slow'

# Everything, slow lane included.
test_all:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/ -q

# Quick smoke subset (consensus math + apps; no transformer builds).
test_fast:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_fixedpoint.py tests/test_sort.py \
	tests/test_consensus_kernel.py tests/test_state.py tests/test_apps.py -q

# Convergence-under-faults gate (docs/RESILIENCE.md): the seeded chaos
# scenario over the local backend, run twice — bit-identical replay,
# full commit via resume, persistent offender voted out.  Seconds, no
# device work.
chaos-smoke:
	$(PY) tools/chaos_smoke.py

# Byzantine robustness gate (docs/ROBUSTNESS.md): tiny breakdown grid
# for both consensus configs + the seeded Byzantine scenario run twice
# (fingerprint-identical, all malformed vectors quarantined, colluders
# voted out).  Seconds on CPU.
robustness-smoke:
	$(PY) tools/robustness_cert.py --smoke

# The full empirical breakdown-point certificate →
# ROBUSTNESS_CERT.json (tolerated colluder fraction per attack, both
# configs, calibrated against the benign-replacement control).
robustness-cert:
	$(PY) tools/robustness_cert.py

# Flight-recorder gate (docs/OBSERVABILITY.md §events): the seeded
# Byzantine scenario twice with byte-identical journal fingerprints,
# the verdict→charge→replacement audit linkage on one lineage id, and
# a complete postmortem bundle from a seeded mini-session.  Seconds on
# CPU, no transformer builds.
obs-smoke:
	$(PY) tools/obs_smoke.py

# Cost-attribution gate (docs/OBSERVABILITY.md §cost-attribution): the
# seeded serving scenario four ways (plane on twice, off twice) —
# byte-identical journal fingerprints across ALL FOUR (timelines,
# ledger samples, and obs records never touch the replay-pinned
# journal), gapless per-request stage decomposition, a cost estimate
# for EVERY key the router's compile universe enumerates, and the
# ledger rebuilt bit-identically from the streamed JSONL via
# tools/obs_query.py.  Seconds on CPU, no transformer builds.
obs-cost-smoke:
	$(PY) tools/obs_cost_smoke.py

# Multi-claim fabric gate (docs/FABRIC.md): the seeded 4-claim ×
# 7-oracle scenario twice — byte-identical PER-CLAIM journal
# fingerprints (replay covers the scheduler interleaving, not just
# the math), one claim's Byzantine offender quarantined and replaced
# without touching sibling claims.  Seconds on CPU.
fabric-smoke:
	$(PY) tools/fabric_smoke.py

# Serving-tier gate (docs/SERVING.md §smoke): the seeded virtual-time
# micro-load (warm/overload/recovery over 3 claims) twice —
# byte-identical journal fingerprints including every shed decision,
# zero warm-phase shed, nonzero overload shed, real cache hits, p99
# reported.  Seconds on CPU, no transformer builds.
serving-smoke:
	$(PY) tools/serving_smoke.py

# Pallas consensus parity gate (docs/PARALLELISM.md §pallas-consensus):
# CPU interpret-mode parity of the fused single-claim and gated
# claim-cube kernels vs the XLA parity oracles (both configs,
# degenerate/quarantined/padded claims, Cairo tie order), plus the
# fallback-counter and typed env-knob smoke.  < 60 s, no transformer
# builds; SVOC_PALLAS_INTERPRET=1 exercises the dispatch layer's
# interpret opt-in path.
pallas-parity:
	JAX_PLATFORMS=cpu SVOC_PALLAS_INTERPRET=1 \
	$(PY) -m pytest tests/test_pallas_consensus.py -q -m 'not slow'

# Sharded claim-cube gate (docs/PARALLELISM.md §sharded-claims): the
# seeded fabric scenario on a pinned 2x4 (claim x oracle) mesh over 8
# simulated CPU devices, twice — byte-identical per-claim journal
# fingerprints — plus an unmeshed run whose fingerprints must MATCH
# the meshed ones (the sharded dispatch is bitwise-exact), nonzero
# sharded dispatches, zero fallbacks.  Seconds on CPU.
shard-smoke:
	$(PY) tools/shard_smoke.py

# Zero-sync hot-path gate (docs/PARALLELISM.md §host-overhead): the
# seeded 4-claim fabric scenario twice with device-resident staging +
# donated dispatch + the batched commit plane pinned ON — byte-identical
# per-claim fingerprints across the two runs AND against an unoptimized
# control (the optimizations are bit-identical numerics + identical
# journal events, never a fingerprint family), quarantine cycles
# produce COUNTED commit_batch_fallback{reason=skip_slots}, and a clean
# 4-claim leg pays C·cycles batched commit RPCs (one per claim-cycle,
# not one per oracle).  Seconds on CPU.
hotpath-smoke:
	$(PY) tools/hotpath_smoke.py

# Compile-plane gate (docs/PARALLELISM.md §compile-plane): the seeded
# 4-claim fabric scenario three ways — unwarmed control, AOT-prewarmed
# over a persistent compilation cache (the child then SIGKILLed), and
# a fresh process restarted on the killed child's cache dir.  Asserts
# byte-identical per-claim + whole-journal fingerprints across all
# three (warmup never journals, never changes numerics) and ZERO
# persistent-cache misses in the restarted child — a warm restart does
# 0 fresh compiles.  ~1 min on CPU.
coldstart-smoke:
	$(PY) tools/coldstart_smoke.py

# Crash-consistency gate (docs/RESILIENCE.md §durability): the seeded
# serving scenario SIGKILLed at 5 NAMED fault-point legs
# (mid-WAL-append torn intent, between tx i and i+1, post-commit
# pre-snapshot, the batched plane's mid-fleet kill, and a restart
# storm killed mid-recovery) in subprocesses, restarted, recovered
# (snapshot + journal-tail replay + WAL reconcile) — 0 duplicate txs
# over the chain logs, 0 unaccounted slots/requests, each leg's named
# point in the durable fired log, recovered fingerprints
# byte-identical across two runs of the full kill/restart matrix.
# ~2 min (parallel cold-jax subprocess waves).
crash-smoke:
	$(PY) tools/crash_smoke.py

# Multi-replica fleet chaos gate (docs/CLUSTER.md): seeded 3-replica ×
# 6-claim scenario with a mid-run replica kill, failover two steps
# later, an injected forwarding fault, and stale-epoch/down-replica
# probes, run twice — asserts replay identity (per-claim + fleet
# fingerprints), zero duplicate txs across the cluster-shared chain
# logs, lineage continuity through every migration, zero unaccounted
# requests, and full cluster fault-point coverage → CLUSTER_SMOKE.json.
cluster-smoke:
	$(PY) tools/cluster_smoke.py

# Live-reconfiguration chaos gate (docs/RECONFIG.md): a rolling
# commit-mode + per-claim-spec re-pin on a seeded 3-replica fleet
# under traffic, run twice — replay identity across the epoch boundary
# (fleet + per-claim fingerprints), zero shed (mid-transition traffic
# DEFERRED and released at commit), zero duplicate txs, lineage
# continuity for every re-pinned claim — plus a seeded abort at each
# of the five reconfig.* fault points, each rolling back to a fleet
# fingerprint byte-identical to never having attempted the plan →
# RECONFIG_SMOKE.json.
reconfig-smoke:
	$(PY) tools/reconfig_smoke.py

# Fleet observability gate (docs/OBSERVABILITY.md §fleet-plane): the
# seeded kill/failover + migrate scenario four ways (plane on twice,
# off twice) — byte-identical fleet fingerprints across ALL FOUR (hop
# records, merged telemetry, SLO alerts and anomaly observations ride
# the obs channel only), 100% hop-chain join coverage (complete
# forward chains == the router's cluster_forwarded count), the merged
# /metrics/fleet exposition equal to the sum of per-source scrapes,
# fleet totals monotonic across the failover (@retired fold), and a
# seeded degradation leg whose SUSTAINED anomaly auto-captures a
# profile and writes a postmortem bundle → FLEET_OBS_SMOKE.json.
# Seconds on CPU, no transformer builds.
fleet-obs-smoke:
	$(PY) tools/fleet_obs_smoke.py

# Deterministic fault-space fuzzer gate (docs/RESILIENCE.md
# §fault-surface): 32 seed-drawn kill/restart schedules over the named
# fault-point registry — SIGKILL at the Nth firing, torn writes,
# injected chain faults, per_tx vs batched, restart storms — each with
# a full same-seed rerun asserting byte-identical recovered
# fingerprints, plus a fault-free felt-wire soak through the batched
# adapter (VERDICT item 9).  FAILS on any invariant violation (the
# failing plan auto-shrinks into tests/fixtures/chaos_corpus/ for
# tier-1 to replay) or if any declared fuzz-surface point never fired.
# Children are jax-free (~1 s each): ~2-3 min on this 1-core
# container; deep mode: tools/chaos_fuzz.py --seeds N.
chaos-fuzz-smoke:
	$(PY) tools/chaos_fuzz.py

# The default verify path: the cheap static gate first, then the chaos
# convergence gates (I/O-plane, then data-plane), then the flight
# recorder, then the fabric and serving tiers, then crash consistency
# and the fault-space fuzzer, then the suite.
verify: lint pallas-parity chaos-smoke robustness-smoke obs-smoke obs-cost-smoke fabric-smoke shard-smoke serving-smoke hotpath-smoke coldstart-smoke chaos-fuzz-smoke crash-smoke cluster-smoke reconfig-smoke fleet-obs-smoke test

# End-of-round gate: lint + the driver-contract guards FIRST (fast,
# loud — round 4 shipped a red test_graft_entry pinning a stale dryrun
# section list), then the chaos gate, then the full hermetic suite.
# Run before EVERY snapshot.
presnapshot:
	$(MAKE) lint
	$(MAKE) pallas-parity
	$(MAKE) chaos-smoke
	$(MAKE) robustness-smoke
	$(MAKE) obs-smoke
	$(MAKE) obs-cost-smoke
	$(MAKE) fabric-smoke
	$(MAKE) shard-smoke
	$(MAKE) serving-smoke
	$(MAKE) hotpath-smoke
	$(MAKE) coldstart-smoke
	$(MAKE) chaos-fuzz-smoke
	$(MAKE) crash-smoke
	$(MAKE) cluster-smoke
	$(MAKE) reconfig-smoke
	$(MAKE) fleet-obs-smoke
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_graft_entry.py tests/test_bench.py -q
	$(MAKE) test

# One-line JSON throughput benchmark (flagship; --config N for others).
bench:
	$(PY) bench.py

# Serving-tier saturation sweep (docs/SERVING.md §bench): offered-QPS
# levels through the continuous-batching tier in virtual time →
# BENCH_SERVING.json (p50/p99 latency, goodput, shed rate, knee).
bench-serving:
	$(PY) bench_serving.py

# Sharded claim-cube mesh sweep (docs/PARALLELISM.md §sharded-claims):
# 1/2/4/8 simulated devices at fixed total work, each point a
# subprocess with the device count forced, in-run bitwise parity →
# BENCH_SHARD_r07.json (scaling verdict is an honest null on hosts
# whose cores can't back the simulated devices).
bench-shard:
	$(PY) bench.py --shard-sweep --claims 64 --claims-oracles 256

# Host-overhead hot-path A/B (docs/PARALLELISM.md §host-overhead):
# per-cycle host ms by stage (stage/h2d/dispatch/sync/journal/commit)
# and commit RPCs per claim-cycle, baseline vs device-resident+batched,
# WAL-attached, fingerprint-identity-gated → BENCH_HOTPATH_r08.json
# (CPU-honest; parsed by tools/decide_perf.py into the commit_mode
# routing decision).
bench-hotpath:
	$(PY) bench_hotpath.py

# Cold-start A/B (docs/PARALLELISM.md §compile-plane): first-request
# latency on an unseen claim bucket, cold vs AOT-prewarmed vs a
# persistent-compilation-cache hit across a literal process restart →
# BENCH_COLDSTART_r09.json (CPU-honest, device_topology-stamped;
# parsed by tools/decide_perf.py into the warmup_mode /
# compilation_cache routing decisions).
bench-coldstart:
	$(PY) bench_coldstart.py

# Cluster scaling bench (docs/CLUSTER.md §bench): aggregate QPS at
# fixed total work for 1/2/4 replicas, fleet invariants asserted per
# point → BENCH_CLUSTER_r11.json (CPU-honest — verdict is a recorded
# null on 1-core hosts, the BENCH_SHARD_r07 precedent; parsed by
# tools/decide_perf.py into the cluster_replicas routing decision).
bench-cluster:
	$(PY) tools/bench_cluster.py

# Round-long liveness-gated hardware measurement campaign (resumes its
# HW_CAMPAIGN.json journal; run in the background for the whole round).
campaign:
	$(PY) tools/hw_campaign.py

# Observability smoke: boot a session on the hermetic CPU mesh, run one
# fetch+commit, scrape GET /metrics, and assert the stage-span
# histograms are present (docs/OBSERVABILITY.md).
metrics-smoke:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	$(PY) -c "import json, urllib.request; \
	from tests.conftest import make_fake_console; \
	from svoc_tpu.apps.web import serve; \
	console = make_fake_console(); \
	srv, _ = serve(console, port=0, block=False); \
	base = 'http://127.0.0.1:%d' % srv.server_address[1]; \
	urllib.request.urlopen(urllib.request.Request(base + '/api/query', data=b'fetch', method='POST'), timeout=30).read(); \
	urllib.request.urlopen(urllib.request.Request(base + '/api/query', data=b'commit', method='POST'), timeout=30).read(); \
	text = urllib.request.urlopen(base + '/metrics', timeout=30).read().decode(); \
	needed = ['svoc_stage_seconds_bucket{stage=\"fetch\"', 'svoc_stage_seconds_bucket{stage=\"fleet\"', 'svoc_stage_seconds_bucket{stage=\"consensus\"', 'svoc_stage_seconds_bucket{stage=\"commit\"', 'svoc_comments_processed_total']; \
	missing = [n for n in needed if n not in text]; \
	assert not missing, 'missing series: %s' % missing; \
	srv.shutdown(); \
	print('metrics-smoke OK: /metrics served %d lines' % len(text.splitlines()))"

# Build/verify the native C++ runtime pieces (they also build lazily
# on first import).
native:
	$(PY) -c "from svoc_tpu.runtime.native import native_available; \
	assert native_available(), 'native build failed'; print('native runtime OK')"

clean:
	rm -rf build dist *.egg-info svoc_tpu/runtime/_build svoc_tpu/runtime/*.so
	find . -name __pycache__ -type d -not -path './.git/*' -exec rm -rf {} +
