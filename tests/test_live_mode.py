"""auto_commit / auto_resume / live_mode composite loops."""

import time

from svoc_tpu.apps.commands import CommandConsole
from svoc_tpu.apps.session import Session, SessionConfig
from tests.test_apps import fake_vectorizer


def make_fast_session():
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    store = CommentStore()
    store.save(SyntheticSource(batch=200)())
    return Session(
        config=SessionConfig(refresh_rate_s=0.05, scraper_rate_s=0.05),
        store=store,
        vectorizer=fake_vectorizer,
    )


def wait_until(pred, timeout_s=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestAutoFlags:
    def test_auto_commit_and_resume_toggle(self):
        c = CommandConsole(make_fast_session())
        assert c.query("auto_commit on") == ["Auto-Commit: ENABLED"]
        assert c.session.auto_commit is True
        assert c.query("auto_resume on") == ["Auto-Resume: ENABLED"]
        assert c.query("auto_commit off") == ["Auto-Commit: DISABLED"]
        assert c.query("auto_commit") == ["Unexpected number of arguments."]

    def test_auto_fetch_with_auto_commit_reaches_chain(self):
        c = CommandConsole(make_fast_session())
        c.query("auto_commit on")
        c.query("auto_resume on")
        c.query("auto_fetch on")
        try:
            assert wait_until(
                lambda: c.session.adapter.cache.get("consensus_active")
            ), "auto loop never committed + resumed"
        finally:
            c.query("auto_fetch off")
            c.stop()

    def test_live_mode_runs_full_pipeline(self):
        from svoc_tpu.io.comment_store import CommentStore

        # Live mode must work from a genuinely EMPTY store: the scraper
        # is what fills it.
        session = Session(
            config=SessionConfig(refresh_rate_s=0.05, scraper_rate_s=0.05),
            store=CommentStore(),
            vectorizer=fake_vectorizer,
        )
        assert session.store.count() == 0
        c = CommandConsole(session)
        out = c.query("live_mode on")
        assert any("Live mode: ENABLED" in line for line in out)
        try:
            assert wait_until(
                lambda: session.adapter.call_consensus_active()
            ), "live pipeline never drove the chain to consensus"
        finally:
            out = c.query("live_mode off")
            assert any("Live mode: DISABLED" in line for line in out)
            c.stop()
        assert session.auto_fetch is False and session.auto_commit is False

    def test_auto_fetch_on_empty_store_waits_not_errors(self):
        """Auto-fetch racing an empty store (live-mode startup: scraper
        and fetch loop begin together) is WAITING, not an error — the
        1024-oracle soak flagged the old error-spam on its first cycle."""
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.utils.metrics import registry

        session = Session(
            config=SessionConfig(refresh_rate_s=0.03),
            store=CommentStore(),  # stays empty: no scraper started
            vectorizer=fake_vectorizer,
        )
        c = CommandConsole(session)
        errors0 = registry.counter("auto_fetch_errors").count
        waiting0 = registry.counter("auto_fetch_waiting").count
        c.query("auto_fetch on")
        try:
            assert wait_until(
                lambda: registry.counter("auto_fetch_waiting").count
                >= waiting0 + 3
            ), "empty-store cycles never counted as waiting"
        finally:
            c.query("auto_fetch off")
            c.stop()
        assert registry.counter("auto_fetch_errors").count == errors0
        # Ingest arriving later unblocks the same loop.
        from svoc_tpu.io.scraper import SyntheticSource

        session.store.save(SyntheticSource(batch=60)())
        c.query("auto_fetch on")
        try:
            assert wait_until(lambda: session.predictions is not None)
        finally:
            c.query("auto_fetch off")
            c.stop()

    def test_rapid_off_on_restarts_scraper(self):
        """off→on with no delay must start a fresh ingest loop, not
        report ENABLED while the old stopping thread dies."""
        from svoc_tpu.io.comment_store import CommentStore

        session = Session(
            config=SessionConfig(refresh_rate_s=0.05, scraper_rate_s=0.05),
            store=CommentStore(),
            vectorizer=fake_vectorizer,
        )
        c = CommandConsole(session)
        try:
            c.query("scraper on")
            c.query("scraper off")
            out = c.query("scraper on")  # immediately — races wind-down
            assert any("ENABLED (synthetic)" in line for line in out)
            before = session.store.count()
            assert wait_until(lambda: session.store.count() > before)
        finally:
            c.stop()
