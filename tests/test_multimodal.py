"""Multimodal (mixture-model) consensus — the reference's documented
future-work scenario (``documentation/README.md:90-103``), for which it
provides no algorithm.  These tests pin the framework's estimator:
generator semantics, EM recovery, both consensus policies, and the
Monte-Carlo comparison against the unimodal two-pass kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.sim.multimodal import (
    benchmark_multimodal,
    em_mixture,
    generate_multimodal_oracles,
    multimodal_consensus,
)

POLES = jnp.array([[0.2, 0.2], [0.8, 0.7]], jnp.float32)


def test_generator_shapes_and_labels():
    values, honest, pole_of = generate_multimodal_oracles(
        jax.random.PRNGKey(0), 32, 5, POLES, 0.03, weights=[0.6, 0.4]
    )
    assert values.shape == (32, 2)
    assert int(honest.sum()) == 27
    # failing oracles carry pole −1; honest carry a valid pole index
    assert bool(jnp.all((pole_of == -1) == ~honest))
    assert bool(jnp.all((pole_of >= 0) == honest))
    # constrained: values inside the open interval
    assert float(values.min()) > 0.0 and float(values.max()) < 1.0
    # honest oracles sit near their assigned pole (sigma=0.03 ⇒ 5σ box)
    hv = values[honest]
    hp = POLES[pole_of[honest]]
    assert float(jnp.max(jnp.linalg.norm(hv - hp, axis=-1))) < 0.15


def test_generator_weights_bias_pole_choice():
    _, honest, pole_of = generate_multimodal_oracles(
        jax.random.PRNGKey(1), 512, 0, POLES, 0.01, weights=[0.9, 0.1]
    )
    frac0 = float(jnp.mean((pole_of == 0).astype(jnp.float32)))
    assert 0.85 < frac0 < 0.95  # ~Binomial(512, 0.9) concentration


def test_em_recovers_separated_poles():
    values, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(2), 64, 0, POLES, 0.03, weights=[0.5, 0.5]
    )
    fit = em_mixture(values, 2)
    # match each true pole to its nearest estimated mean
    d = np.linalg.norm(
        np.asarray(POLES)[:, None, :] - np.asarray(fit.means)[None, :, :],
        axis=-1,
    )
    assert d.min(axis=1).max() < 0.05
    assert np.isclose(float(fit.weights.sum()), 1.0, atol=1e-5)
    assert float(fit.sigmas.min()) >= 1e-3  # floor respected
    # responsibilities are a proper posterior
    assert np.allclose(np.asarray(fit.resp.sum(axis=1)), 1.0, atol=1e-4)


def test_consensus_dominant_policy_lands_on_heavier_pole():
    values, honest, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(3), 64, 4, POLES, 0.03, weights=[0.75, 0.25]
    )
    res = multimodal_consensus(values, 2, 4, policy="dominant")
    assert int(res.reliable.sum()) == 60  # fixed-count contract
    # essence on the dominant pole, far from the other
    assert float(jnp.linalg.norm(res.essence - POLES[0])) < 0.08
    assert float(jnp.linalg.norm(res.essence - POLES[1])) > 0.4


def test_consensus_average_policy_sits_between_poles():
    values, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(4), 64, 4, POLES, 0.03, weights=[0.5, 0.5]
    )
    dom = multimodal_consensus(values, 2, 4, policy="dominant")
    avg = multimodal_consensus(values, 2, 4, policy="average")
    d_near = jnp.min(jnp.linalg.norm(POLES - avg.essence[None, :], axis=-1))
    # the averaged essence is strictly farther from every pole than the
    # dominant essence is from its pole — the "no oracle holds it" case
    assert float(d_near) > 0.2
    assert float(
        jnp.min(jnp.linalg.norm(POLES - dom.essence[None, :], axis=-1))
    ) < 0.08


def test_consensus_policy_validated():
    values, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(5), 16, 2, POLES, 0.03
    )
    with pytest.raises(ValueError, match="policy"):
        multimodal_consensus(values, 2, 2, policy="median")


def test_k1_reduces_to_unimodal_mean():
    pole = jnp.array([[0.4, 0.6]], jnp.float32)
    values, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(6), 32, 0, pole, 0.02
    )
    res = multimodal_consensus(values, 1, 0)
    assert np.allclose(
        np.asarray(res.essence), np.asarray(values.mean(axis=0)), atol=1e-4
    )


def test_consensus_vmaps_over_fleets():
    keys = jax.random.split(jax.random.PRNGKey(7), 4)
    fleets = jax.vmap(
        lambda k: generate_multimodal_oracles(k, 32, 2, POLES, 0.03)[0]
    )(keys)
    out = jax.vmap(lambda v: multimodal_consensus(v, 2, 2).essence)(fleets)
    assert out.shape == (4, 2)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_benchmark_mixture_beats_unimodal_on_balanced_poles():
    cell = benchmark_multimodal(
        jax.random.PRNGKey(8),
        POLES,
        0.03,
        weights=[0.5, 0.5],
        n_oracles=64,
        n_failing=4,
        k_trials=60,
    )
    # nearest-pole error: mixture ~sigma, unimodal includes snap noise
    # + gap landings; require a decisive margin at sampling tolerance
    assert cell["mixture_nearest_pole_error"] < 0.02
    assert (
        cell["unimodal_nearest_pole_error"]
        > 3.0 * cell["mixture_nearest_pole_error"]
    )
    assert cell["pole_recovery_error"] < 0.05


@pytest.mark.slow  # BIC model-selection Monte-Carlo (VERDICT r5 item 6)
def test_select_k_finds_true_pole_count():
    from svoc_tpu.sim.multimodal import select_k

    bimodal, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(10), 64, 0, POLES, 0.03, weights=[0.5, 0.5]
    )
    k2, bics2 = select_k(bimodal, k_max=4)
    assert k2 == 2 and len(bics2) == 4

    unimodal, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(11), 64, 0, POLES[:1], 0.03
    )
    k1, _ = select_k(unimodal, k_max=4)
    assert k1 == 1

    trimodal, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(12),
        96,
        0,
        jnp.array([[0.15, 0.15], [0.5, 0.85], [0.85, 0.2]]),
        0.02,
    )
    k3, _ = select_k(trimodal, k_max=5)
    assert k3 == 3


def test_select_k_capped_by_pole_support():
    from svoc_tpu.sim.multimodal import select_k

    # N=5 with min_support=3: only K=1 is a supportable hypothesis
    values, _, _ = generate_multimodal_oracles(
        jax.random.PRNGKey(13), 5, 0, POLES, 0.03
    )
    k, bics = select_k(values, k_max=16)
    assert len(bics) == 1 and k == 1


def test_select_k_small_fleets_not_overfit():
    """The raw-BIC degeneracy (collapsed near-singleton components
    out-scoring the penalty on tiny fleets) must stay fixed: a
    7-oracle unimodal fleet is K=1, an 8-oracle bimodal one K=2."""
    from svoc_tpu.sim.multimodal import select_k

    for seed in range(20, 30):
        uni, _, _ = generate_multimodal_oracles(
            jax.random.PRNGKey(seed), 7, 0, POLES[:1], 0.03
        )
        assert select_k(uni)[0] == 1, seed
    bi_hits = 0
    for seed in range(20, 30):
        bi, _, _ = generate_multimodal_oracles(
            jax.random.PRNGKey(seed), 8, 0, POLES, 0.03, weights=[0.5, 0.5]
        )
        bi_hits += select_k(bi)[0] == 2
    assert bi_hits >= 8  # a lopsided 8-point draw may honestly read unimodal


@pytest.mark.slow  # N=1024 multimodal fleet (VERDICT r5 item 6)
def test_fleet_scale_multimodal():
    """The mixture estimator at the product config (N=1024, dim 6,
    128 uniform adversaries): dominant-pole essence at ~sigma accuracy
    and both poles recovered.  (Exact identification of all 128
    adversaries is statistically impossible at this scale — same as
    the unimodal fleet tables — so only the essence/pole metrics are
    pinned.)"""
    poles = jnp.array(
        [
            [0.2, 0.2, 0.3, 0.4, 0.5, 0.2],
            [0.8, 0.7, 0.6, 0.5, 0.4, 0.8],
        ],
        jnp.float32,
    )
    cell = benchmark_multimodal(
        jax.random.PRNGKey(42),
        poles,
        0.03,
        weights=[0.6, 0.4],
        n_oracles=1024,
        n_failing=128,
        k_trials=30,
    )
    assert cell["mixture_dominant_pole_pct"] >= 95.0
    assert cell["mixture_nearest_pole_error"] < 0.02
    assert cell["pole_recovery_error"] < 0.05


@pytest.mark.slow  # dominant-weight Monte-Carlo sweep (VERDICT r5 item 6)
def test_multimodal_breakdown_cliff_at_dominant_weight():
    """Coordinated adversaries forming a tight fake pole: the mixture
    estimator holds the honest dominant pole until the adversary share
    exceeds the dominant pole's own weight — for w_dom=0.6 the
    theoretical cliff is frac > 0.6·(1−frac) ⇒ ≈0.375 — then flips.
    (A tight plausible cluster cannot be masked by any scoring rule;
    dominance is the defense, and this pins where it ends.)"""
    from svoc_tpu.sim.multimodal import multimodal_breakdown_curve

    poles = jnp.array([[0.2, 0.2], [0.7, 0.6]], jnp.float32)
    curve = multimodal_breakdown_curve(
        jax.random.PRNGKey(0),
        poles,
        0.03,
        weights=[0.6, 0.4],
        n_oracles=64,
        fractions=(0.1, 0.2, 0.45, 0.55),
        k_trials=60,
    )
    assert curve[0.1]["on_honest_pole_pct"] >= 80.0
    assert curve[0.2]["on_honest_pole_pct"] >= 80.0
    assert curve[0.45]["on_honest_pole_pct"] <= 25.0
    assert curve[0.55]["on_honest_pole_pct"] <= 5.0
    assert curve[0.55]["essence_err"] > 0.5


def test_benchmark_dominant_pole_at_asymmetric_weights():
    cell = benchmark_multimodal(
        jax.random.PRNGKey(9),
        POLES,
        0.03,
        weights=[0.75, 0.25],
        n_oracles=64,
        n_failing=4,
        k_trials=60,
    )
    assert cell["mixture_dominant_pole_pct"] >= 95.0
    assert cell["mixture_nearest_pole_error"] < 0.02
