"""Batched fleet commit: exact equivalence with the sequential tx loop.

The batched path (``consensus/batch.py`` + ``update_predictions_batch``)
must be observably IDENTICAL to looping ``update_prediction`` — final
wsad state, panic index, partial-commit accounting — while doing O(1)
golden recomputes.  Every test here drives both paths on twin contracts
and compares exact integers.
"""

import time

import numpy as np
import pytest

from svoc_tpu.consensus.state import (
    BatchTxError,
    OracleConsensusContract,
)

ADMINS = ["a0", "a1", "a2"]


def make_pair(n_oracles, n_failing, dimension=3, constrained=True, spread=10.0):
    """Twin contracts (sequential reference / batched subject)."""

    def build():
        return OracleConsensusContract(
            ADMINS,
            [f"o{i}" for i in range(n_oracles)],
            required_majority=2,
            n_failing_oracles=n_failing,
            constrained=constrained,
            unconstrained_max_spread=spread,
            dimension=dimension,
        )

    return build(), build()


def fleet(rng, n, m, lo=0.05, hi=0.95):
    return rng.uniform(lo, hi, size=(n, m))


def state_dict(c):
    return {
        "consensus_active": c.consensus_active,
        "value": c.get_consensus_value(),
        "rel1": c.get_first_pass_consensus_reliability(),
        "rel2": c.get_second_pass_consensus_reliability(),
        "skew": c.get_skewness(),
        "kurt": c.get_kurtosis(),
        "oracles": c.get_oracle_value_list("a0"),
        "n_active": c.n_active_oracles,
    }


def run_sequential(c, callers, preds):
    """The reference commit loop; returns (committed, error or None)."""
    for k, (caller, p) in enumerate(zip(callers, preds)):
        try:
            c.update_prediction(caller, p)
        except Exception as e:
            return k, e
    return len(callers), None


def run_batch(c, callers, preds):
    try:
        return c.update_predictions_batch(callers, preds), None
    except BatchTxError as e:
        return e.index, e


@pytest.mark.parametrize("n,n_failing", [(7, 2), (13, 4), (8, 0)])
@pytest.mark.parametrize("constrained", [True, False])
def test_batch_equals_sequential_two_cycles(n, n_failing, constrained):
    rng = np.random.default_rng(n * 100 + n_failing + constrained)
    seq, bat = make_pair(n, n_failing, constrained=constrained)
    callers = [f"o{i}" for i in range(n)]
    for cycle in range(3):  # activation cycle + 2 post-activation cycles
        preds = fleet(rng, n, 3)
        rs = run_sequential(seq, callers, preds)
        rb = run_batch(bat, callers, preds)
        assert rs[0] == rb[0], f"cycle {cycle}: committed count differs"
        assert (rs[1] is None) == (rb[1] is None)
        assert state_dict(seq) == state_dict(bat), f"cycle {cycle}"


def test_fast_path_is_actually_taken(monkeypatch):
    """A healthy varied fleet must certify — the equivalence above would
    silently pass if everything fell back to the sequential loop."""
    rng = np.random.default_rng(0)
    _, bat = make_pair(7, 2)
    callers = [f"o{i}" for i in range(7)]

    def boom(*a, **k):
        raise AssertionError("fell back to the sequential path")

    monkeypatch.setattr(bat, "_sequential_batch", boom)
    bat.update_predictions_batch(callers, fleet(rng, 7, 3))  # activation
    bat.update_predictions_batch(callers, fleet(rng, 7, 3))  # full sweep
    assert bat.consensus_active


def test_validation_failure_mid_batch_commits_prefix():
    rng = np.random.default_rng(1)
    seq, bat = make_pair(7, 2)
    callers = [f"o{i}" for i in range(7)]
    preds = fleet(rng, 7, 3)
    preds[4] = [1.5, 0.5, 0.5]  # interval violation at tx 4
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 4
    assert rb[1].index == 4 and rb[1].oracle_address == "o4"
    assert "interval" in str(rb[1].cause)
    assert state_dict(seq) == state_dict(bat)


def test_unknown_caller_mid_batch():
    rng = np.random.default_rng(2)
    seq, bat = make_pair(7, 2)
    callers = [f"o{i}" for i in range(6)] + ["eve"]
    preds = fleet(rng, 7, 3)
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 6
    assert "not an oracle" in str(rb[1].cause)
    assert state_dict(seq) == state_dict(bat)


def test_final_recompute_panic_reverts_last_tx():
    """Zero-variance fleet: the activation recompute panics on the LAST
    tx exactly like the sequential loop (tx reverted, prefix kept)."""
    seq, bat = make_pair(7, 2)
    callers = [f"o{i}" for i in range(7)]
    preds = [[0.5 + i * 1e-6, 0.5, 0.5] for i in range(7)]
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 6
    assert isinstance(rb[1].cause, ZeroDivisionError)
    assert state_dict(seq) == state_dict(bat)
    assert bat.consensus_active is False
    assert bat.n_active_oracles == 6  # last tx reverted


def test_intermediate_panic_falls_back_to_exact():
    """An interval panic at an INTERMEDIATE recompute (prefix 5 of 7)
    must fail certification and reproduce the exact panic index."""
    rng = np.random.default_rng(3)
    seq, bat = make_pair(7, 2, dimension=2)
    callers = [f"o{i}" for i in range(7)]
    base = fleet(rng, 7, 2)
    run_sequential(seq, callers, base)
    run_batch(bat, callers, base)  # both active, identical
    # 5 identical extremes onto a varied fleet: after tx 4 (0-based) the
    # reliable subset is the five [1,1] rows — zero variance, the Cairo
    # division-by-zero panic, at an INTERMEDIATE prefix.
    preds = [[1.0, 1.0]] * 5 + [[0.0, 0.0]] * 2
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 4
    assert type(rb[1].cause) is type(rs[1])
    assert isinstance(rb[1].cause, ZeroDivisionError)
    assert state_dict(seq) == state_dict(bat)


def test_final_panic_after_intermediates_leaves_prefix_consensus(monkeypatch):
    """When the LAST tx's recompute panics but earlier txs in the batch
    DID recompute (certified fast path), the derived state must be the
    prefix-(T-1) consensus — what the sequential loop leaves — not the
    pre-batch state.  Construction keeps every intermediate prefix
    varied (certifiable) and collapses the reliable subset to identical
    values only on the final tx."""
    rng = np.random.default_rng(9)
    seq, bat = make_pair(7, 2)
    callers = [f"o{i}" for i in range(7)]
    c0 = [0.4, 0.5, 0.6]
    base = fleet(rng, 7, 3)
    # Enable all but o0 so the batch's FIRST tx opens the gate
    # (first_recompute == 1 < T: intermediates recompute).
    for i in range(1, 7):
        seq.update_prediction(f"o{i}", base[i])
        bat.update_prediction(f"o{i}", base[i])
    preds = [list(base[0]), list(base[1]), c0, c0, c0, c0, c0]
    # After tx 6 the five c0 rows are the reliable subset → variance 0
    # → golden panic; after tx 5 (prefix 6) only four c0 rows exist and
    # a varied row completes the subset → certifiable.
    boom = AssertionError("fell back to the sequential path")
    monkeypatch.setattr(
        bat, "_sequential_batch", lambda *a, **k: (_ for _ in ()).throw(boom)
    )
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 6
    assert isinstance(rb[1].cause, ZeroDivisionError)
    assert state_dict(seq) == state_dict(bat)
    # The panic left the PREFIX consensus, not stale pre-batch state.
    assert bat.consensus_active is True


def test_malformed_element_is_a_tx_failure():
    """A non-numeric element is THAT tx's failure (prefix committed),
    exactly like the sequential loop — not an API error."""
    rng = np.random.default_rng(10)
    seq, bat = make_pair(7, 2)
    callers = [f"o{i}" for i in range(7)]
    preds = [list(p) for p in fleet(rng, 7, 3)]
    preds[5][0] = "not-a-number"
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 5
    assert isinstance(rb[1].cause, (TypeError, ValueError))
    assert state_dict(seq) == state_dict(bat)
    with pytest.raises(ValueError):  # API misuse stays an API error
        bat.update_predictions_batch(callers, fleet(rng, 7, 3), encoding="hex")


def test_tiny_reliable_subset_panics_at_the_right_tx():
    """N - n_failing ≤ 3 zeroes the moment denominators: EVERY recompute
    panics (math.cairo:336/:358) — the batch must reproduce the panic at
    the first gate-opening tx, not at the end."""
    rng = np.random.default_rng(11)
    seq, bat = make_pair(6, 3)  # reliable subset = 3 → (n-2)(n-3) = 0
    callers = [f"o{i}" for i in range(6)]
    preds = fleet(rng, 6, 3)
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 5  # panic on the activating (6th) tx
    assert isinstance(rb[1].cause, ZeroDivisionError)
    assert state_dict(seq) == state_dict(bat)


def test_adapter_uncertified_falls_through_to_tx_loop():
    """An uncertifiable fleet through the adapter must complete via the
    per-tx loop (BatchNotCertified never escapes) with exact sequential
    results.  Construction: after a varied activation cycle, commit 64
    IDENTICAL rows — late intermediate prefixes have a zero-variance
    reliable subset (uncertifiable, and the exact engine panics there),
    so the certified fast path is impossible."""
    from svoc_tpu.io.chain import ChainAdapter, ChainCommitError, LocalChainBackend

    n = 64
    rng = np.random.default_rng(12)
    callers = [f"o{i}" for i in range(n)]
    base = fleet(rng, n, 3)
    preds = np.tile(rng.uniform(0.2, 0.8, size=3), (n, 1))

    def build():
        return OracleConsensusContract(
            ADMINS,
            callers,
            n_failing_oracles=8,
            dimension=3,
        )

    seq = build()
    run_sequential(seq, callers, base)
    rs = run_sequential(seq, callers, preds)
    assert rs[1] is not None  # the degenerate cycle panics mid-loop

    bat = build()
    a = ChainAdapter(LocalChainBackend(bat))
    a.update_all_the_predictions(base, batch=True)
    with pytest.raises(ChainCommitError) as ei:
        a.update_all_the_predictions(preds, batch=True)
    assert ei.value.committed == rs[0]
    assert state_dict(seq) == state_dict(bat)


def test_large_magnitude_unconstrained_falls_back():
    """Unconstrained values beyond the f32 guard-band analysis (>16)
    must take the exact path: at magnitude ~12000, float quantization
    scatter could inflate a truly-zero wsad variance past the band and
    mis-certify a fleet whose every recompute panics."""
    n = 64
    seq, bat = make_pair(n, 8, constrained=False, spread=1e9)
    callers = [f"o{i}" for i in range(n)]
    rng = np.random.default_rng(13)
    base = 12000.0 + fleet(rng, n, 3)  # varied activation cycle
    rs = run_sequential(seq, callers, base)
    rb = run_batch(bat, callers, base)
    assert rs[0] == rb[0]
    assert state_dict(seq) == state_dict(bat)
    # Near-identical at large magnitude: exact variance truncates to 0.
    preds = [[12000.0 + i * 1e-6, 0.5, 0.5] for i in range(n)]
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0]
    assert isinstance(rb[1].cause, ZeroDivisionError)
    assert state_dict(seq) == state_dict(bat)


def test_rederive_failure_never_masks_the_tx_error(monkeypatch):
    """Even if certification were unsound (forced here by stubbing it
    out), a panic in the prefix re-derive must not escape as a raw
    exception — the BatchTxError accounting survives."""
    from svoc_tpu.consensus import batch as dev

    n = 7
    _, bat = make_pair(n, 2)
    callers = [f"o{i}" for i in range(n)]
    rng = np.random.default_rng(14)
    run_batch(bat, callers, fleet(rng, n, 3))
    monkeypatch.setattr(
        dev, "certify", lambda *a, **k: np.ones(10_000, dtype=bool)
    )
    # Every prefix (and the final block) is zero-variance → the forced
    # fast path panics at the end AND in the prefix re-derive.
    preds = [[0.5 + i * 1e-6, 0.5, 0.5] for i in range(n)]
    committed, err = run_batch(bat, callers, preds)
    assert committed == n - 1
    assert isinstance(err.cause, ZeroDivisionError)


def test_duplicate_caller_falls_back():
    rng = np.random.default_rng(4)
    seq, bat = make_pair(7, 2)
    first = fleet(rng, 7, 3)
    run_sequential(seq, [f"o{i}" for i in range(7)], first)
    run_batch(bat, [f"o{i}" for i in range(7)], first)
    callers = ["o0", "o1", "o1", "o3", "o4", "o5", "o6"]
    preds = fleet(rng, 7, 3)
    rs = run_sequential(seq, callers, preds)
    rb = run_batch(bat, callers, preds)
    assert rs[0] == rb[0] == 7
    assert state_dict(seq) == state_dict(bat)


def test_batch_equals_sequential_fleet_64():
    """Certification path at fleet scale: 63 intermediate recomputes
    certified on device, final state bit-equal to 64 golden recomputes."""
    rng = np.random.default_rng(5)
    n = 64
    seq, bat = make_pair(n, 16, dimension=6)
    callers = [f"o{i}" for i in range(n)]
    for _ in range(2):
        preds = fleet(rng, n, 6)
        rs = run_sequential(seq, callers, preds)
        rb = run_batch(bat, callers, preds)
        assert rs == (n, None) and rb == (n, None)
        assert state_dict(seq) == state_dict(bat)


def test_fleet_1024_cycle_completes_in_seconds():
    """The BASELINE product config: a full 1024-oracle post-activation
    commit cycle (1023 device-certified recomputes + 1 golden) must take
    seconds, not the sequential path's minutes."""
    rng = np.random.default_rng(6)
    n = 1024
    c = OracleConsensusContract(
        ADMINS,
        [f"o{i}" for i in range(n)],
        n_failing_oracles=256,
        constrained=True,
        dimension=6,
    )
    callers = [f"o{i}" for i in range(n)]
    c.update_predictions_batch(callers, fleet(rng, n, 6))  # activation
    assert c.consensus_active
    t0 = time.perf_counter()
    c.update_predictions_batch(callers, fleet(rng, n, 6))  # full sweep
    dt = time.perf_counter() - t0
    # CI bound is loose (shared CPU); interactively this is ~1-3 s.
    assert dt < 120, f"fleet cycle took {dt:.1f}s"
    # The committed state must be the golden engine's on the final block.
    from svoc_tpu.consensus import wsad_engine as eng

    golden = eng.two_pass_consensus(
        [o.value for o in c.oracles],
        constrained=True,
        n_failing=256,
        max_spread=0,
    )
    assert c.get_consensus_value() == golden["essence"]
    assert c.get_first_pass_consensus_reliability() == (
        golden["reliability_first_pass"]
    )


def test_adapter_batch_commit_accounting():
    """ChainCommitError accounting parity through the adapter, both
    forced-batch and sequential."""
    from svoc_tpu.io.chain import ChainAdapter, ChainCommitError, LocalChainBackend

    rng = np.random.default_rng(7)

    def build():
        return ChainAdapter(
            LocalChainBackend(
                OracleConsensusContract(
                    [0xA0, 0xA1, 0xA2],
                    [0x10 + i for i in range(7)],
                    n_failing_oracles=2,
                    constrained=True,
                    dimension=3,
                )
            )
        )

    good = fleet(rng, 7, 3)
    bad = good.copy()
    bad[4] = [1.5, 0.5, 0.5]

    results = {}
    for name, flag in [("seq", False), ("batch", True)]:
        a = build()
        assert a.update_all_the_predictions(good, batch=flag) == 7
        with pytest.raises(ChainCommitError) as ei:
            a.update_all_the_predictions(bad, batch=flag)
        results[name] = (
            ei.value.committed,
            ei.value.total,
            ei.value.failed_oracle,
            a.backend.contract.get_consensus_value(),
        )
    assert results["seq"] == results["batch"]
    assert results["seq"][0] == 4 and results["seq"][2] == 0x10 + 4


def test_adapter_codec_failure_accounting_parity():
    """A NaN prediction mid-fleet must yield the SAME ChainCommitError
    accounting through the batch path as through the per-tx loop (the
    prefix commits; the bad tx is the failure)."""
    from svoc_tpu.io.chain import ChainAdapter, ChainCommitError, LocalChainBackend

    n = 64
    rng = np.random.default_rng(15)
    preds = fleet(rng, n, 3)
    preds[40, 0] = np.nan

    results = {}
    for name, flag in [("seq", False), ("batch", True)]:
        a = ChainAdapter(
            LocalChainBackend(
                OracleConsensusContract(
                    ADMINS,
                    [f"o{i}" for i in range(n)],
                    n_failing_oracles=8,
                    dimension=3,
                )
            )
        )
        with pytest.raises(ChainCommitError) as ei:
            a.update_all_the_predictions(preds, batch=flag)
        results[name] = (
            ei.value.committed,
            ei.value.total,
            ei.value.failed_oracle,
            a.backend.contract.n_active_oracles,
        )
    assert results["seq"] == results["batch"]
    assert results["seq"][0] == 40


def test_adapter_auto_threshold():
    """Auto mode batches at ≥64 oracles and loops below."""
    from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend

    rng = np.random.default_rng(8)
    n = 64
    contract = OracleConsensusContract(
        ADMINS,
        [f"o{i}" for i in range(n)],
        n_failing_oracles=8,
        dimension=3,
    )
    a = ChainAdapter(LocalChainBackend(contract))
    calls = []
    orig = contract.update_predictions_batch
    contract.update_predictions_batch = lambda *a_, **k: (
        calls.append("batch"),
        orig(*a_, **k),
    )[1]
    assert a.update_all_the_predictions(fleet(rng, n, 3)) == n
    assert calls == ["batch"]
