"""Live reconfiguration plane (ISSUE 19, docs/RECONFIG.md): typed plan
validation, the transactional drain → re-pin → resume state machine,
abort invisibility (byte-identical rollback from every fault point),
fingerprint-epoch continuity, orphan re-adoption, roster growth as a
one-knob plan, and the reconfig chaos-corpus pinning entry."""

import json
import os

import pytest

from svoc_tpu.cluster import (
    ClusterRouter,
    PlacementDirectory,
    ReconfigController,
    ReconfigError,
    ReconfigPlan,
    Replica,
)
from svoc_tpu.durability import faultspace
from svoc_tpu.durability.faultspace import FaultEvent
from svoc_tpu.fabric.registry import ClaimSpec
from svoc_tpu.resilience.retry import RetryPolicy

CORPUS_DIR = os.path.join(
    os.path.dirname(__file__), "fixtures", "chaos_corpus", "reconfig"
)

RECONFIG_POINTS = (
    "reconfig.prepare",
    "reconfig.post_drain",
    "reconfig.post_ship",
    "reconfig.pre_repin",
    "reconfig.pre_resume",
)


# ---------------------------------------------------------------------------
# plan validation (no fleet needed — cheap)
# ---------------------------------------------------------------------------


def test_reconfig_fault_points_declared_for_reconfig_smoke():
    surface = faultspace.surface()
    for point in RECONFIG_POINTS:
        assert point in surface, point
        assert surface[point].smokes == (faultspace.SMOKE_RECONFIG,), point


def test_plan_rejects_bad_knobs():
    with pytest.raises(Exception):
        ReconfigPlan(consensus_impl="quantum")
    with pytest.raises(Exception):
        ReconfigPlan(commit_mode="eventually")
    with pytest.raises(ReconfigError):
        ReconfigPlan(mesh="2by4")
    with pytest.raises(ReconfigError):
        ReconfigPlan(add_replicas=("rX",), remove_replicas=("rX",))


def test_plan_noop_and_needs_repin():
    assert ReconfigPlan().is_noop()
    assert not ReconfigPlan().needs_repin()
    assert ReconfigPlan(commit_mode="batched").needs_repin()
    assert ReconfigPlan(mesh="off").needs_repin()
    growth = ReconfigPlan(add_replicas=("r9",))
    assert not growth.needs_repin()
    assert not growth.is_noop()


def test_plan_roundtrip_and_fingerprint_stability():
    plan = ReconfigPlan(
        commit_mode="batched",
        claims={"c0": ClaimSpec(claim_id="c0", n_oracles=9, dimension=6)},
        add_replicas=("r2",),
    )
    clone = ReconfigPlan.from_dict(plan.to_dict())
    assert clone.fingerprint() == plan.fingerprint()
    assert clone.to_dict() == plan.to_dict()
    assert plan.fingerprint() != ReconfigPlan().fingerprint()


# ---------------------------------------------------------------------------
# unit fleet (claims live, a few served cycles — module-scoped builders)
# ---------------------------------------------------------------------------


def build_fleet(tmp_path, *, n_replicas=2, claims=("c0", "c1"), seed=0):
    from svoc_tpu.serving.scenario import VirtualClock
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry

    metrics = MetricsRegistry()
    journal = EventJournal(registry=metrics)
    chain_dir = str(tmp_path / "chain")
    placement = PlacementDirectory(
        [], path=str(tmp_path / "placement.json")
    )
    master_clock = VirtualClock()

    def builder(
        rid,
        *,
        fingerprint_epoch=0,
        consensus_impl=None,
        mesh=None,
        commit_mode="per_tx",
    ):
        return Replica(
            rid,
            str(tmp_path / f"replica-{rid}"),
            chain_dir=chain_dir,
            seed=seed,
            clock=VirtualClock(),
            lineage_scope="clu",
            commit_mode=commit_mode,
            consensus_impl=consensus_impl,
            mesh=mesh,
            fingerprint_epoch=fingerprint_epoch,
            max_requests_per_step=64,
        )

    router = ClusterRouter(
        placement,
        journal=journal,
        metrics=metrics,
        clock=master_clock,
        retry=RetryPolicy(max_attempts=2, base_s=0.0, cap_s=0.0, jitter_seed=0),
        replica_factory=builder,
        lineage_scope="clu",
        unclaimed_path=str(tmp_path / "unclaimed.json"),
        epochs_path=str(tmp_path / "epochs.json"),
    )
    controller = ReconfigController(
        router,
        builder=builder,
        journal=journal,
        metrics=metrics,
        clock=master_clock,
        prewarm_budget_s=0.5,
    )
    for i in range(n_replicas):
        router.add_replica(builder(f"r{i}"))
    for cid in claims:
        router.add_claim(ClaimSpec(claim_id=cid, n_oracles=7, dimension=6))
    # A little served history so re-pin carries real cursors.
    for step in range(2):
        for cid in claims:
            router.submit(cid, f"comment {cid} step {step}")
        router.step_all()
    return router, placement, controller, metrics


def test_commit_repins_under_new_epoch(tmp_path):
    router, placement, controller, metrics = build_fleet(tmp_path)
    old_config = router.replica("r0").pinned_config()
    assert old_config["commit_mode"] == "per_tx"
    report = controller.apply(ReconfigPlan(commit_mode="batched"))
    assert report["status"] == "committed"
    assert report["epoch"] == 1 == router.reconfig_epoch
    for rid in router.replica_ids():
        config = router.replica(rid).pinned_config()
        assert config["commit_mode"] == "batched"
        assert config["fingerprint_epoch"] == 1
        # The new journal lineage is on disk under the epoch suffix
        # and starts with the continuity record.
        trace = router.replica(rid).trace_path
        assert trace.endswith("trace-e1.jsonl")
        with open(trace) as f:
            first = json.loads(f.readline())
        assert first["event"] == "reconfig.epoch"
        assert first["data"]["prev_fingerprint"]
    # Epoch chain persisted, fingerprint folds it.
    with open(str(tmp_path / "epochs.json")) as f:
        persisted = json.load(f)
    assert persisted["epoch"] == 1
    assert persisted["chain"][0]["plan"] == report["plan_fingerprint"]
    # Post-commit serving continues on the re-pinned stacks.
    assert router.submit("c0", "after repin")["status"] == "admitted"
    router.step_all()
    assert metrics.gauge("reconfig_epoch").value == 1


def test_noop_plan_mints_no_epoch(tmp_path):
    router, _, controller, _ = build_fleet(tmp_path, claims=("c0",))
    before = router.fleet_fingerprint()
    assert controller.apply(ReconfigPlan()) == {"status": "noop"}
    assert router.reconfig_epoch == 0
    assert router.fleet_fingerprint() == before


def test_plan_validate_against_fleet(tmp_path):
    router, _, controller, _ = build_fleet(tmp_path, claims=("c0",))
    with pytest.raises(ReconfigError):
        controller.apply(
            ReconfigPlan(
                claims={
                    "nope": ClaimSpec(
                        claim_id="nope", n_oracles=7, dimension=6
                    )
                }
            )
        )
    with pytest.raises(ReconfigError):
        controller.apply(ReconfigPlan(add_replicas=("r0",)))
    with pytest.raises(ReconfigError):
        controller.apply(ReconfigPlan(remove_replicas=("rZ",)))
    with pytest.raises(ReconfigError):
        controller.apply(ReconfigPlan(remove_replicas=("r0", "r1")))


@pytest.mark.parametrize("point", RECONFIG_POINTS)
def test_abort_rolls_back_byte_identical(tmp_path, point):
    router, _, controller, metrics = build_fleet(tmp_path, claims=("c0",))
    before = router.fleet_fingerprint()
    faultspace.arm(
        faultspace.FaultController(
            [FaultEvent(point=point, nth=1, action="error")]
        )
    )
    try:
        report = controller.apply(ReconfigPlan(commit_mode="batched"))
    finally:
        faultspace.disarm()
    assert report["status"] == "aborted"
    assert router.reconfig_epoch == 0
    assert router.holding() == []
    assert router.fleet_fingerprint() == before
    # No epoch-suffixed journal files survive the abort.
    for rid in router.replica_ids():
        base = router.replica(rid).base_dir
        assert not os.path.exists(os.path.join(base, "trace-e1.jsonl"))
        assert not os.path.exists(os.path.join(base, "wal-e1.jsonl"))
    assert metrics.family_total("reconfig_aborts") == 1.0
    # The fleet still serves after the rollback.
    assert router.submit("c0", "after abort")["status"] == "admitted"
    router.step_all()


def test_operator_abort_request(tmp_path):
    router, _, controller, _ = build_fleet(tmp_path, claims=("c0",))
    assert controller.request_abort()["status"] == "idle"
    before = router.fleet_fingerprint()
    # Arm the abort flag, then apply: the first gate honors it.
    controller._abort_requested = True
    report = controller.apply(ReconfigPlan(commit_mode="batched"))
    assert report["status"] == "aborted"
    assert report["cause"] == "_OperatorAbort"
    assert router.fleet_fingerprint() == before


def test_growth_plan_bounded_rebalance(tmp_path):
    claims = tuple(f"c{i}" for i in range(6))
    router, placement, controller, _ = build_fleet(
        tmp_path, n_replicas=2, claims=claims
    )
    old_roster = list(placement.replicas())
    expected_moves = set()
    probe = PlacementDirectory(old_roster + ["r2"])
    for cid in claims:
        if probe.owner(cid) == "r2":
            expected_moves.add(cid)
    report = controller.apply(ReconfigPlan(add_replicas=("r2",)))
    assert report["status"] == "committed"
    moved = set(report["grown"]["r2"]["moved"])
    # Rendezvous property: ONLY claims whose HRW owner is the newcomer
    # move — growth never reshuffles claims between survivors.
    assert moved == expected_moves
    for cid in claims:
        if cid not in moved:
            assert placement.owner(cid) in old_roster
    assert router.replica("r2").pinned_config()["fingerprint_epoch"] == 1


def test_adopt_orphans_with_continuity(tmp_path):
    router, placement, controller, metrics = build_fleet(
        tmp_path, claims=("c0", "c1")
    )
    # Quarantine c0 by migrating it to a replica that does not exist.
    report = router.migrate("c0", "rZ", reason="test")
    assert report["status"] == "quarantined"
    adoption = router.adopt_orphans()
    assert "c0" in adoption["adopted"]
    assert adoption["adopted"]["c0"]["continuity"] is True
    assert adoption["remaining"] == {}
    owner = placement.owner("c0")
    assert router.replica(owner).has_claim("c0")
    with open(str(tmp_path / "unclaimed.json")) as f:
        assert json.load(f) == {}
    assert metrics.family_total("cluster_adopted") == 1.0
    # The adopted claim serves again.
    assert router.submit("c0", "after adoption")["status"] == "admitted"
    router.step_all()


def test_console_reconfig_and_adopt_commands(tmp_path):
    from svoc_tpu.apps.commands import CommandConsole

    router, _, controller, _ = build_fleet(tmp_path, claims=("c0",))
    console = CommandConsole.__new__(CommandConsole)
    console.cluster = None
    console.reconfig = None
    console._write = None
    # query() reads session.adapter before dispatch; the reconfig and
    # cluster branches never touch the session beyond that.
    console.session = type("S", (), {"adapter": None})()
    router.attach(console)
    controller.attach(console)
    assert console.reconfig is controller

    out = console.query("reconfig status")
    assert any("phase idle" in line for line in out)
    out = console.query("reconfig abort")
    assert any("idle" in line for line in out)
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        json.dump(ReconfigPlan(commit_mode="batched").to_dict(), f)
    out = console.query(f"reconfig apply {plan_path}")
    assert any("committed epoch 1" in line for line in out), out
    out = console.query("cluster adopt-orphans")
    assert any("no orphaned claims" in line for line in out), out


# ---------------------------------------------------------------------------
# hypothesis property: ANY aborted plan is invisible (import-gated)
# ---------------------------------------------------------------------------


try:
    import hypothesis
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - import-gated satellite
    HAVE_HYPOTHESIS = False

if HAVE_HYPOTHESIS:

    @st.composite
    def abortable_plans(draw):
        commit_mode = draw(st.sampled_from([None, "batched"]))
        respec = draw(st.booleans())
        grow = draw(st.booleans())
        claims = (
            {"c0": ClaimSpec(claim_id="c0", n_oracles=9, dimension=6)}
            if respec
            else {}
        )
        plan = ReconfigPlan(
            commit_mode=commit_mode,
            claims=claims,
            add_replicas=("rG",) if grow else (),
        )
        hypothesis.assume(not plan.is_noop())
        point = draw(st.sampled_from(RECONFIG_POINTS))
        return plan, point

    @settings(
        max_examples=6,
        deadline=None,
        suppress_health_check=list(HealthCheck),
    )
    @given(abortable_plans())
    def test_property_abort_is_invisible(tmp_path_factory, plan_and_point):
        """For ANY non-noop plan prefix, an abort at ANY fault point
        leaves the fleet fingerprint byte-identical to never having
        attempted the plan (ISSUE 19's rollback invariant,
        fleet-shape sampled)."""
        plan, point = plan_and_point
        tmp_path = tmp_path_factory.mktemp("prop")
        router, _, controller, _ = build_fleet(tmp_path, claims=("c0",))
        before = router.fleet_fingerprint()
        faultspace.arm(
            faultspace.FaultController(
                [FaultEvent(point=point, nth=1, action="error")]
            )
        )
        try:
            report = controller.apply(plan)
        finally:
            faultspace.disarm()
        assert report["status"] == "aborted", (plan, point)
        assert router.fleet_fingerprint() == before, (plan, point)
        assert router.reconfig_epoch == 0


# ---------------------------------------------------------------------------
# seeded scenario (two small committed runs, module-cached)
# ---------------------------------------------------------------------------


def load_corpus_entry():
    with open(os.path.join(CORPUS_DIR, "rolling-repin-commit.json")) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def committed_runs(tmp_path_factory):
    from svoc_tpu.cluster.reconfig_scenario import replay_corpus_entry

    entry = load_corpus_entry()
    runs = []
    for tag in ("a", "b"):
        workdir = str(tmp_path_factory.mktemp(f"reconfig-{tag}"))
        runs.append(replay_corpus_entry(entry, workdir))
    return runs


def test_scenario_replay_identity_through_epoch_boundary(committed_runs):
    first, second = committed_runs
    assert first["reconfig"]["status"] == "committed"
    assert first["fleet_fingerprint"] == second["fleet_fingerprint"]
    for cid, claim in first["claims"].items():
        assert claim["fingerprint"] == second["claims"][cid]["fingerprint"]
    assert first["epoch_chain"] == second["epoch_chain"]


def test_scenario_exactly_once_and_continuity(committed_runs):
    first, _ = committed_runs
    assert first["duplicate_txs"] == 0
    assert first["requests"]["unaccounted"] == 0.0
    assert first["reconfig_epoch"] == 1
    for rep in first["reconfig"]["replicas"].values():
        for claim in rep["claims"].values():
            assert claim["continuity"] is True
    # Mid-transition traffic was deferred (never shed) and released.
    deferred = [
        p
        for p in first["probes"]
        if p["response"].get("status") == "deferred"
    ]
    assert deferred
    assert first["cluster_counters"]["cluster_unavailable"] == 0.0
    assert first["reconfig"]["deferred_released"] == len(deferred)


def test_reconfig_corpus_entry_invisible_to_durable_fuzzer():
    from svoc_tpu.durability.fuzz import load_corpus

    corpus_root = os.path.dirname(CORPUS_DIR)
    for entry in load_corpus(corpus_root):
        assert entry.get("format") != "svoc-reconfig-corpus-v1"
