"""Sharded claim cube == single-device cube, bitwise, on the 8-device
CPU mesh (docs/PARALLELISM.md §sharded-claims).

The exact-parity contract is the load-bearing property: the fabric
journals essences rounded to 6 decimals, so a mesh that changed even an
ulp could flip a seeded replay's fingerprint.  Parity here is therefore
``array_equal`` (NaN-aware), never ``allclose`` — except for the
pallas-routed composition, which is a different lossless float program
(the ``bench --claims`` 5e-5 bar).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from svoc_tpu.consensus.batch import (
    _claims_consensus_gated_xla,
    _claims_consensus_sanitized_xla,
    pad_claim_cube,
    pow2_bucket,
)
from svoc_tpu.consensus.kernel import ConsensusConfig
from svoc_tpu.parallel.claim_shard import (
    ClaimShardDispatcher,
    fleet_claims_reference,
    sharded_claims_consensus_fn,
    sharded_claims_sanitized_fn,
    sharded_fleet_claims_fn,
)
from svoc_tpu.parallel.mesh import (
    MeshConfigError,
    claim_mesh,
    parse_claim_mesh,
)
from svoc_tpu.sim.generators import claim_fleet_keys
from svoc_tpu.utils.metrics import MetricsRegistry

CFGS = [
    ConsensusConfig(n_failing=2, constrained=True),
    ConsensusConfig(n_failing=3, constrained=False, max_spread=10.0),
]
MESHES = ["1x1", "2x1", "4x1", "8x1", "1x8", "2x4", "4x2", "2x2"]


def exact_eq(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and bool(np.array_equal(a, b, equal_nan=True))


def assert_outputs_exact(out, ref, context=""):
    for field in out._fields:
        assert exact_eq(getattr(out, field), getattr(ref, field)), (
            f"{context}: field {field} diverged from the single-device "
            "cube — the exact-parity contract is broken"
        )


@pytest.fixture(scope="module", autouse=True)
def _needs_8_devices():
    assert jax.device_count() >= 8, "conftest must force 8 virtual CPU devices"


@pytest.fixture(scope="module")
def fixture_cube():
    """A cube exercising every degenerate row the gated kernel guards:
    a quarantined slot, an all-quarantined claim (n_ok=0), a
    single-survivor claim (n_ok=1), and padding claims with hostile
    filler."""
    rng = np.random.default_rng(0)
    c, n, m = 8, 16, 6
    values = rng.uniform(0, 1, size=(c, n, m)).astype(np.float32)
    ok = np.ones((c, n), dtype=bool)
    ok[1, -1] = False  # one quarantined slot
    ok[2, :] = False  # all quarantined — n_ok = 0
    ok[3, 1:] = False  # single survivor — n_ok = 1
    claim_mask = np.ones(c, dtype=bool)
    claim_mask[-2:] = False  # padding rows
    values[6] = 777.0  # hostile filler must never leak
    values[1, 0, 0] = np.nan  # quarantined row carries poison
    ok[1, 0] = False
    return values, ok, claim_mask


class TestShardedDispatchParity:
    @pytest.mark.parametrize(
        "cfg", CFGS, ids=["constrained", "unconstrained"]
    )
    @pytest.mark.parametrize("spec", MESHES)
    def test_gated_bitwise_parity(self, fixture_cube, cfg, spec):
        values, ok, claim_mask = fixture_cube
        ref = _claims_consensus_gated_xla(
            jnp.asarray(values), jnp.asarray(ok), jnp.asarray(claim_mask), cfg
        )
        out = sharded_claims_consensus_fn(claim_mesh(spec), cfg)(
            values, ok, claim_mask
        )
        assert_outputs_exact(out, ref, f"gated mesh {spec}")

    @pytest.mark.parametrize(
        "cfg", CFGS, ids=["constrained", "unconstrained"]
    )
    def test_sanitized_bitwise_parity(self, fixture_cube, cfg):
        values, _ok, claim_mask = fixture_cube
        lo, hi = (0.0, 1.0) if cfg.constrained else (None, None)
        ref, ref_ok = _claims_consensus_sanitized_xla(
            jnp.asarray(values), jnp.asarray(claim_mask), cfg, lo, hi
        )
        for spec in ("2x4", "4x1"):
            out, out_ok = sharded_claims_sanitized_fn(
                claim_mesh(spec), cfg, lo, hi
            )(values, claim_mask)
            assert_outputs_exact(out, ref, f"sanitized mesh {spec}")
            assert exact_eq(out_ok, ref_ok)

    def test_random_shapes_sweep(self):
        """Exactness is not a one-fixture accident: random masks and a
        spread of (C, N, M) shapes stay bitwise across meshes."""
        for seed, (c, n, m) in [
            (1, (4, 64, 6)),
            (2, (16, 8, 2)),
            (3, (2, 128, 3)),
        ]:
            rng = np.random.default_rng(seed)
            values = rng.uniform(0, 1, size=(c, n, m)).astype(np.float32)
            ok = rng.random((c, n)) > 0.1
            claim_mask = np.ones(c, dtype=bool)
            claim_mask[-1] = False
            for cfg in CFGS:
                ref = _claims_consensus_gated_xla(
                    jnp.asarray(values),
                    jnp.asarray(ok),
                    jnp.asarray(claim_mask),
                    cfg,
                )
                for spec in ("2x2", "1x8"):
                    mc, mo = parse_claim_mesh(spec)
                    if c % mc or n % mo:
                        continue
                    out = sharded_claims_consensus_fn(
                        claim_mesh(spec), cfg
                    )(values, ok, claim_mask)
                    assert_outputs_exact(
                        out, ref, f"sweep seed {seed} mesh {spec}"
                    )

    def test_padded_rows_stay_inactive_through_sharded_path(
        self, fixture_cube
    ):
        """`_mask_padded_claims` is shared, not forked: padding claims
        come back invalid with zero essence and empty reliable sets
        from the SHARDED program too, hostile filler included."""
        values, ok, claim_mask = fixture_cube
        cfg = CFGS[0]
        out = sharded_claims_consensus_fn(claim_mesh("2x4"), cfg)(
            values, ok, claim_mask
        )
        pad_rows = ~claim_mask
        assert not np.asarray(out.interval_valid)[pad_rows].any()
        assert np.all(np.asarray(out.essence)[pad_rows] == 0.0)
        assert not np.asarray(out.reliable)[pad_rows].any()


class TestShardedFleet:
    def test_fleet_bitwise_invariant_across_meshes(self):
        """The ``_fleet_body`` contract on the claim cube: global-index
        keyed streams ⇒ every field bitwise identical however (and
        whether) the fleet is sharded."""
        cfg = ConsensusConfig(n_failing=4, constrained=True)
        c, n, w, m = 4, 32, 50, 6
        keys = claim_fleet_keys(jax.random.PRNGKey(3), c)
        windows = jax.random.uniform(jax.random.PRNGKey(11), (c, w, m))
        base = None
        for spec in ("1x1", "2x4", "4x2", "1x8", "4x1"):
            out, honest = sharded_fleet_claims_fn(
                claim_mesh(spec), cfg, n
            )(keys, windows)
            fields = {f: np.asarray(getattr(out, f)) for f in out._fields}
            fields["honest"] = np.asarray(honest)
            if base is None:
                base = fields
                continue
            for name, arr in fields.items():
                assert exact_eq(arr, base[name]), (
                    f"fleet field {name} not sharding-invariant at {spec}"
                )
        # Ground truth roster matches the single-device reference
        # generator (one shared per-oracle impl — no drift possible).
        _vref, href = fleet_claims_reference(keys, windows, n, cfg.n_failing)
        assert exact_eq(base["honest"], href)
        assert int(np.asarray(~base["honest"]).sum()) == c * cfg.n_failing

    def test_fleet_values_match_reference_generator(self):
        """The sharded generation IS the reference generation: gather
        the per-claim cube from a consensus run of the reference values
        and compare essences to the sharded fleet step's."""
        cfg = ConsensusConfig(n_failing=2, constrained=True)
        c, n, w, m = 2, 16, 30, 4
        keys = claim_fleet_keys(jax.random.PRNGKey(7), c)
        windows = jax.random.uniform(jax.random.PRNGKey(13), (c, w, m))
        vref, _href = fleet_claims_reference(keys, windows, n, cfg.n_failing)
        out, _honest = sharded_fleet_claims_fn(claim_mesh("2x4"), cfg, n)(
            keys, windows
        )
        ones = jnp.ones((c, n), dtype=bool)
        ref = _claims_consensus_gated_xla(
            vref, ones, jnp.ones(c, dtype=bool), cfg
        )
        np.testing.assert_allclose(
            np.asarray(out.essence),
            np.asarray(ref.essence),
            rtol=0,
            atol=1e-6,
        )

    def test_gated_fleet_quarantines_in_graph(self):
        """The in-graph gate on the fleet path: admitted masks come
        back sharded, and a healthy in-range fleet admits everything."""
        cfg = ConsensusConfig(n_failing=2, constrained=True)
        c, n, w, m = 2, 16, 30, 4
        keys = claim_fleet_keys(jax.random.PRNGKey(1), c)
        windows = jax.random.uniform(jax.random.PRNGKey(2), (c, w, m))
        out, honest, admitted = sharded_fleet_claims_fn(
            claim_mesh("2x2"), cfg, n, gate=(0.0, 1.0)
        )(keys, windows)
        assert np.asarray(admitted).shape == (c, n)
        assert np.asarray(admitted).all()
        assert np.asarray(out.interval_valid).all()

    def test_no_replica_materializes_the_full_cube(self):
        """The scale-out guarantee, asserted through the PR 1
        ``jax.live_arrays`` gauge: after a sharded fleet dispatch of a
        multi-MB cube, NO device holds live bytes approaching the full
        cube — the fleet only ever exists as device-local shards (and
        the per-claim gather is a program-internal transient, not a
        live replica)."""
        from svoc_tpu.utils.metrics import sample_runtime_gauges

        cfg = ConsensusConfig(n_failing=8, constrained=True)
        c, n, w, m = 8, 2048, 50, 16
        cube_bytes = c * n * m * 4  # 4 MiB f32
        keys = claim_fleet_keys(jax.random.PRNGKey(5), c)
        windows = jax.random.uniform(jax.random.PRNGKey(6), (c, w, m))
        out, honest = sharded_fleet_claims_fn(claim_mesh("2x4"), cfg, n)(
            keys, windows
        )
        jax.block_until_ready(out.essence)
        reg = MetricsRegistry()
        gauges = sample_runtime_gauges(reg)
        per_device = {
            key: val
            for key, val in gauges.items()
            if key.startswith("device_live_bytes")
        }
        assert per_device, "gauge sampled no devices"
        worst = max(per_device.values())
        assert worst < cube_bytes / 2, (
            f"a replica holds {worst:.0f} live bytes >= half the "
            f"{cube_bytes}-byte cube — the fleet materialized somewhere"
        )
        # And no single live array has a full-cube-sized shard.
        for arr in jax.live_arrays():
            for shard in getattr(arr, "addressable_shards", []) or []:
                nbytes = getattr(shard.data, "nbytes", 0)
                assert nbytes < cube_bytes, (
                    f"live array shard of {nbytes} bytes >= the cube"
                )
        del out, honest


class TestMeshConfig:
    def test_parse_claim_mesh(self):
        assert parse_claim_mesh(None) is None
        assert parse_claim_mesh("") is None
        assert parse_claim_mesh("none") is None
        assert parse_claim_mesh("off") is None
        assert parse_claim_mesh("2x4") == (2, 4)
        assert parse_claim_mesh("8X1") == (8, 1)
        assert parse_claim_mesh((4, 2)) == (4, 2)
        for bad in ("2x", "x4", "2x4x1", "ax2", "0x4", "-1x2", (3,)):
            with pytest.raises(MeshConfigError):
                parse_claim_mesh(bad)

    def test_claim_mesh_device_budget(self):
        mesh = claim_mesh("2x4")
        assert mesh.shape == {"claim": 2, "oracle": 4}
        assert claim_mesh("none") is None
        with pytest.raises(MeshConfigError) as err:
            claim_mesh("64x64")
        # The error must name the simulation knob — it is the one fix.
        assert "xla_force_host_platform_device_count" in str(err.value)

    def test_resolve_claim_mesh_env_and_record(self, monkeypatch, tmp_path):
        from svoc_tpu.consensus.dispatch import resolve_claim_mesh

        record = tmp_path / "PERF_DECISIONS.json"
        record.write_text('{"claim_mesh": "4x2"}')
        assert resolve_claim_mesh(path=str(record)) == "4x2"
        record.write_text('{"claim_mesh": "none"}')
        assert resolve_claim_mesh(path=str(record)) is None
        monkeypatch.setenv("SVOC_MESH", "2x4")
        assert resolve_claim_mesh(path=str(record)) == "2x4"
        monkeypatch.setenv("SVOC_MESH", "off")
        assert resolve_claim_mesh(path=str(record)) is None

    def test_pow2_bucket_multiple_of(self):
        assert pow2_bucket(3, multiple_of=2) == 4
        assert pow2_bucket(5, multiple_of=8) == 8
        assert pow2_bucket(4, multiple_of=3) == 6  # pow2 then rounded up
        assert pow2_bucket(0, multiple_of=4) == 4
        with pytest.raises(ValueError):
            pow2_bucket(4, multiple_of=0)

    def test_pad_claim_cube_multiple_of(self):
        values = np.full((3, 4, 2), 0.25, dtype=np.float32)
        padded, ok, claim_mask = pad_claim_cube(values, multiple_of=8)
        assert padded.shape[0] == 8
        assert claim_mask.tolist() == [True] * 3 + [False] * 5
        assert ok.shape == (8, 4) and ok.all()


class TestDispatcher:
    def test_unshardable_cube_counts_fallback(self, fixture_cube):
        values, ok, claim_mask = fixture_cube
        reg = MetricsRegistry()
        d = ClaimShardDispatcher(
            claim_mesh("2x4"), consensus_impl="xla", metrics=reg
        )
        cfg = CFGS[0]
        # N=15 not divisible by the oracle axis: counted fallback, and
        # the result still matches the single-device cube exactly.
        out = d.dispatch_gated(
            values[:, :15], ok[:, :15], claim_mask, cfg
        )
        ref = _claims_consensus_gated_xla(
            jnp.asarray(values[:, :15]),
            jnp.asarray(ok[:, :15]),
            jnp.asarray(claim_mask),
            cfg,
        )
        assert_outputs_exact(out, ref, "fallback path")
        series = dict(
            (tuple(sorted(labels.items())), count)
            for labels, count in reg.family_series("claim_shard_fallback")
        )
        assert series == {(("reason", "oracle_indivisible"),): 1.0}
        assert reg.family_total("claim_shard_dispatches") == 0
        # A shardable cube then counts a dispatch, no new fallbacks.
        d.dispatch_gated(values, ok, claim_mask, cfg)
        assert reg.family_total("claim_shard_dispatches") == 1
        assert reg.family_total("claim_shard_fallback") == 1

    def test_pallas_on_oracle_sharded_mesh_counts_sharded_unsupported(
        self, fixture_cube, monkeypatch
    ):
        from svoc_tpu.consensus.dispatch import FALLBACK_COUNTER

        monkeypatch.setenv("SVOC_PALLAS_INTERPRET", "1")
        values, ok, claim_mask = fixture_cube
        reg = MetricsRegistry()
        d = ClaimShardDispatcher(
            claim_mesh("2x4"), consensus_impl="pallas", metrics=reg
        )
        out = d.dispatch_gated(values, ok, claim_mask, CFGS[0])
        ref = _claims_consensus_gated_xla(
            jnp.asarray(values), jnp.asarray(ok), jnp.asarray(claim_mask),
            CFGS[0],
        )
        # The XLA sharded body served (bitwise), and the unhonored
        # pallas route was counted, never silent.
        assert_outputs_exact(out, ref, "sharded_unsupported path")
        series = dict(
            (tuple(sorted(labels.items())), count)
            for labels, count in reg.family_series(FALLBACK_COUNTER)
        )
        assert series.get((("reason", "sharded_unsupported"),)) == 1.0

    def test_pallas_composes_on_claims_only_mesh(
        self, fixture_cube, monkeypatch
    ):
        from svoc_tpu.consensus.dispatch import FALLBACK_COUNTER

        monkeypatch.setenv("SVOC_PALLAS_INTERPRET", "1")
        values, ok, claim_mask = fixture_cube
        reg = MetricsRegistry()
        d = ClaimShardDispatcher(
            claim_mesh("4x1"), consensus_impl="pallas", metrics=reg
        )
        out = d.dispatch_gated(values, ok, claim_mask, CFGS[0])
        ref = _claims_consensus_gated_xla(
            jnp.asarray(values), jnp.asarray(ok), jnp.asarray(claim_mask),
            CFGS[0],
        )
        # A different lossless float program: the bench --claims bar.
        np.testing.assert_allclose(
            np.asarray(out.essence), np.asarray(ref.essence), atol=5e-5
        )
        assert exact_eq(out.interval_valid, ref.interval_valid)
        series = dict(
            (tuple(sorted(labels.items())), count)
            for labels, count in reg.family_series(FALLBACK_COUNTER)
        )
        assert (("reason", "sharded_unsupported"),) not in series
        assert reg.family_total("claim_shard_dispatches") == 1


class TestRouterIntegration:
    def test_meshed_fabric_fingerprints_equal_unmeshed(self):
        from svoc_tpu.fabric.scenario import run_fabric_scenario

        plain = run_fabric_scenario(0, cycles=4, n_oracles=8)
        meshed = run_fabric_scenario(0, cycles=4, n_oracles=8, mesh="2x4")
        for cid in plain["claims"]:
            assert (
                plain["claims"][cid]["fingerprint"]
                == meshed["claims"][cid]["fingerprint"]
            ), f"mesh changed claim {cid}'s journal — parity broken"
        assert (
            plain["journal_fingerprint"] == meshed["journal_fingerprint"]
        )

    def test_multisession_snapshot_surfaces_mesh(self):
        from svoc_tpu.fabric.session import MultiSession

        multi = MultiSession(mesh="2x1", consensus_impl="xla")
        snap = multi.snapshot()
        assert snap["mesh"] == "2x1"
        assert snap["consensus_impl"] == "xla"
        assert snap["pipelined"] is False
        unmeshed = MultiSession(mesh="off")
        assert unmeshed.snapshot()["mesh"] is None

    def test_pipelined_consensus_trails_one_cycle_then_flushes(self):
        from svoc_tpu.fabric.scenario import run_fabric_scenario

        plain = run_fabric_scenario(1, cycles=5, n_oracles=8)
        piped = run_fabric_scenario(
            1, cycles=5, n_oracles=8, pipelined=True
        )
        piped2 = run_fabric_scenario(
            1, cycles=5, n_oracles=8, pipelined=True
        )
        # Pipelined replays are deterministic (its own fingerprint
        # family — consensus events land one cycle later)…
        assert (
            piped["journal_fingerprint"] == piped2["journal_fingerprint"]
        )
        # …and after the run()-flush the final consensus slices match
        # the unpipelined run's (same math, shifted write-back).
        for cid in plain["claims"]:
            assert (
                piped["claims"][cid]["interval_valid"]
                == plain["claims"][cid]["interval_valid"]
            )
        assert piped["offender_replaced"] and piped["siblings_clean"]

    def test_pipelined_rejects_request_driven_feeds(self):
        from svoc_tpu.fabric.registry import ClaimRegistry
        from svoc_tpu.fabric.router import ClaimRouter

        router = ClaimRouter(
            ClaimRegistry(), pipelined=True, mesh="off", consensus_impl="xla"
        )
        with pytest.raises(ValueError, match="pull-mode only"):
            router.step(feeds={"alpha": np.zeros((1, 6))})

    def test_router_pins_mesh_once_from_env(self, monkeypatch):
        from svoc_tpu.fabric.registry import ClaimRegistry
        from svoc_tpu.fabric.router import ClaimRouter

        monkeypatch.setenv("SVOC_MESH", "2x1")
        router = ClaimRouter(ClaimRegistry(), consensus_impl="xla")
        assert router.mesh_spec == "2x1"
        # Construction-time pinning: clearing the env does not unpin.
        monkeypatch.delenv("SVOC_MESH")
        assert router.mesh_spec == "2x1"
        unpinned = ClaimRouter(ClaimRegistry(), consensus_impl="xla")
        assert unpinned.mesh_spec is None
