"""Guard the driver artifacts: ``__graft_entry__`` must never regress.

Round 1 failed precisely here (MULTICHIP_r01.json rc=124): the dryrun
probed ``jax.devices()`` before pinning the CPU platform, initializing
the TPU plugin, which blocks when the chip is unreachable.  These tests
run the dryrun exactly the way the driver does — a fresh subprocess with
no conftest help — under a hard timeout.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_traces():
    """entry() must return a traceable (fn, args) pair — eval_shape only,
    so the 125M-param flagship doesn't actually compile in CI."""
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.pop(0)
    fn, args = g.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape[-1] == 6  # tracked go_emotions labels


def test_dryrun_multichip_subprocess_fresh_env():
    """The real thing: fresh interpreter, hostile JAX_PLATFORMS, hard
    timeout far below the driver's.  Must print every section mark, in
    order (the list below is the coverage contract)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "tpu,cpu"  # hostile: would hang if probed first
    # Internal budget below the subprocess timeout so a slow section
    # fails loudly with its name, not as an opaque TimeoutExpired.
    # (13 sections incl. the scaling study compile ~8 mesh programs;
    # ~160 s on an unloaded host, so leave real headroom for CI load.)
    env["SVOC_DRYRUN_BUDGET_S"] = "260"
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=320,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    sections = re.findall(r"\[dryrun\] ([\w-]+) ok", proc.stdout)
    assert sections == [
        "sharded-train-step",
        "zero1-train-step",
        "sharded-fleet-consensus",
        "ring-attention",
        "sequence-parallel-forward",
        "dp-serving-end-to-end",
        "pipeline-parallel-forward",
        "packed-forward-dp",
        "int8-packed-serving-dp",
        "packed-pipelined-serving-dp",
        "packed-flash-forward-dp",
        "batched-fleet-commit",
        "dp-serving-scaling",
    ]
    # the scaling study emits its per-width timings for the round
    # artifact (MULTICHIP_r{N}.json captures stdout)
    assert re.search(r"\[dryrun\] scaling-law \[", proc.stdout)


def test_ensure_devices_never_probes_before_pin():
    """Static guard: inside _ensure_devices, every jax.devices() call
    must come after the jax_platforms pin (source-order check)."""
    src = open(os.path.join(REPO, "__graft_entry__.py")).read()
    body = src.split("def _ensure_devices", 1)[1].split("\ndef ", 1)[0]
    pin = body.index('jax.config.update("jax_platforms", "cpu")')
    first_probe = body.index("len(jax.devices())")
    assert pin < first_probe, (
        "_ensure_devices probes jax.devices() before pinning cpu — "
        "this is the round-1 rc=124 bug"
    )
