"""Cost-attribution plane (docs/OBSERVABILITY.md §cost-attribution):
timelines, the shape-keyed cost ledger, profiling, fingerprint
invisibility, persistence, and the serving-lineage audit trail."""

from __future__ import annotations

import json
import os
import urllib.request

import pytest

from svoc_tpu.compile.universe import dispatch_key
from svoc_tpu.consensus.kernel import ConsensusConfig
from svoc_tpu.obsplane.ledger import (
    DEFAULT_ALPHA,
    CostLedger,
    CostModel,
    group_key,
    ledger_key,
)
from svoc_tpu.obsplane.plane import (
    REQUEST_STAGE_HISTOGRAM,
    CostPlane,
    resolve_cost_plane_enabled,
)
from svoc_tpu.obsplane.profiler import ProfileCapture
from svoc_tpu.obsplane.timeline import (
    MARKS,
    STAGE_OF_MARK,
    ObservationLog,
    RequestTimeline,
    read_observations,
)
from svoc_tpu.utils.events import EventJournal, read_trace_events
from svoc_tpu.utils.metrics import MetricsRegistry

CFG = ConsensusConfig(n_failing=2, constrained=True)


def make_key(bucket=4, n_oracles=7, dimension=6, **overrides):
    kwargs = dict(
        sanitized=True,
        sharded=False,
        bucket=bucket,
        n_oracles=n_oracles,
        dimension=dimension,
        cfg=CFG,
        donate=False,
        impl="xla",
        mesh=None,
    )
    kwargs.update(overrides)
    return dispatch_key(**kwargs)


@pytest.fixture(scope="module")
def scenario_on():
    from svoc_tpu.serving.scenario import run_serving_scenario

    return run_serving_scenario(0, cost_plane="on")


@pytest.fixture(scope="module")
def scenario_off():
    from svoc_tpu.serving.scenario import run_serving_scenario

    return run_serving_scenario(0, cost_plane="off")


# ---------------------------------------------------------------------------
# Request timelines
# ---------------------------------------------------------------------------


class TestRequestTimeline:
    def test_stages_telescope_to_e2e(self):
        tl = RequestTimeline("blkt-c0-rq1", "c0", 10.0)
        for i, mark in enumerate(MARKS):
            tl.mark(mark, 10.0 + (i + 1) * 0.5)
        stages = tl.stages()
        assert set(stages) == set(STAGE_OF_MARK.values())
        assert sum(stages.values()) == pytest.approx(tl.e2e_s())
        assert tl.e2e_s() == pytest.approx(len(MARKS) * 0.5)

    def test_first_crossing_wins(self):
        tl = RequestTimeline("blkt-c0-rq2", "c0", 0.0)
        tl.mark("assembled", 1.0)
        tl.mark("assembled", 5.0)  # retry/duplicate mark: ignored
        tl.mark("completed", 2.0)
        assert tl.stages()["queue_wait"] == pytest.approx(1.0)

    def test_skipped_marks_still_telescope(self):
        # A cache-served request never crosses h2d/dispatch/sync; the
        # decomposition must stay gapless regardless.
        tl = RequestTimeline("blkt-c0-rq3", "c0", 0.0)
        tl.mark("assembled", 0.4)
        tl.mark("completed", 1.0)
        stages = tl.stages()
        assert sum(stages.values()) == pytest.approx(tl.e2e_s())
        assert all(v >= 0.0 for v in stages.values())

    def test_out_of_order_marks_clamp_nonnegative(self):
        # A claim mark can land "early" relative to this request's own
        # marks under a live clock; the negative segment clamps to 0
        # (so no stage reads as negative time) at the cost of the sum
        # overshooting e2e by the clamped amount.
        tl = RequestTimeline("blkt-c0-rq4", "c0", 0.0)
        tl.mark("vectorized", 2.0)
        tl.mark("h2d", 1.0)
        tl.mark("completed", 3.0)
        stages = tl.stages()
        assert all(v >= 0.0 for v in stages.values())
        assert stages["h2d"] == 0.0
        assert sum(stages.values()) >= tl.e2e_s()


# ---------------------------------------------------------------------------
# Observation channel
# ---------------------------------------------------------------------------


class TestObservationLog:
    def test_ring_and_filters(self):
        log = ObservationLog()
        log.record("timeline.request", lineage="blkt-c0-rq1", outcome="shed")
        log.record("cost.sample", lineage=None, key="k", seconds=0.1)
        assert len(log) == 2
        assert [r["obs"] for r in log.recent(10)] == [
            "timeline.request",
            "cost.sample",
        ]
        only = log.recent(10, kind="timeline.request")
        assert len(only) == 1 and only[0]["lineage"] == "blkt-c0-rq1"

    def test_obs_lines_invisible_to_journal_recovery(self, tmp_path):
        """The fingerprint-invisibility mechanism: obs records share
        the trace FILE with journal events but ``read_trace_events``
        (the recovery reader) must never see them, while
        ``read_observations`` sees only them."""
        path = str(tmp_path / "trace.jsonl")
        journal = EventJournal(registry=MetricsRegistry())
        journal.set_trace_file(path)
        journal.emit("serving.step", requests=1)
        log = ObservationLog(trace_path=path)
        log.record("cost.sample", lineage=None, key="k", seconds=0.5)
        events = read_trace_events(path)
        assert [e["event"] for e in events] == ["serving.step"]
        obs = read_observations(path)
        assert [r["obs"] for r in obs] == ["cost.sample"]


# ---------------------------------------------------------------------------
# Cost ledger + model
# ---------------------------------------------------------------------------


class TestCostLedger:
    def test_ema_fold_is_deterministic(self):
        ledger = CostLedger(alpha=0.5)
        key = make_key()
        ledger.observe(key, "cold", 1.0)
        ledger.observe(key, "cold", 2.0)  # 1.0 + 0.5*(2.0-1.0)
        ledger.observe(key, "warm", 0.25)
        cell = ledger.to_dict()["entries"][ledger_key(key)]["warmth"]
        assert cell["cold"]["ema_s"] == pytest.approx(1.5)
        assert cell["cold"]["samples"] == 2
        assert cell["warm"]["ema_s"] == pytest.approx(0.25)

    def test_observe_key_str_replays_observe(self):
        """The obs_query reconstruction contract: replaying the
        ``cost.sample`` stream through ``observe_key_str`` in order
        reproduces the live ledger exactly."""
        live = CostLedger()
        rebuilt = CostLedger()
        key = make_key()
        for warmth, s in (("cold", 0.8), ("warm", 0.1), ("warm", 0.3)):
            live.observe(key, warmth, s)
            rebuilt.observe_key_str(
                ledger_key(key), group_key(key), warmth, s
            )
        assert live.to_dict() == rebuilt.to_dict()

    def test_restore_round_trip(self, tmp_path):
        ledger = CostLedger()
        ledger.observe(make_key(), "cold", 1.2)
        ledger.observe(make_key(bucket=8), "warm", 0.4)
        payload = ledger.to_dict()
        fresh = CostLedger()
        assert fresh.restore(payload) == 2
        assert fresh.to_dict() == payload

    def test_estimate_fallback_ladder(self):
        ledger = CostLedger()
        model = CostModel(ledger)
        observed = make_key(bucket=4)
        twin = make_key(bucket=16)  # same (N, M) family, never seen
        foreign = make_key(n_oracles=9, dimension=4)  # other family
        # Empty ledger: nothing to price.
        est = model.estimate(observed)
        assert est["warm"] is None and est["cold"] is None
        ledger.observe(observed, "cold", 1.0)
        ledger.observe(observed, "prewarmed", 0.1)
        assert model.estimate(observed)["cold"]["source"] == "exact"
        # "prewarmed" counts as the warm regime.
        warm = model.estimate(observed)["warm"]
        assert warm["source"] == "exact"
        assert warm["seconds"] == pytest.approx(0.1)
        assert model.estimate(twin)["cold"]["source"] == "group"
        assert model.estimate(foreign)["cold"]["source"] == "global"

    def test_restore_tolerates_garbage(self):
        fresh = CostLedger()
        assert fresh.restore({"entries": None}) == 0
        assert fresh.restore({"version": 1, "entries": {"x": "bad"}}) == 0
        assert len(fresh) == 0


# ---------------------------------------------------------------------------
# CostPlane unit behavior
# ---------------------------------------------------------------------------


class TestCostPlane:
    def test_disabled_plane_is_inert(self):
        metrics = MetricsRegistry()
        plane = CostPlane(enabled=False, metrics=metrics)
        assert plane.timeline_for("l", "c0", 0.0) is None
        plane.claim_mark(["c0"], "h2d")
        plane.observe_dispatch(make_key(), "cold", 0.5)
        plane.shed("l", "c0", "queue_full")
        assert plane._claim_marks == {}
        assert len(plane.obslog) == 0
        assert len(plane.ledger) == 0
        assert plane.snapshot()["enabled"] is False

    def test_complete_folds_claim_marks_and_histograms(self):
        metrics = MetricsRegistry()
        t = {"now": 0.0}
        plane = CostPlane(
            enabled=True, clock=lambda: t["now"], metrics=metrics
        )

        class Req:
            claim = "c0"
            timeline = None

        req = Req()
        req.timeline = plane.timeline_for("blkt-c0-rq1", "c0", 0.0)
        t["now"] = 0.2
        plane.mark_requests([req], "assembled")
        t["now"] = 0.3
        plane.claim_mark(["c0"], "h2d")
        plane.claim_mark(["c0"], "dispatched")
        t["now"] = 0.5
        plane.complete(req, 0.5)
        plane.end_step()
        assert plane._claim_marks == {}
        rec = plane.obslog.recent(1, kind="timeline.request")[0]
        assert rec["data"]["outcome"] == "completed"
        assert rec["data"]["e2e_s"] == pytest.approx(0.5)
        assert sum(rec["data"]["stages"].values()) == pytest.approx(0.5)
        hist = metrics.histogram(
            REQUEST_STAGE_HISTOGRAM,
            labels={"stage": "queue_wait", "claim": "c0"},
        ).snapshot()
        assert hist["count"] == 1

    def test_shed_records_timeline_without_stages(self):
        plane = CostPlane(enabled=True, metrics=MetricsRegistry())
        plane.shed("blkt-c0-rq9", "c0", "queue_full")
        rec = plane.obslog.recent(1, kind="timeline.request")[0]
        assert rec["data"]["outcome"] == "shed"
        assert rec["data"]["reason"] == "queue_full"
        assert rec["data"]["stages"] == {}

    def test_resolution_pin_order(self, monkeypatch):
        # Explicit arg beats the env; env beats the committed routing.
        monkeypatch.setenv("SVOC_COST_PLANE", "on")
        assert resolve_cost_plane_enabled(False) is False
        assert resolve_cost_plane_enabled(None) is True
        monkeypatch.setenv("SVOC_COST_PLANE", "off")
        assert resolve_cost_plane_enabled(None) is False
        assert resolve_cost_plane_enabled(True) is True


# ---------------------------------------------------------------------------
# Serving integration: invisibility, decomposition, persistence, audit
# ---------------------------------------------------------------------------


class TestServingIntegration:
    def test_fingerprint_invariant_on_vs_off(self, scenario_on, scenario_off):
        """The tentpole acceptance: enabling the plane changes NOTHING
        a seeded replay reproduces."""
        assert (
            scenario_on["journal_fingerprint"]
            == scenario_off["journal_fingerprint"]
        )
        assert (
            scenario_on["per_claim_fingerprints"]
            == scenario_off["per_claim_fingerprints"]
        )

    def test_snapshot_carries_costs_section(self, scenario_on):
        costs = scenario_on["snapshot"]["costs"]
        assert costs["enabled"] is True
        assert costs["ledger"]["samples"] > 0
        assert costs["observations"] > 0

    def test_completed_timelines_gapless(self, scenario_on):
        plane = scenario_on["cost_plane"]
        records = [
            r
            for r in plane.obslog.recent(10_000, kind="timeline.request")
            if r["data"]["outcome"] == "completed"
        ]
        assert records
        for rec in records:
            assert sum(rec["data"]["stages"].values()) == pytest.approx(
                rec["data"]["e2e_s"], abs=1e-9
            )

    def test_shed_requests_observed(self, scenario_on):
        plane = scenario_on["cost_plane"]
        shed = [
            r
            for r in plane.obslog.recent(10_000, kind="timeline.request")
            if r["data"]["outcome"] == "shed"
        ]
        assert shed  # the overload phase sheds
        assert all(r["data"]["reason"] for r in shed)

    def test_universe_estimates_cover_every_key(self, scenario_on):
        from svoc_tpu.compile.universe import (
            enumerate_universe,
            registry_groups,
        )

        multi = scenario_on["multi"]
        router = multi.router
        keys = enumerate_universe(
            registry_groups(multi.registry),
            max_claims_per_batch=router.max_claims_per_batch,
            sanitized_dispatch=router.sanitized_dispatch,
            donate=router._donate,
            impl=router.consensus_impl,
            mesh=router.mesh_spec,
            mesh_claim_size=(
                router._shard.claim_size if router._shard else 1
            ),
        )
        assert keys
        model = scenario_on["cost_plane"].model
        for key in keys:
            est = model.estimate(key)
            assert est["warm"] is not None, est["key"]
            assert est["cold"] is not None, est["key"]
            assert est["warm"]["seconds"] > 0

    def test_ledger_persists_on_snapshot_cadence(
        self, scenario_on, tmp_path
    ):
        """Kill/restart continuity: the RecoveryManager's snapshot
        writes the sidecar ledger; a fresh plane restores it and prices
        identically."""
        from svoc_tpu.durability.recovery import RecoveryManager

        plane = scenario_on["cost_plane"]
        manager = RecoveryManager(
            scenario_on["multi"], out_dir=str(tmp_path)
        )
        assert manager._cost_plane() is plane  # resolved via the router
        manager.snapshot()
        assert os.path.exists(manager.cost_ledger_path)
        fresh = CostPlane(enabled=True, metrics=MetricsRegistry())
        restored = fresh.restore_ledger(manager.cost_ledger_path)
        assert restored == len(plane.ledger)
        assert fresh.ledger.to_dict() == plane.ledger.to_dict()

    def test_audit_trail_for_completed_lineage(self, scenario_on):
        """Satellite: every serving request's rq lineage joins the
        flight recorder — admission through commit for a completed
        request."""
        plane = scenario_on["cost_plane"]
        completed = [
            r
            for r in plane.obslog.recent(10_000, kind="timeline.request")
            if r["data"]["outcome"] == "completed"
        ][-1]
        record = scenario_on["multi"].audit(completed["lineage"])
        assert record["found"] is True
        types = [e["event"] for e in record["events"]]
        assert "serving.admitted" in types
        assert record["summary"]

    def test_audit_trail_for_shed_lineage(self, scenario_on):
        """The shed request is auditable too: its lineage carries the
        ``serving.shed`` verdict in the journal AND the plane's
        timeline record, joinable on the same id."""
        plane = scenario_on["cost_plane"]
        shed = [
            r
            for r in plane.obslog.recent(10_000, kind="timeline.request")
            if r["data"]["outcome"] == "shed"
        ][-1]
        record = scenario_on["multi"].audit(shed["lineage"])
        assert record["found"] is True
        shed_events = [
            e for e in record["events"] if e["event"] == "serving.shed"
        ]
        assert shed_events
        assert (
            shed_events[0]["data"]["reason"] == shed["data"]["reason"]
        )


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------


class TestProfileCapture:
    def test_start_stop_cycle(self, tmp_path):
        journal = EventJournal(registry=MetricsRegistry())
        metrics = MetricsRegistry()
        cap = ProfileCapture(
            out_dir=str(tmp_path), journal=journal, metrics=metrics
        )
        assert cap.status()["active"] is None
        started = cap.start(duration_s=30.0)
        assert started["status"] == "started"
        # Monotone index, never a wall-clock timestamp (SVOC008).
        assert started["path"].endswith("profile-0001")
        assert cap.start()["status"] == "already_running"
        stopped = cap.stop()
        assert stopped["status"] == "captured"
        assert cap.stop()["status"] == "idle"
        events = [e for e in journal.recent() if e.type == "profile.captured"]
        assert len(events) == 1
        assert events[0].data["path"].endswith("profile-0001")
        assert (
            metrics.counter(
                "profile_captures", labels={"trigger": "manual"}
            ).count
            == 1
        )

    def test_auto_capture_rate_limited(self, tmp_path):
        metrics = MetricsRegistry()
        t = {"now": 0.0}
        cap = ProfileCapture(
            out_dir=str(tmp_path),
            journal=EventJournal(registry=MetricsRegistry()),
            metrics=metrics,
            auto_min_interval_s=120.0,
            clock=lambda: t["now"],
        )
        first = cap.maybe_capture("slo_burn")
        assert first is not None and first["status"] == "started"
        cap.stop()
        t["now"] = 60.0  # inside the window: suppressed + counted
        assert cap.maybe_capture("slo_burn") is None
        assert (
            metrics.counter(
                "profile_suppressed", labels={"reason": "rate_limit"}
            ).count
            == 1
        )
        t["now"] = 200.0  # window elapsed: captures again
        again = cap.maybe_capture("breaker_open")
        assert again is not None and again["status"] == "started"
        cap.stop()

    def test_degrades_loudly_but_open(self, tmp_path, monkeypatch):
        metrics = MetricsRegistry()
        cap = ProfileCapture(out_dir=str(tmp_path), metrics=metrics)

        def boom(_dir):
            raise RuntimeError("no profiler backend")

        import jax.profiler

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        result = cap.start()
        assert result["status"] == "error"
        assert "no profiler backend" in result["error"]
        assert (
            metrics.counter(
                "profile_errors", labels={"stage": "start"}
            ).count
            == 1
        )
        # Serving keeps going: the capture object stays usable.
        assert cap.status()["active"] is None


# ---------------------------------------------------------------------------
# Postmortem: auto-profile hook + visible suppression
# ---------------------------------------------------------------------------


class TestPostmortemIntegration:
    def _monitor(self, tmp_path, **kwargs):
        from svoc_tpu.utils.postmortem import PostmortemMonitor

        journal = EventJournal(registry=MetricsRegistry())
        metrics = MetricsRegistry()
        monitor = PostmortemMonitor(
            out_dir=str(tmp_path),
            journal=journal,
            registry=metrics,
            **kwargs,
        ).install()
        return journal, metrics, monitor

    def test_breaker_open_triggers_auto_capture(self, tmp_path):
        captured = []

        class FakeProfiler:
            def maybe_capture(self, trigger):
                captured.append(trigger)

        journal, _metrics, monitor = self._monitor(
            tmp_path, profiler=FakeProfiler(), min_interval_s=0.0
        )
        try:
            journal.emit("breaker.transition", to="open")
            journal.emit("slo.alert", slo="request_latency")
            journal.emit("serving.step", requests=0)  # not incident-class
        finally:
            monitor.uninstall()
        assert captured == ["breaker_open", "slo_burn"]

    def test_suppression_counted_and_latched_once(self, tmp_path):
        t = {"now": 0.0}
        journal, metrics, monitor = self._monitor(
            tmp_path, min_interval_s=60.0, clock=lambda: t["now"]
        )
        try:
            journal.emit("breaker.transition", to="open")  # bundles
            t["now"] = 1.0
            journal.emit("breaker.transition", to="open")  # suppressed
            t["now"] = 2.0
            journal.emit("breaker.transition", to="open")  # suppressed
        finally:
            monitor.uninstall()
        assert len(monitor.bundles) == 1
        # EVERY suppression counts; the journal latches ONE event.
        assert (
            metrics.counter(
                "postmortem_suppressed", labels={"reason": "rate_limit"}
            ).count
            == 2
        )
        latched = [
            e for e in journal.recent() if e.type == "postmortem.suppressed"
        ]
        assert len(latched) == 1
        assert latched[0].data["reason"] == "rate_limit"
        assert latched[0].data["trigger"] == "breaker_open"

    def test_latch_rearms_after_next_bundle(self, tmp_path):
        t = {"now": 0.0}
        journal, _metrics, monitor = self._monitor(
            tmp_path, min_interval_s=60.0, clock=lambda: t["now"]
        )
        try:
            journal.emit("breaker.transition", to="open")  # bundle 1
            t["now"] = 1.0
            journal.emit("breaker.transition", to="open")  # latch fires
            t["now"] = 120.0
            journal.emit("breaker.transition", to="open")  # bundle 2
            t["now"] = 121.0
            journal.emit("breaker.transition", to="open")  # re-latched
        finally:
            monitor.uninstall()
        assert len(monitor.bundles) == 2
        latched = [
            e for e in journal.recent() if e.type == "postmortem.suppressed"
        ]
        assert len(latched) == 2


# ---------------------------------------------------------------------------
# Console + web surface
# ---------------------------------------------------------------------------


class TestConsoleAndWeb:
    def test_console_commands_degrade_without_plane(self):
        from tests.conftest import make_fake_console

        console = make_fake_console()
        assert any("cost" in line for line in console.query("costs"))
        assert any(
            "profiler" in line.lower()
            for line in console.query("profile status")
        )

    def test_profile_endpoint(self, tmp_path):
        from svoc_tpu.apps.commands import CommandConsole
        from svoc_tpu.apps.web import serve
        from tests.test_apps import make_session

        console = CommandConsole(make_session())
        srv, _thread = serve(console, port=0, block=False)
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            # No profiler attached: 503, serving untouched.
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(f"{base}/api/profile", timeout=10)
            assert exc_info.value.code == 503
            ProfileCapture(out_dir=str(tmp_path)).attach(console)
            with urllib.request.urlopen(
                f"{base}/api/profile", timeout=10
            ) as r:
                status = json.loads(r.read())
            assert status["available"] is True
            assert status["active"] is None
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"{base}/api/profile?action=bogus", timeout=10
                )
            assert exc_info.value.code == 400
        finally:
            srv.shutdown()

    def test_costs_command_renders_live_ledger(self, scenario_on):
        """The console ``costs`` view over a real post-scenario plane:
        summary line + per-key warmth cells."""
        from tests.conftest import make_fake_console

        console = make_fake_console()
        console.serving = scenario_on  # duck-typed: .cost_plane lookup

        class Holder:
            cost_plane = scenario_on["cost_plane"]

        console.serving = Holder()
        out = console.query("costs")
        joined = "\n".join(out)
        assert "enabled" in joined
        assert "ms" in joined
