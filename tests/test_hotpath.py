"""Zero-sync hot path (PR 13, docs/PARALLELISM.md §host-overhead):
device-resident dispatch, the vectorized write-back's exactness
contract, and the batched commit plane's parity/WAL/reconcile
semantics."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.consensus.state import BatchTxError
from svoc_tpu.durability.wal import CommitIntentWAL
from svoc_tpu.fabric.registry import ClaimSpec
from svoc_tpu.fabric.session import MultiSession
from svoc_tpu.io.chain import (
    BatchCommitUnsupported,
    ChainAdapter,
    LocalChainBackend,
)
from svoc_tpu.utils.events import EventJournal
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as process_registry
from svoc_tpu.utils.rounding import round6, round6_list


# ---------------------------------------------------------------------------
# round6: the write-back's bit-exactness contract
# ---------------------------------------------------------------------------


class TestRound6:
    def test_matches_python_round_on_random_bulk(self):
        rng = np.random.default_rng(0)
        arr = rng.uniform(-2, 2, size=20000)
        got = round6(arr)
        want = np.array([round(float(x), 6) for x in arr])
        assert (got == want).all()

    def test_matches_python_round_on_half_boundaries(self):
        """The divergence region: np.round alone disagrees with Python
        round on a large fraction of half-boundary-adjacent values —
        the fixup lane must close ALL of them."""
        rng = np.random.default_rng(1)
        ks = rng.integers(0, 2_000_000, size=20000)
        adv = (2 * ks + 1) * 5e-7  # decimal ...5 at the 7th place
        ties = np.arange(1, 2001, 2) / 128.0  # exactly representable ties
        near = adv + rng.uniform(-1e-9, 1e-9, size=adv.size)
        for arr in (adv, ties, near):
            got = round6(arr)
            want = np.array([round(float(x), 6) for x in arr])
            assert (got == want).all()
        # the fixup lane is load-bearing: plain np.round must diverge
        # somewhere in this set, else the test lost its teeth
        plain = np.round(adv, 6)
        want = np.array([round(float(x), 6) for x in adv])
        assert (plain != want).any()

    def test_matches_python_round_on_huge_magnitudes(self):
        """Above ~2^53/1e6 the scaled product leaves float64's
        integer-exact range and np.round double-rounds (review finding:
        the half-boundary lane cannot flag these) — the magnitude lane
        must route them to Python's exact rounding."""
        repro = np.array([9826986099.587141, -9826986099.587141])
        got = round6(repro)
        want = np.array([round(float(x), 6) for x in repro])
        assert (got == want).all()
        rng = np.random.default_rng(12)
        big = rng.uniform(1e9, 1e12, size=5000) * rng.choice(
            [-1.0, 1.0], size=5000
        )
        got = round6(big)
        want = np.array([round(float(x), 6) for x in big])
        assert (got == want).all()

    def test_non_finite_and_shapes(self):
        special = np.array([np.nan, np.inf, -np.inf, 0.0, -0.0])
        got = round6(special)
        assert np.isnan(got[0]) and got[1] == np.inf and got[2] == -np.inf
        rows = round6_list(np.array([[0.1234565, 0.5], [1.5e-7, -2.25]]))
        assert rows == [
            [round(0.1234565, 6), 0.5],
            [round(1.5e-7, 6), -2.25],
        ]
        assert all(isinstance(x, float) for row in rows for x in row)


class TestVectorizedEncode:
    def test_encode_matrix_matches_per_row_loop(self):
        from svoc_tpu.ops.fixedpoint import encode_matrix, encode_vector

        rng = np.random.default_rng(2)
        m = rng.uniform(-3, 3, size=(32, 6))
        assert encode_matrix(m) == [encode_vector(r) for r in m]

    def test_encode_matrix_on_error_none_marks_bad_rows(self):
        from svoc_tpu.ops.fixedpoint import encode_matrix, encode_vector

        m = np.full((4, 3), 0.25)
        m[1, 0] = np.nan
        m[3] = 1e60  # finite but beyond the int64 fast lane
        got = encode_matrix(m, on_error="none")
        assert got[0] == encode_vector(m[0])
        assert got[1] is None
        assert got[3] == encode_vector(m[3])  # exact lane still encodes
        with pytest.raises(ValueError):
            encode_matrix(m)  # default mirrors the raising loop

    def test_to_wsad_rows_matches_loop(self):
        from svoc_tpu.ops.fixedpoint import to_wsad, to_wsad_rows

        rng = np.random.default_rng(3)
        m = rng.uniform(-5, 5, size=(16, 4))
        assert to_wsad_rows(m) == [
            [to_wsad(float(x)) for x in row] for row in m
        ]


# ---------------------------------------------------------------------------
# Donation safety
# ---------------------------------------------------------------------------


class TestDonationSafety:
    def test_donated_cube_is_consumed_and_outputs_match(self):
        """The donated twin must (a) produce the undonated program's
        exact outputs and (b) actually consume its input — re-reading
        a donated buffer is the SVOC004 bug class, and the runtime
        enforces it where donation is supported."""
        import jax.numpy as jnp

        from svoc_tpu.consensus.batch import claims_consensus_gated
        from svoc_tpu.consensus.kernel import ConsensusConfig

        rng = np.random.default_rng(4)
        values = rng.uniform(0, 1, size=(4, 8, 6)).astype(np.float32)
        ok = np.ones((4, 8), dtype=bool)
        mask = np.array([True, True, True, False])
        cfg = ConsensusConfig(n_failing=2, constrained=True)

        plain = claims_consensus_gated(
            jnp.asarray(values), jnp.asarray(ok), jnp.asarray(mask), cfg
        )
        donated_in = jnp.array(values)
        donated = claims_consensus_gated(
            donated_in, jnp.asarray(ok), jnp.asarray(mask), cfg,
            donate=True,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.essence), np.asarray(donated.essence)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.reliable), np.asarray(donated.reliable)
        )
        if donated_in.is_deleted():
            with pytest.raises(RuntimeError):
                np.asarray(donated_in)

    def test_staging_reuse_does_not_corrupt_prior_outputs(self):
        """In-place staging mutation across cycles must never alias a
        live dispatch's inputs/outputs (the CPU zero-copy hazard the
        explicit H2D copy exists for): consecutive device-resident
        cycles must reproduce the unstaged cycles' journal exactly."""
        multi_a = _tiny_multi(device_resident=True, scope="stga")
        multi_b = _tiny_multi(device_resident=False, scope="stga")
        multi_a.run(4)
        multi_b.run(4)
        assert {
            c: multi_a.claim_fingerprint(c) for c in multi_a.claim_ids()
        } == {
            c: multi_b.claim_fingerprint(c) for c in multi_b.claim_ids()
        }


# ---------------------------------------------------------------------------
# Fabric fingerprint identity (both consensus configs)
# ---------------------------------------------------------------------------


def _tiny_multi(
    *,
    device_resident: bool = False,
    commit_mode: str = "per_tx",
    scope: str = "hp",
    constrained_only: bool = False,
    wal_path=None,
):
    from conftest import fake_sentiment_vectorizer
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource
    from svoc_tpu.sim.generators import claim_seed

    def store_factory(claim_id):
        store = CommentStore()
        store.save(
            SyntheticSource(batch=80, seed=claim_seed(11, claim_id))()
        )
        return store

    multi = MultiSession(
        base_seed=11,
        vectorizer=fake_sentiment_vectorizer,
        store_factory=store_factory,
        journal=EventJournal(),
        metrics=MetricsRegistry(),
        lineage_scope=scope,
        max_claims_per_batch=4,
        device_resident=device_resident,
        commit_mode=commit_mode,
    )
    multi.add_claim(ClaimSpec(claim_id="alpha", n_oracles=8))
    multi.add_claim(ClaimSpec(claim_id="beta", n_oracles=8))
    if not constrained_only:
        # The unconstrained estimator config rides the same cube in its
        # own (N, M, cfg) group — "both configs" in one fabric.
        multi.add_claim(
            ClaimSpec(
                claim_id="gamma",
                n_oracles=8,
                constrained=False,
                max_spread=10.0,
            )
        )
    if wal_path is not None:
        multi.attach_wal(CommitIntentWAL(str(wal_path)))
    return multi


class TestFingerprintIdentity:
    def test_optimized_equals_baseline_both_configs(self):
        """device_resident + batched commits are NOT a fingerprint
        family: constrained AND unconstrained claims must digest
        byte-identically against the unoptimized path."""
        base = _tiny_multi()
        opt = _tiny_multi(device_resident=True, commit_mode="batched")
        base.run(5)
        opt.run(5)
        for cid in base.claim_ids():
            assert base.claim_fingerprint(cid) == opt.claim_fingerprint(
                cid
            ), cid

    def test_wal_attached_identity(self, tmp_path):
        """The batched plane's WAL records differ (intent_batch /
        landed_batch) but the JOURNAL must not — fingerprints stay
        identical with a WAL riding both runs."""
        base = _tiny_multi(
            scope="hpw", wal_path=tmp_path / "a.wal",
            constrained_only=True,
        )
        opt = _tiny_multi(
            scope="hpw", wal_path=tmp_path / "b.wal",
            device_resident=True, commit_mode="batched",
            constrained_only=True,
        )
        base.run(4)
        opt.run(4)
        for cid in base.claim_ids():
            assert base.claim_fingerprint(cid) == opt.claim_fingerprint(cid)
        # and the WAL record FAMILIES are what changed
        base_kinds = {r["kind"] for r in base._wal.records()}
        opt_kinds = {r["kind"] for r in opt._wal.records()}
        assert "intent" in base_kinds and "landed" in base_kinds
        assert "intent_batch" in opt_kinds and "landed_batch" in opt_kinds
        assert "intent" not in opt_kinds


# ---------------------------------------------------------------------------
# Batched commit plane: parity, RPC accounting, fallbacks
# ---------------------------------------------------------------------------


def _adapter_pair(n_oracles=8, dimension=4):
    from svoc_tpu.consensus.state import OracleConsensusContract

    def contract():
        return OracleConsensusContract(
            admins=[0xA0, 0xA1, 0xA2],
            oracles=[0x10 + i for i in range(n_oracles)],
            required_majority=2,
            n_failing_oracles=2,
            constrained=True,
            dimension=dimension,
        )

    return (
        ChainAdapter(LocalChainBackend(contract())),
        ChainAdapter(LocalChainBackend(contract())),
    )


def _rpc_counts():
    return {
        mode: process_registry.counter(
            "chain_commit_rpcs", labels={"mode": mode}
        ).count
        for mode in ("tx", "batch")
    }


class TestBatchedCommitParity:
    def test_state_parity_and_rpc_counts(self):
        from svoc_tpu.resilience.retry import commit_fleet_with_resume

        per_tx, batched = _adapter_pair()
        rng = np.random.default_rng(5)
        before = _rpc_counts()
        for _cycle in range(3):
            block = rng.uniform(0.05, 0.95, size=(8, 4))
            out_a = commit_fleet_with_resume(per_tx, block)
            out_b = commit_fleet_with_resume(
                batched, block, commit_mode="batched"
            )
            assert out_a == out_b
        after = _rpc_counts()
        assert after["tx"] - before["tx"] == 3 * 8
        assert after["batch"] - before["batch"] == 3
        # bit-identical final chain state
        assert (
            per_tx.get_the_predictions() == batched.get_the_predictions()
        )

    def test_unsupported_backend_falls_back_counted(self):
        from svoc_tpu.resilience.retry import commit_fleet_with_resume

        class WrappedBackend:
            """A chaos-wrapper-shaped backend: forwards the protocol
            trio only — no batched entrypoint."""

            def __init__(self, inner):
                self.inner = inner

            def call(self, fn):
                return self.inner.call(fn)

            def call_as(self, caller, fn):
                return self.inner.call_as(caller, fn)

            def invoke(self, caller, fn, /, **kwargs):
                return self.inner.invoke(caller, fn, **kwargs)

        plain, _ = _adapter_pair()
        wrapped = ChainAdapter(WrappedBackend(plain.backend))
        rng = np.random.default_rng(6)
        block = rng.uniform(0.05, 0.95, size=(8, 4))
        fallback = process_registry.counter(
            "commit_batch_fallback", labels={"reason": "unsupported"}
        )
        before = fallback.count
        out = commit_fleet_with_resume(
            wrapped, block, commit_mode="batched"
        )
        assert out.complete and out.sent == 8
        assert fallback.count == before + 1

    def test_skip_slots_force_per_tx_counted(self):
        from svoc_tpu.resilience.retry import commit_fleet_with_resume

        adapter, _ = _adapter_pair()
        rng = np.random.default_rng(7)
        block = rng.uniform(0.05, 0.95, size=(8, 4))
        fallback = process_registry.counter(
            "commit_batch_fallback", labels={"reason": "skip_slots"}
        )
        before = fallback.count
        out = commit_fleet_with_resume(
            adapter, block, skip=(3,), commit_mode="batched"
        )
        assert out.sent == 7 and out.total == 7
        assert fallback.count == before + 1

    def test_adapter_raises_unsupported_before_any_mutation(self):
        adapter, _ = _adapter_pair()
        with pytest.raises(BatchCommitUnsupported) as ei:
            adapter.update_predictions_batched(
                np.full((8, 4), 0.5), skip=(1,)
            )
        assert ei.value.reason == "skip_slots"

    def test_commit_mode_resolution(self, tmp_path, monkeypatch):
        import json

        from svoc_tpu.consensus.dispatch import (
            CommitModeError,
            resolve_commit_mode,
            validate_commit_mode,
        )

        record = tmp_path / "PERF_DECISIONS.json"
        monkeypatch.delenv("SVOC_COMMIT_MODE", raising=False)
        assert resolve_commit_mode(str(record)) == "per_tx"  # absent
        record.write_text(json.dumps({"commit_mode": "batched"}))
        assert resolve_commit_mode(str(record)) == "batched"
        monkeypatch.setenv("SVOC_COMMIT_MODE", "per_tx")
        assert resolve_commit_mode(str(record)) == "per_tx"  # env wins
        monkeypatch.setenv("SVOC_COMMIT_MODE", "bogus")
        with pytest.raises(CommitModeError):
            resolve_commit_mode(str(record))
        with pytest.raises(CommitModeError):
            validate_commit_mode("nope")


# ---------------------------------------------------------------------------
# WAL + reconciler: the batched record family
# ---------------------------------------------------------------------------


class _MidBatchDeath:
    """A backend whose batched entrypoint applies a prefix and then
    dies WITHOUT reporting — the process-kill shape for the batch
    plane (the adapter's landed_batch-of-prefix append is the last
    durable record)."""

    def __init__(self, inner: LocalChainBackend, fail_at: int):
        self.inner = inner
        self.fail_at = fail_at

    def call(self, fn):
        return self.inner.call(fn)

    def call_as(self, caller, fn):
        return self.inner.call_as(caller, fn)

    def invoke(self, caller, fn, /, **kwargs):
        return self.inner.invoke(caller, fn, **kwargs)

    def update_predictions_batched(self, callers, predictions):
        k = self.fail_at
        self.inner.update_predictions_batched(
            list(callers)[:k], list(predictions)[:k]
        )
        raise BatchTxError(k, list(callers)[k], RuntimeError("rpc died"))


class TestBatchedWalReconcile:
    def _fleet_block(self, n=8, m=4, seed=8):
        rng = np.random.default_rng(seed)
        return rng.uniform(0.05, 0.95, size=(n, m))

    def test_mid_batch_kill_reconciles_prefix_landed_suffix_stranded(
        self, tmp_path
    ):
        from svoc_tpu.durability.reconcile import (
            LANDED_BATCH,
            STRANDED,
            reconcile_wal,
        )
        from svoc_tpu.ops.fixedpoint import encode_matrix

        plain, _ = _adapter_pair()
        dying = ChainAdapter(_MidBatchDeath(plain.backend, fail_at=5))
        block = self._fleet_block()
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        oracles = dying.call_oracle_list()
        cycle = wal.cycle(
            "blk-test-000001",
            oracles=oracles,
            payloads=encode_matrix(block),
        )
        cycle.new_attempt(0)
        with pytest.raises(Exception) as ei:
            dying.update_predictions_batched(
                block, lineage="blk-test-000001", wal=cycle
            )
        assert getattr(ei.value, "sent_count", None) == 5
        # Simulate the kill: no done record, no in-process resume.
        kinds = [r["kind"] for r in wal.records()]
        assert kinds == ["cycle", "intent_batch", "landed_batch"]
        assert wal.records()[-1]["slots"] == [0, 1, 2, 3, 4]

        report = reconcile_wal(
            wal,
            lambda _claim: plain,
            journal=EventJournal(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
        )
        (cyc,) = report.cycles
        by_class = {}
        for v in cyc.slots:
            by_class.setdefault(v.classification, []).append(v.slot)
        assert by_class[LANDED_BATCH] == [0, 1, 2, 3, 4]
        assert by_class[STRANDED] == [5, 6, 7]
        assert all(
            v.resent for v in cyc.slots if v.classification == STRANDED
        )
        assert cyc.closed and report.unaccounted == 0
        # resent payloads landed: the chain now holds the whole block
        assert plain.get_the_predictions() == encode_matrix(block)

    def test_kill_between_rpc_and_landed_batch_uses_chain_digest(
        self, tmp_path
    ):
        """intent_batch with NO landed record: every slot classifies
        through the chain-digest columns — the applied batch reads
        landed_chain, nothing is resent, zero duplicates."""
        from svoc_tpu.durability.reconcile import LANDED_CHAIN, reconcile_wal
        from svoc_tpu.ops.fixedpoint import encode_matrix

        adapter, _ = _adapter_pair()
        block = self._fleet_block(seed=9)
        payloads = encode_matrix(block)
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        cycle = wal.cycle(
            "blk-test-000002",
            oracles=adapter.call_oracle_list(),
            payloads=payloads,
        )
        cycle.new_attempt(0)
        cycle.intent_batch(range(8))
        # the RPC itself landed...
        adapter.backend.update_predictions_batched(
            adapter.call_oracle_list(), payloads
        )
        # ...and the process died before landed_batch.
        report = reconcile_wal(
            wal,
            lambda _claim: adapter,
            journal=EventJournal(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
        )
        (cyc,) = report.cycles
        assert {v.classification for v in cyc.slots} == {LANDED_CHAIN}
        assert report.resent == 0 and cyc.closed

    def test_completed_lineage_dedup_after_batched_done(self, tmp_path):
        """A batched cycle's done record feeds the exactly-once replay
        dedup exactly like a per-tx one."""
        from svoc_tpu.resilience.retry import commit_fleet_with_resume

        adapter, _ = _adapter_pair()
        block = self._fleet_block(seed=10)
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        oracles = adapter.call_oracle_list()
        from svoc_tpu.ops.fixedpoint import encode_matrix

        cycle = wal.cycle(
            "blk-test-000003",
            oracles=oracles,
            payloads=encode_matrix(block),
        )
        out = commit_fleet_with_resume(
            adapter, block, commit_mode="batched", wal=cycle,
            lineage="blk-test-000003",
        )
        assert out.complete
        assert "blk-test-000003" in wal.completed_lineages()
