"""Architecture parity: converted HF RoBERTa weights must reproduce the
torch model's logits through the from-scratch Flax encoder."""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from svoc_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    convert_roberta_state_dict,
)
from svoc_tpu.models.encoder import SentimentEncoder  # noqa: E402


@pytest.fixture(scope="module")
def tiny_hf_model():
    config = transformers.RobertaConfig(
        vocab_size=256,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=34,  # max_len 32 + pad 1 + 1
        num_labels=5,
        pad_token_id=1,
        layer_norm_eps=1e-5,
    )
    torch.manual_seed(0)
    model = transformers.RobertaForSequenceClassification(config)
    model.eval()
    return model


def test_logit_parity_with_torch(tiny_hf_model):
    cfg = config_from_hf(tiny_hf_model.config)
    assert cfg.dtype == jnp.bfloat16  # default; override for the test
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = convert_roberta_state_dict(tiny_hf_model.state_dict(), cfg)
    flax_model = SentimentEncoder(cfg)

    rng = np.random.default_rng(0)
    b, t = 3, 16
    ids = rng.integers(4, 256, size=(b, t)).astype(np.int32)
    lengths = [16, 9, 5]
    mask = np.zeros((b, t), np.int32)
    for i, ln in enumerate(lengths):
        mask[i, :ln] = 1
        ids[i, ln:] = cfg.pad_id

    with torch.no_grad():
        torch_logits = tiny_hf_model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).logits.numpy()

    flax_logits = np.asarray(
        flax_model.apply(params, jnp.asarray(ids), jnp.asarray(mask))
    )
    np.testing.assert_allclose(flax_logits, torch_logits, atol=2e-4)


def test_config_mapping(tiny_hf_model):
    cfg = config_from_hf(tiny_hf_model.config)
    assert cfg.vocab_size == 256
    assert cfg.n_layers == 2
    assert cfg.n_labels == 5
    assert cfg.max_len == 32
    assert cfg.pad_id == 1


def test_params_npz_round_trip(tmp_path):
    """save_params/load_params must round-trip a params tree exactly and
    the loaded tree must drive the encoder to identical logits."""
    import jax
    import jax.numpy as jnp

    from svoc_tpu.models.configs import TINY_TEST
    from svoc_tpu.models.convert import load_params, save_params
    from svoc_tpu.models.encoder import SentimentEncoder, init_params

    model = SentimentEncoder(TINY_TEST)
    params = init_params(model, seed=1)
    p = tmp_path / "tiny.npz"
    save_params(str(p), params)
    loaded = load_params(str(p))

    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert len(flat_a) == len(flat_b)

    ids = jnp.ones((2, 16), jnp.int32)
    mask = jnp.ones_like(ids)
    np.testing.assert_allclose(
        np.asarray(model.apply(params, ids, mask)),
        np.asarray(model.apply(loaded, ids, mask)),
        atol=1e-6,
    )
