"""Sharded consensus == single-device kernel, on an 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.parallel.mesh import MeshSpec, best_mesh, make_mesh
from svoc_tpu.parallel.sharded import sharded_consensus_fn, sharded_fleet_step_fn


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must force 8 virtual CPU devices"
    return best_mesh("oracle")


CFGS = [
    ConsensusConfig(n_failing=2, constrained=True),
    ConsensusConfig(n_failing=3, constrained=False, max_spread=10.0),
]


@pytest.mark.parametrize("cfg", CFGS, ids=["constrained", "unconstrained"])
def test_sharded_matches_single_device(mesh, cfg):
    key = jax.random.PRNGKey(7)
    n, m = 64, 6
    values = jax.random.uniform(key, (n, m))
    ref = consensus_step(values, cfg)
    fn = sharded_consensus_fn(mesh, cfg)
    out = fn(values)

    np.testing.assert_allclose(out.essence, ref.essence, rtol=1e-5)
    np.testing.assert_allclose(
        out.essence_first_pass, ref.essence_first_pass, rtol=1e-5
    )
    np.testing.assert_allclose(
        float(out.reliability_first_pass),
        float(ref.reliability_first_pass),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(out.reliability_second_pass),
        float(ref.reliability_second_pass),
        rtol=1e-5,
    )
    np.testing.assert_array_equal(np.asarray(out.reliable), np.asarray(ref.reliable))
    np.testing.assert_allclose(out.quadratic_risk, ref.quadratic_risk, rtol=1e-5)
    np.testing.assert_allclose(out.skewness, ref.skewness, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out.kurtosis, ref.kurtosis, rtol=1e-4, atol=1e-5)


def test_fleet_step_sharding_invariance(mesh):
    """The fleet is keyed by global oracle index, so a 1-device and an
    8-device mesh must produce identical fleets and consensus."""
    cfg = ConsensusConfig(n_failing=8, constrained=True)
    n_oracles, w, m = 64, 50, 6
    key = jax.random.PRNGKey(3)
    window = jax.random.uniform(jax.random.PRNGKey(11), (w, m))

    mesh1 = make_mesh(MeshSpec(("oracle",), (1,)))
    out8, honest8 = sharded_fleet_step_fn(mesh, cfg, n_oracles)(key, window)
    out1, honest1 = sharded_fleet_step_fn(mesh1, cfg, n_oracles)(key, window)

    np.testing.assert_array_equal(np.asarray(honest8), np.asarray(honest1))
    np.testing.assert_allclose(out8.essence, out1.essence, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(out8.reliable), np.asarray(out1.reliable)
    )
    # sanity: fleet injects the configured number of failures
    assert int(jnp.sum(~honest8)) == cfg.n_failing


def test_fleet_step_detects_failures(mesh):
    """With a tight honest window, rank-based masking should flag mostly
    the injected uniform oracles."""
    cfg = ConsensusConfig(n_failing=8, constrained=True)
    n_oracles, w, m = 64, 50, 6
    # Tight honest cluster near 0.5 → failing uniforms stick out.
    window = 0.5 + 0.01 * jax.random.normal(jax.random.PRNGKey(0), (w, m))
    window = jnp.clip(window, 0.0, 1.0)
    fn = sharded_fleet_step_fn(mesh, cfg, n_oracles)
    hits = 0
    trials = 10
    for t in range(trials):
        out, honest = fn(jax.random.PRNGKey(100 + t), window)
        hits += int(jnp.all(out.reliable == honest))
    assert hits >= 8, f"only {hits}/{trials} exact identifications"
