"""Obsolete-contract lineage cross-checks (VERDICT r4 item 7).

The reference retired two earlier contract generations
(``/root/reference/contract/obsolete/src``).  They stay excluded from
the framework proper — the live ``contract.cairo`` semantics are the
product — but their RECORDED numeric outcomes are reproduced here as
golden-engine parity cells, so the one "no" row in the SURVEY §2
coverage table closes honestly instead of by exclusion:

- the obsolete ND constrained reliability is ``wsad() -
  sqrt(average(qr)) * 2`` with NO division by the dimension
  (``contract_nd.cairo:417-419``); the live contract divides mean risk
  by dim (``contract.cairo:436-439``).  On the 7-oracle 2-D fixture of
  ``obsolete/tests/test_nd.cairo:148-156`` that formula yields the
  **0.798** second-pass reliability recorded at ``test_nd.cairo:179``
  (and re-quoted at ``tests/test_contract.cairo:188``) — "lower than
  for the 1d case".
- the 1-D constrained lineage (``contract_1d_constrained.cairo:270``,
  same no-dim formula at dimension 1) ran at the OLD 1e18 wsad scale
  (``signed_decimal.cairo:82`` notes the 1e6 scale replaced 1e18); on
  the ``obsolete/tests/test_1d_constrained.cairo:116-124`` predictions
  it lands at 0.925 — the higher 1-D value that comment compares
  against ("the number of dimensions increase the required number of
  oracles to fill the space").
"""

from __future__ import annotations

from svoc_tpu.consensus import wsad_engine as E
from svoc_tpu.ops.fixedpoint import WSAD, div_trunc, wsad_sqrt, wsad_to_string

# obsolete/tests/test_nd.cairo:148-156 (wsad = 1e6, dimension 2)
ND_PREDICTIONS = [
    [492954, 334814],
    [437692, 410445],
    [967794, 564219],
    [431029, 387225],
    [487609, 337990],
    [284178, 485072],
    [990059, 558600],
]

# obsolete/tests/test_1d_constrained.cairo:116-124 (wsad = 1e18)
PREDICTIONS_1D = [
    283665728520555872,
    444978808172189056,
    456312246206240704,
    577063812648590720,
    353406129181719872,
    439786381700248704,
    422154759299759040,
]

N_FAILING = 2  # both deploy fixtures: n_failing_oracles = 2


def obsolete_constrained_two_pass(values):
    """The obsolete constrained flow (``contract_nd.cairo:396-460``):
    identical to the live two-pass except reliability omits the /dim —
    built from the SAME exact-int engine primitives the live golden
    model uses, so any engine regression breaks both."""
    n = len(values)
    e1 = E.nd_smooth_median(values)
    qr = E.nd_quadratic_risk(values, e1)
    rel1 = WSAD - wsad_sqrt(E.average(qr)) * 2
    reliable = [False] * n
    for rank, (idx, _risk) in enumerate(E.indexed_sort_host(qr)):
        reliable[idx] = rank < n - N_FAILING
    rv = [v for v, ok in zip(values, reliable) if ok]
    e2 = E.nd_smooth_median(rv)
    qr2 = E.nd_quadratic_risk(rv, e1)  # centered on essence₁, like the live one
    rel2 = WSAD - wsad_sqrt(E.average(qr2)) * 2
    return e2, rel1, rel2


def test_obsolete_nd_records_0_798():
    _e2, rel1, rel2 = obsolete_constrained_two_pass(ND_PREDICTIONS)
    assert rel2 == 798964  # the recorded 0.798, exact wsad int
    assert wsad_to_string(rel2, 3) == "0.798"
    assert wsad_to_string(rel1, 3) == "0.396"
    # the live /dim formula on the same block reads HIGHER — the very
    # change that motivated the dimension normalization
    live = E.two_pass_consensus(ND_PREDICTIONS, constrained=True, n_failing=2)
    assert live["reliability_second_pass"] == 857846
    assert live["reliability_second_pass"] > rel2


def test_obsolete_1d_lineage_at_1e18_scale():
    """The 1-D lineage at its own 1e18 wsad scale, via local
    Cairo-faithful helpers (``math.cairo:272-292`` sqrt, rounded
    wsad mul/div, truncating average)."""
    W = 10**18

    def wsad_div18(a, b):
        return div_trunc(a * W + div_trunc(b, 2), b)

    def wsad_mul18(a, b):
        return div_trunc(a * b + W // 2, W)

    def sqrt18(value):
        if value == 0:
            return 0
        g, g2 = div_trunc(value, 2), div_trunc(value, 2) + W
        for _ in range(50):  # MAX_SQRT_ITERATIONS
            if g == g2:
                break
            n = wsad_div18(value, g)
            g2, g = g, div_trunc(g + n, 2)
        return g

    preds = PREDICTIONS_1D
    srt = sorted(preds)
    e1 = div_trunc(srt[len(preds) // 2 - 1] + srt[len(preds) // 2], 2)
    qr = [wsad_mul18(p - e1, p - e1) for p in preds]
    rel1 = W - sqrt18(div_trunc(sum(qr), len(preds))) * 2
    order = sorted(range(len(preds)), key=lambda i: (qr[i], -i))  # Cairo ties
    reliable = [False] * len(preds)
    for rank, idx in enumerate(order):
        reliable[idx] = rank < len(preds) - N_FAILING
    rv = [p for p, ok in zip(preds, reliable) if ok]
    srt2 = sorted(rv)
    e2 = div_trunc(srt2[len(rv) // 2 - 1] + srt2[len(rv) // 2], 2)
    qr2 = [wsad_mul18(p - e1, p - e1) for p in rv]
    rel2 = W - sqrt18(div_trunc(sum(qr2), len(rv))) * 2

    assert abs(e1 / W - 0.431) < 5e-4  # both medians on the same pair
    assert e2 == e1
    assert f"{rel1 / W:.3f}" == "0.831"
    assert f"{rel2 / W:.3f}" == "0.925"
    # the comment's cross-lineage claim: 1-D rel2 > the ND 0.798
    assert rel2 / W > 0.798964
