"""Native runtime: C++ tokenizer parity + throughput sanity."""

import numpy as np
import pytest

from svoc_tpu.io.scraper import SyntheticSource
from svoc_tpu.models.tokenizer import HashingTokenizer
from svoc_tpu.runtime import NativeHashingTokenizer, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain available"
)


def pairs(vocab=50265, pad=1, max_len=64):
    return (
        HashingTokenizer(vocab, pad_id=pad, max_len=max_len),
        NativeHashingTokenizer(vocab, pad_id=pad, max_len=max_len),
    )


class TestNativeTokenizerParity:
    def test_special_id_layout(self):
        py, cc = pairs()
        assert (py.pad_id, py.bos_id, py.eos_id) == (
            cc.pad_id,
            cc.bos_id,
            cc.eos_id,
        )

    def test_ascii_bit_parity(self):
        py, cc = pairs()
        texts = SyntheticSource(batch=64, seed=7)() + [
            "",
            "a",
            "Hello, World!  punctuation...and--dashes",
            "UPPER lower MiXeD 12345 0xdeadbeef",
            "word " * 200,  # truncation path
            "trailing word",
        ]
        ids_py, mask_py = py(texts, 64)
        ids_cc, mask_cc = cc(texts, 64)
        np.testing.assert_array_equal(ids_py, ids_cc)
        np.testing.assert_array_equal(mask_py, mask_cc)

    def test_other_vocab_and_pad(self):
        py, cc = pairs(vocab=30522, pad=0, max_len=32)
        texts = ["the quick brown fox", "jumps. over! the? lazy dog"]
        ids_py, mask_py = py(texts, 32)
        ids_cc, mask_cc = cc(texts, 32)
        np.testing.assert_array_equal(ids_py, ids_cc)
        np.testing.assert_array_equal(mask_py, mask_cc)

    def test_shapes_and_dtype(self):
        _, cc = pairs()
        ids, mask = cc(["one two three"], 16)
        assert ids.shape == (1, 16) and ids.dtype == np.int32
        assert mask.sum() == 5  # bos + 3 words + eos

    def test_faster_than_python(self):
        """The point of the native path: meaningfully outrun Python."""
        import time

        py, cc = pairs()
        texts = SyntheticSource(batch=2048, seed=1)()

        t0 = time.perf_counter()
        py(texts, 128)
        t_py = time.perf_counter() - t0

        t0 = time.perf_counter()
        cc(texts, 128)
        t_cc = time.perf_counter() - t0
        assert t_cc < t_py, (t_cc, t_py)


class TestLoadTokenizerPrefersNative:
    def test_default_path_is_native(self):
        from svoc_tpu.models.tokenizer import load_tokenizer

        tok = load_tokenizer(None, 50265, pad_id=1, max_len=64)
        assert isinstance(tok, NativeHashingTokenizer)


def test_native_packer_matches_python_exactly():
    """The C++ packer must be BIT-identical to the Python reference on
    every output array, across row caps, empty lists, overlong lists,
    and segment-cap flushes."""
    import numpy as np
    import pytest

    from svoc_tpu.runtime import native_available, native_pack_tokens_raw
    from svoc_tpu.models.packing import PackedBatch, pack_tokens

    if not native_available():
        pytest.skip("native runtime unavailable")

    rng = np.random.default_rng(0)
    cases = []
    for trial in range(20):
        n = int(rng.integers(1, 40))
        lists = [
            list(rng.integers(4, 1000, size=int(rng.integers(0, 40))))
            for _ in range(n)
        ]
        seq = int(rng.integers(8, 33))
        max_seg = int(rng.integers(1, 6))
        rows = None if trial % 3 else int(rng.integers(1, 8))
        cases.append((lists, seq, max_seg, rows))
    cases.append(([[]], 8, 2, None))  # degenerate empty list
    cases.append(([list(range(4, 100))], 16, 2, None))  # overlong

    for lists, seq, max_seg, rows in cases:
        ref, ref_n = pack_tokens(lists, seq, max_seg, pad_id=1, rows=rows)
        raw = native_pack_tokens_raw(lists, seq, max_seg, pad_id=1, rows=rows)
        got = PackedBatch(*raw[:6])
        assert raw[6] == ref_n, (lists, seq, max_seg, rows)
        for name in PackedBatch._fields:
            np.testing.assert_array_equal(
                getattr(got, name), getattr(ref, name),
                err_msg=f"{name} mismatch @ seq={seq} max_seg={max_seg} rows={rows}",
            )


def test_packers_reject_zero_rows():
    import pytest

    from svoc_tpu.models.packing import pack_tokens
    from svoc_tpu.runtime import native_available, native_pack_tokens_raw

    with pytest.raises(ValueError, match="rows"):
        pack_tokens([[5, 6]], 8, 2, pad_id=1, rows=0)
    if native_available():
        with pytest.raises(ValueError, match="rows"):
            native_pack_tokens_raw([[5, 6]], 8, 2, pad_id=1, rows=0)
