"""Native runtime: C++ tokenizer parity + throughput sanity."""

import numpy as np
import pytest

from svoc_tpu.io.scraper import SyntheticSource
from svoc_tpu.models.tokenizer import HashingTokenizer
from svoc_tpu.runtime import NativeHashingTokenizer, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain available"
)


def pairs(vocab=50265, pad=1, max_len=64):
    return (
        HashingTokenizer(vocab, pad_id=pad, max_len=max_len),
        NativeHashingTokenizer(vocab, pad_id=pad, max_len=max_len),
    )


class TestNativeTokenizerParity:
    def test_special_id_layout(self):
        py, cc = pairs()
        assert (py.pad_id, py.bos_id, py.eos_id) == (
            cc.pad_id,
            cc.bos_id,
            cc.eos_id,
        )

    def test_ascii_bit_parity(self):
        py, cc = pairs()
        texts = SyntheticSource(batch=64, seed=7)() + [
            "",
            "a",
            "Hello, World!  punctuation...and--dashes",
            "UPPER lower MiXeD 12345 0xdeadbeef",
            "word " * 200,  # truncation path
            "trailing word",
        ]
        ids_py, mask_py = py(texts, 64)
        ids_cc, mask_cc = cc(texts, 64)
        np.testing.assert_array_equal(ids_py, ids_cc)
        np.testing.assert_array_equal(mask_py, mask_cc)

    def test_other_vocab_and_pad(self):
        py, cc = pairs(vocab=30522, pad=0, max_len=32)
        texts = ["the quick brown fox", "jumps. over! the? lazy dog"]
        ids_py, mask_py = py(texts, 32)
        ids_cc, mask_cc = cc(texts, 32)
        np.testing.assert_array_equal(ids_py, ids_cc)
        np.testing.assert_array_equal(mask_py, mask_cc)

    def test_shapes_and_dtype(self):
        _, cc = pairs()
        ids, mask = cc(["one two three"], 16)
        assert ids.shape == (1, 16) and ids.dtype == np.int32
        assert mask.sum() == 5  # bos + 3 words + eos

    def test_faster_than_python(self):
        """The point of the native path: meaningfully outrun Python."""
        import time

        py, cc = pairs()
        texts = SyntheticSource(batch=2048, seed=1)()

        t0 = time.perf_counter()
        py(texts, 128)
        t_py = time.perf_counter() - t0

        t0 = time.perf_counter()
        cc(texts, 128)
        t_cc = time.perf_counter() - t0
        assert t_cc < t_py, (t_cc, t_py)


class TestLoadTokenizerPrefersNative:
    def test_default_path_is_native(self):
        from svoc_tpu.models.tokenizer import load_tokenizer

        tok = load_tokenizer(None, 50265, pad_id=1, max_len=64)
        assert isinstance(tok, NativeHashingTokenizer)
