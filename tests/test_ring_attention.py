"""Ring attention vs monolithic softmax on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.parallel.mesh import MeshSpec, make_mesh
from svoc_tpu.parallel.ring_attention import (
    dense_attention_reference,
    ring_attention_fn,
)


def make_qkv(key, b=2, t=64, h=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), dtype)
    k = jax.random.normal(kk, (b, t, h, d), dtype)
    v = jax.random.normal(kv, (b, t, h, d), dtype)
    return q, k, v


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(MeshSpec(("seq",), (8,)))


class TestRingAttention:
    def test_matches_dense(self, seq_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(0))
        kmask = jnp.ones(k.shape[:2], jnp.int32)
        ring = ring_attention_fn(seq_mesh)
        out = ring(q, k, v, kmask)
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_matches_dense_with_padding(self, seq_mesh):
        """Padding in arbitrary positions must survive the ring rotation."""
        q, k, v = make_qkv(jax.random.PRNGKey(1))
        kmask = (
            jax.random.uniform(jax.random.PRNGKey(2), k.shape[:2]) > 0.3
        ).astype(jnp.int32)
        # Guarantee at least one real key per row.
        kmask = kmask.at[:, 0].set(1)
        ring = ring_attention_fn(seq_mesh)
        out = ring(q, k, v, kmask)
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_extreme_logits_stable(self, seq_mesh):
        """The streaming softmax must not overflow where a naive
        exp-sum would."""
        q, k, v = make_qkv(jax.random.PRNGKey(3))
        q = q * 100.0  # logits ~ O(10^3)
        kmask = jnp.ones(k.shape[:2], jnp.int32)
        out = ring_attention_fn(seq_mesh)(q, k, v, kmask)
        assert np.isfinite(np.asarray(out)).all()
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=5e-5, rtol=5e-5
        )

    def test_bf16_path(self, seq_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(4), dtype=jnp.bfloat16)
        kmask = jnp.ones(k.shape[:2], jnp.int32)
        out = ring_attention_fn(seq_mesh)(q, k, v, kmask)
        assert out.dtype == jnp.bfloat16
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out, np.float32),
            np.asarray(ref, np.float32),
            atol=3e-2,
        )

    def test_long_sequence_memory_shape(self, seq_mesh):
        """T=1024 over 8 shards: per-device blocks are [B,128,H,D]."""
        q, k, v = make_qkv(jax.random.PRNGKey(5), b=1, t=1024, h=2, d=8)
        kmask = jnp.ones(k.shape[:2], jnp.int32)
        out = ring_attention_fn(seq_mesh)(q, k, v, kmask)
        assert out.shape == (1, 1024, 2, 8)
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )


def test_ring_flash_inner_matches_dense_reference(seq_mesh):
    """The ring-outer/flash-inner composition (Pallas per-hop blocks,
    log-sum-exp merge) must be exact vs the monolithic softmax, masks
    included."""
    key = jax.random.PRNGKey(4)
    b, t, h, d = 2, 64, 2, 16  # 8 ring hops of 8-token blocks
    q = jax.random.normal(key, (b, t, h, d), jnp.float32)
    kmask = jnp.ones((b, t), jnp.int32).at[0, 40:].set(0)

    flash_ring = ring_attention_fn(seq_mesh, block_impl="flash")(q, q, q, kmask)
    ref = dense_attention_reference(q, q, q, kmask)
    np.testing.assert_allclose(
        np.asarray(flash_ring), np.asarray(ref), atol=2e-4
    )

    dense_ring = ring_attention_fn(seq_mesh, block_impl="dense")(q, q, q, kmask)
    np.testing.assert_allclose(
        np.asarray(flash_ring), np.asarray(dense_ring), atol=2e-4
    )


def test_ring_flash_all_padding_row_is_zero(seq_mesh):
    """Regression (round-3 review): an all-padding batch row must come
    out of the flash ring as exactly 0 — previously each hop's
    degenerate uniform-average accumulated additively (n_dev× mean(V))
    because the −1e30 lse sentinels absorbed in float32."""
    key = jax.random.PRNGKey(11)
    b, t, h, d = 2, 64, 2, 16
    q = jax.random.normal(key, (b, t, h, d), jnp.float32)
    kmask = jnp.ones((b, t), jnp.int32).at[1, :].set(0)  # row 1: padding

    out = ring_attention_fn(seq_mesh, block_impl="flash")(q, q, q, kmask)
    assert float(jnp.abs(out[1]).max()) == 0.0
    # the real row is untouched by the convention
    ref = dense_attention_reference(q, q, q, kmask)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), atol=2e-4
    )


def test_ring_attention_backward_matches_dense():
    """The two-pass ring VJP (dk/dv accumulators travel with their
    rotating block) must reproduce dense-attention gradients on the
    8-way seq mesh, including key padding."""
    mesh = make_mesh(MeshSpec(("seq",), (8,)))
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 64, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32) for _ in range(3)
    )
    kmask = jnp.asarray(
        (np.arange(t)[None, :] < np.array([[t], [t - 20]])).astype(np.int32)
    )
    cot = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    ring = ring_attention_fn(mesh)
    gf = jax.grad(
        lambda *a: jnp.sum(ring(*a, kmask) * cot), argnums=(0, 1, 2)
    )(q, k, v)
    gd = jax.grad(
        lambda *a: jnp.sum(dense_attention_reference(*a, kmask) * cot),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b_ in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=1e-5, err_msg=f"d{name}"
        )


def test_ring_dense_all_padding_row_zero_forward_and_grad(seq_mesh):
    """Regression (round-3 review): an all-padding batch row through the
    DENSE ring must return exactly 0 forward (the flash-path convention)
    with exactly-zero dq/dk/dv for it — previously the forward emitted
    the degenerate uniform average of V while the VJP returned zeros,
    an inconsistent gradient.  The live row must stay dense-exact both
    ways."""
    rng = np.random.default_rng(7)
    b, t, h, d = 2, 64, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        for _ in range(3)
    )
    kmask = jnp.ones((b, t), jnp.int32).at[1, :].set(0)  # row 1 all pad
    cot = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    ring = ring_attention_fn(seq_mesh)

    out = ring(q, k, v, kmask)
    assert float(jnp.abs(out[1]).max()) == 0.0
    ref = dense_attention_reference(q, k, v, kmask)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), atol=2e-5, rtol=2e-5
    )

    gf = jax.grad(
        lambda *a: jnp.sum(ring(*a, kmask) * cot), argnums=(0, 1, 2)
    )(q, k, v)
    gd = jax.grad(
        lambda *a: jnp.sum(dense_attention_reference(*a, kmask) * cot),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b_ in zip("qkv", gf, gd):
        a, b_ = np.asarray(a), np.asarray(b_)
        assert float(np.abs(a[1]).max()) == 0.0, f"d{name} dead row"
        np.testing.assert_allclose(
            a[0], b_[0], atol=1e-5, err_msg=f"d{name} live row"
        )
