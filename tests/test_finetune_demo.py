"""Bounded CI variant of the fine-tune demo (VERDICT r3 item 8).

The full run (``tools/finetune_demo.py``, committed as
``FINETUNE_r04.json``) trains to macro-F1 ≥ 0.99; here a 12-step slice
proves the mechanics end to end on the virtual mesh: loss descends,
the mid-run orbax checkpoint replays bit-exactly on the same mesh, and
restores bit-exactly onto a different data×model layout.
"""

import json


def test_finetune_demo_mechanics(tmp_path):
    from tools.finetune_demo import main

    out = tmp_path / "ft.json"
    # target-f1 0: the CI slice asserts mechanics, not convergence.
    rc = main(
        ["--steps", "12", "--batch", "16", "--target-f1", "0.0",
         "--out", str(out)]
    )
    report = json.loads(out.read_text())
    assert rc == 0, report
    assert report["zero1_opt_sharding"] is False
    assert report["same_mesh_replay_max_abs_param_delta"] == 0.0
    assert report["changed_mesh_restore_max_abs_param_delta"] == 0.0
    assert report["loss_curve"][-1] < report["loss_curve"][0]


def test_finetune_demo_zero1_checkpoint_mechanics(tmp_path):
    """The PARALLELISM.md claim under test: with ZeRO-1-sharded
    optimizer state, the mid-run orbax checkpoint still replays
    bit-exactly on the same mesh AND restores bit-exactly onto a
    different data×model layout (4×2 → 2×4)."""
    from tools.finetune_demo import main

    out = tmp_path / "ft_zero1.json"
    rc = main(
        ["--steps", "12", "--batch", "16", "--target-f1", "0.0",
         "--out", str(out), "--zero1"]
    )
    report = json.loads(out.read_text())
    assert rc == 0, report
    assert report["zero1_opt_sharding"] is True
    assert report["same_mesh_replay_max_abs_param_delta"] == 0.0
    assert report["changed_mesh_restore_max_abs_param_delta"] == 0.0
    assert report["loss_curve"][-1] < report["loss_curve"][0]
