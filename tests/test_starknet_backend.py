"""StarknetBackend encoding against a mocked ``starknet_py``.

The real Sepolia path (``client/contract.py`` semantics) can't reach a
network in CI, but its *encoding* can be pinned: calldata felts
(two's-complement wsad), the fixed V3 resource bounds, per-oracle signed
tx order, and the account bootstrap from the ``sepolia.json`` layout
(``client/README.md:38-77``).  A fake ``starknet_py`` records every
call.
"""

from __future__ import annotations

import json
import sys
import types

import pytest

from svoc_tpu.ops.fixedpoint import FELT_PRIME, float_to_fwsad

RESOURCE_BOUND = (259806, 153060543928007)  # client/contract.py:29


# ---------------------------------------------------------------------------
# fake starknet_py
# ---------------------------------------------------------------------------


class FakeFunction:
    def __init__(self, log, provider, name, views):
        self._log = log
        self._provider = provider
        self._name = name
        self._views = views

    async def call(self):
        self._log.append(("call", self._provider, self._name))
        return (self._views.get(self._name, []),)

    async def invoke_v3(self, **kwargs):
        self._log.append(("invoke_v3", self._provider, self._name, kwargs))


class FakeFunctions:
    def __init__(self, log, provider, views):
        self._log = log
        self._provider = provider
        self._views = views

    def __getitem__(self, name):
        return FakeFunction(self._log, self._provider, name, self._views)


class FakeContract:
    #: shared recorders, reset per test via the fixture
    log: list = []
    views: dict = {}

    def __init__(self, provider, address):
        self.provider = provider
        self.address = address
        self.functions = FakeFunctions(self.log, provider, self.views)

    @classmethod
    async def from_address(cls, provider, address):
        cls.log.append(("from_address", provider, address))
        return cls(provider, address)


class FakeResourceBounds:
    def __init__(self, max_amount, max_price_per_unit):
        self.max_amount = max_amount
        self.max_price_per_unit = max_price_per_unit

    def __eq__(self, other):
        return (self.max_amount, self.max_price_per_unit) == (
            other.max_amount,
            other.max_price_per_unit,
        )

    def __repr__(self):
        return f"FakeResourceBounds({self.max_amount}, {self.max_price_per_unit})"


class FakeFullNodeClient:
    def __init__(self, node_url):
        self.node_url = node_url


class FakeKeyPair:
    def __init__(self, key):
        self.key = key

    @classmethod
    def from_private_key(cls, key):
        return cls(key)


class FakeAccount:
    def __init__(self, client, address, key_pair, chain):
        self.client = client
        self.address = address
        self.key_pair = key_pair
        self.chain = chain

    def __repr__(self):
        return f"FakeAccount({self.address})"


class FakeChainId:
    SEPOLIA = "SN_SEPOLIA"


def _module(name, **attrs):
    m = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(m, k, v)
    return m


@pytest.fixture()
def fake_starknet(monkeypatch):
    FakeContract.log = []
    FakeContract.views = {}
    mods = {
        "starknet_py": _module("starknet_py"),
        "starknet_py.contract": _module(
            "starknet_py.contract", Contract=FakeContract
        ),
        "starknet_py.net": _module("starknet_py.net"),
        "starknet_py.net.client_models": _module(
            "starknet_py.net.client_models", ResourceBounds=FakeResourceBounds
        ),
        "starknet_py.net.full_node_client": _module(
            "starknet_py.net.full_node_client", FullNodeClient=FakeFullNodeClient
        ),
        "starknet_py.net.account": _module("starknet_py.net.account"),
        "starknet_py.net.account.account": _module(
            "starknet_py.net.account.account", Account=FakeAccount
        ),
        "starknet_py.net.models": _module("starknet_py.net.models"),
        "starknet_py.net.models.chains": _module(
            "starknet_py.net.models.chains", StarknetChainId=FakeChainId
        ),
        "starknet_py.net.signer": _module("starknet_py.net.signer"),
        "starknet_py.net.signer.stark_curve_signer": _module(
            "starknet_py.net.signer.stark_curve_signer", KeyPair=FakeKeyPair
        ),
    }
    for name, mod in mods.items():
        monkeypatch.setitem(sys.modules, name, mod)
    return FakeContract


# ---------------------------------------------------------------------------
# account / deployment file parsing
# ---------------------------------------------------------------------------


def _write_sepolia_json(path):
    data = {
        "admins_addresses": [f"0x{0xA0 + i:x}" for i in range(3)],
        "admins_private_keys": [f"0x{100 + i:x}" for i in range(3)],
        "oracles_addresses": [f"0x{0x10 + i:x}" for i in range(8)],
        "oracles_private_keys": [f"0x{200 + i:x}" for i in range(8)],
    }
    path.write_text(json.dumps(data))


def test_load_account_data_reference_layout(tmp_path):
    from svoc_tpu.io.chain import load_account_data

    p = tmp_path / "sepolia.json"
    _write_sepolia_json(p)
    admins, oracles = load_account_data(str(p))
    assert len(admins) == 3 and len(oracles) == 8
    assert admins[0] == {"address": "0xa0", "private_key": "0x64"}
    assert oracles[7]["address"] == "0x17"


def test_load_contract_info(tmp_path):
    from svoc_tpu.io.chain import load_contract_info

    p = tmp_path / "contract_info.json"
    p.write_text(
        json.dumps(
            {
                "rpc": "https://rpc.example/sepolia",
                "declared_address": "0x123",
                "deployed_address": "0x456",
            }
        )
    )
    rpc, declared, deployed = load_contract_info(str(p))
    assert rpc == "https://rpc.example/sepolia"
    assert (declared, deployed) == (0x123, 0x456)


def test_build_accounts_keyed_by_int_address(fake_starknet):
    from svoc_tpu.io.chain import build_starknet_accounts

    client = FakeFullNodeClient("https://rpc.example")
    admins = [{"address": "0xa0", "private_key": "0x1"}]
    oracles = [{"address": "0x10", "private_key": "0x2"}]
    accounts = build_starknet_accounts(client, admins, oracles)
    assert set(accounts) == {0xA0, 0x10}
    acct = accounts[0x10]
    assert acct.client is client
    assert acct.key_pair.key == "0x2"
    assert acct.chain == FakeChainId.SEPOLIA


# ---------------------------------------------------------------------------
# backend call/invoke encoding
# ---------------------------------------------------------------------------


def make_backend(fake_starknet, accounts=None):
    from svoc_tpu.io.chain import StarknetBackend

    client = FakeFullNodeClient("https://rpc.example")
    return StarknetBackend(
        "https://rpc.example", 0xDE9, accounts or {}, client=client
    )


def test_reads_use_node_client_contract(fake_starknet):
    backend = make_backend(fake_starknet)
    # ABI resolution happened once against the node client.
    kind, provider, address = fake_starknet.log[0]
    assert kind == "from_address" and address == 0xDE9
    assert isinstance(provider, FakeFullNodeClient)

    fake_starknet.views["get_predictions_dimension"] = 6
    assert backend.call("get_predictions_dimension") == 6
    assert fake_starknet.log[-1][2] == "get_predictions_dimension"


def test_invoke_signs_with_caller_account_and_v3_bounds(fake_starknet):
    accounts = {0x10: FakeAccount(None, "0x10", FakeKeyPair("k"), "SN_SEPOLIA")}
    backend = make_backend(fake_starknet, accounts)
    backend.invoke(0x10, "update_prediction", prediction=[1, 2, 3])

    kind, provider, name, kwargs = fake_starknet.log[-1]
    assert (kind, name) == ("invoke_v3", "update_prediction")
    assert provider is accounts[0x10]  # signed by the caller's account
    assert kwargs["prediction"] == [1, 2, 3]
    assert kwargs["l1_resource_bounds"] == FakeResourceBounds(*RESOURCE_BOUND)


def test_adapter_update_all_predictions_order_and_felts(fake_starknet):
    """The full commit path over the mocked chain: one tx per oracle in
    oracle-list order (client/contract.py:200-208), negative wsad values
    prime-wrapped (client/contract.py:48-53)."""
    from svoc_tpu.io.chain import ChainAdapter

    oracle_addrs = [0x10, 0x11, 0x12]
    accounts = {
        a: FakeAccount(None, hex(a), FakeKeyPair("k"), "SN_SEPOLIA")
        for a in oracle_addrs
    }
    backend = make_backend(fake_starknet, accounts)
    fake_starknet.views["get_oracle_list"] = oracle_addrs
    adapter = ChainAdapter(backend)

    predictions = [[0.25, -0.5], [1.0, 2.5], [-0.000001, 0.0]]
    assert adapter.update_all_the_predictions(predictions) == 3

    invokes = [e for e in fake_starknet.log if e[0] == "invoke_v3"]
    assert [e[1] for e in invokes] == [accounts[a] for a in oracle_addrs]
    sent = [e[3]["prediction"] for e in invokes]
    assert sent[0] == [250000, FELT_PRIME - 500000]
    assert sent[1] == [1000000, 2500000]
    assert sent[2] == [FELT_PRIME - 1, 0]
    assert sent[0][1] == float_to_fwsad(-0.5)


def test_starknet_backend_from_files(fake_starknet, tmp_path):
    from svoc_tpu.io.chain import starknet_backend_from_files

    info = tmp_path / "contract_info.json"
    info.write_text(
        json.dumps(
            {
                "rpc": "https://rpc.example/sepolia",
                "declared_address": "0x123",
                "deployed_address": "0x456",
            }
        )
    )
    sepolia = tmp_path / "sepolia.json"
    _write_sepolia_json(sepolia)

    backend = starknet_backend_from_files(str(info), str(sepolia))
    assert backend.deployed_address == 0x456
    assert backend.client.node_url == "https://rpc.example/sepolia"
    assert len(backend.accounts) == 11  # 3 admins + 8 oracles
    assert 0xA0 in backend.accounts and 0x17 in backend.accounts


def test_cli_adapter_wiring(fake_starknet, tmp_path):
    """--contract-info/--accounts build a Sepolia-backed adapter; one
    without the other is rejected; neither means local simulator."""
    from svoc_tpu.apps.cli import build_adapter, build_parser
    from svoc_tpu.io.chain import StarknetBackend

    info = tmp_path / "contract_info.json"
    info.write_text(
        json.dumps(
            {
                "rpc": "https://rpc.example",
                "declared_address": "0x1",
                "deployed_address": "0x2",
            }
        )
    )
    sepolia = tmp_path / "sepolia.json"
    _write_sepolia_json(sepolia)

    parser = build_parser()
    args = parser.parse_args(
        ["--contract-info", str(info), "--accounts", str(sepolia)]
    )
    adapter = build_adapter(args)
    assert isinstance(adapter.backend, StarknetBackend)

    assert build_adapter(parser.parse_args([])) is None

    with pytest.raises(SystemExit, match="together"):
        build_adapter(parser.parse_args(["--contract-info", str(info)]))


# ---------------------------------------------------------------------------
# declare / deploy (contract/README.md:41-66 flow)
# ---------------------------------------------------------------------------


class FakeDeployResult:
    def __init__(self, log, address):
        self._log = log
        self.deployed_contract = types.SimpleNamespace(address=address)

    async def wait_for_acceptance(self):
        self._log.append(("wait_for_acceptance", "deploy"))
        return self


class FakeDeclareResult:
    def __init__(self, log, class_hash):
        self._log = log
        self.class_hash = class_hash

    async def wait_for_acceptance(self):
        self._log.append(("wait_for_acceptance", "declare"))
        return self

    async def deploy_v3(self, constructor_args, auto_estimate):
        self._log.append(("deploy_v3", constructor_args, auto_estimate))
        return FakeDeployResult(self._log, address=0xDE9107)


def test_declare_and_deploy_pins_tx_shape(fake_starknet):
    """The declare->deploy flow: Sierra+CASM declared from the paying
    account, constructor args in the ABI order of contract.cairo:236-245
    with the wsad-felt max_spread, both txs awaited to acceptance."""
    from svoc_tpu.io.chain import declare_and_deploy, to_hex
    from svoc_tpu.io.deploy import DeployConfig, constructor_calldata

    log = fake_starknet.log

    async def declare_v3(account, compiled_contract, compiled_contract_casm,
                         auto_estimate):
        log.append(
            ("declare_v3", account, compiled_contract, compiled_contract_casm,
             auto_estimate)
        )
        return FakeDeclareResult(log, class_hash=0xC1A55)

    fake_starknet.declare_v3 = declare_v3

    cfg = DeployConfig(
        admins=[1, 2, 3],
        oracles=list(range(10, 17)),
        enable_oracle_replacement=True,
        required_majority=2,
        n_failing_oracles=2,
        constrained=False,
        unconstrained_max_spread=10.0,
        dimension=2,
    )
    account = object()
    result = declare_and_deploy(account, cfg, "SIERRA_JSON", "CASM_JSON")

    assert log[0] == ("declare_v3", account, "SIERRA_JSON", "CASM_JSON", True)
    assert log[1] == ("wait_for_acceptance", "declare")
    kind, args, auto = log[2][0], log[2][1], log[2][2]
    assert kind == "deploy_v3" and auto is True
    # ABI order + encoding (contract.cairo:236-245); max_spread crosses
    # as a wsad felt (10.0 -> 10_000_000).
    assert args == {
        "admins": [1, 2, 3],
        "enable_oracle_replacement": True,
        "required_majority": 2,
        "n_failing_oracles": 2,
        "constrained": False,
        "unconstrained_max_spread": 10_000_000,
        "dimension": 2,
        "oracles": [10, 11, 12, 13, 14, 15, 16],
    }
    assert log[3] == ("wait_for_acceptance", "deploy")

    assert result.class_hash == 0xC1A55
    assert result.address == 0xDE9107
    info = result.contract_info("https://rpc.example")
    assert info == {
        "rpc": "https://rpc.example",
        "declared_address": to_hex(0xC1A55),
        "deployed_address": to_hex(0xDE9107),
    }
    # The typed args serialize to the same felts as the raw calldata
    # documented in contract/README.md:41-66 (span length prefixes).
    felts = constructor_calldata(cfg)
    assert felts[0] == 3 and felts[4:10] == [1, 2, 2, 0, 10_000_000, 2]
    assert felts[10] == 7


# ---------------------------------------------------------------------------
# failure paths + nonce ordering in the commit loop (round-3 hardening)
# ---------------------------------------------------------------------------


def _commit_fixture(fake_starknet, failing_rpc_at=None):
    """Backend + adapter over 4 oracle accounts; optionally make the
    fake RPC raise on the Nth invoke_v3 (0-based)."""
    from svoc_tpu.io.chain import ChainAdapter

    oracle_addrs = [0x10, 0x11, 0x12, 0x13]
    accounts = {
        a: FakeAccount(None, hex(a), FakeKeyPair("k"), "SN_SEPOLIA")
        for a in oracle_addrs
    }
    backend = make_backend(fake_starknet, accounts)
    fake_starknet.views["get_oracle_list"] = oracle_addrs

    if failing_rpc_at is not None:
        invokes = {"n": 0}
        orig = FakeFunction.invoke_v3

        async def flaky_invoke(self, **kwargs):
            if self._name == "update_prediction":
                if invokes["n"] == failing_rpc_at:
                    invokes["n"] += 1
                    raise ConnectionError("RPC node dropped the request")
                invokes["n"] += 1
            return await orig(self, **kwargs)

        FakeFunction.invoke_v3 = flaky_invoke
    return ChainAdapter(backend), oracle_addrs, accounts


_ORIG_INVOKE = FakeFunction.invoke_v3


def test_commit_loop_rpc_failure_partial_accounting(fake_starknet):
    """An RPC failure on the 3rd oracle's tx must surface as
    ChainCommitError with committed=2 — the first two txs ARE on chain
    (client/contract.py:200-224 has no rollback)."""
    from svoc_tpu.io.chain import ChainCommitError

    adapter, oracle_addrs, _ = _commit_fixture(fake_starknet, failing_rpc_at=2)
    predictions = [[0.1, 0.2]] * 4
    try:
        with pytest.raises(ChainCommitError) as exc:
            adapter.update_all_the_predictions(predictions)
        e = exc.value
        assert e.committed == 2
        assert e.total == 4
        assert e.failed_oracle == oracle_addrs[2]
        assert isinstance(e.cause, ConnectionError)
        # the two successful txs went out in oracle-list order, signed
        # by the right accounts
        invokes = [x for x in fake_starknet.log if x[0] == "invoke_v3"]
        assert [x[1].address for x in invokes] == ["0x10", "0x11"]
    finally:
        FakeFunction.invoke_v3 = _ORIG_INVOKE


def test_commit_loop_success_after_transient_failure(fake_starknet):
    """Retrying a failed commit resubmits from oracle 0 (idempotent on
    the contract: update_prediction overwrites the oracle's value)."""
    from svoc_tpu.io.chain import ChainCommitError

    adapter, oracle_addrs, _ = _commit_fixture(fake_starknet, failing_rpc_at=1)
    predictions = [[0.1, 0.2]] * 4
    try:
        with pytest.raises(ChainCommitError):
            adapter.update_all_the_predictions(predictions)
        # second attempt: the fake RPC has recovered
        n = adapter.update_all_the_predictions(predictions)
        assert n == 4
        invokes = [x for x in fake_starknet.log if x[0] == "invoke_v3"]
        # 1 successful from attempt 1 + 4 from attempt 2
        assert [x[1].address for x in invokes] == [
            "0x10", "0x10", "0x11", "0x12", "0x13",
        ]
    finally:
        FakeFunction.invoke_v3 = _ORIG_INVOKE


def test_commit_nonce_ordering_per_account(fake_starknet):
    """Each account's txs must be submitted strictly sequentially (the
    nonce space of a Starknet account admits no gaps): two commit
    rounds produce monotonically increasing per-account nonces, and no
    account's second tx is submitted before its first returned."""
    adapter, oracle_addrs, accounts = _commit_fixture(fake_starknet)

    nonces = {}
    orig = FakeFunction.invoke_v3

    async def nonce_invoke(self, **kwargs):
        acct = self._provider
        nonces.setdefault(acct.address, []).append(len(nonces.get(acct.address, [])))
        return await orig(self, **kwargs)

    FakeFunction.invoke_v3 = nonce_invoke
    try:
        predictions = [[0.1, 0.2]] * 4
        assert adapter.update_all_the_predictions(predictions) == 4
        assert adapter.update_all_the_predictions(predictions) == 4
        # every account saw exactly nonces [0, 1], in order
        assert nonces == {hex(a): [0, 1] for a in oracle_addrs}
    finally:
        FakeFunction.invoke_v3 = _ORIG_INVOKE
