"""Contract simulator state machine — mirrors the scenarios of
``contract/tests/test_contract.cairo`` (deployment state, activation
gate, prediction flow, replacement votes, access control)."""

import pytest

from svoc_tpu.consensus.state import ContractError, OracleConsensusContract

ADMINS = ["Akashi", "Ozu", "Higuchi"]
ORACLES = [f"oracle_0{i}" for i in range(7)]

# test_contract.cairo:150-158
PREDICTIONS_2D = [
    [0.492954, 0.334814],
    [0.437692, 0.410445],
    [0.967794, 0.564219],
    [0.431029, 0.387225],
    [0.487609, 0.337990],
    [0.284178, 0.485072],
    [0.990059, 0.558600],
]


def make_constrained(dimension=2):
    # deploy_constrained_contract calldata (test_contract.cairo:28-59)
    return OracleConsensusContract(
        ADMINS,
        ORACLES,
        enable_oracle_replacement=True,
        required_majority=2,
        n_failing_oracles=2,
        constrained=True,
        unconstrained_max_spread=0.0,
        dimension=dimension,
    )


def test_initial_state():
    c = make_constrained()
    # test_contract.cairo:140-143
    assert c.consensus_active is False
    assert c.get_consensus_value() == [0, 0]
    assert c.get_first_pass_consensus_reliability() == 0
    assert c.get_second_pass_consensus_reliability() == 0
    assert c.get_admin_list() == ADMINS
    assert c.get_oracle_list() == ORACLES
    assert c.get_replacement_propositions() == [None] * 3


def test_activation_gate():
    """Consensus is only computed once every oracle committed
    (contract.cairo:447-449)."""
    c = make_constrained()
    for i in range(6):
        c.update_prediction(ORACLES[i], PREDICTIONS_2D[i])
        assert c.consensus_active is False
        assert c.get_consensus_value() == [0, 0]
    c.update_prediction(ORACLES[6], PREDICTIONS_2D[6])
    assert c.consensus_active is True
    assert c.get_consensus_value() != [0, 0]
    # afterwards every commit recomputes
    before = c.get_consensus_value()
    c.update_prediction(ORACLES[0], [0.111, 0.999])
    assert c.get_consensus_value() != before


def test_full_constrained_run_marks_two_unreliable():
    c = make_constrained()
    for o, p in zip(ORACLES, PREDICTIONS_2D):
        c.update_prediction(o, p)
    dump = c.get_oracle_value_list("Akashi")
    reliable_flags = [r for (_, _, _, r) in dump]
    assert sum(not r for r in reliable_flags) == 2
    # outliers (0.9677.., 0.5642..) and (0.9900.., 0.5586..) are masked
    assert reliable_flags[2] is False and reliable_flags[6] is False
    assert 0 <= c.get_first_pass_consensus_reliability(as_floats=True) <= 1
    assert 0 <= c.get_second_pass_consensus_reliability(as_floats=True) <= 1


def test_not_an_oracle_rejected():
    c = make_constrained()
    with pytest.raises(ContractError, match="not an oracle"):
        c.update_prediction("eve", [0.5, 0.5])


def test_constrained_interval_check_on_input():
    c = make_constrained()
    with pytest.raises(AssertionError, match="interval"):
        c.update_prediction(ORACLES[0], [1.5, 0.5])
    with pytest.raises(AssertionError, match="interval"):
        c.update_prediction(ORACLES[0], [-0.1, 0.5])


def test_admin_only_oracle_value_list():
    c = make_constrained()
    with pytest.raises(ContractError, match="not admin"):
        c.get_oracle_value_list("oracle_00")


def test_replacement_vote_flow():
    """test_contract.cairo:195-213: proposition + 1 vote -> no change,
    second vote reaches majority -> address swapped, everything reset."""
    c = make_constrained()
    for o, p in zip(ORACLES, PREDICTIONS_2D):
        c.update_prediction(o, p)

    old_oracle = 6
    c.update_proposition("Akashi", (old_oracle, "oracle_XX"))
    assert c.get_oracle_list()[old_oracle] == "oracle_06"
    c.vote_for_a_proposition("Akashi", 0, True)  # self-vote already set; still 1 voter
    assert c.get_oracle_list()[old_oracle] == "oracle_06"
    c.vote_for_a_proposition("Ozu", 0, True)
    assert c.get_oracle_list()[old_oracle] == "oracle_XX"
    # reset rules (contract.cairo:578-579)
    assert c.get_replacement_propositions() == [None] * 3
    assert not any(c.vote_matrix.values())
    # replaced oracle keeps its old value/flags (contract.cairo:573-576)
    dump = c.get_oracle_value_list("Akashi")
    assert dump[old_oracle][0] == "oracle_XX"
    assert dump[old_oracle][2] is True  # still enabled


def test_proposition_change_forfeits_votes():
    c = make_constrained()
    c.update_proposition("Akashi", (0, "oracle_XX"))
    c.vote_for_a_proposition("Ozu", 0, True)
    # ... but majority=2 already reached -> replaced. Use majority 3 variant:
    c2 = OracleConsensusContract(
        ADMINS, ORACLES, required_majority=3, dimension=2
    )
    c2.update_proposition("Akashi", (0, "oracle_XX"))
    c2.vote_for_a_proposition("Ozu", 0, True)
    assert c2.vote_matrix[(1, 0)] is True
    # changing the proposition zeroes the collected column, then self-votes
    c2.update_proposition("Akashi", (1, "oracle_YY"))
    assert c2.vote_matrix[(1, 0)] is False
    assert c2.vote_matrix[(0, 0)] is True


def test_replacement_guards():
    c = make_constrained()
    with pytest.raises(ContractError, match="not an admin"):
        c.update_proposition("eve", (0, "oracle_XX"))
    with pytest.raises(ContractError, match="wrong old oracle index"):
        c.update_proposition("Akashi", (99, "oracle_XX"))
    with pytest.raises(ContractError, match="already in the team"):
        c.update_proposition("Akashi", (0, "oracle_01"))
    c_disabled = OracleConsensusContract(
        ADMINS, ORACLES, enable_oracle_replacement=False, dimension=2
    )
    with pytest.raises(ContractError, match="replacement disabled"):
        c_disabled.update_proposition("Akashi", (0, "oracle_XX"))
    with pytest.raises(ContractError, match="replacement disabled"):
        c_disabled.get_replacement_propositions()


def test_interval_panic_reverts_the_commit():
    """A Cairo panic reverts the whole transaction: the triggering
    oracle must stay disabled with its old value, and later commits
    must not see the poisoned state."""
    from svoc_tpu.consensus.wsad_engine import IntervalError

    c = make_constrained()
    # 5 oracles at [1,1], 2 at [0,0]: the smooth median lands on [1,1],
    # mean qr = 4/7 > 1/2, so rel1 = 1 - 2*sqrt(mean_qr/2) ≈ -0.069 < 0.
    extremes = [[1.0, 1.0]] * 5 + [[0.0, 0.0]] * 2
    for o, p in zip(ORACLES[:6], extremes[:6]):
        c.update_prediction(o, p)
    with pytest.raises(IntervalError):
        c.update_prediction(ORACLES[6], extremes[6])
    dump = c.get_oracle_value_list("Akashi")
    assert dump[6][2] is False  # still disabled
    assert dump[6][1] == [0, 0]  # old (zero) value retained
    assert c.n_active_oracles == 6
    assert c.consensus_active is False


def test_zero_variance_panics_like_cairo():
    """Near-identical predictions drive the reliable set's sample
    variance to exactly 0 in wsad fixed point, and skewness/kurtosis
    divide by sqrt(variance) UNGUARDED — in the reference contract too
    (``math.cairo:320-343``), where the tx panics with 'Division by 0'.
    The simulator must reproduce the panic and revert the triggering
    commit (found by the live-mode soak: a degenerate vectorizer that
    maps every comment to the same vector wedges the fleet exactly
    like it would on chain)."""
    c = make_constrained()
    # Distinct at the 6th decimal: differences of 1e-6 wsad-square to
    # 1e-12 < 1 wsad unit, so every component variance truncates to 0.
    for i, o in enumerate(ORACLES[:6]):
        c.update_prediction(o, [0.5 + i * 1e-6, 0.5])
    with pytest.raises(ZeroDivisionError, match="i128 division by zero"):
        c.update_prediction(ORACLES[6], [0.5 + 6e-6, 0.5])
    # Reverted, exactly like the interval panic above.
    assert c.consensus_active is False
    assert c.n_active_oracles == 6


def test_vote_out_of_range_target_is_harmless():
    """Cairo's LegacyMap reads default-false/None for unknown keys, so
    voting for a non-existent admin's proposition must not crash (and a
    majority on an empty out-of-range column panics on unwrap)."""
    c = make_constrained()
    c.vote_for_a_proposition("Akashi", 5, True)  # single vote: no effect
    assert c.get_oracle_list() == ORACLES
    with pytest.raises(ContractError, match="unwrap"):
        c.vote_for_a_proposition("Ozu", 5, True)  # majority on empty col
    with pytest.raises(ContractError, match="unwrap"):
        c.vote_for_a_proposition("Akashi", -1, True)
        c.vote_for_a_proposition("Ozu", -1, True)


def test_felt_encoding_path():
    """Predictions can arrive as felt252 calldata exactly as the chain
    client sends them (client/contract.py:218)."""
    from svoc_tpu.ops.fixedpoint import float_to_fwsad

    c = OracleConsensusContract(
        ADMINS,
        ORACLES,
        constrained=False,
        unconstrained_max_spread=10.0,
        dimension=2,
    )
    c.update_prediction(
        ORACLES[0],
        [float_to_fwsad(-1.25), float_to_fwsad(2.5)],
        encoding="felt",
    )
    dump = c.get_oracle_value_list("Akashi")
    assert dump[0][1] == [-1_250_000, 2_500_000]
