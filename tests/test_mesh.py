"""Mesh helpers: factorizations, validation, hybrid single-slice path."""

import pytest

from svoc_tpu.parallel.mesh import MeshSpec, best_mesh, hybrid_mesh, make_mesh


def test_make_mesh_validates_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(MeshSpec(("oracle",), (16,)))


def test_best_mesh_uses_all_devices():
    m = best_mesh()
    assert m.axis_names == ("oracle",)
    assert m.devices.size == 8


def test_hybrid_mesh_single_slice():
    """CPU virtual devices have no slice_index → one slice, and the
    ici spec need not cover every device."""
    m = hybrid_mesh(MeshSpec(("data", "model"), (2, 2)))
    assert m.axis_names == ("replica", "data", "model")
    assert m.devices.shape == (1, 2, 2)


def test_hybrid_mesh_validates_oversized_spec():
    with pytest.raises(ValueError, match="needs 32 devices"):
        hybrid_mesh(MeshSpec(("oracle",), (32,)))
