"""Mesh helpers: factorizations, validation, hybrid single-slice path."""

import pytest

from svoc_tpu.parallel.mesh import MeshSpec, best_mesh, hybrid_mesh, make_mesh


def test_make_mesh_validates_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(MeshSpec(("oracle",), (16,)))


def test_best_mesh_uses_all_devices():
    m = best_mesh()
    assert m.axis_names == ("oracle",)
    assert m.devices.size == 8


def test_hybrid_mesh_single_slice():
    """CPU virtual devices have no slice_index → one slice, and the
    ici spec need not cover every device."""
    m = hybrid_mesh(MeshSpec(("data", "model"), (2, 2)))
    assert m.axis_names == ("replica", "data", "model")
    assert m.devices.shape == (1, 2, 2)


def test_hybrid_mesh_validates_oversized_spec():
    with pytest.raises(ValueError, match="needs 32 devices"):
        hybrid_mesh(MeshSpec(("oracle",), (32,)))


def test_hybrid_mesh_multi_slice_branch(monkeypatch):
    """Exercise the multi-slice branch (round-1/2 verdicts: previously
    dead in every test env).  create_hybrid_device_mesh needs real
    slice topology, so it is faked — everything around it (slice
    accounting, ici-coverage validation, grid reshape, axis naming) is
    real, and the resulting mesh then runs a REAL sharded computation."""
    import jax
    import numpy as np
    from jax.experimental import mesh_utils

    calls = {}

    def fake_hybrid(mesh_shape, dcn_mesh_shape):
        calls["mesh_shape"] = tuple(mesh_shape)
        calls["dcn_mesh_shape"] = tuple(dcn_mesh_shape)
        n = int(np.prod(mesh_shape)) * int(np.prod(dcn_mesh_shape))
        return np.array(jax.devices()[:n]).reshape(
            tuple(np.multiply(mesh_shape, dcn_mesh_shape))
        )

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)

    m = hybrid_mesh(MeshSpec(("oracle",), (4,)), n_slices=2)
    assert calls == {"mesh_shape": (1, 4), "dcn_mesh_shape": (2, 1)}
    assert m.axis_names == ("replica", "oracle")
    assert m.devices.shape == (2, 4)

    # The mesh is usable for real sharded consensus: oracle axis over
    # the ici dimension, outputs replicated over the dcn axis.
    from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
    from svoc_tpu.parallel.sharded import sharded_consensus_fn

    cfg = ConsensusConfig(n_failing=2, constrained=True)
    values = jax.random.uniform(jax.random.PRNGKey(0), (16, 6))
    out = sharded_consensus_fn(m, cfg, axis="oracle")(values)
    ref = consensus_step(values, cfg)
    np.testing.assert_allclose(
        np.asarray(out.essence), np.asarray(ref.essence), rtol=1e-5
    )


def test_hybrid_mesh_multi_slice_rejects_partial_ici_coverage(monkeypatch):
    """A multi-slice ici spec must cover every chip of a slice."""
    import jax
    import numpy as np
    from jax.experimental import mesh_utils

    monkeypatch.setattr(
        mesh_utils,
        "create_hybrid_device_mesh",
        lambda *a, **k: np.array(jax.devices()),
    )
    with pytest.raises(ValueError, match="covers 2 chips but"):
        hybrid_mesh(MeshSpec(("oracle",), (2,)), n_slices=2)
