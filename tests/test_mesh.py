"""Mesh helpers: factorizations, validation, hybrid single-slice path."""

import pytest

from svoc_tpu.parallel.mesh import MeshSpec, best_mesh, hybrid_mesh, make_mesh


def test_make_mesh_validates_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(MeshSpec(("oracle",), (16,)))


def test_best_mesh_uses_all_devices():
    m = best_mesh()
    assert m.axis_names == ("oracle",)
    assert m.devices.size == 8


def test_hybrid_mesh_single_slice():
    """CPU virtual devices have no slice_index → one slice, and the
    ici spec need not cover every device."""
    m = hybrid_mesh(MeshSpec(("data", "model"), (2, 2)))
    assert m.axis_names == ("replica", "data", "model")
    assert m.devices.shape == (1, 2, 2)


def test_hybrid_mesh_validates_oversized_spec():
    with pytest.raises(ValueError, match="needs 32 devices"):
        hybrid_mesh(MeshSpec(("oracle",), (32,)))


def test_hybrid_mesh_multi_slice_branch(monkeypatch):
    """Exercise the multi-slice branch (round-1/2 verdicts: previously
    dead in every test env).  create_hybrid_device_mesh needs real
    slice topology, so it is faked — everything around it (slice
    accounting, ici-coverage validation, grid reshape, axis naming) is
    real, and the resulting mesh then runs a REAL sharded computation."""
    import jax
    import numpy as np
    from jax.experimental import mesh_utils

    calls = {}

    def fake_hybrid(mesh_shape, dcn_mesh_shape):
        calls["mesh_shape"] = tuple(mesh_shape)
        calls["dcn_mesh_shape"] = tuple(dcn_mesh_shape)
        n = int(np.prod(mesh_shape)) * int(np.prod(dcn_mesh_shape))
        return np.array(jax.devices()[:n]).reshape(
            tuple(np.multiply(mesh_shape, dcn_mesh_shape))
        )

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)

    m = hybrid_mesh(MeshSpec(("oracle",), (4,)), n_slices=2)
    assert calls == {"mesh_shape": (1, 4), "dcn_mesh_shape": (2, 1)}
    assert m.axis_names == ("replica", "oracle")
    assert m.devices.shape == (2, 4)

    # The mesh is usable for real sharded consensus: oracle axis over
    # the ici dimension, outputs replicated over the dcn axis.
    from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
    from svoc_tpu.parallel.sharded import sharded_consensus_fn

    cfg = ConsensusConfig(n_failing=2, constrained=True)
    values = jax.random.uniform(jax.random.PRNGKey(0), (16, 6))
    out = sharded_consensus_fn(m, cfg, axis="oracle")(values)
    ref = consensus_step(values, cfg)
    np.testing.assert_allclose(
        np.asarray(out.essence), np.asarray(ref.essence), rtol=1e-5
    )


def test_hybrid_mesh_multi_slice_rejects_partial_ici_coverage(monkeypatch):
    """A multi-slice ici spec must cover every chip of a slice."""
    import jax
    import numpy as np
    from jax.experimental import mesh_utils

    monkeypatch.setattr(
        mesh_utils,
        "create_hybrid_device_mesh",
        lambda *a, **k: np.array(jax.devices()),
    )
    with pytest.raises(ValueError, match="covers 2 chips but"):
        hybrid_mesh(MeshSpec(("oracle",), (2,)), n_slices=2)


def test_init_distributed_contract(monkeypatch):
    """The multi-host bring-up law: auto-detection is always ATTEMPTED
    (no silent skip of TPU-pod/Slurm launches), a lone host where
    detection finds nothing is a no-op, an explicitly configured
    bring-up never fails silently, and a late call (XLA backend live)
    is benign alone but loud when configured."""
    import jax
    from jax._src import distributed as _dist
    from jax._src import xla_bridge

    from svoc_tpu.parallel.mesh import init_distributed

    for var in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(_dist.global_state, "client", None, raising=False)

    # --- late call (the live test backend): benign alone, loud when
    # a bring-up is configured
    assert xla_bridge.backends_are_initialized()
    assert init_distributed() is False
    with pytest.raises(RuntimeError, match="before any JAX backend"):
        init_distributed(coordinator_address="10.0.0.1:1234", num_processes=4)

    # --- pre-backend behavior (simulated): detection attempted, no-op
    # only when jax itself finds no cluster
    monkeypatch.setattr(xla_bridge, "backends_are_initialized", lambda: False)
    calls = []

    def fake_initialize(**kw):
        calls.append(kw)
        if not any(kw.values()):
            raise RuntimeError("Please specify coordinator_address")

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    assert init_distributed() is False  # attempted, nothing detected
    assert len(calls) == 1
    assert init_distributed(
        coordinator_address="10.0.0.1:1234", num_processes=4, process_id=1
    ) is True
    assert calls[-1]["coordinator_address"] == "10.0.0.1:1234"
    assert calls[-1]["num_processes"] == 4

    # already initialized by the launcher -> True, no re-init
    monkeypatch.setattr(_dist.global_state, "client", object(), raising=False)
    n = len(calls)
    assert init_distributed() is True
    assert len(calls) == n
