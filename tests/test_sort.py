"""Indexed sort: Cairo merge-sort tie order, host vs jittable lexsort."""

import itertools
import random

import jax.numpy as jnp
import numpy as np

from svoc_tpu.ops.sort import argsort_cairo, indexed_sort_host, reliability_mask


def test_fixture_from_cairo_unit_test():
    # test_math.cairo:10-19: sort([3,2,1]) -> [(2,1),(1,2),(0,3)]
    assert indexed_sort_host([3, 2, 1]) == [(2, 1), (1, 2), (0, 3)]


def test_ties_descending_index():
    # The merge step takes the right element on ties (sort.cairo:96-101),
    # so equal values come out in descending original-index order.
    assert [i for i, _ in indexed_sort_host([5, 5, 5, 5])] == [3, 2, 1, 0]
    assert [i for i, _ in indexed_sort_host([1, 5, 5, 0])] == [3, 0, 2, 1]


def test_argsort_cairo_matches_host_exhaustive():
    # All value tuples over a small alphabet up to length 6, batched
    # through one vmapped device call per length.
    import jax

    for n in range(1, 7):
        combos = list(itertools.product([0, 1, 2], repeat=n))
        batch = jnp.array(combos, dtype=jnp.int32)
        dev = np.asarray(jax.vmap(argsort_cairo)(batch))
        for vals, perm in zip(combos, dev):
            host = [i for i, _ in indexed_sort_host(list(vals))]
            assert host == perm.tolist(), f"mismatch for {vals}"


def test_argsort_cairo_matches_host_random():
    rng = random.Random(0)
    for _ in range(50):
        n = rng.randint(1, 40)
        vals = [rng.randint(-1000, 1000) for _ in range(n)]
        host = [i for i, _ in indexed_sort_host(vals)]
        dev = argsort_cairo(jnp.array(vals, dtype=jnp.int32)).tolist()
        assert host == dev


def test_reliability_mask_marks_worst():
    risk = jnp.array([0.5, 3.0, 0.1, 2.0, 0.2])
    mask = np.asarray(reliability_mask(risk, 2))
    # worst two risks (3.0 at idx 1, 2.0 at idx 3) are masked out
    assert mask.tolist() == [True, False, True, False, True]
