"""tools/decide_perf.py — the measured-results → PERF_DECISIONS rules.

The routing record must be a pure function of qualifying TPU
measurements: CPU fallbacks never qualify, the best LOSSLESS variant
wins the flagship, and the pallas consensus routes only on a clean,
matching, faster measurement (hang ⇒ xla by walkover)."""

import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
)

import decide_perf  # noqa: E402


def tpu_result(value, extra_detail=None):
    return {
        "value": value,
        "detail": {"backend": "tpu", **(extra_detail or {})},
    }


def cpu_result(value):
    return {
        "value": value,
        "detail": {
            "backend": "cpu",
            "backend_fallback": "probe timed out",
            "small_mode": True,
        },
    }


def campaign(items, rc=0):
    return {
        "items": [
            {"name": name, "results": [{"rc": rc, "result": result}]}
            for name, result in items
        ]
    }


def write(tmp_path, data):
    path = tmp_path / "HW_CAMPAIGN.json"
    path.write_text(json.dumps(data))
    return [str(path)]


def test_cpu_fallbacks_never_qualify(tmp_path):
    paths = write(tmp_path, campaign([("bench_config0", cpu_result(9999.0))]))
    assert decide_perf.latest_tpu_results(paths) == {}
    decisions, _ = decide_perf.decide({})
    assert decisions == {}


def test_best_lossless_variant_wins(tmp_path):
    paths = write(
        tmp_path,
        campaign(
            [
                ("bench_config0", tpu_result(4500.0, {"mfu_estimate": 0.5})),
                ("bench_config8", tpu_result(12000.0, {"mfu_estimate": 0.5})),
                ("bench_config12", tpu_result(13500.0, {"mfu_estimate": 0.55})),
                ("bench_config10", tpu_result(25000.0)),  # int8: excluded
            ]
        ),
    )
    results = decide_perf.latest_tpu_results(paths)
    decisions, evidence = decide_perf.decide(results)
    assert decisions["flagship_variant"] == "packed_flash"
    assert set(evidence["flagship_variant"]) == {"dense", "packed", "packed_flash"}


def test_config0_already_routed_credits_actual_variant():
    results = {
        "bench_config0": tpu_result(12000.0, {"flagship_variant": "packed"}),
        "bench_config12": tpu_result(11000.0),
    }
    decisions, evidence = decide_perf.decide(results)
    assert decisions["flagship_variant"] == "packed"
    assert "dense" not in evidence["flagship_variant"]


def test_routed_config0_never_clobbers_better_dedicated_measurement():
    results = {
        "bench_config0": tpu_result(9000.0, {"flagship_variant": "packed"}),
        "bench_config8": tpu_result(12000.0),
        "bench_config12": tpu_result(10000.0),
    }
    decisions, evidence = decide_perf.decide(results)
    # packed keeps its dedicated 12000 measurement and wins the argmax
    assert decisions["flagship_variant"] == "packed"
    assert evidence["flagship_variant"]["packed"]["comments_per_sec"] == 12000.0


def test_failed_attempts_never_qualify(tmp_path):
    paths = write(
        tmp_path, campaign([("bench_config8", tpu_result(12000.0))], rc=1)
    )
    assert decide_perf.latest_tpu_results(paths) == {}


def test_pallas_routes_only_on_clean_win():
    base = {
        "pallas_kernel_active": True,
        "pallas_hung": False,
        "pallas_info": {"essence_match_xla": True},
        "n_oracles": 1024,
    }
    win = {"bench_config6": tpu_result(0.3, {**base, "pallas_vs_xla_speedup": 1.3})}
    lose = {"bench_config6": tpu_result(0.5, {**base, "pallas_vs_xla_speedup": 0.8})}
    hung = {
        "bench_config6": tpu_result(
            0.5,
            {
                **base,
                "pallas_hung": True,
                "pallas_vs_xla_speedup": None,
                "pallas_info": {"hung_after_s": 300, "hang_stage": "compile"},
            },
        )
    }
    mismatch = {
        "bench_config6": tpu_result(
            0.3,
            {**base, "pallas_vs_xla_speedup": 1.3,
             "pallas_info": {"essence_match_xla": False}},
        )
    }
    assert decide_perf.decide(win)[0]["consensus_impl"] == "pallas"
    assert decide_perf.decide(lose)[0]["consensus_impl"] == "xla"
    assert decide_perf.decide(hung)[0]["consensus_impl"] == "xla"
    assert decide_perf.decide(mismatch)[0]["consensus_impl"] == "xla"
    assert decide_perf.decide(hung)[1]["consensus_impl"]["hang_info"] is not None


def test_main_exit_3_without_measurements(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(tmp_path / "PERF_DECISIONS.json"))
    assert decide_perf.main([]) == 3
    assert not (tmp_path / "PERF_DECISIONS.json").exists()


def test_main_writes_record(tmp_path, monkeypatch):
    (tmp_path / "HW_CAMPAIGN.json").write_text(
        json.dumps(campaign([("bench_config8", tpu_result(12000.0))]))
    )
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(tmp_path / "PERF_DECISIONS.json"))
    assert decide_perf.main([]) == 0
    record = json.loads((tmp_path / "PERF_DECISIONS.json").read_text())
    assert record["flagship_variant"] == "packed"
    assert "evidence" in record and "decided_at" in record


def test_dry_run_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(tmp_path / "PERF_DECISIONS.json"))
    monkeypatch.setattr(
        decide_perf,
        "latest_tpu_results",
        lambda paths: {"bench_config8": tpu_result(12000.0)},
    )
    assert decide_perf.main(["--dry-run"]) == 0
    assert not (tmp_path / "PERF_DECISIONS.json").exists()
