"""tools/decide_perf.py — the measured-results → PERF_DECISIONS rules.

The routing record must be a pure function of qualifying TPU
measurements: CPU fallbacks never qualify, the best LOSSLESS variant
wins the flagship, and the pallas consensus routes only on a clean,
matching, faster measurement (hang ⇒ xla by walkover)."""

import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
)

import decide_perf  # noqa: E402


def tpu_result(value, extra_detail=None):
    return {
        "value": value,
        "detail": {"backend": "tpu", **(extra_detail or {})},
    }


def cpu_result(value):
    return {
        "value": value,
        "detail": {
            "backend": "cpu",
            "backend_fallback": "probe timed out",
            "small_mode": True,
        },
    }


def campaign(items, rc=0):
    return {
        "items": [
            {"name": name, "results": [{"rc": rc, "result": result}]}
            for name, result in items
        ]
    }


def write(tmp_path, data):
    path = tmp_path / "HW_CAMPAIGN.json"
    path.write_text(json.dumps(data))
    return [str(path)]


def test_cpu_fallbacks_never_qualify(tmp_path):
    paths = write(tmp_path, campaign([("bench_config0", cpu_result(9999.0))]))
    assert decide_perf.latest_tpu_results(paths) == {}
    decisions, _ = decide_perf.decide({})
    assert decisions == {}


def test_best_lossless_variant_wins(tmp_path):
    paths = write(
        tmp_path,
        campaign(
            [
                ("bench_config0", tpu_result(4500.0, {"mfu_estimate": 0.5})),
                ("bench_config8", tpu_result(12000.0, {"mfu_estimate": 0.5})),
                ("bench_config12", tpu_result(13500.0, {"mfu_estimate": 0.55})),
                ("bench_config10", tpu_result(25000.0)),  # int8: excluded
            ]
        ),
    )
    results = decide_perf.latest_tpu_results(paths)
    decisions, evidence = decide_perf.decide(results)
    assert decisions["flagship_variant"] == "packed_flash"
    assert set(evidence["flagship_variant"]) == {"dense", "packed", "packed_flash"}


def test_config0_already_routed_credits_actual_variant():
    results = {
        "bench_config0": tpu_result(12000.0, {"flagship_variant": "packed"}),
        "bench_config12": tpu_result(11000.0),
    }
    decisions, evidence = decide_perf.decide(results)
    assert decisions["flagship_variant"] == "packed"
    assert "dense" not in evidence["flagship_variant"]


def test_routed_config0_never_clobbers_better_dedicated_measurement():
    results = {
        "bench_config0": tpu_result(9000.0, {"flagship_variant": "packed"}),
        "bench_config8": tpu_result(12000.0),
        "bench_config12": tpu_result(10000.0),
    }
    decisions, evidence = decide_perf.decide(results)
    # packed keeps its dedicated 12000 measurement and wins the argmax
    assert decisions["flagship_variant"] == "packed"
    assert evidence["flagship_variant"]["packed"]["comments_per_sec"] == 12000.0


def test_failed_attempts_never_qualify(tmp_path):
    paths = write(
        tmp_path, campaign([("bench_config8", tpu_result(12000.0))], rc=1)
    )
    assert decide_perf.latest_tpu_results(paths) == {}


def test_pallas_routes_only_on_clean_win():
    base = {
        "pallas_kernel_active": True,
        "pallas_hung": False,
        "pallas_info": {"essence_match_xla": True},
        "n_oracles": 1024,
    }
    win = {"bench_config6": tpu_result(0.3, {**base, "pallas_vs_xla_speedup": 1.3})}
    lose = {"bench_config6": tpu_result(0.5, {**base, "pallas_vs_xla_speedup": 0.8})}
    hung = {
        "bench_config6": tpu_result(
            0.5,
            {
                **base,
                "pallas_hung": True,
                "pallas_vs_xla_speedup": None,
                "pallas_info": {"hung_after_s": 300, "hang_stage": "compile"},
            },
        )
    }
    mismatch = {
        "bench_config6": tpu_result(
            0.3,
            {**base, "pallas_vs_xla_speedup": 1.3,
             "pallas_info": {"essence_match_xla": False}},
        )
    }
    assert decide_perf.decide(win)[0]["consensus_impl"] == "pallas"
    assert decide_perf.decide(lose)[0]["consensus_impl"] == "xla"
    assert decide_perf.decide(hung)[0]["consensus_impl"] == "xla"
    assert decide_perf.decide(mismatch)[0]["consensus_impl"] == "xla"
    assert decide_perf.decide(hung)[1]["consensus_impl"]["hang_info"] is not None


def test_flash_diverged_verdict_excludes_packed_flash():
    """An on-TPU 'diverged' parity verdict routes the flagship back to
    the best non-flash variant (VERDICT r4 item 2); rounding-equivalent
    and unmeasured keep it eligible."""
    results = {
        "bench_config0": tpu_result(4500.0),
        "bench_config8": tpu_result(9200.0),
        "bench_config12": tpu_result(9600.0),
    }
    d_div, e_div = decide_perf.decide(dict(results), "diverged")
    assert d_div["flagship_variant"] == "packed"
    assert d_div["flash_numerics"] == "diverged"
    assert e_div["flash_numerics"]["packed_flash_eligible"] is False
    d_ok, _ = decide_perf.decide(dict(results), "rounding-equivalent")
    assert d_ok["flagship_variant"] == "packed_flash"
    d_none, e_none = decide_perf.decide(dict(results), None)
    assert d_none["flagship_variant"] == "packed_flash"
    assert "flash_numerics" not in d_none and "flash_numerics" not in e_none


def test_load_flash_verdict_requires_tpu_platform(tmp_path):
    path = tmp_path / "FLASH_PARITY.json"
    assert decide_perf.load_flash_verdict(str(tmp_path)) is None
    path.write_text(json.dumps({"platform": "cpu", "verdict": "diverged"}))
    assert decide_perf.load_flash_verdict(str(tmp_path)) is None
    path.write_text(json.dumps({"platform": "tpu", "verdict": "rounding-equivalent"}))
    assert decide_perf.load_flash_verdict(str(tmp_path)) == "rounding-equivalent"
    path.write_text("{corrupt")
    assert decide_perf.load_flash_verdict(str(tmp_path)) is None


def test_config6_hang_walkover_records_xla(tmp_path):
    """With no clean config-6 measurement but a recorded on-HW timeout,
    consensus_impl is decided 'xla' by walkover instead of staying
    pending (VERDICT r4 item 3)."""
    hang = {"item": "consensus1024", "source": "HW_QUEUE_RESULTS.json",
            "timeout_after_s": 420.1}
    decisions, evidence = decide_perf.decide(
        {"bench_config8": tpu_result(9000.0)}, None, hang
    )
    assert decisions["consensus_impl"] == "xla"
    assert evidence["consensus_impl"]["walkover"]
    # a clean config-6 result takes precedence over the hang evidence
    clean = {
        "bench_config6": tpu_result(0.3, {
            "pallas_kernel_active": True, "pallas_hung": False,
            "pallas_info": {"essence_match_xla": True},
            "pallas_vs_xla_speedup": 1.4, "n_oracles": 1024,
        })
    }
    decisions2, _ = decide_perf.decide(clean, None, hang)
    assert decisions2["consensus_impl"] == "pallas"


def test_config6_hang_evidence_requires_stage_level_records(tmp_path):
    """A whole-script timeout (dead tunnel) is NOT hang evidence; a
    consensus probe line with timeout:true (embedded stdout_tail or a
    TPU_PROBE.json entry) or a bench_config6 hard timeout is."""
    path = tmp_path / "HW_QUEUE_RESULTS.json"
    assert decide_perf.config6_hang_evidence([str(path)]) is None
    # whole-script tpu_probe timeout, no stage records: proves nothing
    path.write_text(json.dumps({"items": [
        {"name": "tpu_probe", "rc": "timeout", "seconds": 900.1,
         "stdout_tail": []},
        {"name": "bench_config0", "results": [{"rc": "timeout", "seconds": 5}]},
    ]}))
    assert decide_perf.config6_hang_evidence([str(path)]) is None
    # the round-4 shape: consensus1024 stage record inside stdout_tail,
    # neighbors alive around it
    path.write_text(json.dumps({"items": [
        {"name": "tpu_probe", "rc": "timeout", "seconds": 900.1,
         "stdout_tail": [
             '{"probe": "grid_copy", "ok": true}',
             '{"probe": "consensus1024", "ok": false, "timeout": true, "elapsed_s": 420.1}',
             "not json at all",
         ]},
    ]}))
    ev = decide_perf.config6_hang_evidence([str(path)])
    assert ev["item"] == "consensus1024" and ev["timeout_after_s"] == 420.1
    # TPU_PROBE.json shape: a top-level list of probe entries
    probe_path = tmp_path / "TPU_PROBE.json"
    probe_path.write_text(json.dumps([
        {"probe": "backend", "ok": True},
        {"probe": "consensus512", "ok": False, "timeout": True, "elapsed_s": 300.0},
    ]))
    ev2 = decide_perf.config6_hang_evidence([str(probe_path)])
    assert ev2["item"] == "consensus512"
    # bench_config6's own hard timeout qualifies (its dead-tunnel mode
    # is cpu-fallback, not timeout)
    path.write_text(json.dumps({"items": [
        {"name": "bench_config6", "results": [
            {"rc": "cpu-fallback", "seconds": 250.0},
            {"rc": "timeout", "seconds": 1810.0},
        ]},
    ]}))
    ev3 = decide_perf.config6_hang_evidence([str(path)])
    assert ev3["item"] == "bench_config6"


def test_replayed_lines_never_qualify_as_measurements(tmp_path):
    """A campaign_replay line recycled into a journal must not feed the
    routing as a fresh capture (code-review r5)."""
    replay = tpu_result(9582.95, {"replayed_from": "HW_CAMPAIGN.json"})
    paths = write(tmp_path, campaign([("bench_config12", replay)]))
    assert decide_perf.latest_tpu_results(paths) == {}


def test_iter_result_entries_tolerates_malformed_journals(tmp_path):
    path = tmp_path / "J.json"
    path.write_text(json.dumps({"items": [
        "not-a-dict",
        {"name": "a", "results": None},
        {"name": "b", "results": ["oops", {"rc": 0, "result": {"v": 1}}]},
        {"probe": "flat", "ok": True},
    ]}))
    entries = list(decide_perf.iter_result_entries([str(path)]))
    names = [n for _, n, _ in entries]
    assert names == ["a", "b", "flat"]


def test_main_exit_3_without_measurements(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(tmp_path / "PERF_DECISIONS.json"))
    assert decide_perf.main([]) == 3
    assert not (tmp_path / "PERF_DECISIONS.json").exists()


def test_main_writes_record(tmp_path, monkeypatch):
    (tmp_path / "HW_CAMPAIGN.json").write_text(
        json.dumps(campaign([("bench_config8", tpu_result(12000.0))]))
    )
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(tmp_path / "PERF_DECISIONS.json"))
    assert decide_perf.main([]) == 0
    record = json.loads((tmp_path / "PERF_DECISIONS.json").read_text())
    assert record["flagship_variant"] == "packed"
    assert "evidence" in record and "decided_at" in record


def test_main_merges_prior_record(tmp_path, monkeypatch):
    """A run that re-derives only a subset of decisions must not drop a
    previously committed flagship_variant (code-review r5)."""
    out = tmp_path / "PERF_DECISIONS.json"
    out.write_text(json.dumps({
        "flagship_variant": "packed_flash",
        "evidence": {"flagship_variant": {"packed_flash": {"comments_per_sec": 9582.95}}},
    }))
    # only hang evidence survives: no flagship measurements at all
    (tmp_path / "TPU_PROBE.json").write_text(json.dumps([
        {"probe": "consensus1024", "ok": False, "timeout": True, "elapsed_s": 420.1},
    ]))
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(out))
    assert decide_perf.main([]) == 0
    record = json.loads(out.read_text())
    assert record["consensus_impl"] == "xla"  # newly decided
    assert record["flagship_variant"] == "packed_flash"  # preserved
    assert "flagship_variant" in record["evidence"]  # evidence preserved


def test_main_carries_prior_diverged_verdict_without_artifact(
    tmp_path, monkeypatch
):
    """A committed 'diverged' verdict must keep excluding packed_flash
    on a fresh checkout where FLASH_PARITY.json is absent (code-review
    r5): the merged record may never route through a kernel it records
    as diverged."""
    out = tmp_path / "PERF_DECISIONS.json"
    out.write_text(json.dumps({
        "flagship_variant": "packed",
        "flash_numerics": "diverged",
        "evidence": {},
    }))
    (tmp_path / "HW_CAMPAIGN.json").write_text(json.dumps(campaign([
        ("bench_config8", tpu_result(9271.0)),
        ("bench_config12", tpu_result(9583.0)),  # top value, but diverged
    ])))
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(out))
    assert decide_perf.main([]) == 0
    record = json.loads(out.read_text())
    assert record["flash_numerics"] == "diverged"
    assert record["flagship_variant"] == "packed"


def test_merged_record_drops_contradictory_prior_flagship(
    tmp_path, monkeypatch, capsys
):
    """Advisor round 5: a PRIOR flagship_variant=packed_flash merged
    with a flash_numerics verdict that EXCLUDES packed_flash is a
    self-contradictory record — with no qualifying measurement to
    re-derive the routing, the stale variant must be dropped (bench.py's
    default routing takes over), with the drop recorded in evidence."""
    out = tmp_path / "PERF_DECISIONS.json"
    out.write_text(json.dumps({
        "flagship_variant": "packed_flash",
        "flash_numerics": "diverged",
        "evidence": {"flagship_variant": {"packed_flash": {}}},
    }))
    # Only consensus evidence survives — nothing re-derives the flagship.
    (tmp_path / "TPU_PROBE.json").write_text(json.dumps([
        {"probe": "consensus1024", "ok": False, "timeout": True,
         "elapsed_s": 420.1},
    ]))
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(out))
    assert decide_perf.main([]) == 0
    record = json.loads(out.read_text())
    assert record["flash_numerics"] == "diverged"
    assert "flagship_variant" not in record  # contradiction resolved
    assert "dropped" in record["evidence"]["flagship_variant"]
    assert "dropped prior flagship_variant" in capsys.readouterr().out
    # A re-derivable routing (fresh measurements present) re-routes to a
    # non-excluded variant instead of dropping.
    (tmp_path / "HW_CAMPAIGN.json").write_text(json.dumps(campaign([
        ("bench_config8", tpu_result(9271.0)),
    ])))
    assert decide_perf.main([]) == 0
    record = json.loads(out.read_text())
    assert record["flagship_variant"] == "packed"


def test_run_item_labels_replay_as_cpu_fallback(tmp_path):
    """hw_queue must not record a campaign-replay line as a fresh
    hardware capture (code-review r5)."""
    import sys

    import hw_queue

    line = json.dumps({
        "metric": "m", "value": 9582.95, "unit": "c/s", "vs_baseline": 1,
        "detail": {"backend": "tpu", "replayed_from": "HW_CAMPAIGN.json"},
    })
    out = hw_queue.run_item(
        "bench_config0", [sys.executable, "-c", f"print({line!r})"], 30
    )
    assert out["rc"] == "cpu-fallback"
    assert out["result"]["detail"]["replayed_from"]


def test_dry_run_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(tmp_path / "PERF_DECISIONS.json"))
    monkeypatch.setattr(
        decide_perf,
        "latest_tpu_results",
        lambda paths: {"bench_config8": tpu_result(12000.0)},
    )
    assert decide_perf.main(["--dry-run"]) == 0
    assert not (tmp_path / "PERF_DECISIONS.json").exists()


# ---------------------------------------------------------------------------
# Grid-format evidence (ISSUE 11 satellite): the claims A/B grid and
# the sharded-cube sweep flow through decide() instead of hand edits.
# ---------------------------------------------------------------------------


def _claims_grid(platform, mode, speedup, match=True):
    return {
        "artifact": "claim-cube pallas-vs-xla A/B grid",
        "platform": platform,
        "items": [
            {
                "metric": "claim-cube consensus 64x1024x6",
                "detail": {
                    "device_topology": {"platform": platform.split("-")[0]},
                    "pallas_ab": {
                        "pallas_mode": mode,
                        "pallas_hung": False,
                        "pallas_vs_xla_speedup": speedup,
                        "pallas_info": {"essence_match_xla": match},
                    },
                },
            }
        ],
    }


def test_claims_grid_tpu_compiled_win_routes_pallas():
    grid = _claims_grid("tpu", "compiled", 4.2)
    decisions, evidence = decide_perf.decide({}, claims_grid=grid)
    assert decisions["consensus_impl"] == "pallas"
    assert evidence["consensus_impl"]["pallas_vs_xla_speedup"] == 4.2


def test_claims_grid_interpret_only_records_xla_walkover():
    grid = _claims_grid("cpu-smoke", "interpret", None)
    decisions, evidence = decide_perf.decide({}, claims_grid=grid)
    assert decisions["consensus_impl"] == "xla"
    assert "walkover" in evidence["consensus_impl"]
    assert evidence["consensus_impl"]["tpu_grid"] is False


def test_claims_grid_never_overrides_config6_measurement():
    c6 = tpu_result(1.0)
    c6["detail"].update(
        pallas_vs_xla_speedup=2.0,
        pallas_hung=False,
        pallas_info={"essence_match_xla": True},
        pallas_kernel_active=True,
    )
    grid = _claims_grid("cpu-smoke", "interpret", None)
    decisions, _ = decide_perf.decide(
        {"bench_config6": c6}, claims_grid=grid
    )
    # The real measurement wins; the grid walkover never demotes it.
    assert decisions["consensus_impl"] == "pallas"


def _shard_grid(platform, verdict, parity=True, items=()):
    return {
        "artifact": "sharded claim-cube mesh sweep (ISSUE 11)",
        "platform": platform,
        "parity_all_zero": parity,
        "scaling_verdict": verdict,
        "scaling_vs_1x1": {"1x1": 1.0, "4x1": 1.9},
        "scaling_blocker": None if verdict == "scales" else "1 core",
        "items": list(items),
    }


def _shard_item(mesh, cps, platform="tpu"):
    return {
        "rc": 0,
        "detail": {
            "mesh": mesh,
            "sharded_claims_per_s": cps,
            "parity_max_abs_diff": 0.0,
            "device_topology": {"platform": platform},
        },
    }


def test_shard_grid_tpu_scaling_routes_best_mesh():
    grid = _shard_grid(
        "tpu",
        "scales",
        items=[_shard_item("1x1", 1000.0), _shard_item("4x1", 1900.0)],
    )
    decisions, evidence = decide_perf.decide({}, shard_grid=grid)
    assert decisions["claim_mesh"] == "4x1"
    assert evidence["claim_mesh"]["best_mesh_claims_per_s"] == 1900.0


def test_shard_grid_cpu_null_records_none():
    grid = _shard_grid(
        "cpu-simulated-devices",
        "null",
        items=[
            _shard_item("1x1", 1000.0, "cpu"),
            _shard_item("4x1", 900.0, "cpu"),
        ],
    )
    decisions, evidence = decide_perf.decide({}, shard_grid=grid)
    assert decisions["claim_mesh"] == "none"
    assert evidence["claim_mesh"]["scaling_blocker"] == "1 core"
    assert evidence["claim_mesh"]["tpu_grid"] is False


def test_shard_grid_parity_breakage_never_routes_a_mesh():
    grid = _shard_grid(
        "tpu",
        "scales",
        parity=False,
        items=[_shard_item("1x1", 1000.0), _shard_item("4x1", 1900.0)],
    )
    decisions, _ = decide_perf.decide({}, shard_grid=grid)
    assert decisions["claim_mesh"] == "none"


def test_resolve_claim_mesh_consumes_the_committed_record(tmp_path):
    from svoc_tpu.consensus.dispatch import resolve_claim_mesh

    record = tmp_path / "PERF_DECISIONS.json"
    record.write_text(json.dumps({"claim_mesh": "4x1"}))
    assert resolve_claim_mesh(path=str(record)) == "4x1"
    record.write_text(json.dumps({"claim_mesh": "none"}))
    assert resolve_claim_mesh(path=str(record)) is None


def test_claims_grid_walkover_never_demotes_prior_measured_pallas(
    tmp_path, monkeypatch
):
    """Queue artifacts reset + committed CPU grid present: the grid's
    xla walkover must not overwrite a PRIOR measured pallas routing
    through the prior-merge (code-review r11)."""
    out = tmp_path / "PERF_DECISIONS.json"
    out.write_text(
        json.dumps(
            {
                "consensus_impl": "pallas",
                "evidence": {
                    "consensus_impl": {"pallas_vs_xla_speedup": 4.0}
                },
            }
        )
    )
    monkeypatch.setattr(decide_perf, "REPO", str(tmp_path))
    monkeypatch.setattr(decide_perf, "OUT", str(out))
    monkeypatch.setattr(decide_perf, "latest_tpu_results", lambda paths: {})
    monkeypatch.setattr(
        decide_perf, "config6_hang_evidence", lambda paths: None
    )
    grid = _claims_grid("cpu-smoke", "interpret", None)
    monkeypatch.setattr(
        decide_perf,
        "load_grid",
        lambda path: grid if "CLAIMS" in path else _shard_grid(
            "cpu-simulated-devices", "null", items=[]
        ),
    )
    assert decide_perf.main([]) == 0
    record = json.loads(out.read_text())
    assert record["consensus_impl"] == "pallas"  # the measurement stands
    assert record["claim_mesh"] == "none"


# ---------------------------------------------------------------------------
# Compile plane: warmup_mode / compilation_cache from the cold-start A/B
# (ISSUE 15 satellite — host-side evidence, like commit_mode)
# ---------------------------------------------------------------------------


def _coldstart_grid(checks_override=None):
    checks = {
        "numerics_identical_across_legs": True,
        "prewarmed_speedup_ge_5": True,
        "restart_speedup_ge_5": True,
        "zero_fresh_compiles_after_restart": True,
        "cache_only_faster_than_cold": True,
    }
    checks.update(checks_override or {})
    return {
        "artifact": "BENCH_COLDSTART",
        "checks": checks,
        "speedups_vs_cold": {
            "prewarm": 63.7,
            "restart": 65.3,
            "restart_nowarm": 2.6,
        },
        "legs": {"restart": {"fresh_compiles_during_dispatch": 0}},
    }


def test_coldstart_clean_ab_routes_prewarm_and_persistent():
    decisions, evidence = decide_perf.coldstart_decisions(_coldstart_grid())
    assert decisions == {
        "warmup_mode": "prewarm",
        "compilation_cache": "persistent",
    }
    assert evidence["warmup_mode"]["host_measured"]
    assert evidence["compilation_cache"]["restart_speedup"] == 65.3
    assert "blocker" not in evidence["warmup_mode"]


def test_coldstart_fresh_compiles_block_the_cache_not_the_warmup():
    decisions, evidence = decide_perf.coldstart_decisions(
        _coldstart_grid({"zero_fresh_compiles_after_restart": False})
    )
    # The restart leg leaked compiles: the CACHE decision records the
    # honest null, but in-process prewarming still measured its win.
    assert decisions["warmup_mode"] == "prewarm"
    assert decisions["compilation_cache"] == "off"
    assert "zero_fresh_compiles_after_restart" in evidence[
        "compilation_cache"
    ]["blocker"]


def test_coldstart_numerics_break_blocks_everything():
    decisions, _evidence = decide_perf.coldstart_decisions(
        _coldstart_grid({"numerics_identical_across_legs": False})
    )
    assert decisions == {
        "warmup_mode": "none",
        "compilation_cache": "off",
    }


def test_coldstart_absent_or_malformed_grid_decides_nothing(tmp_path):
    assert decide_perf.coldstart_decisions(None) == ({}, {})
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2, 3]")
    assert decide_perf.load_coldstart_grid(str(bad)) is None
    assert (
        decide_perf.load_coldstart_grid(str(tmp_path / "absent.json"))
        is None
    )


def test_resolvers_consume_the_committed_compile_plane_record(
    tmp_path, monkeypatch
):
    from svoc_tpu.consensus.dispatch import (
        resolve_compilation_cache,
        resolve_warmup_mode,
    )

    # conftest pins both knobs off via env for suite hermeticity — the
    # env outranks the record, so clear it to exercise record routing.
    monkeypatch.delenv("SVOC_WARMUP", raising=False)
    monkeypatch.delenv("SVOC_COMPILATION_CACHE", raising=False)
    record = tmp_path / "PERF_DECISIONS.json"
    record.write_text(
        json.dumps(
            {"warmup_mode": "prewarm", "compilation_cache": "persistent"}
        )
    )
    assert resolve_warmup_mode(str(record)) == "prewarm"
    assert resolve_compilation_cache(str(record)) == "persistent"
