"""Pipeline-parallel forward vs the dense encoder (8-device CPU mesh)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.parallel.mesh import MeshSpec, make_mesh
from svoc_tpu.parallel.pipeline import pipeline_forward_fn


def batch(cfg, key, b, t=16, lengths=None):
    ids = jax.random.randint(key, (b, t), 4, cfg.vocab_size, jnp.int32)
    mask = np.ones((b, t), np.int32)
    if lengths:
        ids = np.array(ids)
        for i, ln in enumerate(lengths):
            mask[i, ln:] = 0
            ids[i, ln:] = cfg.pad_id
        ids = jnp.asarray(ids)
    return ids, jnp.asarray(mask)


def test_two_stage_pipeline_matches_dense():
    """TINY (2 layers) over 2 stages, 4 microbatches: GPipe schedule
    must be logit-exact vs the single-device encoder."""
    cfg = TINY_TEST
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    mesh = make_mesh(MeshSpec(("stage",), (2,)))
    fwd = pipeline_forward_fn(mesh, cfg, n_microbatches=4)
    ids, mask = batch(cfg, jax.random.PRNGKey(0), b=8, lengths=[16, 9, 16, 3, 16, 16, 12, 16])
    ref = model.apply(params, ids, mask)
    out = fwd(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_eight_stage_pipeline_matches_dense():
    """One layer per stage across all 8 devices (8-layer tiny config)."""
    cfg = dataclasses.replace(TINY_TEST, n_layers=8)
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=1)
    mesh = make_mesh(MeshSpec(("stage",), (8,)))
    fwd = pipeline_forward_fn(mesh, cfg, n_microbatches=2)
    ids, mask = batch(cfg, jax.random.PRNGKey(1), b=4)
    ref = model.apply(params, ids, mask)
    out = fwd(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4)


def test_pipeline_composes_with_data_parallel():
    """pp × dp: a (stage=2, data=4) mesh runs 4 independent pipeline
    replicas over batch shards."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = TINY_TEST
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=2)
    mesh = make_mesh(MeshSpec(("stage", "data"), (2, 4)))
    fwd = pipeline_forward_fn(mesh, cfg, n_microbatches=2, data_axis="data")
    ids, mask = batch(cfg, jax.random.PRNGKey(2), b=16)
    ids = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    mask = jax.device_put(mask, NamedSharding(mesh, P("data", None)))
    ref = model.apply(params, np.asarray(ids), np.asarray(mask))
    out = fwd(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pipeline_rejects_indivisible_layers():
    cfg = TINY_TEST  # 2 layers
    mesh = make_mesh(MeshSpec(("stage",), (8,)))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward_fn(mesh, cfg, n_microbatches=2)


def test_pipeline_bf16_matches_dense_encoder():
    """bf16 parity, two-tier (round-3 review finding — fp32 einsums on
    bf16 configs silently diverged):

    1. the shared encoder math is BIT-exact with the flax modules when
       both run eagerly (same op/cast order, nothing for XLA to fuse);
    2. the jitted pipeline stays within bf16-rounding distance of the
       jitted flax forward — exact bit-parity between differently-
       structured jitted graphs is not attainable, XLA freely elides
       intermediate bf16 roundings per fusion decision (~1e-2 shifts).
    """
    from svoc_tpu.parallel.encoder_math import (
        cls_head,
        embed_tokens,
        encoder_block,
        local_position_ids,
    )

    cfg = dataclasses.replace(TINY_TEST, dtype=jnp.bfloat16)
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=3)
    ids, mask = batch(cfg, jax.random.PRNGKey(3), b=4, lengths=[16, 7, 16, 11])

    # tier 1: eager shared math == eager flax, bitwise
    p = params["params"]
    x = embed_tokens(ids, local_position_ids(mask, cfg), p, cfg)
    for i in range(cfg.n_layers):
        x = encoder_block(x, mask, p[f"block_{i}"], cfg)
    manual = cls_head(x[:, 0, :].astype(cfg.dtype), p, cfg)
    eager_ref = model.apply(params, ids, mask)
    np.testing.assert_array_equal(np.asarray(manual), np.asarray(eager_ref))

    # tier 2: jitted pipeline ~ jitted flax at bf16-rounding scale
    mesh = make_mesh(MeshSpec(("stage",), (2,)))
    fwd = pipeline_forward_fn(mesh, cfg, n_microbatches=2)
    out = fwd(params, ids, mask)
    jit_ref = jax.jit(model.apply)(params, ids, mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jit_ref), atol=2e-2
    )
