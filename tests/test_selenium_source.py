"""Selenium ingest path, executed against a fake webdriver.

The image has no browser, so ``SeleniumHNSource`` was the one
import-gated, never-executed stretch of the ingest path (VERDICT r3
"missing" item 2).  A faked ``selenium`` package makes every line of it
run: construction (headless option), the reference's wait-then-extract
page flow (``client/scraper.py:25-42`` + ``hn_scraper.js:3-9``), the
scrape loop integration, the console's ``hn-live`` source selection,
and browser cleanup when a claim loses.
"""

import sys
import types

import pytest

HN_COMMENTS = ["first fake comment", "second fake comment", "third one"]


class FakeDriver:
    def __init__(self, options=None):
        self.options = options
        self.visited = []
        self.scripts = []
        self.quit_called = False

    def get(self, url):
        self.visited.append(url)

    def execute_script(self, script):
        self.scripts.append(script)
        return list(HN_COMMENTS)

    def quit(self):
        self.quit_called = True


@pytest.fixture()
def fake_selenium(monkeypatch):
    """Install a minimal selenium package into sys.modules."""
    drivers = []

    selenium = types.ModuleType("selenium")
    webdriver = types.ModuleType("selenium.webdriver")
    firefox = types.ModuleType("selenium.webdriver.firefox")
    firefox_options = types.ModuleType("selenium.webdriver.firefox.options")
    common = types.ModuleType("selenium.webdriver.common")
    by_mod = types.ModuleType("selenium.webdriver.common.by")
    support = types.ModuleType("selenium.webdriver.support")
    ui = types.ModuleType("selenium.webdriver.support.ui")

    class Options:
        def __init__(self):
            self.arguments = []

        def add_argument(self, a):
            self.arguments.append(a)

    def Firefox(options=None):
        d = FakeDriver(options)
        drivers.append(d)
        return d

    class By:
        CSS_SELECTOR = "css selector"

    class _Condition:
        def __init__(self, locator):
            self.locator = locator

        def __call__(self, driver):
            return True  # page "has" comments

    def presence_of_element_located(locator):
        return _Condition(locator)

    class WebDriverWait:
        def __init__(self, driver, timeout):
            self.driver = driver
            self.timeout = timeout

        def until(self, condition):
            assert condition(self.driver)
            return True

    webdriver.Firefox = Firefox
    firefox_options.Options = Options
    by_mod.By = By
    support.expected_conditions = types.ModuleType(
        "selenium.webdriver.support.expected_conditions"
    )
    support.expected_conditions.presence_of_element_located = (
        presence_of_element_located
    )
    ui.WebDriverWait = WebDriverWait
    selenium.webdriver = webdriver
    webdriver.firefox = firefox
    firefox.options = firefox_options
    webdriver.common = common
    common.by = by_mod
    webdriver.support = support
    support.ui = ui

    mods = {
        "selenium": selenium,
        "selenium.webdriver": webdriver,
        "selenium.webdriver.firefox": firefox,
        "selenium.webdriver.firefox.options": firefox_options,
        "selenium.webdriver.common": common,
        "selenium.webdriver.common.by": by_mod,
        "selenium.webdriver.support": support,
        "selenium.webdriver.support.expected_conditions": (
            support.expected_conditions
        ),
        "selenium.webdriver.support.ui": ui,
    }
    for name, mod in mods.items():
        monkeypatch.setitem(sys.modules, name, mod)
    return drivers


def test_selenium_source_page_flow(fake_selenium):
    from svoc_tpu.io.scraper import COMMENT_SELECTOR, HN_URL, SeleniumHNSource

    src = SeleniumHNSource(headless=True, timeout_s=3.0)
    driver = fake_selenium[0]
    assert "--headless" in driver.options.arguments

    comments = src()
    assert comments == HN_COMMENTS
    assert driver.visited == [HN_URL]
    # the reference's in-page extraction (hn_scraper.js:3-9)
    assert COMMENT_SELECTOR in driver.scripts[0]
    assert "textContent" in driver.scripts[0]

    src.close()
    assert driver.quit_called


def test_selenium_source_headful_option(fake_selenium):
    from svoc_tpu.io.scraper import SeleniumHNSource

    SeleniumHNSource(headless=False)
    assert "--headless" not in fake_selenium[0].options.arguments


def test_scrape_loop_with_selenium_source(fake_selenium):
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SeleniumHNSource, run_scraper

    store = CommentStore()
    total = run_scraper(
        store, SeleniumHNSource(), rate_s=0.0, max_rounds=2, sleep=lambda s: None
    )
    assert total == 2 * len(HN_COMMENTS)
    assert store.count() == 2 * len(HN_COMMENTS)


def _join_scraper(console, timeout=5.0):
    t = console._scraper_thread
    if t is not None:
        t.join(timeout=timeout)


def test_console_selects_hn_live_source(fake_selenium):
    """live_scraper=True + selenium present → the 'hn-live' source runs
    and fills the store; stopping releases the browser (loop-exit
    finally)."""
    import time

    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from tests.conftest import fake_sentiment_vectorizer

    session = Session(
        config=SessionConfig(scraper_rate_s=0.05, live_scraper=True),
        store=CommentStore(),
        vectorizer=fake_sentiment_vectorizer,
    )
    c = CommandConsole(session)
    out = c.query("scraper on")
    assert out == ["Scraper: ENABLED (hn-live)"]
    try:
        deadline = time.time() + 5
        while session.store.count() == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert session.store.count() >= len(HN_COMMENTS)
    finally:
        c.query("scraper off")
        c.stop()
        # Join before fixture teardown removes the fake modules — an
        # in-flight round would otherwise import real selenium and die
        # noisily in the background.
        _join_scraper(c)
    assert any(d.quit_called for d in fake_selenium), (
        "scraper stop leaked the browser (loop-exit discard)"
    )


def test_lost_claim_quits_the_browser(fake_selenium, monkeypatch):
    """A scraper claim superseded DURING its source build must quit the
    browser it launched (the supersession discard branch of
    CommandConsole._start_scraper), while the winning claim's loop
    keeps its own.  Deterministic: the first Firefox launch blocks
    until a second 'scraper on' has claimed the slot and committed."""
    import threading
    import time

    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from tests.conftest import fake_sentiment_vectorizer

    first_build_started = threading.Event()
    release_first_build = threading.Event()
    webdriver = sys.modules["selenium.webdriver"]
    orig_firefox = webdriver.Firefox
    n_builds = []

    def slow_first_firefox(options=None):
        n_builds.append(1)
        if len(n_builds) == 1:
            first_build_started.set()
            assert release_first_build.wait(5)
        return orig_firefox(options)

    monkeypatch.setattr(webdriver, "Firefox", slow_first_firefox)

    session = Session(
        config=SessionConfig(scraper_rate_s=0.05, live_scraper=True),
        store=CommentStore(),
        vectorizer=fake_sentiment_vectorizer,
    )
    c = CommandConsole(session)
    results = {}

    def first_claim():
        results["first"] = c.query("scraper on")

    t = threading.Thread(target=first_claim)
    t.start()
    try:
        assert first_build_started.wait(5)
        # Second claim wins the slot while the first is mid-build.
        out = c.query("scraper on")
        assert out == ["Scraper: ENABLED (hn-live)"]
        release_first_build.set()
        t.join(timeout=5)
        assert results["first"] == [
            "Scraper: not started (superseded or stopped)"
        ]
        # Driver construction order: the first claim blocks BEFORE its
        # FakeDriver exists, so the winner's driver is [0] and the
        # superseded claim's is [1].  The loser's must be quit; the
        # winner's loop keeps its own alive.
        deadline = time.time() + 5
        while not fake_selenium[1].quit_called and time.time() < deadline:
            time.sleep(0.02)
        assert fake_selenium[1].quit_called, "lost claim leaked its browser"
        assert not fake_selenium[0].quit_called
    finally:
        release_first_build.set()
        c.query("scraper off")
        c.stop()
        _join_scraper(c)