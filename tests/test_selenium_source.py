"""Selenium ingest path, executed against a fake webdriver.

The image has no browser, so ``SeleniumHNSource`` was the one
import-gated, never-executed stretch of the ingest path (VERDICT r3
"missing" item 2).  A faked ``selenium`` package makes every line of it
run: construction (headless option), the reference's wait-then-extract
page flow (``client/scraper.py:25-42`` + ``hn_scraper.js:3-9``), the
scrape loop integration, the console's ``hn-live`` source selection,
and browser cleanup when a claim loses.  Graceful degradation (ISSUE 3)
runs against directly-injected fake drivers: a wait timeout or one bad
post skips that unit of work, counts a ``scrape_faults`` metric, and
the scrape continues.
"""

import sys
import types

import pytest

HN_COMMENTS = ["first fake comment", "second fake comment", "third one"]


class FakeElement:
    def __init__(self, text):
        self._text = text

    def get_attribute(self, name):
        assert name == "textContent"
        return self._text


class FlakyElement:
    """A post whose extraction times out (WebDriverWait-style expiry /
    DOM churn mid-read)."""

    def get_attribute(self, name):
        from svoc_tpu.io.scraper import ScrapeTimeout

        raise ScrapeTimeout("post wait expired")


class FakeDriver:
    """Element-only fake (no execute_script): exercises the degraded
    per-element extraction path."""

    def __init__(self, options=None, elements=None):
        self.options = options
        self.visited = []
        self.quit_called = False
        self.elements = (
            [FakeElement(t) for t in HN_COMMENTS]
            if elements is None
            else elements
        )

    def get(self, url):
        self.visited.append(url)

    def find_elements(self, by, selector):
        # By.CSS_SELECTOR's literal value — the source avoids the
        # selenium import by passing the raw string.
        assert by == "css selector"
        return self.elements

    def quit(self):
        self.quit_called = True


class ScriptedFakeDriver(FakeDriver):
    """Full fake: the reference's one-round-trip in-page extraction."""

    def __init__(self, options=None, elements=None):
        super().__init__(options, elements)
        self.scripts = []

    def execute_script(self, script):
        self.scripts.append(script)
        return [e.get_attribute("textContent").strip() for e in self.elements]


@pytest.fixture()
def fake_selenium(monkeypatch):
    """Install a minimal selenium package into sys.modules."""
    drivers = []

    selenium = types.ModuleType("selenium")
    webdriver = types.ModuleType("selenium.webdriver")
    firefox = types.ModuleType("selenium.webdriver.firefox")
    firefox_options = types.ModuleType("selenium.webdriver.firefox.options")
    common = types.ModuleType("selenium.common")
    exceptions = types.ModuleType("selenium.common.exceptions")

    class Options:
        def __init__(self):
            self.arguments = []

        def add_argument(self, a):
            self.arguments.append(a)

    def Firefox(options=None):
        d = ScriptedFakeDriver(options)
        drivers.append(d)
        return d

    class TimeoutException(Exception):
        pass

    webdriver.Firefox = Firefox
    firefox_options.Options = Options
    exceptions.TimeoutException = TimeoutException
    selenium.webdriver = webdriver
    webdriver.firefox = firefox
    firefox.options = firefox_options
    selenium.common = common
    common.exceptions = exceptions

    mods = {
        "selenium": selenium,
        "selenium.webdriver": webdriver,
        "selenium.webdriver.firefox": firefox,
        "selenium.webdriver.firefox.options": firefox_options,
        "selenium.common": common,
        "selenium.common.exceptions": exceptions,
    }
    for name, mod in mods.items():
        monkeypatch.setitem(sys.modules, name, mod)
    return drivers


def test_selenium_source_page_flow(fake_selenium):
    from svoc_tpu.io.scraper import COMMENT_SELECTOR, HN_URL, SeleniumHNSource

    src = SeleniumHNSource(headless=True, timeout_s=3.0)
    driver = fake_selenium[0]
    assert "--headless" in driver.options.arguments

    comments = src()
    assert comments == HN_COMMENTS
    assert driver.visited == [HN_URL]
    # the reference's one-round-trip in-page extraction (hn_scraper.js:3-9)
    assert COMMENT_SELECTOR in driver.scripts[0]
    assert "textContent" in driver.scripts[0]

    src.close()
    assert driver.quit_called


def test_selenium_source_headful_option(fake_selenium):
    from svoc_tpu.io.scraper import SeleniumHNSource

    SeleniumHNSource(headless=False)
    assert "--headless" not in fake_selenium[0].options.arguments


def test_scrape_loop_with_selenium_source(fake_selenium):
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SeleniumHNSource, run_scraper

    store = CommentStore()
    total = run_scraper(
        store, SeleniumHNSource(), rate_s=0.0, max_rounds=2, sleep=lambda s: None
    )
    assert total == 2 * len(HN_COMMENTS)
    assert store.count() == 2 * len(HN_COMMENTS)


# ---------------------------------------------------------------------------
# Graceful degradation (ISSUE 3) — no selenium package needed: drivers
# inject directly.
# ---------------------------------------------------------------------------


def _fault_count(stage):
    from svoc_tpu.utils.metrics import registry

    return registry.counter("scrape_faults", labels={"stage": stage}).count


def test_flaky_post_is_skipped_and_counted():
    """One post timing out mid-extraction skips THAT post only."""
    from svoc_tpu.io.scraper import SeleniumHNSource

    elements = [FakeElement("a"), FlakyElement(), FakeElement("b")]
    src = SeleniumHNSource(driver=FakeDriver(elements=elements), timeout_s=1.0)
    before = _fault_count("post")
    assert src() == ["a", "b"]
    assert _fault_count("post") == before + 1


def test_page_wait_timeout_skips_round():
    """An empty/slow page past the wait deadline yields an empty round
    (counted), never an exception out of the scraper thread."""
    from svoc_tpu.io.scraper import SeleniumHNSource

    src = SeleniumHNSource(driver=FakeDriver(elements=[]), timeout_s=0.05)
    before = _fault_count("page")
    assert src() == []
    assert _fault_count("page") == before + 1


def test_script_failure_degrades_to_per_element_extraction():
    """The fast path failing (in-page script error) falls back to the
    per-element loop, which still skips individual bad posts."""
    from svoc_tpu.io.scraper import SeleniumHNSource

    class BrokenScriptDriver(FakeDriver):
        def execute_script(self, script):
            raise RuntimeError("script blew up")

    elements = [FakeElement("a"), FlakyElement(), FakeElement("b")]
    src = SeleniumHNSource(
        driver=BrokenScriptDriver(elements=elements), timeout_s=1.0
    )
    before_page, before_post = _fault_count("page"), _fault_count("post")
    assert src() == ["a", "b"]
    assert _fault_count("page") == before_page + 1
    assert _fault_count("post") == before_post + 1


def test_blank_posts_dropped():
    from svoc_tpu.io.scraper import SeleniumHNSource

    elements = [FakeElement("  keep  "), FakeElement("   "), FakeElement("")]
    src = SeleniumHNSource(driver=FakeDriver(elements=elements))
    assert src() == ["keep"]


def test_run_scraper_survives_source_failures():
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import run_scraper

    calls = []

    def source():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("browser crashed")
        return ["ok comment"]

    store = CommentStore()
    before = _fault_count("round")
    total = run_scraper(
        store, source, rate_s=0.0, max_rounds=3, sleep=lambda s: None
    )
    assert total == 2  # round 1 degraded, rounds 2-3 stored
    assert store.count() == 2
    assert _fault_count("round") == before + 1


def test_run_scraper_fault_plan_hook():
    """The chaos hook: an injected 'scrape' fault degrades exactly the
    scheduled rounds."""
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import run_scraper
    from svoc_tpu.resilience import FaultPlan, FaultSpec
    from svoc_tpu.utils.metrics import MetricsRegistry

    plan = FaultPlan(
        0,
        [FaultSpec(op="scrape", max_fires=1)],
        registry=MetricsRegistry(),
    )
    store = CommentStore()
    total = run_scraper(
        store,
        lambda: ["x"],
        rate_s=0.0,
        max_rounds=3,
        sleep=lambda s: None,
        fault_plan=plan,
    )
    assert total == 2  # first round injected, two landed
    assert len(plan.history()) == 1


def _join_scraper(console, timeout=5.0):
    t = console._scraper_thread
    if t is not None:
        t.join(timeout=timeout)


def test_console_selects_hn_live_source(fake_selenium):
    """live_scraper=True + selenium present → the 'hn-live' source runs
    and fills the store; stopping releases the browser (loop-exit
    finally)."""
    import time

    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from tests.conftest import fake_sentiment_vectorizer

    session = Session(
        config=SessionConfig(scraper_rate_s=0.05, live_scraper=True),
        store=CommentStore(),
        vectorizer=fake_sentiment_vectorizer,
    )
    c = CommandConsole(session)
    out = c.query("scraper on")
    assert out == ["Scraper: ENABLED (hn-live)"]
    try:
        deadline = time.time() + 5
        while session.store.count() == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert session.store.count() >= len(HN_COMMENTS)
    finally:
        c.query("scraper off")
        c.stop()
        # Join before fixture teardown removes the fake modules — an
        # in-flight round would otherwise import real selenium and die
        # noisily in the background.
        _join_scraper(c)
    assert any(d.quit_called for d in fake_selenium), (
        "scraper stop leaked the browser (loop-exit discard)"
    )


def test_lost_claim_quits_the_browser(fake_selenium, monkeypatch):
    """A scraper claim superseded DURING its source build must quit the
    browser it launched (the supersession discard branch of
    CommandConsole._start_scraper), while the winning claim's loop
    keeps its own.  Deterministic: the first Firefox launch blocks
    until a second 'scraper on' has claimed the slot and committed."""
    import threading
    import time

    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from tests.conftest import fake_sentiment_vectorizer

    first_build_started = threading.Event()
    release_first_build = threading.Event()
    webdriver = sys.modules["selenium.webdriver"]
    orig_firefox = webdriver.Firefox
    n_builds = []

    def slow_first_firefox(options=None):
        n_builds.append(1)
        if len(n_builds) == 1:
            first_build_started.set()
            assert release_first_build.wait(5)
        return orig_firefox(options)

    monkeypatch.setattr(webdriver, "Firefox", slow_first_firefox)

    session = Session(
        config=SessionConfig(scraper_rate_s=0.05, live_scraper=True),
        store=CommentStore(),
        vectorizer=fake_sentiment_vectorizer,
    )
    c = CommandConsole(session)
    results = {}

    def first_claim():
        results["first"] = c.query("scraper on")

    t = threading.Thread(target=first_claim)
    t.start()
    try:
        assert first_build_started.wait(5)
        # Second claim wins the slot while the first is mid-build.
        out = c.query("scraper on")
        assert out == ["Scraper: ENABLED (hn-live)"]
        release_first_build.set()
        t.join(timeout=5)
        assert results["first"] == [
            "Scraper: not started (superseded or stopped)"
        ]
        # Driver construction order: the first claim blocks BEFORE its
        # FakeDriver exists, so the winner's driver is [0] and the
        # superseded claim's is [1].  The loser's must be quit; the
        # winner's loop keeps its own alive.
        deadline = time.time() + 5
        while not fake_selenium[1].quit_called and time.time() < deadline:
            time.sleep(0.02)
        assert fake_selenium[1].quit_called, "lost claim leaked its browser"
        assert not fake_selenium[0].quit_called
    finally:
        release_first_build.set()
        c.query("scraper off")
        c.stop()
        _join_scraper(c)
