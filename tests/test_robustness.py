"""Byzantine-oracle hardening suite (docs/ROBUSTNESS.md).

Covers the ISSUE-4 surface end to end: attack strategies, the batched
breakdown sweep + certificate, the quarantine gate (host and in-graph
twins), the gated consensus kernel/shard_map, the gated commit path
(skip slots, health accounting, faithful refusal), felt decode
boundaries, saturating wsad ops, and the seeded Byzantine chaos
scenario's replay/acceptance invariants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.consensus.kernel import (
    ConsensusConfig,
    consensus_step,
    consensus_step_gated,
    consensus_step_gated_batched,
)
from svoc_tpu.robustness.attacks import ATTACK_NAMES, apply_attack
from svoc_tpu.robustness.certify import breakdown_sweep, certificate
from svoc_tpu.robustness.sanitize import (
    WSAD_LIMIT,
    QuarantinedInputError,
    QuarantineGate,
    SanitizeConfig,
    quarantine_mask_jax,
    quarantine_reasons_jax,
)
from svoc_tpu.utils.metrics import MetricsRegistry

CFG = ConsensusConfig(n_failing=2, constrained=True)


def _fleet(seed=0, n=8, m=6, lo=0.1, hi=0.9):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.uniform(lo, hi, (n, m)), jnp.float32)


class TestAttacks:
    @pytest.mark.parametrize("attack_id", range(len(ATTACK_NAMES)))
    def test_attacks_touch_only_colluder_slots(self, attack_id):
        values = _fleet()
        mask = jnp.asarray([True, False, True, False] + [False] * 4)
        out = apply_attack(
            jax.random.PRNGKey(1), values, mask, attack_id, 0.4, 2
        )
        changed = np.any(
            np.asarray(out) != np.asarray(values), axis=-1
        )
        np.testing.assert_array_equal(changed, np.asarray(mask))

    @pytest.mark.parametrize("attack_id", range(len(ATTACK_NAMES)))
    def test_attacks_emit_gate_admissible_values(self, attack_id):
        """Clipped attacks stay syntactically valid — the whole point
        of the taxonomy is adversaries the gate CANNOT catch."""
        out = apply_attack(
            jax.random.PRNGKey(2),
            _fleet(),
            jnp.asarray([True] * 4 + [False] * 4),
            attack_id,
            5.0,  # absurd magnitude: the clip must still hold
            2,
        )
        ok = quarantine_mask_jax(out, 0.0, 1.0)
        assert bool(jnp.all(ok))

    def test_cluster_attack_is_masked_at_design_fraction(self):
        """n_failing colluders planted far off-center are exactly the
        oracles the two-pass mask drops."""
        values = _fleet(lo=0.4, hi=0.6)
        mask = jnp.asarray([True, True] + [False] * 6)
        attacked = apply_attack(
            jax.random.PRNGKey(3), values, mask, ATTACK_NAMES.index("cluster"),
            0.9, 2,
        )
        out = consensus_step(attacked, CFG)
        reliable = np.asarray(out.reliable)
        assert not reliable[0] and not reliable[1]
        assert reliable[2:].all()

    def test_straddle_attacks_above_the_design_budget(self):
        """k > n_failing colluders: the straddle cut must clamp into
        the honest subset — the all-slots rank would hit the +inf tail
        and the isfinite fallback would park the whole coalition at
        the honest center (a no-op attack masquerading as tolerated)."""
        values = _fleet(lo=0.4, hi=0.6)
        aid = ATTACK_NAMES.index("straddle")
        for k in (3, 4):  # both above n_failing=2
            mask = jnp.asarray([True] * k + [False] * (8 - k))
            attacked = apply_attack(
                jax.random.PRNGKey(5), values, mask, aid, 0.4, 2
            )
            center = np.median(np.asarray(values)[k:], axis=0)
            dist = np.linalg.norm(
                np.asarray(attacked)[:k] - center[None, :], axis=-1
            )
            # Colluders sit on a real boundary band, not at jitter
            # distance (the 1e-3 noise) from the center.
            assert (dist > 0.02).all(), dist

    def test_drift_scales_with_round_frac(self):
        values = _fleet()
        mask = jnp.asarray([True] + [False] * 7)
        aid = ATTACK_NAMES.index("drift")
        key = jax.random.PRNGKey(4)
        early = apply_attack(
            key, values, mask, aid, 0.6, 2, round_frac=0.1, clip=None
        )
        late = apply_attack(
            key, values, mask, aid, 0.6, 2, round_frac=1.0, clip=None
        )
        d_early = float(jnp.linalg.norm(early[0] - values[0]))
        d_late = float(jnp.linalg.norm(late[0] - values[0]))
        assert d_late > d_early * 5


class TestCertify:
    @pytest.fixture(scope="class")
    def sweep(self):
        return breakdown_sweep(
            jax.random.PRNGKey(0),
            CFG,
            n_oracles=8,
            colluder_counts=[0, 1, 2, 3],
            magnitudes=[0.45],
            n_trials=8,
        )

    def test_zero_colluders_zero_deviation(self, sweep):
        for cell in sweep["cells"]:
            if cell.colluders == 0:
                assert cell.mean_deviation == 0.0
                assert cell.mean_capture == 0.0

    def test_grid_is_complete(self, sweep):
        assert len(sweep["cells"]) == len(ATTACK_NAMES) * 4 * 1
        assert set(sweep["benign_deviation"]) == {0, 1, 2, 3}

    def test_certificate_tolerates_design_fraction(self, sweep):
        cert = certificate(sweep)
        assert cert["certified"]
        for attack, entry in cert["attacks"].items():
            assert (
                entry["tolerated_fraction"] >= cert["design_fraction"]
            ), attack

    def test_certificate_is_prefix_monotone(self):
        """A passing count ABOVE a failing one must not extend the
        certificate."""
        sweep = breakdown_sweep(
            jax.random.PRNGKey(1),
            CFG,
            n_oracles=8,
            colluder_counts=[0, 1, 2],
            magnitudes=[0.45],
            attacks=("cluster",),
            n_trials=4,
        )
        # Forge a gap: count 1 fails, count 2 passes.
        for cell in sweep["cells"]:
            if cell.colluders == 1:
                object.__setattr__(cell, "mean_deviation", 99.0)
        cert = certificate(sweep)
        assert cert["attacks"]["cluster"]["tolerated_colluders"] == 0

    def test_attack_subset_uses_global_taxonomy_ids(self):
        """A sweep over a non-prefix attack SUBSET must evaluate that
        attack, not whatever sits at the subset position in the global
        ``lax.switch`` table (straddle at subset position 0 must not
        silently run cluster)."""
        kw = dict(
            n_oracles=8,
            colluder_counts=[2],
            magnitudes=[0.45],
            n_trials=8,
        )
        full = breakdown_sweep(jax.random.PRNGKey(0), CFG, **kw)
        sub = breakdown_sweep(
            jax.random.PRNGKey(0), CFG, attacks=("straddle",), **kw
        )
        ref = {
            (c.attack, c.colluders, c.magnitude): c.mean_deviation
            for c in full["cells"]
        }
        (cell,) = sub["cells"]
        assert cell.attack == "straddle"
        # Attack keys fold in the CELL index, so the 1e-3 intra-
        # coalition jitter differs between the two grids — agreement
        # is to jitter tolerance, which still cleanly separates
        # straddle (~0.02 here) from cluster (~10x that).
        assert cell.mean_deviation == pytest.approx(
            ref[("straddle", 2, 0.45)], rel=0.02
        )

    def test_drift_cells_cover_the_schedule_not_the_endpoint(self, sweep):
        """Drift trials run at round_frac=(i+1)/T — a drift cell's mean
        deviation must sit strictly BELOW its shift twin's (which hits
        full magnitude every trial), or the schedule isn't being
        exercised and drift degenerates into a shift duplicate."""
        by = {
            (c.attack, c.colluders): c.mean_deviation
            for c in sweep["cells"]
            if c.magnitude == 0.45
        }
        for k in (2, 3):
            assert by[("drift", k)] < by[("shift", k)] * 0.999


class TestQuarantineGate:
    def test_reasons_and_precedence(self):
        gate = QuarantineGate(SanitizeConfig(0.0, 1.0), MetricsRegistry())
        block = np.full((5, 3), 0.5)
        block[1, 0] = np.nan
        block[2, 1] = np.inf
        block[3, 2] = 1.5
        report = gate.inspect(block)
        assert report.reasons == {1: "nan", 2: "inf", 3: "range"}
        np.testing.assert_array_equal(
            report.ok, [True, False, False, False, True]
        )
        # NaN wins over a simultaneous range violation.
        both = np.full((1, 3), 2.0)
        both[0, 1] = np.nan
        assert gate.inspect(both).reasons == {0: "nan"}

    def test_codec_reason_unconstrained(self):
        gate = QuarantineGate(SanitizeConfig(None, None), MetricsRegistry())
        block = np.full((2, 3), 1e20)
        block[1, 0] = WSAD_LIMIT * 2
        report = gate.inspect(block)
        assert report.reasons == {1: "codec"}

    def test_jax_twin_matches_host_gate(self):
        rng = np.random.default_rng(5)
        block = rng.uniform(-0.5, 1.5, (16, 6))
        block[3, 0] = np.nan
        block[7, 5] = -np.inf
        gate = QuarantineGate(SanitizeConfig(0.0, 1.0), MetricsRegistry())
        host = gate.inspect(block)
        dev = np.asarray(quarantine_mask_jax(jnp.asarray(block), 0.0, 1.0))
        np.testing.assert_array_equal(host.ok, dev)

    def test_jax_reason_masks_are_disjoint(self):
        block = np.full((4, 2), 0.5)
        block[0, 0] = np.nan
        block[1, 0] = np.inf
        block[2, 0] = -3.0
        masks = quarantine_reasons_jax(jnp.asarray(block), 0.0, 1.0)
        stacked = np.stack(
            [np.asarray(m) for m in masks]
        )
        assert (stacked.sum(axis=0) <= 1).all()

    def test_metrics_counted_once(self):
        reg = MetricsRegistry()
        gate = QuarantineGate(SanitizeConfig(0.0, 1.0), reg)
        block = np.full((2, 2), 0.5)
        block[0, 0] = np.nan
        gate.inspect(block)
        gate.inspect(block, count=False)
        assert reg.family_total("oracle_quarantine") == 1


class TestGatedKernel:
    def test_all_ones_mask_equals_plain_step(self):
        values = _fleet()
        plain = consensus_step(values, CFG)
        gated = consensus_step_gated(values, jnp.ones(8, bool), CFG)
        for name in plain._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(plain, name)),
                np.asarray(getattr(gated, name)),
                atol=1e-6,
                err_msg=name,
            )

    def test_nan_vector_never_poisons_and_never_reliable(self):
        values = _fleet().at[3].set(jnp.nan)
        ok = quarantine_mask_jax(values, 0.0, 1.0)
        out = consensus_step_gated(values, ok, CFG)
        assert not bool(out.reliable[3])
        for leaf in (out.essence, out.skewness, out.kurtosis,
                     out.reliability_first_pass, out.reliability_second_pass):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_mask_budget_drops_worst_of_admitted(self):
        """Quarantine must not absorb the n_failing budget: with one
        quarantined and two Byzantine-but-admitted outliers, the
        outliers still get dropped."""
        values = _fleet(lo=0.45, hi=0.55)
        values = values.at[0].set(0.95).at[1].set(0.95)
        values = values.at[2].set(jnp.nan)
        ok = quarantine_mask_jax(values, 0.0, 1.0)
        out = consensus_step_gated(values, ok, CFG)
        reliable = np.asarray(out.reliable)
        assert not reliable[0] and not reliable[1] and not reliable[2]
        assert reliable.sum() == 5  # 7 admitted - n_failing

    def test_all_quarantined_block_is_invalid_not_nan(self):
        values = jnp.full((6, 4), jnp.nan)
        out = consensus_step_gated(values, jnp.zeros(6, bool), CFG)
        assert not bool(out.interval_valid)
        assert np.all(np.isfinite(np.asarray(out.essence)))
        assert np.all(np.isfinite(np.asarray(out.skewness)))

    def test_batched_form_matches_loop(self):
        rng = np.random.default_rng(7)
        blocks = jnp.asarray(rng.uniform(0.1, 0.9, (3, 8, 6)), jnp.float32)
        blocks = blocks.at[1, 2].set(jnp.nan)
        ok = jax.vmap(lambda v: quarantine_mask_jax(v, 0.0, 1.0))(blocks)
        batched = consensus_step_gated_batched(blocks, ok, CFG)
        for b in range(3):
            single = consensus_step_gated(blocks[b], ok[b], CFG)
            np.testing.assert_allclose(
                np.asarray(batched.essence[b]),
                np.asarray(single.essence),
                atol=1e-6,
            )


class TestDegenerateKernel:
    """Satellite: n_failing >= N-1 must yield interval_valid=False."""

    @pytest.mark.parametrize("n_failing", [7, 8, 20])
    def test_plain_step_degenerate_is_invalid(self, n_failing):
        out = consensus_step(_fleet(), ConsensusConfig(n_failing=n_failing))
        assert not bool(out.interval_valid)
        for leaf in (out.skewness, out.kurtosis):
            assert not np.any(np.isnan(np.asarray(leaf)))

    def test_plain_step_minimum_viable_block_stays_valid(self):
        out = consensus_step(_fleet(), ConsensusConfig(n_failing=6))
        # 2 reliable oracles: still a (thin) consensus.
        assert bool(out.interval_valid)


class TestGatedCommitPath:
    def _session(self, registry=None):
        from conftest import fake_sentiment_vectorizer

        from svoc_tpu.apps.session import Session, SessionConfig
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.io.scraper import SyntheticSource

        store = CommentStore()
        store.save(SyntheticSource(batch=120)())
        return Session(
            config=SessionConfig(),
            store=store,
            vectorizer=fake_sentiment_vectorizer,
        )

    def test_clean_fetch_reports_clean_gate(self):
        session = self._session()
        preview = session.fetch()
        assert preview["quarantine"]["quarantined"] == []
        assert preview["quarantine"]["admitted"] == 7
        snap = session.resilience_snapshot()
        assert snap["input_quarantine"]["quarantined"] == []

    def test_faithful_commit_refuses_dirty_block(self):
        session = self._session()
        session.fetch()
        with session.lock:
            session.predictions[2, 0] = np.nan
        with pytest.raises(QuarantinedInputError) as e:
            session.commit()
        assert e.value.report.reasons == {2: "nan"}
        # No tx reached the chain.
        assert not session.adapter.call_consensus_active()

    def test_resilient_commit_skips_and_charges_health(self):
        session = self._session()
        session.fetch()
        with session.lock:
            session.predictions[4, 1] = np.inf
        outcome = session.commit_resilient()
        assert outcome.sent == 6
        assert outcome.complete  # skips are not failures
        # The skipped oracle never committed: consensus (which needs
        # every oracle) is still inactive, and the supervisor holds a
        # pending quarantine penalty for slot 4's address.
        assert not session.adapter.call_consensus_active()
        addr = session.adapter.call_oracle_list()[4]
        assert (
            session.supervisor._pending_failures[addr]
            == session.config.supervisor.quarantine_penalty
        )

    def test_skip_indices_excluded_from_chain_loop(self):
        from svoc_tpu.resilience.retry import (
            RetryPolicy,
            commit_fleet_with_resume,
        )

        session = self._session()
        session.fetch()
        outcome = commit_fleet_with_resume(
            session.adapter,
            session.predictions,
            RetryPolicy(max_attempts=2, base_s=0.0, cap_s=0.0, jitter_seed=0),
            skip=(0, 3),
            sleep=lambda s: None,
        )
        assert outcome.sent == 5
        assert outcome.complete

    def test_resume_past_skipped_slot_still_complete(self):
        """A transient failure AFTER a quarantine-skipped slot: the
        resumed cycle must land every eligible tx and report
        complete=True — skipped slots are excluded from ``total``
        exactly as from ``sent``, even across a resume — and the
        zero-progress breaker accounting must count LANDED txs, not
        the skip-advanced index delta."""
        from svoc_tpu.resilience.breaker import CircuitBreaker
        from svoc_tpu.resilience.retry import (
            RetryPolicy,
            commit_fleet_with_resume,
        )
        from test_resilience import FlakyOracleBackend

        from svoc_tpu.consensus.state import OracleConsensusContract
        from svoc_tpu.io.chain import ChainAdapter

        contract = OracleConsensusContract(
            admins=[0xA0, 0xA1, 0xA2],
            oracles=[0x10 + i for i in range(7)],
            required_majority=2,
            n_failing_oracles=2,
            constrained=True,
            dimension=3,
        )
        # Slot 2 fails once (transient); slot 0 is quarantine-skipped.
        backend = FlakyOracleBackend(contract, {0x12: 1})
        adapter = ChainAdapter(backend)
        breaker = CircuitBreaker(failure_threshold=2, registry=None)
        predictions = np.full((7, 3), 0.5)
        outcome = commit_fleet_with_resume(
            adapter,
            predictions,
            RetryPolicy(max_attempts=3, base_s=0.0, cap_s=0.0, jitter_seed=0),
            breaker=breaker,
            skip=(0,),
            sleep=lambda s: None,
        )
        assert outcome.sent == 6
        assert outcome.total == 6
        assert outcome.complete
        assert outcome.stranded == ()

    def test_zero_progress_failure_behind_skip_counts_on_breaker(self):
        """Slot 0 skipped, slot 1 (the first attempted tx) hard-down:
        both attempts land ZERO txs, so both must record breaker
        failures even though the failure index (1) is past start (0)
        — with threshold 2 the breaker OPENS before the stranded
        resume can proceed.  (The index-delta accounting would have
        credited attempt 1 as progress and never tripped.)"""
        from svoc_tpu.resilience.breaker import CircuitBreaker
        from svoc_tpu.resilience.retry import (
            CircuitOpenError,
            RetryPolicy,
            commit_fleet_with_resume,
        )
        from svoc_tpu.utils.metrics import MetricsRegistry
        from test_resilience import FlakyOracleBackend

        from svoc_tpu.consensus.state import OracleConsensusContract
        from svoc_tpu.io.chain import ChainAdapter

        contract = OracleConsensusContract(
            admins=[0xA0, 0xA1, 0xA2],
            oracles=[0x10 + i for i in range(7)],
            required_majority=2,
            n_failing_oracles=2,
            constrained=True,
            dimension=3,
        )
        backend = FlakyOracleBackend(contract, {0x11: 10**9})
        adapter = ChainAdapter(backend)
        breaker = CircuitBreaker(
            failure_threshold=2, registry=MetricsRegistry()
        )
        with pytest.raises(CircuitOpenError):
            commit_fleet_with_resume(
                adapter,
                np.full((7, 3), 0.5),
                RetryPolicy(
                    max_attempts=2, base_s=0.0, cap_s=0.0, jitter_seed=0
                ),
                breaker=breaker,
                skip=(0,),
                sleep=lambda s: None,
            )

    def test_chain_skip_validates_indices(self):
        session = self._session()
        session.fetch()
        with pytest.raises(ValueError):
            session.adapter.update_all_the_predictions(
                session.predictions, skip=(99,)
            )
        with pytest.raises(ValueError):
            session.adapter.update_all_the_predictions(
                session.predictions, batch=True, skip=(1,)
            )


class TestByzantineScenario:
    @pytest.fixture(scope="class")
    def runs(self):
        from svoc_tpu.resilience.chaos import run_byzantine_scenario

        return run_byzantine_scenario(0), run_byzantine_scenario(0)

    def test_replay_is_bit_identical(self, runs):
        first, second = runs
        assert first["fingerprint"] == second["fingerprint"]

    def test_all_injections_quarantined_zero_false(self, runs):
        first, _ = runs
        assert first["injections"] > 0
        assert first["missed_injections"] == 0
        assert first["false_quarantines"] == 0

    def test_offenders_voted_out_and_consensus_holds(self, runs):
        first, _ = runs
        assert first["colluders_voted_out"]
        assert first["injector_voted_out"]
        assert first["consensus_active"]
        assert first["essence_in_band"]
        assert first["duplicate_txs"] == 0


class TestFeltBoundaries:
    """Satellite: felt decode must refuse out-of-window calldata."""

    def test_valid_windows_roundtrip(self):
        from svoc_tpu.ops.fixedpoint import (
            felt_to_wsad,
            wsad_to_felt,
        )

        for w in (0, 1, -1, 10**18, -(10**18), 2**127 - 1, -(2**127)):
            assert felt_to_wsad(wsad_to_felt(w)) == w

    @pytest.mark.parametrize(
        "felt",
        [
            -1,
            2**127,  # dead zone start (I128_MAX + 1)
            2**200,  # deep dead zone
            # one below the negative window
            3618502788666131213697322783095070105623107215331596699973092056135872020481
            - 2**127
            - 1,
            # the prime itself and beyond
            3618502788666131213697322783095070105623107215331596699973092056135872020481,
            3618502788666131213697322783095070105623107215331596699973092056135872020481
            + 5,
        ],
    )
    def test_out_of_window_felts_raise(self, felt):
        from svoc_tpu.ops.fixedpoint import FeltRangeError, felt_to_wsad

        with pytest.raises(FeltRangeError):
            felt_to_wsad(felt)

    def test_decode_vector_validates(self):
        from svoc_tpu.ops.fixedpoint import FeltRangeError, decode_vector

        with pytest.raises(FeltRangeError):
            decode_vector([500_000, 2**127])

    def test_window_edges_decode(self):
        from svoc_tpu.ops.fixedpoint import (
            FELT_PRIME,
            I128_MAX,
            I128_MIN,
            felt_to_wsad,
        )

        assert felt_to_wsad(I128_MAX) == I128_MAX
        assert felt_to_wsad(FELT_PRIME + I128_MIN) == I128_MIN


class TestSaturatingOps:
    def test_add_saturates_never_wraps(self):
        from svoc_tpu.ops.fixedpoint import (
            I128_MAX,
            I128_MIN,
            wsad_add_sat,
        )

        assert wsad_add_sat(I128_MAX, 1) == I128_MAX
        assert wsad_add_sat(I128_MIN, -1) == I128_MIN
        assert wsad_add_sat(5, 7) == 12
        assert wsad_add_sat(I128_MAX, I128_MAX) == I128_MAX

    def test_mul_saturates_and_counts(self):
        from svoc_tpu.ops.fixedpoint import I128_MAX, I128_MIN, wsad_mul, wsad_mul_sat
        from svoc_tpu.utils.metrics import registry

        before = registry.family_total("wsad_overflows")
        big = 2**100
        assert wsad_mul_sat(big, big) == I128_MAX
        assert wsad_mul_sat(big, -big) == I128_MIN
        assert registry.family_total("wsad_overflows") == before + 2
        # In-range products match the exact op bit for bit.
        assert wsad_mul_sat(1_500_000, 2_000_000) == wsad_mul(
            1_500_000, 2_000_000
        )


class TestGatedSharding:
    @pytest.fixture(scope="class")
    def mesh(self):
        from svoc_tpu.parallel.serving import serving_mesh

        return serving_mesh()

    def test_ungated_sharded_degenerate_block_is_invalid(self, mesh):
        """Kernel parity for the n_failing >= N-1 guard: the UNGATED
        sharded body must flag the degenerate config invalid too, not
        report a confident one-oracle essence."""
        from svoc_tpu.parallel.sharded import sharded_consensus_fn

        n = 8
        deg = ConsensusConfig(n_failing=n - 1, constrained=True)
        fn = sharded_consensus_fn(mesh, deg, axis="data")
        vals = _fleet(n=n)
        out = fn(vals)
        assert not bool(np.asarray(out.interval_valid))
        ref = consensus_step(vals, deg)
        assert not bool(np.asarray(ref.interval_valid))

    def test_gated_matches_ungated_on_clean_window(self, mesh):
        from svoc_tpu.parallel.serving import fleet_step_fn

        rng = np.random.default_rng(9)
        window = jnp.asarray(rng.uniform(0.2, 0.8, (50, 6)), jnp.float32)
        key = jax.random.PRNGKey(0)
        plain = fleet_step_fn(mesh, CFG, 16)
        gated = fleet_step_fn(mesh, CFG, 16, gate=(0.0, 1.0))
        out_p, honest_p = plain(key, window)
        out_g, honest_g, admitted = gated(key, window)
        assert np.asarray(admitted).all()
        np.testing.assert_array_equal(
            np.asarray(honest_p), np.asarray(honest_g)
        )
        for name in out_p._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(out_p, name)),
                np.asarray(getattr(out_g, name)),
                atol=1e-6,
                err_msg=name,
            )

    def test_poisoned_window_is_contained(self, mesh):
        from svoc_tpu.parallel.serving import fleet_step_fn

        rng = np.random.default_rng(10)
        window = jnp.asarray(rng.uniform(0.2, 0.8, (50, 6)), jnp.float32)
        wbad = window.at[:, 0].set(jnp.nan)
        gated = fleet_step_fn(mesh, CFG, 16, gate=(0.0, 1.0))
        out, _honest, admitted = gated(jax.random.PRNGKey(0), wbad)
        # Every bootstrap averages some poisoned comment → only the
        # uniform "failing" oracles survive the gate; the step must
        # flag itself invalid and stay finite, never NaN-poisoned.
        assert not bool(out.interval_valid)
        assert np.all(np.isfinite(np.asarray(out.essence)))
        assert int(np.asarray(admitted).sum()) < 16
