"""Property-based tests (hypothesis) for the numeric foundations.

The example-based suites pin Cairo fixtures and reference recordings;
these cover the INVARIANTS across arbitrary inputs — codec round trips,
sort/rank permutation laws, packing bijections, consensus mask
cardinality — where a counterexample means a real parity bug, not a
tolerance issue.  Deadlines are disabled: jit compilation on first
example would trip hypothesis's per-example timer.
"""

import math
from fractions import Fraction

import jax.numpy as jnp
import numpy as np
import pytest

# Optional dependency (pyproject [test] extra): without it this module
# skips at collection instead of erroring out of the tier-1 run.
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, assume, given, settings
from hypothesis import strategies as st

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.models.packing import pack_tokens
from svoc_tpu.ops.fixedpoint import (
    FELT_PRIME,
    WSAD,
    div_trunc,
    felt_to_wsad,
    float_to_fwsad,
    fwsad_to_float,
    to_wsad,
    wsad_mul,
    wsad_to_felt,
)
from svoc_tpu.ops.sort import indexed_sort_host, reliability_mask
from svoc_tpu.ops.stats import rank_array

COMMON = settings(
    deadline=None,
    max_examples=60,
    suppress_health_check=[HealthCheck.too_slow],
)

# wsad ints that survive the i128 range with room for mul's rescale.
wsad_ints = st.integers(min_value=-(10**15), max_value=10**15)
floats_unit = st.floats(
    min_value=-1e6, max_value=1e6, allow_nan=False, allow_infinity=False
)


class TestFixedpointProperties:
    @COMMON
    @given(floats_unit)
    def test_float_felt_roundtrip_within_grid(self, x):
        """float → felt252 → float loses at most one wsad step (the
        codec TRUNCATES like the reference's ``int(x*1e6)``,
        ``client/contract.py:48-49`` — not round-to-nearest)."""
        back = fwsad_to_float(float_to_fwsad(x))
        assert abs(back - x) < 1.0 / WSAD + 1e-9
        assert abs(back) <= abs(x) + 1e-12  # truncation: toward zero

    @COMMON
    @given(wsad_ints)
    def test_felt_wrap_is_involutive(self, w):
        felt = wsad_to_felt(w)
        assert 0 <= felt < FELT_PRIME
        assert felt_to_wsad(felt) == w

    @COMMON
    @given(floats_unit)
    def test_to_wsad_truncates_toward_zero(self, x):
        w = to_wsad(x)
        assert abs(w) <= abs(x) * WSAD + 1e-6  # never rounds away from zero
        assert abs(w / WSAD - x) < 1.0 / WSAD + 1e-9

    @COMMON
    @given(wsad_ints, wsad_ints)
    def test_wsad_mul_matches_independent_rational_oracle(self, a, b):
        """signed_decimal.cairo:110-112 semantics via an INDEPENDENT
        oracle: exact rational (a·b + WSAD/2) / WSAD truncated toward
        zero with Fraction/math.trunc — shares no code with the
        implementation's div_trunc."""
        expected = math.trunc(Fraction(a * b + WSAD // 2, WSAD))
        assert wsad_mul(a, b) == expected

    def test_wsad_mul_signed_pinned_cases(self):
        """Hand-derived signed cases (wsad scale 1e6): the +HALF bias is
        added BEFORE the truncating division, so negative products round
        toward zero asymmetrically."""
        # 1.5 * 2.0 = 3.0
        assert wsad_mul(1_500_000, 2_000_000) == 3_000_000
        # (-3) * 0.5: a·b = -1.5e12; +HALF → -1_499_999_500_000;
        # truncating division by 1e6 gives -1_499_999 — i.e. -1.499999,
        # one ulp toward zero from the exact -1.5 (the bias is ADDED,
        # not sign-symmetric; Cairo's i128 div truncates toward zero)
        assert wsad_mul(-3_000_000, 500_000) == -1_499_999
        # one ulp * 1.0: (1_000_000 + 500_000)/1e6 truncates to 1 —
        # the +HALF bias rounds the positive half-ulp UP
        assert wsad_mul(1, 1_000_000) == 1
        # minus one ulp * 1.0 → (-1_000_000 + 500_000)/1e6 truncates
        # to 0 — the same bias rounds the negative half-ulp up too
        assert wsad_mul(-1, 1_000_000) == 0

    @COMMON
    @given(st.integers(-(10**18), 10**18), st.integers(-(10**18), 10**18))
    def test_div_trunc_truncates_toward_zero(self, a, b):
        assume(b != 0)
        q = div_trunc(a, b)
        assert abs(q) == abs(a) // abs(b)
        assert q * a * b >= 0 or q == 0  # sign follows a*b


class TestSortRankProperties:
    @COMMON
    @given(st.lists(st.integers(-(10**9), 10**9), min_size=1, max_size=40))
    def test_indexed_sort_permutation_with_cairo_tie_order(self, values):
        """IndexedMergeSort parity: output values ascending, indices a
        permutation, and ties in DESCENDING original-index order — the
        Cairo merge takes the right element on ties
        (``sort.cairo:96-101``), which decides which oracle gets masked
        and must be reproduced exactly (NOT a stable sort)."""
        pairs = indexed_sort_host(values)
        assert sorted(i for i, _ in pairs) == list(range(len(values)))
        assert [v for _, v in pairs] == sorted(values)
        for (i1, v1), (i2, v2) in zip(pairs, pairs[1:]):
            if v1 == v2:
                assert i1 > i2  # Cairo tie order: right half first

    @COMMON
    @given(st.lists(st.integers(-(10**6), 10**6), min_size=2, max_size=32))
    def test_rank_array_is_a_permutation_with_reference_orientation(self, xs):
        scores = jnp.asarray(np.asarray(xs, np.float32))
        normalized, ranks = rank_array(scores)
        r = np.asarray(ranks)
        assert sorted(r.tolist()) == list(range(len(xs)))
        # Reference orientation (oracle_scheduler.py:94-104): the
        # SMALLEST score gets the HIGHEST rank (least deviant).
        assert r[int(np.argmin(xs))] == len(xs) - 1 or xs.count(min(xs)) > 1
        np.testing.assert_allclose(
            np.asarray(normalized), r / (len(xs) - 1), atol=1e-6
        )

    @COMMON
    @given(
        st.lists(
            st.floats(0, 100, allow_nan=False), min_size=3, max_size=24
        ),
        st.integers(0, 8),
    )
    def test_reliability_mask_cardinality(self, risks, n_failing):
        n_failing = min(n_failing, len(risks) - 1)
        # Compare in float32 — the mask is computed in float32, where
        # float64 near-ties can collapse into exact ties (broken by the
        # Cairo descending-index order, not by magnitude).
        risks32 = np.asarray(risks, np.float32)
        mask = np.asarray(reliability_mask(jnp.asarray(risks32), n_failing))
        assert mask.sum() == len(risks) - n_failing
        # The masked-out entries carry the LARGEST risks.
        if n_failing:
            worst_kept = max(risks32[mask], default=np.float32(0.0))
            best_dropped = min(risks32[~mask])
            assert worst_kept <= best_dropped


class TestPackingProperties:
    @COMMON
    @given(
        st.lists(
            st.lists(st.integers(4, 1000), min_size=0, max_size=12),
            min_size=1,
            max_size=16,
        )
    )
    def test_pack_tokens_owner_bijection_and_content(self, token_lists):
        seq_len, max_segments, pad_id = 16, 4, 1
        batch, n = pack_tokens(token_lists, seq_len, max_segments, pad_id)
        assert n == len(token_lists)  # rows=None consumes everything
        owners = batch.owner[batch.seg_valid > 0]
        assert sorted(owners.tolist()) == list(range(len(token_lists)))
        # Each segment's tokens reproduce its (truncated) input.
        for r in range(batch.ids.shape[0]):
            for s in range(max_segments):
                if not batch.seg_valid[r, s]:
                    continue
                seg_tokens = batch.ids[r][batch.seg[r] == s + 1]
                owner = batch.owner[r, s]
                expected = list(token_lists[owner][:seq_len]) or [pad_id]
                assert seg_tokens.tolist() == expected
                # positions restart at pad_id + 1 per segment
                pos = batch.pos[r][batch.seg[r] == s + 1]
                assert pos.tolist() == list(
                    range(pad_id + 1, pad_id + 1 + len(seg_tokens))
                )


class TestQuarantineGateProperties:
    """Gate invariants (ISSUE 4): honest finite in-range fleets are
    NEVER quarantined; any NaN/Inf/out-of-range component ALWAYS is."""

    @COMMON
    @given(
        st.integers(2, 12),
        st.integers(1, 6),
        st.integers(0, 2**31 - 1),
    )
    def test_honest_fleets_never_quarantined(self, n, m, seed):
        from svoc_tpu.robustness.sanitize import (
            QuarantineGate,
            SanitizeConfig,
        )
        from svoc_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(seed)
        block = rng.uniform(0.0, 1.0, (n, m))
        report = QuarantineGate(
            SanitizeConfig(0.0, 1.0), MetricsRegistry()
        ).inspect(block)
        assert report.clean
        assert report.ok.all()

    @COMMON
    @given(
        st.integers(2, 12),
        st.integers(1, 6),
        st.integers(0, 2**31 - 1),
        st.data(),
    )
    def test_any_bad_component_always_quarantined(self, n, m, seed, data):
        from svoc_tpu.robustness.sanitize import (
            WSAD_LIMIT,
            QuarantineGate,
            SanitizeConfig,
            quarantine_mask_jax,
        )
        from svoc_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(seed)
        block = rng.uniform(0.0, 1.0, (n, m))
        slot = data.draw(st.integers(0, n - 1))
        comp = data.draw(st.integers(0, m - 1))
        bad = data.draw(
            st.sampled_from(
                [
                    float("nan"),
                    float("inf"),
                    float("-inf"),
                    -0.25,
                    1.25,
                    WSAD_LIMIT * 2,
                ]
            )
        )
        block[slot, comp] = bad
        report = QuarantineGate(
            SanitizeConfig(0.0, 1.0), MetricsRegistry()
        ).inspect(block)
        assert slot in report.reasons
        assert not report.ok[slot]
        # Only the poisoned slot is refused (no collateral quarantine),
        # and the in-graph twin agrees with the host gate exactly.
        assert report.quarantined_slots == [slot]
        dev_mask = np.asarray(
            quarantine_mask_jax(jnp.asarray(block), 0.0, 1.0)
        )
        np.testing.assert_array_equal(report.ok, dev_mask)


class TestSaturatingWsadProperties:
    """Saturating-op invariants (ISSUE 4): results live in the i128
    window, saturation NEVER wraps sign, and in-range results are
    bit-identical to the exact ops."""

    huge_ints = st.integers(min_value=-(2**140), max_value=2**140)

    @COMMON
    @given(huge_ints, huge_ints)
    def test_add_sat_is_clamped_exact_sum(self, a, b):
        from svoc_tpu.ops.fixedpoint import I128_MAX, I128_MIN, wsad_add_sat

        got = wsad_add_sat(a, b)
        exact = a + b
        assert got == min(max(exact, I128_MIN), I128_MAX)
        assert I128_MIN <= got <= I128_MAX
        # Saturation never wraps sign: the clamped result agrees in
        # sign with the exact value (zero is sign-neutral).
        if exact != 0:
            assert (got >= 0) == (exact >= 0)

    @COMMON
    @given(huge_ints, huge_ints)
    def test_mul_sat_is_clamped_exact_product(self, a, b):
        from svoc_tpu.ops.fixedpoint import (
            I128_MAX,
            I128_MIN,
            wsad_mul,
            wsad_mul_sat,
        )

        got = wsad_mul_sat(a, b)
        exact = wsad_mul(a, b)
        assert got == min(max(exact, I128_MIN), I128_MAX)
        if exact != 0:
            assert (got >= 0) == (exact >= 0)

    @COMMON
    @given(wsad_ints, wsad_ints)
    def test_in_range_operands_match_exact_ops(self, a, b):
        from svoc_tpu.ops.fixedpoint import wsad_add_sat, wsad_mul, wsad_mul_sat

        assert wsad_add_sat(a, b) == a + b
        assert wsad_mul_sat(a, b) == wsad_mul(a, b)


class TestFeltBoundaryProperties:
    @COMMON
    @given(st.integers(min_value=2**127, max_value=2**200))
    def test_dead_zone_and_oversized_felts_always_raise(self, x):
        """Everything between the positive window and the negative
        window — and ≥ the prime — must refuse to decode (the seed
        silently wrapped these into fabricated values)."""
        from svoc_tpu.ops.fixedpoint import (
            FELT_PRIME,
            FeltRangeError,
            felt_to_wsad,
        )

        assume(x < FELT_PRIME - 2**127 or x >= FELT_PRIME)
        try:
            felt_to_wsad(x)
            raised = False
        except FeltRangeError:
            raised = True
        assert raised


class TestConsensusProperties:
    @COMMON
    @given(
        st.integers(4, 12),
        st.integers(1, 3),
        st.integers(0, 2**31 - 1),
    )
    def test_two_pass_invariants(self, n_oracles, n_failing, seed):
        assume(n_failing < n_oracles - 1)
        rng = np.random.default_rng(seed)
        values = jnp.asarray(
            rng.uniform(0.02, 0.98, size=(n_oracles, 3)), jnp.float32
        )
        out = consensus_step(
            values, ConsensusConfig(n_failing=n_failing, constrained=True)
        )
        reliable = np.asarray(out.reliable)
        assert reliable.sum() == n_oracles - n_failing
        essence = np.asarray(out.essence)
        # The restricted smooth median stays inside the reliable set's
        # per-component hull.
        kept = np.asarray(values)[reliable]
        assert np.all(essence >= kept.min(axis=0) - 1e-6)
        assert np.all(essence <= kept.max(axis=0) + 1e-6)
        assert 0.0 <= float(out.reliability_first_pass) <= 1.0
        assert 0.0 <= float(out.reliability_second_pass) <= 1.0


import pytest  # noqa: E402  (grouped with the fuzz suite it serves)
from conftest import make_fake_console  # noqa: E402


@pytest.fixture(scope="module")
def fuzz_console():
    """One console reused across fuzz examples (construction is the
    expensive part); each example restores the flags the dispatcher may
    flip so examples stay independent and failures reproduce."""
    return make_fake_console(n_comments=100)


class TestConsoleFuzz:
    @settings(deadline=None, max_examples=120)
    @given(
        st.text(
            alphabet=st.characters(
                whitelist_categories=("L", "N", "P", "S", "Z")
            ),
            max_size=60,
        )
    )
    def test_dispatcher_never_raises_and_session_survives(
        self, fuzz_console, text
    ):
        """query() converts every failure to an 'error:'/'Unknown'
        line — arbitrary input must neither raise nor wedge the
        session (the reference's eel REPL has the same contract,
        web_interface.py:133-303)."""
        console = fuzz_console
        try:
            out = console.query(text)
            assert isinstance(out, list)
            assert all(isinstance(line, str) for line in out)
            # The session stays fully usable afterwards.
            assert console.query("dimension") == ["Dimension: 6"]
        finally:
            # 'exit'/'auto_fetch on'/'scraper on' examples flip durable
            # state — restore it so examples stay order-independent.
            console.stop()
            console.session.application_on = True
            console.session.auto_commit = False
            console.session.auto_resume = False


class TestBatchCommitEquivalence:
    """The batched fleet commit's contract, as a law: for ANY tx
    sequence — valid, out-of-interval, wrong-dimension, unknown caller,
    duplicate caller, degenerate values — the batch produces the same
    final wsad state, committed count, and failure class as looping
    ``update_prediction``."""

    @staticmethod
    def _state(c):
        return (
            c.consensus_active,
            c.n_active_oracles,
            tuple(c.consensus_value),
            c.reliability_first_pass,
            c.reliability_second_pass,
            tuple(tuple(o.value) + (o.enabled, o.reliable) for o in c.oracles),
        )

    @settings(
        deadline=None, max_examples=25,
        suppress_health_check=[HealthCheck.too_slow],
    )
    @given(
        n_oracles=st.integers(min_value=5, max_value=9),
        n_failing=st.integers(min_value=0, max_value=4),
        seed=st.integers(min_value=0, max_value=2**31),
        n_cycles=st.integers(min_value=1, max_value=3),
        corrupt=st.lists(
            st.tuples(
                st.integers(min_value=0, max_value=2),  # cycle
                st.integers(min_value=0, max_value=8),  # tx index
                st.sampled_from(
                    ["interval", "dim", "caller", "dup", "degenerate"]
                ),
            ),
            max_size=3,
        ),
    )
    def test_batch_equals_sequential(
        self, n_oracles, n_failing, seed, n_cycles, corrupt
    ):
        import numpy as np

        from svoc_tpu.consensus.state import (
            BatchTxError,
            OracleConsensusContract,
        )

        assume(n_failing < n_oracles)

        def build():
            return OracleConsensusContract(
                ["a0"],
                [f"o{i}" for i in range(n_oracles)],
                n_failing_oracles=n_failing,
                constrained=True,
                dimension=2,
            )

        rng = np.random.default_rng(seed)
        seq, bat = build(), build()
        for cycle in range(n_cycles):
            callers = [f"o{i}" for i in range(n_oracles)]
            preds = [list(p) for p in rng.uniform(0.05, 0.95, (n_oracles, 2))]
            for c_cycle, t, kind in corrupt:
                if c_cycle != cycle or t >= n_oracles:
                    continue
                if kind == "interval":
                    preds[t][0] = 1.5
                elif kind == "dim":
                    preds[t] = [0.5]
                elif kind == "caller":
                    callers[t] = "eve"
                elif kind == "dup":
                    callers[t] = callers[0]
                elif kind == "degenerate":
                    for j in range(t, n_oracles):
                        preds[j] = [0.5, 0.5]

            seq_res = None
            for k, (caller, p) in enumerate(zip(callers, preds)):
                try:
                    seq.update_prediction(caller, p)
                except Exception as e:
                    seq_res = (k, type(e).__name__)
                    break

            try:
                n = bat.update_predictions_batch(callers, preds)
                bat_res = None
                assert n == n_oracles
            except BatchTxError as e:
                bat_res = (e.index, type(e.cause).__name__)

            assert seq_res == bat_res
            assert self._state(seq) == self._state(bat)
