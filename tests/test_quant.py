"""W8A8 dynamic-PTQ serving path (svoc_tpu/models/quant.py).

Quantization is lossy by construction, so parity bounds here are
looser than the float-path bit-parity tests: what must hold is that
the PRODUCT output — sum-normalized tracked sentiment vectors — stays
close to the float forward's, and that the packed/unpacked quantized
paths agree with each other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.models.quant import (
    qdense,
    quantize_dense,
    quantize_params,
    quantized_forward,
    quantized_size_bytes,
)
from svoc_tpu.models.sentiment import SentimentPipeline
from svoc_tpu.parallel.encoder_math import dense

CFG = TINY_TEST
TEXTS = [
    "the rollout went great, everyone is thrilled",
    "this outage is infuriating and support is silent",
    "mildly annoyed by the new UI but it works",
    "nervous about the migration tomorrow",
    "deeply sorry about the data loss",
    "what an exciting launch day!",
]


def _params():
    return init_params(SentimentEncoder(CFG), seed=3)


class TestQDense:
    def test_matches_float_dense_within_quant_error(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 16, 64)), jnp.float32)
        p = {
            "kernel": jnp.asarray(rng.normal(size=(64, 32)), jnp.float32),
            "bias": jnp.asarray(rng.normal(size=(32,)), jnp.float32),
        }
        ref = np.asarray(dense(x, p, jnp.float32))
        out = np.asarray(qdense(x, quantize_dense(p), jnp.float32))
        # Two int8 grids (row activations x channel weights): relative
        # error ~1% of the row-scale x channel-scale envelope.
        denom = np.maximum(np.abs(ref).max(), 1.0)
        assert np.abs(out - ref).max() / denom < 0.02

    def test_preserves_exact_zero_rows(self):
        p = {
            "kernel": jnp.ones((8, 4), jnp.float32),
            "bias": jnp.zeros((4,), jnp.float32),
        }
        out = np.asarray(qdense(jnp.zeros((2, 8)), quantize_dense(p), jnp.float32))
        np.testing.assert_array_equal(out, 0.0)


class TestQuantizedTree:
    def test_kernels_int8_rest_verbatim(self):
        params = _params()
        q = quantize_params(params, CFG)
        b0 = q["params"]["block_0"]
        for name in ("query", "key", "value", "out"):
            assert b0["attention"][name]["w_int8"].dtype == jnp.int8
        for name in ("ffn_in", "ffn_out"):
            assert b0[name]["w_int8"].dtype == jnp.int8
        # embeddings / norms / head untouched (identical leaves)
        np.testing.assert_array_equal(
            np.asarray(q["params"]["tok_emb"]["embedding"]),
            np.asarray(params["params"]["tok_emb"]["embedding"]),
        )
        assert "kernel" in q["params"]["head_dense"]

    def test_smaller_than_float_tree(self):
        params = _params()
        float_bytes = sum(
            l.size * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(params)
        )
        assert quantized_size_bytes(quantize_params(params, CFG)) < float_bytes


class TestQuantizedForward:
    def test_logits_track_float_forward(self):
        params = _params()
        rng = np.random.default_rng(1)
        ids = jnp.asarray(
            rng.integers(2, CFG.vocab_size, size=(4, 32)), jnp.int32
        )
        mask = jnp.ones_like(ids).at[1, 20:].set(0).at[3, 8:].set(0)
        ids = jnp.where(mask > 0, ids, CFG.pad_id)
        ref = np.asarray(SentimentEncoder(CFG).apply(params, ids, mask))
        out = np.asarray(
            quantized_forward(quantize_params(params, CFG), ids, mask, CFG)
        )
        assert out.shape == ref.shape
        assert np.abs(out - ref).max() < 0.15 * max(1.0, np.abs(ref).max())
        # ranking of labels survives quantization per row
        agree = np.mean(np.argmax(out, -1) == np.argmax(ref, -1))
        assert agree >= 0.75

    def test_qkv_share_one_activation_quantization(self):
        """The traced forward quantizes each DISTINCT activation once:
        4 per layer (x for Q/K/V, attn ctx, post-ln x, gelu out) plus
        one softmax reduce_max per layer — the naive per-call qdense
        emitted 6 per layer (Q/K/V re-quantized the same x; part of
        config 10's missing int8 speedup)."""
        from collections import Counter

        params = _params()
        qparams = quantize_params(params, CFG)
        ids = jnp.ones((2, 16), jnp.int32)
        mask = jnp.ones((2, 16), jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda q, i, m: quantized_forward(q, i, m, CFG)
        )(qparams, ids, mask)
        n_max = Counter(str(e.primitive) for e in jaxpr.eqns)["reduce_max"]
        # 4 quantizations + 1 softmax max per layer; the naive scheme
        # would show 7 per layer.
        assert n_max == 5 * CFG.n_layers, n_max


class TestPipelineIntegration:
    def test_int8_vectors_close_to_float(self):
        fp = SentimentPipeline(
            cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None, seed=5
        )
        qp = SentimentPipeline(
            cfg=CFG,
            seq_len=32,
            batch_size=4,
            tokenizer_name=None,
            seed=5,
            quant="int8",
        )
        ref = fp(TEXTS)
        out = qp(TEXTS)
        assert out.shape == ref.shape == (len(TEXTS), 6)
        np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)
        assert np.abs(out - ref).max() < 0.05

    def test_packed_int8_matches_unpacked_int8(self):
        qp = SentimentPipeline(
            cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None, seed=5,
            quant="int8",
        )
        unpacked = qp(TEXTS)
        packed = qp.call_packed(TEXTS, max_segments=4)
        # Same int8 kernels, same per-segment math: differences come only
        # from row-level activation scales (different packing of rows).
        np.testing.assert_allclose(packed, unpacked, atol=0.05)

    def test_quant_requires_dense_attention(self):
        import dataclasses

        with pytest.raises(ValueError, match="dense"):
            SentimentPipeline(
                cfg=dataclasses.replace(CFG, attention="flash"),
                seq_len=32,
                batch_size=4,
                tokenizer_name=None,
                quant="int8",
            )

    def test_unknown_quant_rejected(self):
        with pytest.raises(ValueError, match="int8"):
            SentimentPipeline(
                cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None,
                quant="int4",
            )

    def test_quantized_tree_with_quant_none_rejected(self):
        """A pre-folded int8 tree passed to a FLOAT pipeline must fail
        with a clear config error, not a trace-time KeyError (ADVICE
        r3)."""
        qp = SentimentPipeline(
            cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None, seed=5,
            quant="int8",
        )
        with pytest.raises(ValueError, match="pre-quantized"):
            SentimentPipeline(
                cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None,
                params=qp.params,
            )


class TestPersistence:
    def test_quantized_tree_roundtrips_npz_and_serves(self, tmp_path):
        """save_params/load_params must preserve the folded tree
        dtype-exactly (int8 kernels included) and a pipeline handed the
        loaded tree must serve without re-folding, matching the
        fresh-fold pipeline bit-for-bit."""
        from svoc_tpu.models.convert import load_params, save_params
        from svoc_tpu.models.quant import is_quantized_tree, quantize_params

        params = _params()
        q = quantize_params(params, CFG)
        path = save_params(str(tmp_path / "int8_tree"), q)
        loaded = load_params(path)
        assert is_quantized_tree(loaded)
        assert not is_quantized_tree(params)
        b0 = loaded["params"]["block_0"]["attention"]["query"]
        assert b0["w_int8"].dtype == np.int8
        np.testing.assert_array_equal(
            b0["w_int8"],
            np.asarray(q["params"]["block_0"]["attention"]["query"]["w_int8"]),
        )

        fresh = SentimentPipeline(
            cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None, seed=3,
            params=params, quant="int8",
        )
        from_disk = SentimentPipeline(
            cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None, seed=3,
            params=loaded, quant="int8",
        )
        np.testing.assert_array_equal(fresh(TEXTS), from_disk(TEXTS))

    def test_quant_rejects_params_dtype(self):
        with pytest.raises(ValueError, match="params_dtype"):
            SentimentPipeline(
                cfg=CFG, seq_len=32, batch_size=4, tokenizer_name=None,
                quant="int8", params_dtype="bfloat16",
            )
