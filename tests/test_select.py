"""ops/select.py — the sort-free consensus-window compaction.

Must be EXACTLY the stable-argsort selection it replaced (the packed
serving paths' window semantics: first window_size valid segments in
packer order) whenever the window fills; zero-padding when it cannot.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.ops.select import first_valid_window


def argsort_reference(vecs, valid, w):
    order = np.argsort(np.logical_not(valid), kind="stable")
    return np.asarray(vecs)[order[:w]]


@pytest.mark.parametrize("n,w,seed", [(64, 16, 0), (2048, 50, 1), (48, 48, 2)])
def test_matches_stable_argsort_when_window_fills(n, w, seed):
    rng = np.random.default_rng(seed)
    vecs = rng.uniform(-1, 1, (n, 6)).astype(np.float32)
    valid = np.zeros(n, bool)
    valid[rng.choice(n, size=max(w, n // 3), replace=False)] = True
    got = np.asarray(first_valid_window(jnp.asarray(vecs), jnp.asarray(valid), w))
    np.testing.assert_array_equal(got, argsort_reference(vecs, valid, w))


def test_exact_in_f32_no_mxu_rounding():
    # Values with >8 mantissa bits of structure survive the matmul
    # gather bit-exactly (HIGHEST precision; a bf16 MXU pass would not).
    vecs = np.full((256, 4), np.float32(1 + 2**-20))
    vecs[7] = np.float32(1 - 2**-20)
    valid = np.ones(256, bool)
    got = np.asarray(first_valid_window(jnp.asarray(vecs), jnp.asarray(valid), 16))
    np.testing.assert_array_equal(got, vecs[:16])


def test_short_window_pads_with_zeros():
    vecs = np.ones((8, 3), np.float32)
    valid = np.array([0, 1, 0, 0, 1, 0, 0, 0], bool)
    got = np.asarray(first_valid_window(jnp.asarray(vecs), jnp.asarray(valid), 4))
    np.testing.assert_array_equal(got[:2], vecs[[1, 4]])
    np.testing.assert_array_equal(got[2:], 0)


def test_shape_mismatch_raises():
    with pytest.raises(ValueError):
        first_valid_window(jnp.ones((4, 2)), jnp.ones(5, bool), 2)


# Optional dependency (pyproject [test] extra): without it the
# property-based tail of this module skips at collection instead of
# erroring the whole file out of the tier-1 run.
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402


@settings(
    max_examples=60,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    n=st.integers(4, 96),
    w=st.integers(1, 24),
    seed=st.integers(0, 2**31 - 1),
    p_valid=st.floats(0.0, 1.0),
)
def test_property_matches_argsort_or_zero_pads(n, w, seed, p_valid):
    """For ANY validity pattern: where the window fills, exact equality
    with the stable-argsort selection; where it cannot, packer-order
    prefix + zero padding — the law the packed consensus window rests
    on."""
    rng = np.random.default_rng(seed)
    vecs = rng.standard_normal((n, 3)).astype(np.float32)
    valid = rng.random(n) < p_valid
    got = np.asarray(first_valid_window(jnp.asarray(vecs), jnp.asarray(valid), w))
    k = int(valid.sum())
    ref = argsort_reference(vecs, valid, w)
    if k >= w:
        np.testing.assert_array_equal(got, ref)
    else:
        np.testing.assert_array_equal(got[:k], ref[:k])
        np.testing.assert_array_equal(got[k:], 0)
