"""Flight recorder: journal, rotation, lineage, SSE typed frames,
postmortem bundles, SLO burn rates (docs/OBSERVABILITY.md §events).

Covers the ISSUE-5 acceptance surface: journal thread-safety and
bounded-ring semantics, replay-stable fingerprints (wall time never
participates), the shared span/event JSONL rotation, lineage
propagation end-to-end through a tiny pipeline (fetch → quarantine →
resilient commit with one injected fault → audit record complete), the
``/api/events?journal=1`` typed-frame stream and ``/api/audit``
endpoint, bundle round-trips with the auto-trigger monitor, and the
burn-rate math fixtures.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from svoc_tpu.utils.events import (
    ALERT_TYPES,
    EventJournal,
    RotatingJsonlWriter,
    audit_record,
    mint_lineage,
)
from svoc_tpu.utils.metrics import MetricsRegistry, Tracer


# ---------------------------------------------------------------------------
# journal semantics
# ---------------------------------------------------------------------------


class TestEventJournal:
    def test_emit_and_recent_with_filters(self):
        j = EventJournal(MetricsRegistry())
        j.emit("block.fetched", lineage="blk-000001", n_comments=30)
        j.emit("commit.sent", lineage="blk-000001", sent=7)
        j.emit("block.fetched", lineage="blk-000002", n_comments=31)
        assert [e.seq for e in j.recent()] == [1, 2, 3]
        assert [e.type for e in j.recent(type="block.fetched")] == [
            "block.fetched",
            "block.fetched",
        ]
        assert [e.seq for e in j.recent(lineage="blk-000001")] == [1, 2]
        # the tail cut applies AFTER the filter
        assert [e.seq for e in j.recent(1, lineage="blk-000001")] == [2]
        assert j.last_seq() == 3
        assert j.counts_by_type() == {"block.fetched": 2, "commit.sent": 1}

    def test_since_is_a_cursor(self):
        j = EventJournal(MetricsRegistry())
        for i in range(5):
            j.emit("x", i=i)
        assert [e.seq for e in j.since(2)] == [3, 4, 5]
        assert [e.seq for e in j.since(2, limit=2)] == [3, 4]
        assert j.since(5) == []

    def test_ring_is_bounded(self):
        j = EventJournal(MetricsRegistry(), capacity=8)
        for i in range(50):
            j.emit("x", i=i)
        events = j.recent()
        assert len(events) == 8
        assert events[-1].seq == 50

    def test_data_is_json_safe(self):
        j = EventJournal(MetricsRegistry())
        rec = j.emit(
            "x",
            a=np.int64(3),
            b=np.float32(0.5),
            c=(1, 2),
            d={"k": {4, 5}},
            e=object(),
        )
        json.loads(rec.to_json())  # must not raise
        assert rec.data["a"] == 3
        assert rec.data["c"] == [1, 2]
        assert rec.data["d"]["k"] == [4, 5]
        assert isinstance(rec.data["e"], str)

    def test_thread_safety_unique_seqs(self):
        j = EventJournal(MetricsRegistry(), capacity=4096)
        n_threads, per_thread = 8, 100

        def worker(tid):
            for i in range(per_thread):
                j.emit("x", tid=tid, i=i)

        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = j.recent()
        assert len(events) == n_threads * per_thread
        seqs = [e.seq for e in events]
        assert len(set(seqs)) == len(seqs)  # no lost/duplicated seq
        # STRICT ring ordering: seq allocation happens under the same
        # lock hold as the append, or a preempted emitter could land a
        # lower seq after a higher one and the SSE cursor
        # (`since(last_seq)`) would re-send duplicate frames.
        assert seqs == sorted(seqs)
        assert j.counts_by_type() == {"x": n_threads * per_thread}

    def test_fingerprint_ignores_wall_time(self, monkeypatch):
        import svoc_tpu.utils.events as ev

        j1 = EventJournal(MetricsRegistry())
        j2 = EventJournal(MetricsRegistry())
        times = iter([100.0, 200.0, 5000.0, 6000.0, 7000.0])
        monkeypatch.setattr(ev.time, "time", lambda: next(times))
        for j in (j1, j2):
            j.emit("block.fetched", lineage="blk-000001", n=1)
            j.emit("commit.sent", lineage="blk-000001", sent=7)
        assert j1.recent()[0].ts != j2.recent()[0].ts
        assert j1.fingerprint() == j2.fingerprint()
        j2.emit("commit.failed")
        assert j1.fingerprint() != j2.fingerprint()

    def test_subscriber_runs_and_errors_are_contained(self):
        reg = MetricsRegistry()
        j = EventJournal(reg)
        seen = []

        def good(rec):
            seen.append(rec.type)

        def bad(rec):
            raise RuntimeError("boom")

        j.subscribe(bad)
        j.subscribe(good)
        j.emit("x")
        assert seen == ["x"]
        assert reg.counter("event_subscriber_errors").count == 1
        j.unsubscribe(good)
        j.emit("y")
        assert seen == ["x"]

    def test_summary_counts_alerts_fingerprint(self):
        j = EventJournal(MetricsRegistry())
        j.emit("block.fetched")
        j.emit("slo.alert", slo="commit_success")
        j.emit("breaker.transition", to="open", backend="chain")
        j.emit("breaker.transition", to="closed", backend="chain")
        s = j.summary(last_alerts=5)
        assert s["events"] == 4
        assert s["counts_by_type"]["breaker.transition"] == 2
        alert_types = [a["event"] for a in s["alerts"]]
        assert "slo.alert" in alert_types
        # breaker transitions: only →open is alert-class
        assert (
            sum(1 for a in s["alerts"] if a["event"] == "breaker.transition")
            == 1
        )
        assert s["fingerprint"] == j.fingerprint()
        assert "slo.alert" in ALERT_TYPES


# ---------------------------------------------------------------------------
# rotation (shared by spans and events)
# ---------------------------------------------------------------------------


class TestRotation:
    def test_writer_rotates_and_keeps_k_segments(self, tmp_path):
        reg = MetricsRegistry()
        path = str(tmp_path / "trace.jsonl")
        w = RotatingJsonlWriter(path, max_bytes=200, keep=2, registry=reg)
        for i in range(60):
            w.write_line(json.dumps({"i": i, "pad": "x" * 24}))
        segs = w.segments()
        assert segs == [path, path + ".1", path + ".2"]
        for seg in segs:
            assert os.path.getsize(seg) <= 200 + 64
        # No segment beyond keep.
        assert not os.path.exists(path + ".3")
        gauge = reg.gauge(
            "trace_file_bytes", labels={"path": "trace.jsonl"}
        )
        assert gauge.get() == os.path.getsize(path)
        # every surviving line still parses
        for seg in segs:
            for line in open(seg):
                json.loads(line)

    def test_writer_accounts_bytes_not_chars(self, tmp_path):
        """Multibyte payloads must count their UTF-8 bytes — counting
        str length would let a segment blow the documented byte cap
        ~4× on CJK/emoji content."""
        reg = MetricsRegistry()
        path = str(tmp_path / "trace.jsonl")
        w = RotatingJsonlWriter(path, max_bytes=400, keep=1, registry=reg)
        line = json.dumps({"text": "你好世界" * 40}, ensure_ascii=False)
        assert len(line) < 400 < len(line.encode("utf-8"))
        for _ in range(6):
            w.write_line(line)
        for seg in w.segments():
            assert os.path.getsize(seg) <= 400 + len(line.encode()) + 1

    def test_set_trace_file_releases_old_writer_handle(self, tmp_path):
        from svoc_tpu.utils.events import shared_writer

        reg = MetricsRegistry()
        t = Tracer(reg)
        old = str(tmp_path / "old.jsonl")
        t.set_trace_file(old)
        with t.span("fetch"):
            pass
        writer = shared_writer(old)
        assert writer._file is not None  # handle open after the write
        t.set_trace_file(str(tmp_path / "new.jsonl"))
        assert writer._file is None  # released; reopens lazily if written

    def test_tracer_and_journal_share_rotating_file(self, tmp_path, monkeypatch):
        path = str(tmp_path / "flight.jsonl")
        monkeypatch.setenv(Tracer.TRACE_ENV, path)
        reg = MetricsRegistry()
        t = Tracer(reg)
        j = EventJournal(reg)
        with t.span("fetch", lineage="blk-000001"):
            pass
        j.emit("block.fetched", lineage="blk-000001", n=1)
        t.flush()
        lines = [json.loads(line) for line in open(path)]
        assert {"name" in rec or "event" in rec for rec in lines} == {True}
        span_lines = [rec for rec in lines if "name" in rec]
        event_lines = [rec for rec in lines if "event" in rec]
        assert span_lines[0]["lineage"] == "blk-000001"
        assert event_lines[0]["lineage"] == "blk-000001"

    def test_trace_write_error_is_surfaced_not_silent(self, tmp_path):
        """Satellite fix: a failing trace path bumps
        ``trace_write_errors`` and emits one ``trace.write_error``
        event instead of latching an invisible flag."""
        from svoc_tpu.utils import events as ev

        reg = MetricsRegistry()
        t = Tracer(reg)
        bad = str(tmp_path / "no" / "such" / "dir" / "t.jsonl")
        t.set_trace_file(bad)
        before_events = len(ev.journal.recent(type="trace.write_error"))
        with t.span("fetch"):
            pass  # must not raise
        assert len(t.recent()) == 1  # span survived
        assert reg.counter("trace_write_errors").count == 1
        events = ev.journal.recent(type="trace.write_error")
        assert len(events) == before_events + 1
        assert bad in str(events[-1].data.get("path"))
        # the latch is one-shot: further spans don't re-count
        with t.span("fetch"):
            pass
        assert reg.counter("trace_write_errors").count == 1
        # reconfiguring clears the latch
        good = str(tmp_path / "ok.jsonl")
        t.set_trace_file(good)
        with t.span("fetch"):
            pass
        t.flush()
        assert os.path.exists(good)


# ---------------------------------------------------------------------------
# lineage propagation
# ---------------------------------------------------------------------------


class TestLineage:
    def test_mint_is_deterministic(self):
        assert mint_lineage(31) == "blk-00001f"
        assert mint_lineage(4, prefix="cyc") == "cyc-000004"

    def test_span_inheritance_and_annotation(self):
        t = Tracer(MetricsRegistry())
        with t.span("fetch"):
            assert t.current_lineage() is None
            assert t.annotate_lineage("blk-000003")
            assert t.current_lineage() == "blk-000003"
            with t.span("vectorize"):
                with t.span("tokenize"):
                    pass
            with t.span("fleet", lineage="blk-override"):
                pass
        by_name = {s.name: s for s in t.recent()}
        assert by_name["fetch"].lineage == "blk-000003"
        assert by_name["vectorize"].lineage == "blk-000003"
        assert by_name["tokenize"].lineage == "blk-000003"
        assert by_name["fleet"].lineage == "blk-override"
        # no open span → annotate is a no-op returning False
        assert t.annotate_lineage("x") is False

    def test_lineage_does_not_leak_across_threads(self):
        t = Tracer(MetricsRegistry())
        got = {}

        def worker():
            with t.span("tokenize"):
                got["lineage"] = t.current_lineage()

        with t.span("fetch", lineage="blk-000009"):
            th = threading.Thread(target=worker)
            th.start()
            th.join()
        assert got["lineage"] is None

    def test_prefetch_pipeline_tags_producer_spans_and_errors(self):
        from svoc_tpu.io.pipeline import PrefetchPipeline
        from svoc_tpu.utils import events as ev
        from svoc_tpu.utils.metrics import tracer as default_tracer

        def tokenizer(texts, seq_len):
            if texts[0] == "crash":
                raise RuntimeError("tokenizer exploded")
            return np.zeros((len(texts), seq_len)), np.ones((len(texts), seq_len))

        pipe = PrefetchPipeline(
            [["a", "b"], ["crash"]], tokenizer, 8, lineage="blk-00000a"
        )
        with pytest.raises(RuntimeError):
            for _ in pipe:
                pass
        pipe.close()
        spans = [
            s
            for s in default_tracer.recent()
            if s.name == "tokenize" and s.lineage == "blk-00000a"
        ]
        assert spans, "producer tokenize span missing its lineage"
        errors = ev.journal.recent(
            type="pipeline.producer_error", lineage="blk-00000a"
        )
        assert errors and "tokenizer exploded" in errors[-1].data["error"]


# ---------------------------------------------------------------------------
# end-to-end: fetch → quarantine → resilient commit → audit record
# ---------------------------------------------------------------------------


def _event_types(journal, lineage, after_seq=0):
    return {
        e.type
        for e in journal.recent(lineage=lineage)
        if e.seq > after_seq
    }


class TestAuditEndToEnd:
    def test_tiny_pipeline_audit_record_complete(self):
        """fetch → (poisoned slot) quarantine → resilient commit with
        one injected transient fault → the audit record joins every leg
        on the block's lineage id."""
        from svoc_tpu.io.chain import ChainAdapter
        from svoc_tpu.resilience.faults import (
            FaultInjectingBackend,
            FaultPlan,
            FaultSpec,
        )
        from svoc_tpu.utils import events as ev
        from tests.test_apps import make_session

        session = make_session()
        # One transient commit fault on oracle 0x12 (slot 2), exactly
        # once — forces a commit.retried + resume on the same block.
        plan = FaultPlan(
            seed=1,
            specs=[
                FaultSpec(
                    op="invoke:update_prediction",
                    target=0x12,
                    probability=1.0,
                    max_fires=1,
                )
            ],
            registry=MetricsRegistry(),
        )
        session.adapter = ChainAdapter(
            FaultInjectingBackend(session.adapter.backend, plan)
        )
        session.supervisor.adapter = session.adapter

        before = ev.journal.last_seq()
        session.fetch()
        lineage = session.last_lineage
        assert lineage is not None
        # Poison one slot AFTER the (clean) fetch verdict: the commit
        # path re-inspects its snapshot, skips the slot, and charges
        # the oracle — all under the same block lineage.
        with session.lock:
            session.predictions[0, :] = np.nan
        outcome = session.commit_resilient()
        # 6 eligible slots (7 − 1 quarantined), all landed: 1 tx before
        # the injected fault, 5 on the resumed second attempt.
        assert outcome.sent == 6 and outcome.attempts == 2
        assert outcome.complete

        types = _event_types(ev.journal, lineage, after_seq=before)
        assert {
            "block.fetched",
            "quarantine.verdict",
            "consensus.result",
            "commit.skipped",
            "commit.retried",
            "commit.sent",
            "supervisor.charge",
        } <= types

        record = session.audit()
        assert record["found"] and record["lineage"] == lineage
        summary = record["summary"]
        assert summary["commit_sent"] == 6
        assert summary["commit_skipped"] >= 1
        assert summary["commit_retries"] == 1
        assert summary["charged"] == ["0x10"]
        assert summary["interval_valid"] is True
        # spans joined on the same id
        span_names = {s["name"] for s in record["spans"]}
        assert {"fetch", "consensus", "commit"} <= span_names

    def test_audit_record_unknown_lineage(self):
        rec = audit_record("blk-ffffff")
        assert rec["found"] is False and rec["events"] == []

    def test_scenario_journal_fingerprints_replay(self):
        """Chaos + Byzantine scenarios now fold the event stream into
        their replay witness (cheap versions of `make obs-smoke`)."""
        from svoc_tpu.resilience.chaos import run_chaos_scenario

        r1 = run_chaos_scenario(cycles=4, registry=MetricsRegistry())
        r2 = run_chaos_scenario(cycles=4, registry=MetricsRegistry())
        assert r1["journal_events"] > 0
        assert r1["journal_fingerprint"] == r2["journal_fingerprint"]
        assert r1["fingerprint"] == r2["fingerprint"]


# ---------------------------------------------------------------------------
# web surfaces: typed SSE frames + the audit endpoint
# ---------------------------------------------------------------------------


@pytest.fixture()
def server():
    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.web import serve
    from tests.test_apps import make_session

    console = CommandConsole(make_session())
    srv, _thread = serve(console, port=0, block=False)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, console
    srv.shutdown()


class TestWebSurfaces:
    def test_audit_endpoint_roundtrip_and_404(self, server):
        base, console = server
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"{base}/api/audit/blk-ffffff", timeout=10)
        assert exc_info.value.code == 404
        console.session.fetch()
        lineage = console.session.last_lineage
        with urllib.request.urlopen(
            f"{base}/api/audit/{lineage}", timeout=10
        ) as r:
            record = json.loads(r.read())
        assert record["lineage"] == lineage and record["found"]
        assert any(e["event"] == "block.fetched" for e in record["events"])

    def test_events_stream_typed_journal_frames_opt_in(self, server):
        """?journal=1 streams named `event: journal` frames for new
        events; the unnamed state_version frames are unchanged."""
        base, console = server
        with urllib.request.urlopen(
            f"{base}/api/events?journal=1", timeout=10
        ) as r:

            def next_frame():
                name = None
                while True:
                    line = r.readline().decode()
                    if line.startswith("event: "):
                        name = line[7:].strip()
                    elif line.startswith("data: "):
                        return name, json.loads(line[6:])

            name, first = next_frame()
            assert name is None and "state_version" in first
            console.session.fetch()  # emits journal events + bumps state
            seen_types = set()
            saw_state_frame = False
            for _ in range(12):
                name, payload = next_frame()
                if name == "journal":
                    seen_types.add(payload["event"])
                    assert "seq" in payload
                elif "state_version" in payload:
                    saw_state_frame = True
                if "block.fetched" in seen_types and saw_state_frame:
                    break
            assert "block.fetched" in seen_types
            assert saw_state_frame

    def test_plain_events_stream_has_no_named_frames(self, server):
        base, console = server
        with urllib.request.urlopen(f"{base}/api/events", timeout=10) as r:
            # initial frame
            while True:
                line = r.readline().decode()
                if line.startswith("data: "):
                    break
            console.session.fetch()
            # next frame must be the unnamed state_version push
            while True:
                line = r.readline().decode()
                if not line.strip() or line.startswith(":"):
                    continue
                assert not line.startswith("event: ")
                if line.startswith("data: "):
                    assert "state_version" in json.loads(line[6:])
                    break


# ---------------------------------------------------------------------------
# postmortem bundles
# ---------------------------------------------------------------------------


class TestPostmortem:
    def test_bundle_roundtrip_completeness(self, tmp_path):
        from svoc_tpu.utils.postmortem import BUNDLE_KEYS, build_bundle
        from tests.test_apps import make_session

        session = make_session()
        session.fetch()
        session.commit()
        path = build_bundle(
            out_dir=str(tmp_path), trigger="manual", session=session
        )
        with open(path) as f:
            bundle = json.load(f)
        for key in BUNDLE_KEYS:
            assert key in bundle, key
        assert bundle["format"] == "svoc-postmortem-v1"
        assert bundle["journal"]["fingerprint"]
        assert any(
            e["event"] == "commit.sent" for e in bundle["journal"]["events"]
        )
        assert bundle["resilience"]["breaker"] == "closed"
        assert bundle["config"]["n_oracles"] == 7
        assert "stage_seconds" in bundle["metrics"]
        assert not os.path.exists(path + ".tmp")  # atomic write

    def test_monitor_triggers_on_breaker_open_and_rate_limits(self, tmp_path):
        from svoc_tpu.utils.postmortem import PostmortemMonitor

        reg = MetricsRegistry()
        j = EventJournal(reg)
        clock_now = [0.0]
        monitor = PostmortemMonitor(
            out_dir=str(tmp_path),
            registry=reg,
            journal=j,
            min_interval_s=60.0,
            max_bundles=2,
            clock=lambda: clock_now[0],
        ).install()
        try:
            j.emit("breaker.transition", to="open", backend="chain")
            assert len(monitor.bundles) == 1
            with open(monitor.bundles[0]) as f:
                bundle = json.load(f)
            assert bundle["trigger"] == "breaker_open"
            assert bundle["trigger_event"]["event"] == "breaker.transition"
            # journaled, and the bundle event does not re-trigger
            assert j.recent(type="postmortem.bundle")
            # rate limit: a second incident inside the window is skipped
            j.emit("breaker.transition", to="open", backend="chain")
            assert len(monitor.bundles) == 1
            # ... but fires after the window
            clock_now[0] = 61.0
            j.emit("breaker.transition", to="open", backend="chain")
            assert len(monitor.bundles) == 2
            # lifetime cap
            clock_now[0] = 200.0
            j.emit("breaker.transition", to="open", backend="chain")
            assert len(monitor.bundles) == 2
        finally:
            monitor.uninstall()

    def test_monitor_classification(self, tmp_path):
        from svoc_tpu.utils.events import EventRecord
        from svoc_tpu.utils.postmortem import PostmortemMonitor

        m = PostmortemMonitor(out_dir=str(tmp_path), journal=EventJournal())

        def rec(type_, **data):
            return EventRecord(1, 0.0, type_, None, data)

        assert m.classify(rec("breaker.transition", to="open")) == "breaker_open"
        assert m.classify(rec("breaker.transition", to="closed")) is None
        assert (
            m.classify(rec("quarantine.verdict", total=7, admitted=3))
            == "quarantine_spike"
        )
        assert m.classify(rec("quarantine.verdict", total=7, admitted=6)) is None
        assert (
            m.classify(rec("consensus.result", interval_valid=False))
            == "interval_invalid"
        )
        assert m.classify(rec("consensus.result", interval_valid=True)) is None
        assert m.classify(rec("pipeline.producer_error")) == "producer_error"
        assert m.classify(rec("crash")) == "crash"
        assert m.classify(rec("postmortem.bundle")) is None


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------


class TestSLO:
    def _evaluator(self, sample, **kwargs):
        from svoc_tpu.utils.slo import SLODefinition, SLOEvaluator

        reg = MetricsRegistry()
        j = EventJournal(reg)
        clock_now = [0.0]
        slo = SLODefinition(
            name="test",
            description="fixture",
            objective=kwargs.pop("objective", 0.99),
            sample=sample,
            fast_window_s=kwargs.pop("fast_window_s", 300.0),
            slow_window_s=kwargs.pop("slow_window_s", 3600.0),
            **kwargs,
        )
        ev = SLOEvaluator([slo], registry=reg, journal=j, clock=lambda: clock_now[0])
        return ev, reg, j, clock_now

    def test_burn_rate_math(self):
        """100 events with 10 % errors against a 1 % budget → burn 10×."""
        state = {"good": 0.0, "total": 0.0}
        ev, reg, _j, clock = self._evaluator(
            lambda: (state["good"], state["total"])
        )
        ev.evaluate()  # baseline at t=0
        clock[0] = 100.0
        state["good"], state["total"] = 90.0, 100.0
        snap = ev.evaluate()["test"]
        assert snap["fast"]["error_rate"] == pytest.approx(0.1)
        assert snap["fast"]["burn"] == pytest.approx(10.0)
        assert reg.gauge(
            "slo_burn_rate", labels={"slo": "test", "window": "fast"}
        ).get() == pytest.approx(10.0)

    def test_windows_differ_fast_recovers(self):
        """Errors burn the fast window, then a clean fast window decays
        to zero while the slow window still remembers them."""
        state = {"good": 0.0, "total": 0.0}
        ev, _reg, _j, clock = self._evaluator(
            lambda: (state["good"], state["total"]),
            fast_window_s=100.0,
            slow_window_s=1000.0,
        )
        ev.evaluate()
        clock[0] = 50.0
        state["good"], state["total"] = 50.0, 100.0  # 50% errors
        snap = ev.evaluate()["test"]
        assert snap["fast"]["burn"] == pytest.approx(50.0)
        # 400 s later: a clean window of traffic
        clock[0] = 450.0
        state["good"], state["total"] = 250.0, 300.0
        snap = ev.evaluate()["test"]
        assert snap["fast"]["error_rate"] == pytest.approx(0.0)
        assert snap["slow"]["error_rate"] == pytest.approx(50 / 300, rel=1e-4)

    def test_no_traffic_is_zero_burn(self):
        ev, _reg, _j, clock = self._evaluator(lambda: (0.0, 0.0))
        snap = ev.evaluate()["test"]
        assert snap["fast"]["burn"] == 0.0 and not snap["alerting"]

    def test_alert_emitted_once_and_latched(self):
        state = {"good": 0.0, "total": 0.0}
        ev, reg, j, clock = self._evaluator(
            lambda: (state["good"], state["total"]),
            objective=0.9,
            fast_burn_alert=2.0,
            slow_burn_alert=1.0,
        )
        ev.evaluate()
        clock[0] = 10.0
        state["good"], state["total"] = 10.0, 100.0  # 90% errors, budget 10%
        snap = ev.evaluate()["test"]
        assert snap["alerting"]
        assert len(j.recent(type="slo.alert")) == 1
        assert reg.counter("slo_alerts", labels={"slo": "test"}).count == 1
        # still alerting next pass → latched, no duplicate event
        clock[0] = 20.0
        state["good"], state["total"] = 11.0, 110.0
        assert ev.evaluate()["test"]["alerting"]
        assert len(j.recent(type="slo.alert")) == 1
        assert ev.alerting() == ["test"]

    def test_default_slos_shape(self):
        from svoc_tpu.utils.slo import default_slos

        reg = MetricsRegistry()
        slos = default_slos(reg)
        assert [s.name for s in slos] == [
            "commit_success",
            "consensus_latency",
            "quarantine_admission",
        ]
        # latency source: bucketized good/total from the histogram
        reg.stage_histogram("consensus").observe(0.01)
        reg.stage_histogram("consensus").observe(10.0)
        good, total = slos[1].sample()
        assert total == 2.0 and good == 1.0
