"""Flash attention kernel vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.ops.pallas_attention import flash_attention
from svoc_tpu.parallel.ring_attention import dense_attention_reference


def qkv(key, b=2, t=128, h=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d), dtype),
        jax.random.normal(kk, (b, t, h, d), dtype),
        jax.random.normal(kv, (b, t, h, d), dtype),
    )


class TestFlashAttention:
    def test_matches_dense(self):
        q, k, v = qkv(jax.random.PRNGKey(0))
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_matches_dense_with_padding(self):
        q, k, v = qkv(jax.random.PRNGKey(1))
        kmask = (
            jax.random.uniform(jax.random.PRNGKey(2), k.shape[:2]) > 0.4
        ).astype(jnp.int32)
        kmask = kmask.at[:, 0].set(1)
        out = flash_attention(q, k, v, kmask, block_q=32, block_k=32)
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_fully_masked_block_stable(self):
        """A K block that is 100% padding must not produce NaNs."""
        q, k, v = qkv(jax.random.PRNGKey(3), t=64)
        kmask = jnp.zeros((2, 64), jnp.int32).at[:, :32].set(1)
        out = flash_attention(q, k, v, kmask, block_q=32, block_k=32)
        ref = dense_attention_reference(q, k, v, kmask)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_bf16(self):
        q, k, v = qkv(jax.random.PRNGKey(4), dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        assert out.dtype == jnp.bfloat16
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32),
            np.asarray(ref, np.float32),
            atol=3e-2,
        )

    def test_rejects_indivisible_seq(self):
        q, k, v = qkv(jax.random.PRNGKey(5), t=100)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64)


# -- backward pass (FlashAttention-2 custom VJP) ----------------------------


def test_flash_backward_matches_dense():
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 32, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32) for _ in range(3)
    )
    kmask = jnp.asarray(
        (np.arange(t)[None, :] < np.array([[t], [t - 10]])).astype(np.int32)
    )
    cot = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)

    gf = jax.grad(
        lambda *a: jnp.sum(
            flash_attention(*a, kmask, block_q=8, block_k=16) * cot
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda *a: jnp.sum(dense_attention_reference(*a, kmask) * cot),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b_ in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=1e-4, err_msg=f"d{name}"
        )


def test_flash_backward_masked_keys_get_zero_grad():
    """Keys the mask removes cannot influence the loss — their k/v
    gradients must be EXACTLY zero (p is hard-zeroed, unlike the dense
    path's exp(-1e30) residue)."""
    rng = np.random.default_rng(1)
    b, t, h, d = 1, 16, 1, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32) for _ in range(3)
    )
    n_real = 10
    kmask = jnp.asarray((np.arange(t)[None, :] < n_real).astype(np.int32))
    _, dk, dv = jax.grad(
        lambda *a: jnp.sum(flash_attention(*a, kmask, block_q=8, block_k=8)),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert np.all(np.asarray(dk)[0, n_real:] == 0)
    assert np.all(np.asarray(dv)[0, n_real:] == 0)


def test_flash_backward_bf16_smoke():
    rng = np.random.default_rng(2)
    b, t, h, d = 1, 16, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16) for _ in range(3)
    )
    grads = jax.grad(
        lambda *a: jnp.sum(
            flash_attention(*a, block_q=8, block_k=8).astype(jnp.float32)
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, x in zip(grads, (q, k, v)):
        assert g.shape == x.shape and g.dtype == x.dtype
        assert np.all(np.isfinite(np.asarray(g, np.float32)))


# -- segment-tag (packed) masking -------------------------------------------


def dense_segment_reference(q, k, v, seg):
    """Dense packed attention with the flash dead-row convention:
    token i attends token j iff seg[i] == seg[j] > 0; a padding query
    (seg 0) attends nothing and outputs exactly 0."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
    m = (seg[:, :, None] == seg[:, None, :]) & (seg[:, None, :] > 0)
    probs = jax.nn.softmax(jnp.where(m[:, None], scores, -1e30), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    dead = ~m.any(-1)  # [B, Tq]
    return jnp.where(dead[:, :, None, None], 0.0, out)


def _segments(b=2, t=64, seed=7, max_segments=5):
    """Random contiguous segment layouts with a padding tail."""
    rng = np.random.default_rng(seed)
    seg = np.zeros((b, t), np.int32)
    for i in range(b):
        pos = 0
        for s in range(1, max_segments + 1):
            length = int(rng.integers(3, t // max_segments + 1))
            if pos + length > t:
                break
            seg[i, pos : pos + length] = s
            pos += length
    return jnp.asarray(seg)


class TestFlashSegments:
    def test_segments_match_dense_blockdiag(self):
        q, k, v = qkv(jax.random.PRNGKey(10), t=64)
        seg = _segments(t=64)
        out = flash_attention(
            q, k, v, segment_ids=seg, block_q=16, block_k=16
        )
        ref = dense_segment_reference(q, k, v, seg)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_segments_blocks_straddle_boundaries(self):
        """Block sizes that do NOT align with segment boundaries must
        still mask exactly (a tile can contain pieces of 3 segments)."""
        q, k, v = qkv(jax.random.PRNGKey(11), t=64)
        seg = _segments(t=64, seed=12)
        for bq, bk in [(8, 32), (32, 8), (64, 64)]:
            out = flash_attention(
                q, k, v, segment_ids=seg, block_q=bq, block_k=bk
            )
            ref = dense_segment_reference(q, k, v, seg)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5,
                err_msg=f"bq={bq} bk={bk}",
            )

    def test_segments_rejects_both_masks(self):
        q, k, v = qkv(jax.random.PRNGKey(12), t=32)
        seg = _segments(t=32)
        with pytest.raises(ValueError, match="not both"):
            flash_attention(q, k, v, jnp.ones((2, 32), jnp.int32), segment_ids=seg)

    def test_segments_backward_matches_dense(self):
        rng = np.random.default_rng(3)
        b, t, h, d = 2, 32, 2, 8
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
            for _ in range(3)
        )
        seg = _segments(b=b, t=t, seed=14, max_segments=3)
        cot = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
        gf = jax.grad(
            lambda *a: jnp.sum(
                flash_attention(*a, segment_ids=seg, block_q=8, block_k=16)
                * cot
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        gd = jax.grad(
            lambda *a: jnp.sum(dense_segment_reference(*a, seg) * cot),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b_ in zip("qkv", gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=1e-4, err_msg=f"d{name}"
            )

    def test_segments_padding_gets_zero_grad(self):
        """Padding tokens (seg 0) are outside every softmax support —
        their q/k/v gradients must be EXACTLY zero."""
        rng = np.random.default_rng(4)
        b, t, h, d = 1, 32, 1, 8
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
            for _ in range(3)
        )
        seg = jnp.asarray(
            np.where(np.arange(t)[None, :] < 20, 1 + np.arange(t)[None, :] // 10, 0),
            jnp.int32,
        )
        dq, dk, dv = jax.grad(
            lambda *a: jnp.sum(
                flash_attention(*a, segment_ids=seg, block_q=8, block_k=8)
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        pad = np.asarray(seg)[0] == 0
        assert np.all(np.asarray(dq)[0, pad] == 0)
        assert np.all(np.asarray(dk)[0, pad] == 0)
        assert np.all(np.asarray(dv)[0, pad] == 0)


@pytest.mark.slow  # interpret-mode Pallas accuracy study (VERDICT r5 item 6); the parity + backward tests stay tier-1
def test_flash_is_more_accurate_than_dense_reference_in_bf16():
    """The flash-numerics adjudication's core claim, pinned on the
    interpret path (same dtype chain as Mosaic, different op order):
    against an f32-truth dense attention, the bf16 flash kernel's error
    stays within the 4-ulp bound AND below the bf16 dense reference's
    own error (the dense path rounds softmax P to bf16 before PV,
    ring_attention.py:71; flash keeps P in f32).  At (256, 128) the
    flash-vs-dense diff here reproduces the on-HW probe's 0.015625
    exactly — the 'match_dense: false' at naive atol 2e-3 was a
    tolerance bug, not kernel numerics (FLASH_PROBE.json, VERDICT r4
    item 2)."""
    h, d = 12, 64
    b, t = 64, 128  # same seq as the flagship; smaller batch for CI
    q = jax.random.normal(
        jax.random.fold_in(jax.random.PRNGKey(0), 7), (b, t, h, d), jnp.bfloat16
    )
    mask = jnp.ones((b, t), jnp.int32)
    qf = q.astype(jnp.float32)
    truth = np.asarray(dense_attention_reference(qf, qf, qf, mask))
    dense_bf16 = np.asarray(
        dense_attention_reference(q, q, q, mask)
    ).astype(np.float32)
    flash_bf16 = np.asarray(
        flash_attention(q, q, q, mask, block_q=256, block_k=256)
    ).astype(np.float32)
    scale = float(np.max(np.abs(truth)))
    bound = 4.0 * 2.0**-8 * scale  # 4 x eps_bf16 x out scale
    err_flash = float(np.max(np.abs(flash_bf16 - truth)))
    err_dense = float(np.max(np.abs(dense_bf16 - truth)))
    assert err_flash <= bound, (err_flash, bound)
    assert err_flash <= err_dense, (err_flash, err_dense)
    # The on-HW corroboration the adjudication cites: the interpret
    # path's flash-vs-dense diff lands on FLASH_PROBE.json's 0.015625
    # (exactly, on this jax version) — within 1-2 bf16 ulp of the
    # output scale either way, so the on-silicon divergence is fully
    # explained by the dtype chain.  Asserted as the ulp window, not
    # exact equality: an f32 reduction-order change across jax/XLA
    # versions may shift one element by an adjacent bf16 step without
    # touching the property this test guards.
    flash_vs_dense = float(np.max(np.abs(flash_bf16 - dense_bf16)))
    assert flash_vs_dense <= 2 * 0.015625, flash_vs_dense
