"""Flash attention kernel vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.ops.pallas_attention import flash_attention
from svoc_tpu.parallel.ring_attention import dense_attention_reference


def qkv(key, b=2, t=128, h=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d), dtype),
        jax.random.normal(kk, (b, t, h, d), dtype),
        jax.random.normal(kv, (b, t, h, d), dtype),
    )


class TestFlashAttention:
    def test_matches_dense(self):
        q, k, v = qkv(jax.random.PRNGKey(0))
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_matches_dense_with_padding(self):
        q, k, v = qkv(jax.random.PRNGKey(1))
        kmask = (
            jax.random.uniform(jax.random.PRNGKey(2), k.shape[:2]) > 0.4
        ).astype(jnp.int32)
        kmask = kmask.at[:, 0].set(1)
        out = flash_attention(q, k, v, kmask, block_q=32, block_k=32)
        ref = dense_attention_reference(q, k, v, kmask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_fully_masked_block_stable(self):
        """A K block that is 100% padding must not produce NaNs."""
        q, k, v = qkv(jax.random.PRNGKey(3), t=64)
        kmask = jnp.zeros((2, 64), jnp.int32).at[:, :32].set(1)
        out = flash_attention(q, k, v, kmask, block_q=32, block_k=32)
        ref = dense_attention_reference(q, k, v, kmask)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_bf16(self):
        q, k, v = qkv(jax.random.PRNGKey(4), dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        assert out.dtype == jnp.bfloat16
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32),
            np.asarray(ref, np.float32),
            atol=3e-2,
        )

    def test_rejects_indivisible_seq(self):
        q, k, v = qkv(jax.random.PRNGKey(5), t=100)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64)
