"""Fleet observability plane (docs/OBSERVABILITY.md §fleet-plane):
hop-chain join completeness, aggregator merge math, anomaly
determinism, replay invisibility, and retired-replica monotonicity
over the seeded kill/failover + migrate fleet scenario."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pytest

from svoc_tpu.obsplane.anomaly import AnomalyConfig, AnomalyDetector
from svoc_tpu.obsplane.fleet import (
    ACCOUNTING_FAMILIES,
    FleetAggregator,
)
from svoc_tpu.obsplane.hopchain import chain_stats, join_hop_chains
from svoc_tpu.obsplane.timeline import ObservationLog, read_observations
from svoc_tpu.utils.metrics import MetricsRegistry

# ---------------------------------------------------------------------------
# seeded fleet scenario: plane ON and OFF, module-cached (one run each)
# ---------------------------------------------------------------------------

PLAN = dict(
    seed=3,
    n_replicas=3,
    n_claims=3,
    total_steps=8,
    arrivals_per_step=4,
    kill_replica="r1",
    kill_at_step=4,
    migrate_at_step=7,
)


@pytest.fixture(scope="module")
def fleet_runs(tmp_path_factory):
    from svoc_tpu.cluster.scenario import run_cluster_scenario

    runs = {}
    for tag, plane in (("on", True), ("off", False)):
        workdir = str(tmp_path_factory.mktemp(f"fleet-obs-{tag}"))
        runs[tag] = run_cluster_scenario(
            workdir, PLAN["seed"], fleet_plane=plane,
            **{k: v for k, v in PLAN.items() if k != "seed"},
        )
    return runs


def hop_records(result):
    recs = []
    for path in result["fleet_obs"]["obs_paths"].values():
        recs.extend(
            r for r in read_observations(path) if r.get("obs") == "hop"
        )
    return recs


# ---------------------------------------------------------------------------
# replay invisibility (the tentpole gate)
# ---------------------------------------------------------------------------


def test_plane_invisible_to_fleet_fingerprint(fleet_runs):
    on, off = fleet_runs["on"], fleet_runs["off"]
    assert on["fleet_fingerprint"] == off["fleet_fingerprint"]
    for cid, claim in on["claims"].items():
        assert claim["fingerprint"] == off["claims"][cid]["fingerprint"]


def test_off_run_carries_no_plane_state(fleet_runs):
    assert fleet_runs["off"]["fleet_obs"] == {"enabled": False}


# ---------------------------------------------------------------------------
# hop-chain join completeness
# ---------------------------------------------------------------------------


def test_hop_join_gapless(fleet_runs):
    """Every chain classifies; complete forward chains exactly equal
    the router's cluster_forwarded counter total — no hop is invisible
    to the cross-replica join."""
    chains = join_hop_chains(hop_records(fleet_runs["on"]))
    assert chains, "scenario produced no hop chains"
    stats = chain_stats(chains)
    classified = sum(stats["by_classification"].values())
    assert classified == stats["chains"]
    assert set(stats["by_classification"]) <= {
        "complete", "terminal", "died_mid_hop"
    }

    forwarded = sum(
        e["count"]
        for counters in fleet_runs["on"]["fleet_obs"][
            "per_source_counters"
        ].values()
        for e in counters
        if e["name"] == "cluster_forwarded"
    )
    complete_forwards = sum(
        1
        for c in chains.values()
        if c["reason"] == "forward" and c["classification"] == "complete"
    )
    assert complete_forwards == forwarded


def test_failover_chain_joins_across_replicas(fleet_runs):
    """The failover migration hop has BOTH sides (send on the recovery
    stack, recv on the adopter) — the cross-replica causal edge."""
    chains = join_hop_chains(hop_records(fleet_runs["on"]))
    failovers = [c for c in chains.values() if c["reason"] == "failover"]
    assert failovers
    for c in failovers:
        assert c["classification"] == "complete"
        sides = {r["data"]["side"] for r in c["records"]}
        assert {"send", "recv"} <= sides
        assert c["src"] != c["dst"]


def test_mid_hop_death_classification():
    """A send with no matching recv/end is a died-mid-hop chain; an
    answered retry keeps its dead first attempt visible."""
    base = {"chain": "h000001", "claim": "c9", "src": "a", "dst": "b",
            "reason": "forward"}
    died = [{"obs": "hop", "data": {**base, "side": "send", "hop": 0}}]
    chains = join_hop_chains(died)
    assert chains["h000001"]["classification"] == "died_mid_hop"
    assert chains["h000001"]["outcome"] == "lost"
    assert chains["h000001"]["dead_attempts"] == [0]

    retried = died + [
        {"obs": "hop", "data": {**base, "side": "send", "hop": 1}},
        {"obs": "hop", "data": {**base, "side": "recv", "hop": 1}},
    ]
    chains = join_hop_chains(retried)
    assert chains["h000001"]["classification"] == "complete"
    assert chains["h000001"]["dead_attempts"] == [0]
    assert chains["h000001"]["outcome"] == "delivered"


# ---------------------------------------------------------------------------
# aggregator merge math
# ---------------------------------------------------------------------------


def test_merge_counters_sum_and_gauges_label():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("serving_admitted").add(3)
    b.counter("serving_admitted").add(4)
    a.counter("serving_shed", labels={"reason": "queue"}).add(2)
    a.gauge("queue_depth").set(5)
    b.gauge("queue_depth").set(7)

    merged = FleetAggregator().merge({"r0": a, "r1": b})
    assert merged.family_total("serving_admitted") == 7.0
    shed = merged.family_series("serving_shed")
    assert shed == [({"reason": "queue"}, 2.0)]
    # Gauges cannot sum — one series per replica.
    depths = {
        tuple(sorted(lbl.items())): g.get()
        for (key, g) in merged.gauges.items()
        for (name, lbl) in [merged._labels.get(key, (key, {}))]
        if name == "queue_depth"
    }
    assert depths == {
        (("replica", "r0"),): 5.0,
        (("replica", "r1"),): 7.0,
    }


def test_merge_histograms_bucket_wise_and_timers():
    a, b = MetricsRegistry(), MetricsRegistry()
    grid = (0.1, 1.0)
    for v in (0.05, 0.5):
        a.histogram("latency", buckets=grid).observe(v)
    b.histogram("latency", buckets=grid).observe(5.0)
    a.timer("step").observe(0.2)
    b.timer("step").observe(0.4)

    merged = FleetAggregator().merge({"r0": a, "r1": b})
    h = merged.histogram("latency", buckets=grid)
    assert h.count == 3
    assert h.sum == pytest.approx(5.55)
    assert h._counts == [1, 1, 1]  # one per bucket incl. +Inf overflow
    t = merged.timer("step")
    assert t.n == 2
    assert t.total_s == pytest.approx(0.6)
    assert t.max_s == pytest.approx(0.4)


def test_merge_histogram_grid_mismatch_keeps_replica_series():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("latency", buckets=(0.1, 1.0)).observe(0.5)
    b.histogram("latency", buckets=(0.2, 2.0)).observe(0.5)

    merged = FleetAggregator().merge({"r0": a, "r1": b})
    # First grid wins the unlabeled series; the mismatched source is
    # preserved under its replica label instead of corrupting bucket
    # sums (docs/OBSERVABILITY.md §fleet-plane).
    labeled = [
        lbl
        for key in merged.histograms
        for (name, lbl) in [merged._labels.get(key, (key, {}))]
        if name == "latency" and lbl
    ]
    assert {"replica": "r1"} in labeled


def test_retired_fold_under_retired_label():
    live = MetricsRegistry()
    live.counter("serving_completed").add(10)
    agg = FleetAggregator()
    agg.retire("r1", [
        {"name": "serving_completed", "labels": {}, "count": 6.0},
    ])
    merged = agg.merge({"r0": live})
    assert merged.family_total("serving_completed") == 16.0
    series = dict(
        (tuple(sorted(lbl.items())), n)
        for lbl, n in merged.family_series("serving_completed")
    )
    assert series[(("replica", "r1@retired"),)] == 6.0


# ---------------------------------------------------------------------------
# retired-replica monotonicity through the kill
# ---------------------------------------------------------------------------


def test_fleet_totals_never_step_backward(fleet_runs):
    history = fleet_runs["on"]["fleet_obs"]["accounting_history"]
    # The scenario drives at least one step_all per planned step (the
    # failover window adds a recovery step).
    assert len(history) >= PLAN["total_steps"]
    for family in ACCOUNTING_FAMILIES:
        series = [h.get(family, 0.0) for h in history]
        for prev, cur in zip(series, series[1:]):
            assert cur >= prev, (
                f"{family} stepped backward: {series}"
            )


def test_retired_replica_in_snapshot_and_accounting(fleet_runs):
    snap = fleet_runs["on"]["fleet_obs"]
    assert snap["enabled"] is True
    assert "r1" in snap["retired"]
    assert "r1" not in snap["sources"]
    obs = snap["observations"]
    assert "router" in obs
    for acct in obs.values():
        assert acct["records"] >= 0
        assert acct["last_seq"] >= acct["records"]
        assert acct["dropped"] == 0


# ---------------------------------------------------------------------------
# anomaly detector determinism
# ---------------------------------------------------------------------------

SERIES = [0, 0, 1, 0, 1, 9, 18, 28, 29, 30]


def run_detector(cfg=None):
    det = AnomalyDetector(cfg)
    alerts = []
    for step, total in enumerate(SERIES):
        alerts.extend(det.on_step(step, {("r0", "serving_shed"): total}))
    return det, alerts


def test_anomaly_deterministic_and_sustained():
    _, first = run_detector()
    _, second = run_detector()
    assert first == second
    assert first, "the step series must breach"
    sustained = [a for a in first if a["sustained"]]
    assert len(sustained) == 1
    assert sustained[0]["streak"] == AnomalyConfig().sustain_steps
    # Streaks keep counting past the sustained edge.
    assert max(a["streak"] for a in first) > sustained[0]["streak"]


def test_anomaly_breaches_not_absorbed():
    """A breach must not teach the baseline that shedding is normal:
    the EWMA mean is identical before and after the breach step."""
    det = AnomalyDetector()
    for step, total in enumerate(SERIES[:5]):
        det.on_step(step, {("r0", "serving_shed"): total})
    state = det._series[("r0", "serving_shed")]
    mean_before = state.mean
    alerts = det.on_step(5, {("r0", "serving_shed"): SERIES[5]})
    assert alerts and alerts[0]["trigger"] == "z"
    assert state.mean == mean_before


def test_anomaly_guardrail_always_armed():
    cfg = AnomalyConfig(guardrails={"serving_shed": 4.0})
    det = AnomalyDetector(cfg)
    det.on_step(0, {("r0", "serving_shed"): 0})
    alerts = det.on_step(1, {("r0", "serving_shed"): 5})
    assert alerts and alerts[0]["trigger"] == "guardrail"


def test_anomaly_quiet_on_healthy_scenario(fleet_runs):
    """The small seeded plan degrades gently (deltas under min_delta's
    reach of the learned baseline) — no SUSTAINED page, so the smoke's
    dedicated degradation leg is what exercises the trigger chain."""
    snap = fleet_runs["on"]["fleet_obs"]
    sustained = [a for a in snap["recent_anomalies"] if a["sustained"]]
    assert not sustained
    assert snap["bundles"] == []


# ---------------------------------------------------------------------------
# observation-channel loss accounting
# ---------------------------------------------------------------------------


def test_obs_lines_dropped_latch_and_counter(tmp_path):
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")
    metrics = MetricsRegistry()
    log = ObservationLog(
        trace_path=str(blocker / "obs.jsonl"),
        metrics=metrics,
        owner="r9",
    )
    log.record("probe", n=1)
    assert log.write_error_latched
    log.record("probe", n=2)
    assert log.dropped >= 2
    series = dict(
        (tuple(sorted(lbl.items())), n)
        for lbl, n in metrics.family_series("obs_lines_dropped")
    )
    assert series[(("replica", "r9"),)] == float(log.dropped)
    # The ring keeps every record the sidecar lost.
    assert log.last_seq() == 2
    assert len(log.recent(10)) == 2


def test_fleet_accounting_carries_observations(fleet_runs):
    acct = fleet_runs["on"]["fleet_obs"]
    live = acct["observations"]
    assert set(live) >= {"router", "r0", "r2"}
    exposition = acct["exposition"]
    assert "svoc_serving_admitted_total" in exposition
    assert 'replica="r1@retired"' in exposition
