"""Sequence-parallel forward vs the plain flax encoder, same params."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.parallel.mesh import MeshSpec, make_mesh
from svoc_tpu.parallel.sp_encoder import sequence_parallel_forward_fn


@pytest.fixture(scope="module")
def setup():
    cfg = TINY_TEST
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    mesh = make_mesh(MeshSpec(("seq",), (8,)))
    fwd = sequence_parallel_forward_fn(mesh, cfg)
    return cfg, model, params, fwd


def batch(cfg, key, b=2, t=64, lengths=None):
    ids = jax.random.randint(key, (b, t), 4, cfg.vocab_size, jnp.int32)
    mask = np.ones((b, t), np.int32)
    if lengths:
        ids = np.array(ids)  # writable copy
        for i, ln in enumerate(lengths):
            mask[i, ln:] = 0
            ids[i, ln:] = cfg.pad_id
        ids = jnp.asarray(ids)
    return ids, jnp.asarray(mask)


class TestSequenceParallelEncoder:
    def test_matches_dense_full_mask(self, setup):
        cfg, model, params, fwd = setup
        ids, mask = batch(cfg, jax.random.PRNGKey(0))
        ref = model.apply(params, ids, mask)
        out = fwd(params, ids, mask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4
        )

    def test_matches_dense_with_padding(self, setup):
        """Padding spanning shard boundaries: global position ids and
        ring attention masking must both hold."""
        cfg, model, params, fwd = setup
        ids, mask = batch(
            cfg, jax.random.PRNGKey(1), b=3, t=64, lengths=[64, 23, 5]
        )
        ref = model.apply(params, ids, mask)
        out = fwd(params, ids, mask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4
        )

    def test_long_sequence_beyond_single_block(self, setup):
        cfg, model, params, fwd = setup
        t = cfg.max_len  # 64 for TINY_TEST: 8 tokens per shard
        ids, mask = batch(cfg, jax.random.PRNGKey(2), b=1, t=t)
        ref = model.apply(params, ids, mask)
        out = fwd(params, ids, mask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4
        )


def test_sp_forward_flash_inner_matches_dense(setup):
    """cfg.attention='flash' runs the Pallas kernel inside every ring
    hop; logits must still match the dense single-device encoder,
    padding included."""
    cfg, model, params, _ = setup
    flash_cfg = dataclasses.replace(cfg, attention="flash")
    mesh = make_mesh(MeshSpec(("seq",), (8,)))
    fwd = sequence_parallel_forward_fn(mesh, flash_cfg)
    ids, mask = batch(cfg, jax.random.PRNGKey(2), b=3, t=64, lengths=[64, 30, 9])
    ref = model.apply(params, ids, mask)
    out = fwd(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_sp_encoder_gradient_matches_dense():
    """The sequence-parallel classifier is differentiable end to end
    (ring custom VJP inside shard_map): parameter gradients must match
    the dense encoder's."""
    mesh = make_mesh(MeshSpec(("seq",), (8,)))
    cfg = TINY_TEST
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    sp_fwd = sequence_parallel_forward_fn(mesh, cfg)
    rng = np.random.default_rng(3)
    t = 64
    ids = jnp.asarray(rng.integers(4, cfg.vocab_size, (2, t)), jnp.int32)
    mask = jnp.ones((2, t), jnp.int32)
    g_sp = jax.grad(lambda p: jnp.sum(sp_fwd(p, ids, mask) ** 2))(params)
    g_dense = jax.grad(lambda p: jnp.sum(model.apply(p, ids, mask) ** 2))(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_sp), jax.tree_util.tree_leaves(g_dense)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)
