"""Fixed-point codec parity with the reference arithmetic.

Golden values follow ``contract/src/signed_decimal.cairo`` and
``client/contract.py:35-53``.
"""

import numpy as np
import pytest

from svoc_tpu.ops import fixedpoint as fp


def test_wsad_constants():
    assert fp.WSAD == 1_000_000
    assert fp.HALF_WSAD == 500_000


def test_div_trunc_toward_zero():
    # Cairo I128Div is sign-magnitude: -7 / 2 == -3, not Python's -4.
    assert fp.div_trunc(7, 2) == 3
    assert fp.div_trunc(-7, 2) == -3
    assert fp.div_trunc(7, -2) == -3
    assert fp.div_trunc(-7, -2) == 3


def test_wsad_mul_rounding():
    # (a*b + 0.5e6) / 1e6, truncating.
    assert fp.wsad_mul(fp.WSAD, fp.WSAD) == fp.WSAD
    assert fp.wsad_mul(500_000, 500_000) == 250_000  # 0.5*0.5
    assert fp.wsad_mul(1, 1) == 0  # 1e-12 rounds to 0... (1+5e5)//1e6 = 0
    assert fp.wsad_mul(1_500_000, 1_000_001) == 1_500_002  # rounded up
    # negative product keeps the +half bias then truncates toward zero
    assert fp.wsad_mul(-500_000, 500_000) == -249_999


def test_wsad_div():
    assert fp.wsad_div(fp.WSAD, fp.WSAD) == fp.WSAD
    assert fp.wsad_div(1, 3) == 333_333  # (1*1e6 + 1) / 3 truncated
    assert fp.wsad_div(fp.WSAD, 3 * fp.WSAD) == 333_333
    assert fp.wsad_div(2 * fp.WSAD, 3 * fp.WSAD) == 666_667  # rounds


def test_sqrt_newton():
    # test_math.cairo:21-37: sqrt(9) == 3 in wsad.
    assert fp.wsad_sqrt(9 * fp.WSAD) == 3 * fp.WSAD
    assert fp.wsad_sqrt(0) == 0
    assert abs(fp.wsad_sqrt(2 * fp.WSAD) - 1_414_213) <= 1
    # converges for large values within the 50-iteration cap
    v = fp.wsad_sqrt(fp.to_wsad(400.0))
    assert abs(v - fp.to_wsad(20.0)) <= 2


def test_felt_roundtrip():
    for x in [0.0, 0.5, -0.5, 123.456789, -123.456789, 1e-6, -1e-6]:
        felt = fp.float_to_fwsad(x)
        assert 0 <= felt < fp.FELT_PRIME
        back = fp.fwsad_to_float(felt)
        assert back == pytest.approx(x, abs=1e-6)
    # negatives wrap above I128_MAX
    assert fp.float_to_fwsad(-1.0) > fp.I128_MAX


def test_encode_decode_vector():
    v = np.array([0.25, -0.75, 3.5])
    felts = fp.encode_vector(v)
    out = fp.decode_vector(felts)
    np.testing.assert_allclose(out, v, atol=1e-6)


def test_quantize_matches_to_wsad():
    xs = np.array([0.1234567, -0.1234567, 2.0000005])
    q = fp.quantize(xs)
    for x, qx in zip(xs, q):
        assert qx == pytest.approx(fp.from_wsad(fp.to_wsad(float(x))), abs=1e-12)


def test_to_cairo_fixture_reproduces_recorded_vectors():
    """The fixture generator must emit the exact source lines recorded
    in the reference contract test (test_contract.cairo:253-261 — the
    Gaussian fixture's first rows), incl. prime-wrapped negatives."""
    from svoc_tpu.ops.fixedpoint import FELT_PRIME, to_cairo_fixture

    out = to_cairo_fixture([[20.202804, 16.401132], [25.630344, 13.501687]])
    assert out.splitlines() == [
        "array![20202804, 16401132].span(),",
        "array![25630344, 13501687].span(),",
    ]
    neg = to_cairo_fixture([[-1.5]])
    assert neg == f"array![{FELT_PRIME - 1_500_000}].span(),"


class TestWsadToString:
    """``utils.cairo:283-297`` decimal rendering (truncated, lfilled)."""

    def test_reference_shapes(self):
        from svoc_tpu.ops.fixedpoint import wsad_to_string

        assert wsad_to_string(1_234_567, 3) == "1.234"
        assert wsad_to_string(1_234_567, 6) == "1.234567"
        assert wsad_to_string(-500_000, 3) == "-0.500"
        assert wsad_to_string(20_714_285, 3) == "20.714"
        # lfill zero-padding: 0.004999 at 3 digits is "0.004"
        assert wsad_to_string(4_999, 3) == "0.004"
        # truncation, never rounding (Cairo integer division)
        assert wsad_to_string(999_999, 2) == "0.99"
        assert wsad_to_string(0, 3) == "0.000"
        assert wsad_to_string(7, 0) == "0."

    def test_felt_roundtrip(self):
        from svoc_tpu.ops.fixedpoint import (
            felt_wsad_to_string,
            float_to_fwsad,
        )

        assert felt_wsad_to_string(float_to_fwsad(-1.25), 3) == "-1.250"
        assert felt_wsad_to_string(float_to_fwsad(2.5), 2) == "2.50"

    def test_bad_digits_rejected(self):
        import pytest

        from svoc_tpu.ops.fixedpoint import wsad_to_string

        with pytest.raises(ValueError):
            wsad_to_string(1, 7)
