"""Crash-consistent durability (ISSUE 8): commit-intent WAL, restart
reconciliation, snapshot/restore, graceful drain, shutdown bundles."""

import json
import os

import numpy as np
import pytest

from svoc_tpu.consensus.state import OracleConsensusContract
from svoc_tpu.durability import (
    CommitIntentWAL,
    DurableLocalBackend,
    duplicate_predictions,
    payload_digest,
    read_wal,
    reconcile_wal,
    replay_chain_log,
)
from svoc_tpu.durability.wal import seal_jsonl
from svoc_tpu.io.chain import ChainAdapter, ChainCommitError, LocalChainBackend
from svoc_tpu.resilience import RetryPolicy, commit_fleet_with_resume
from svoc_tpu.utils.events import EventJournal, read_trace_events
from svoc_tpu.utils.metrics import MetricsRegistry

ADMINS = [0xA0, 0xA1, 0xA2]
ORACLES = [0x10 + i for i in range(7)]


def make_contract(**kwargs):
    defaults = dict(
        admins=ADMINS,
        oracles=ORACLES,
        required_majority=2,
        n_failing_oracles=2,
        constrained=True,
        dimension=6,
    )
    defaults.update(kwargs)
    return OracleConsensusContract(**defaults)


def fleet_predictions(seed=0, n=7, dim=6):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.05, 0.95, size=(n, dim))


def fast_policy(**kwargs):
    defaults = dict(max_attempts=4, base_s=0.0, cap_s=0.0, jitter_seed=0)
    defaults.update(kwargs)
    return RetryPolicy(**defaults)


def encode_fleet(predictions):
    from svoc_tpu.ops.fixedpoint import encode_vector

    return [encode_vector(p) for p in predictions]


# ---------------------------------------------------------------------------
# WAL mechanics
# ---------------------------------------------------------------------------


class TestCommitIntentWAL:
    def test_cycle_records_round_trip(self, tmp_path):
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        payloads = encode_fleet(fleet_predictions())
        cycle = wal.cycle(
            "blk1-000001", claim="alpha", oracles=ORACLES, payloads=payloads
        )
        cycle.new_attempt(0)
        cycle.intent(0, ORACLES[0], payloads[0])
        cycle.landed(0)
        cycle.done(1)
        kinds = [r["kind"] for r in wal.records()]
        assert kinds == ["cycle", "intent", "landed", "done"]
        assert wal.records()[0]["payloads"][0] == payloads[0]
        assert wal.completed_lineages() == {"blk1-000001"}

    def test_torn_tail_is_ignored_and_sealed(self, tmp_path):
        path = str(tmp_path / "wal.jsonl")
        wal = CommitIntentWAL(path)
        wal.cycle("blk1-000001", oracles=[1], payloads=[[5]])
        wal.close()
        with open(path, "a") as f:
            f.write('{"kind": "intent", "slo')  # the mid-append kill
        records = read_wal(path)
        assert [r["kind"] for r in records] == ["cycle"]
        # A new WAL over the same file seals the torn bytes so later
        # appends cannot corrupt two lines at once.
        wal2 = CommitIntentWAL(path)
        wal2.close_cycle("blk1-000001")
        assert [r["kind"] for r in wal2.records()] == ["cycle", "done"]

    def test_seal_jsonl_truncates_only_torn_bytes(self, tmp_path):
        path = str(tmp_path / "x.jsonl")
        with open(path, "w") as f:
            f.write('{"a": 1}\n{"b": 2}\n{"torn')
        assert seal_jsonl(path)
        assert open(path).read() == '{"a": 1}\n{"b": 2}\n'
        assert not seal_jsonl(path)  # idempotent

    def test_rotate_refuses_open_cycles(self, tmp_path):
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        wal.cycle("blk1-000001", oracles=[1], payloads=[[5]])
        with pytest.raises(RuntimeError, match="open cycles"):
            wal.rotate()
        wal.close_cycle("blk1-000001")
        wal.rotate()
        assert wal.records() == []
        assert os.path.exists(str(tmp_path / "wal.jsonl.1"))
        # Post-rotation, the dedup set restarts empty.
        assert wal.completed_lineages() == set()


# ---------------------------------------------------------------------------
# The pre-report death window (satellite regression)
# ---------------------------------------------------------------------------


class LyingBackend:
    """Dies at one oracle's tx but reports an OVER-ADVANCED committed
    index with ``sent_count=None`` — the backend crashed before its
    partial-commit accounting ran (legacy/third-party raiser shape)."""

    def __init__(self, contract, fail_at, overstate=2, fail_times=1):
        self.inner = LocalChainBackend(contract)
        self.fail_at = fail_at
        self.overstate = overstate
        self.fail_times = fail_times
        self.sends = {}

    def call(self, fn):
        return self.inner.call(fn)

    def call_as(self, caller, fn):
        return self.inner.call_as(caller, fn)

    def invoke(self, caller, fn, /, **kwargs):
        if fn == "update_prediction":
            idx = self.inner.contract.get_oracle_list().index(caller)
            if idx == self.fail_at and self.fail_times > 0:
                self.fail_times -= 1
                raise ChainCommitError(
                    committed=idx + self.overstate,  # the lie
                    total=len(self.inner.contract.get_oracle_list()),
                    failed_oracle=caller,
                    cause=RuntimeError("backend died before reporting"),
                    sent_count=None,
                )
            self.sends[caller] = self.sends.get(caller, 0) + 1
        return self.inner.invoke(caller, fn, **kwargs)


class TestPreReportDeathWindow:
    def test_wal_cursor_rescues_overadvanced_resume(self, tmp_path):
        contract = make_contract()
        backend = LyingBackend(contract, fail_at=3)
        adapter = ChainAdapter(backend)
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        predictions = fleet_predictions()
        cycle = wal.cycle(
            "blk1-000001",
            oracles=ORACLES,
            payloads=encode_fleet(predictions),
        )
        outcome = commit_fleet_with_resume(
            adapter,
            predictions,
            fast_policy(),
            sleep=lambda s: None,
            registry=MetricsRegistry(),
            journal=EventJournal(registry=MetricsRegistry()),
            wal=cycle,
        )
        # The WAL cursor pinned the resume at the REAL failure index:
        # every oracle's tx landed exactly once, none skipped.
        assert outcome.complete and outcome.sent == 7
        assert all(backend.sends[o] == 1 for o in ORACLES)
        assert contract.consensus_active

    def test_without_wal_the_lie_loses_transactions(self):
        # The pre-fix behavior, pinned so the regression stays visible:
        # trusting the over-advanced index skips the slots the backend
        # never actually sent.
        contract = make_contract()
        backend = LyingBackend(contract, fail_at=3)
        adapter = ChainAdapter(backend)
        outcome = commit_fleet_with_resume(
            adapter,
            fleet_predictions(),
            fast_policy(),
            sleep=lambda s: None,
            registry=MetricsRegistry(),
            journal=EventJournal(registry=MetricsRegistry()),
        )
        assert ORACLES[3] not in backend.sends  # lost
        assert ORACLES[4] not in backend.sends  # lost
        # Only 5 txs actually landed — and the lie fools the
        # accounting too: the index-delta fallback credits the phantom
        # slots, so outcome.sent even over-reports.
        assert sum(backend.sends.values()) == 5
        assert outcome.sent >= 7


# ---------------------------------------------------------------------------
# Reconciliation decision table
# ---------------------------------------------------------------------------


class DeadReadsBackend:
    """Writes work; the value-list read the reconciler needs fails —
    the 'backend unreachable' column."""

    def __init__(self, contract):
        self.inner = LocalChainBackend(contract)

    def call(self, fn):
        return self.inner.call(fn)

    def call_as(self, caller, fn):
        raise RuntimeError("rpc down")

    def invoke(self, caller, fn, /, **kwargs):
        return self.inner.invoke(caller, fn, **kwargs)


def open_cycle_wal(tmp_path, predictions, landed_slots, sent_slots,
                   skip=()):
    """A WAL as a crash would leave it: cycle open, ``sent_slots``
    actually on chain, ``landed_slots`` ⊆ sent with durable records."""
    contract = make_contract()
    backend = LocalChainBackend(contract)
    adapter = ChainAdapter(backend)
    payloads = encode_fleet(predictions)
    wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
    cycle = wal.cycle(
        "blk1-000001", oracles=ORACLES, payloads=payloads, skip=skip
    )
    cycle.new_attempt(0)
    for slot in sent_slots:
        cycle.intent(slot, ORACLES[slot], payloads[slot])
        adapter._invoke_prediction_felts(ORACLES[slot], payloads[slot])
        if slot in landed_slots:
            cycle.landed(slot)
    return wal, contract, adapter


class TestReconcileDecisionTable:
    def test_reachable_backend_all_cells(self, tmp_path):
        predictions = fleet_predictions()
        wal, contract, adapter = open_cycle_wal(
            tmp_path, predictions,
            landed_slots={0, 1}, sent_slots=[0, 1, 2], skip=(6,),
        )
        journal = EventJournal(registry=MetricsRegistry())
        report = reconcile_wal(
            wal, lambda claim: adapter, journal=journal,
            registry=MetricsRegistry(),
        )
        (cycle,) = report.cycles
        by_slot = {v.slot: v for v in cycle.slots}
        assert by_slot[0].classification == "landed_durable"
        assert by_slot[1].classification == "landed_durable"
        # slot 2's tx hit the chain, its landed record did not: the
        # digest witness classifies it landed — NOT resent.
        assert by_slot[2].classification == "landed_chain"
        assert not by_slot[2].resent
        for slot in (3, 4, 5):
            assert by_slot[slot].classification == "stranded"
            assert by_slot[slot].resent
        assert by_slot[6].classification == "skipped"
        assert cycle.closed
        assert report.unknown == 0 and report.unaccounted == 0
        # The resends landed: every non-skip slot now stores its WAL
        # payload (slot 6 was quarantine-skipped, so the fleet is one
        # short of consensus activation — by design).
        payloads = wal.records()[0]["payloads"]
        for slot in range(6):
            assert adapter.get_the_prediction(slot) == payloads[slot]
        events = journal.recent(type="durability.reconcile")
        assert len(events) == 1 and events[0].data["stranded"] == 3
        # Idempotent: a second pass finds nothing open.
        assert reconcile_wal(
            wal, lambda claim: adapter, journal=journal,
            registry=MetricsRegistry(),
        ).open_cycles == 0

    def test_unreachable_backend_never_resends(self, tmp_path):
        predictions = fleet_predictions()
        wal, contract, _ = open_cycle_wal(
            tmp_path, predictions,
            landed_slots={0}, sent_slots=[0, 1],
        )
        dead = ChainAdapter(DeadReadsBackend(contract))
        invoked = []
        dead._invoke_prediction_felts = lambda *a: invoked.append(a)
        report = reconcile_wal(
            wal, lambda claim: dead,
            journal=EventJournal(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
        )
        (cycle,) = report.cycles
        by_slot = {v.slot: v for v in cycle.slots}
        # Durable evidence still classifies without the chain...
        assert by_slot[0].classification == "landed_durable"
        # ...everything else is unknown: no resend on missing evidence
        # (slot 1 IS on chain — resending it would be the duplicate).
        for slot in range(1, 7):
            assert by_slot[slot].classification == "unknown"
        assert not invoked
        assert not cycle.closed  # stays open for a later pass
        assert report.unaccounted == 0


# ---------------------------------------------------------------------------
# Durable chain log
# ---------------------------------------------------------------------------


class TestChainLog:
    def test_replay_rebuilds_contract_state(self, tmp_path):
        path = str(tmp_path / "chain.jsonl")
        contract = make_contract()
        adapter = ChainAdapter(DurableLocalBackend(contract, path))
        predictions = fleet_predictions()
        adapter.update_all_the_predictions(predictions, batch=False)
        fresh = make_contract()
        assert replay_chain_log(path, fresh) == 7
        assert fresh.consensus_active
        assert fresh.get_consensus_value() == contract.get_consensus_value()
        assert duplicate_predictions(path) == []

    def test_duplicate_detection(self, tmp_path):
        path = str(tmp_path / "chain.jsonl")
        backend = DurableLocalBackend(make_contract(), path)
        felts = encode_fleet(fleet_predictions())[0]
        backend.invoke(ORACLES[0], "update_prediction", prediction=felts)
        backend.invoke(ORACLES[0], "update_prediction", prediction=felts)
        assert len(duplicate_predictions(path)) == 1


# ---------------------------------------------------------------------------
# Journal durability: fsync writer, export/restore, trace-tail replay
# ---------------------------------------------------------------------------


class TestJournalDurability:
    def test_fsync_flag_from_env(self, tmp_path, monkeypatch):
        from svoc_tpu.utils.events import RotatingJsonlWriter

        monkeypatch.setenv(RotatingJsonlWriter.FSYNC_ENV, "1")
        w = RotatingJsonlWriter(
            str(tmp_path / "t.jsonl"), registry=MetricsRegistry()
        )
        assert w.fsync
        w.write_line('{"event": "x", "seq": 1}')
        w.close()
        monkeypatch.delenv(RotatingJsonlWriter.FSYNC_ENV)
        w2 = RotatingJsonlWriter(
            str(tmp_path / "t2.jsonl"), registry=MetricsRegistry()
        )
        assert not w2.fsync

    def test_export_restore_preserves_seqs_and_fingerprint(self):
        reg = MetricsRegistry()
        j = EventJournal(registry=reg)
        j.emit("block.fetched", lineage="blk1-000001", n_comments=3)
        j.emit("commit.sent", lineage="blk1-000001", sent=7)
        fp = j.fingerprint()
        restored = EventJournal(registry=MetricsRegistry())
        restored.restore(j.export_ring())
        assert restored.fingerprint() == fp
        assert restored.last_seq() == 2
        # Numbering continues, not restarts.
        assert restored.emit("commit.sent", sent=1).seq == 3

    def test_read_trace_events_filters_and_tolerates_torn_tail(
        self, tmp_path
    ):
        path = str(tmp_path / "trace.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"name": "fetch", "duration_s": 0.1}) + "\n")
            f.write(
                json.dumps({"event": "block.fetched", "seq": 1, "data": {}})
                + "\n"
            )
            f.write(
                json.dumps({"event": "commit.sent", "seq": 2, "data": {}})
                + "\n"
            )
            f.write('{"event": "commit.fai')  # torn by the kill
        events = read_trace_events(path)
        assert [e["seq"] for e in events] == [1, 2]  # span line skipped
        assert [e["seq"] for e in read_trace_events(path, since_seq=1)] == [2]


# ---------------------------------------------------------------------------
# Session + WAL integration (exactly-once across re-execution)
# ---------------------------------------------------------------------------


def make_session(tmp_path=None, wal=None):
    from conftest import fake_sentiment_vectorizer
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    store = CommentStore()
    store.save(SyntheticSource(batch=120, seed=7)())
    # This suite pins the PER-TX WAL record family (per-slot
    # intent/landed mechanics) regardless of the committed commit_mode
    # record — the batched family (intent_batch/landed_batch) has its
    # own coverage in tests/test_hotpath.py.
    session = Session(
        config=SessionConfig(commit_mode="per_tx"),
        store=store,
        vectorizer=fake_sentiment_vectorizer,
        journal=EventJournal(registry=MetricsRegistry()),
    )
    if wal is not None:
        session.attach_wal(wal)
    return session


class TestSessionWalIntegration:
    def test_commit_resilient_journals_cycle(self, tmp_path):
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        session = make_session(wal=wal)
        session.fetch()
        outcome = session.commit_resilient()
        assert outcome.complete
        kinds = [r["kind"] for r in wal.records()]
        assert kinds[0] == "cycle" and kinds[-1] == "done"
        assert kinds.count("intent") == 7 and kinds.count("landed") == 7
        assert wal.records()[0]["lineage"] == session.last_lineage

    def test_failure_closed_cycle_does_not_dedup_a_retry(self, tmp_path):
        # Review fix: a done record carrying failed=... must NOT let a
        # later retry silently no-op — the commit never completed.
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        cycle = wal.cycle("blk9-000001", oracles=[1], payloads=[[5]])
        cycle.done(0, failed="circuit_open")
        assert wal.completed_lineages() == set()
        # ...but it does NOT wedge rotation (its outcome was reported;
        # rotation only follows a snapshot, so it can never
        # re-execute) — one transient failure must not grow the active
        # log for the process lifetime.
        wal.rotate()
        assert wal.records() == []

    def test_reconcile_resolves_failure_closed_cycles(self, tmp_path):
        predictions = fleet_predictions()
        wal, contract, adapter = open_cycle_wal(
            tmp_path, predictions, landed_slots={0}, sent_slots=[0],
        )
        # The commit reported a failure (deadline mid-fleet) before
        # the crash: done{failed} closed it for reporting, not for
        # durability.
        wal.close_cycle("blk1-000001", sent=1, note=None)
        records = wal.records()
        # rewrite the done as failure-closed
        os.remove(wal.path)
        wal2 = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        for r in records[:-1]:
            wal2._append(r)
        wal2._append(
            {"kind": "done", "lineage": "blk1-000001", "sent": 1,
             "stranded": [], "failed": "deadline"}
        )
        report = reconcile_wal(
            wal2, lambda claim: adapter,
            journal=EventJournal(registry=MetricsRegistry()),
            registry=MetricsRegistry(),
        )
        (cycle,) = report.cycles
        assert cycle.count("stranded") == 6 and cycle.closed
        # Cleanly closed now: dedups and rotates.
        assert "blk1-000001" in wal2.completed_lineages()
        wal2.rotate()

    def test_replayed_lineage_skips_chain_writes(self, tmp_path):
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        session = make_session(wal=wal)
        session.fetch()
        first = session.commit_resilient()
        contract = session.adapter.backend.contract
        before = [list(o.value) for o in contract.oracles]
        # Re-execution of the same block (a snapshot-replayed step):
        # the WAL's done record short-circuits the chain writes.
        replay = session.commit_resilient()
        assert replay.sent == first.sent and replay.attempts == 0
        assert [list(o.value) for o in contract.oracles] == before
        events = session.journal.recent(type="commit.sent")
        assert events[-1].data.get("replayed") is True


# ---------------------------------------------------------------------------
# Snapshot / restore (multi-session) + changed membership
# ---------------------------------------------------------------------------


def make_multi(names, journal=None, metrics=None, scope="t"):
    from svoc_tpu.fabric.registry import ClaimSpec
    from svoc_tpu.fabric.scenario import deterministic_vectorizer
    from svoc_tpu.fabric.session import MultiSession
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource
    from svoc_tpu.sim.generators import claim_seed

    def store_factory(claim_id):
        store = CommentStore()
        store.save(SyntheticSource(batch=80, seed=claim_seed(3, claim_id))())
        return store

    multi = MultiSession(
        base_seed=3,
        vectorizer=deterministic_vectorizer,
        store_factory=store_factory,
        journal=journal if journal is not None else EventJournal(
            registry=MetricsRegistry()
        ),
        metrics=metrics if metrics is not None else MetricsRegistry(),
        lineage_scope=scope,
        max_claims_per_batch=len(names),
    )
    for name in names:
        multi.add_claim(ClaimSpec(claim_id=name))
    return multi


class TestSnapshotRestore:
    def test_round_trip_preserves_service_state(self, tmp_path):
        from svoc_tpu.utils.checkpoint import (
            load_snapshot,
            multi_session_to_dict,
            restore_multi_session,
            save_snapshot,
        )

        multi = make_multi(["alpha", "beta"])
        multi.run(3)
        session = multi.get("alpha").session
        session.supervisor.record_commit_failure(ORACLES[0])
        session.supervisor.step()
        path = str(tmp_path / "snapshot.json")
        save_snapshot(path, multi_session_to_dict(multi))

        fresh = make_multi(["alpha", "beta"])
        payload = load_snapshot(path)
        report = restore_multi_session(payload, fresh)
        assert report["restored"] == ["alpha", "beta"]
        assert not report["unclaimed"] and not report["fresh"]
        assert fresh.router.steps == 3
        restored = fresh.get("alpha")
        assert restored.cycles == 3
        rs = restored.session
        assert rs.simulation_step == session.simulation_step
        # health_snapshot keys off the cached oracle list — warm the
        # fresh adapter's cache like a real resume would.
        rs.adapter.call_oracle_list()
        assert rs.supervisor.health_snapshot() == (
            session.supervisor.health_snapshot()
        )
        # Lineage continuity: the next fetch mints claim 4, never a
        # re-mint of a published id.
        rs.fetch()
        assert rs.last_lineage == f"blk{'t'}-alpha-{4:06x}"

    def test_round_trip_preserves_predictions_and_operator_toggles(
        self, tmp_path
    ):
        """Regression for the SVOC013-confirmed snapshot gaps: the
        published predictions payload, the web-plane state_version, and
        the operator's auto_fetch/auto_commit/auto_resume toggles were
        mutable session state the durable serializers never read — a
        crash + recover silently reset them (the cursor said "window N
        published" with nothing left to commit, and an incident-time
        auto_commit OFF flipped back on)."""
        import numpy as np

        from svoc_tpu.utils.checkpoint import (
            multi_session_to_dict,
            restore_multi_session,
        )

        multi = make_multi(["alpha"])
        multi.run(2)
        session = multi.get("alpha").session
        assert session.predictions is not None  # run() published
        before_preds = np.asarray(session.predictions).copy()
        session.auto_fetch = True
        session.auto_commit = False
        session.auto_resume = True
        session.state_version += 3
        before_version = session.state_version

        fresh = make_multi(["alpha"])
        report = restore_multi_session(multi_session_to_dict(multi), fresh)
        assert report["restored"] == ["alpha"]
        rs = fresh.get("alpha").session
        np.testing.assert_array_equal(
            np.asarray(rs.predictions), before_preds
        )
        assert rs.auto_fetch is True
        assert rs.auto_commit is False
        assert rs.auto_resume is True
        # monotonic across the restore: a web client polling with a
        # pre-crash version still sees the next redraw
        assert rs.state_version >= before_version

    def test_changed_membership_quarantines_orphans(self, tmp_path):
        from svoc_tpu.utils.checkpoint import (
            multi_session_to_dict,
            restore_multi_session,
        )

        multi = make_multi(["alpha", "beta"])
        multi.run(2)
        payload = multi_session_to_dict(multi)
        # Membership changed between snapshot and restore: alpha is
        # gone, gamma is new.
        target = make_multi(["beta", "gamma"])
        report = restore_multi_session(payload, target)
        assert report["restored"] == ["beta"]
        assert report["unclaimed"] == ["alpha"]
        assert report["fresh"] == ["gamma"]
        # The orphan's full state sits in the snapshot's unclaimed
        # section — recoverable, never dropped.
        assert "session" in payload["unclaimed"]["alpha"]
        assert payload["unclaimed"]["alpha"]["cycles"] == 2
        # The survivors still serve.
        target.run(1)
        assert target.get("beta").cycles == 3

    def test_unclaimed_survives_later_snapshots_and_is_reclaimable(
        self, tmp_path
    ):
        from svoc_tpu.durability.recovery import RecoveryManager
        from svoc_tpu.utils.checkpoint import (
            load_snapshot,
            restore_multi_session,
        )

        multi = make_multi(["alpha", "beta"])
        multi.run(2)
        RecoveryManager(multi, out_dir=str(tmp_path)).snapshot()
        # Restart with alpha gone: its state quarantines...
        survivor = make_multi(["beta"])
        manager = RecoveryManager(survivor, out_dir=str(tmp_path))
        report = manager.recover()
        assert report["membership"]["unclaimed"] == ["alpha"]
        # ...and SURVIVES the next cadence snapshot overwriting the
        # file (review fix: it used to vanish within one interval).
        survivor.run(1)
        manager.snapshot()
        payload = load_snapshot(manager.snapshot_path)
        assert "alpha" in payload["unclaimed"]
        # A roster that has alpha back reclaims it from quarantine.
        reborn = make_multi(["alpha", "beta"])
        report2 = restore_multi_session(payload, reborn)
        assert "alpha" in report2["restored"]
        assert report2["unclaimed"] == []
        assert reborn.get("alpha").cycles == 2

    def test_fingerprint_discontinuity_refuses_recovery(self, tmp_path):
        from svoc_tpu.durability.recovery import RecoveryError, RecoveryManager
        from svoc_tpu.utils.checkpoint import load_snapshot, save_snapshot

        journal = EventJournal(registry=MetricsRegistry())
        multi = make_multi(["alpha", "beta"], journal=journal)
        multi.run(1)
        manager = RecoveryManager(multi, out_dir=str(tmp_path))
        manager.snapshot()
        payload = load_snapshot(manager.snapshot_path)
        payload["journal"]["events"][0]["data"]["n_comments"] = 999
        save_snapshot(manager.snapshot_path, payload)
        fresh = make_multi(["alpha", "beta"])
        with pytest.raises(RecoveryError, match="fingerprint"):
            RecoveryManager(fresh, out_dir=str(tmp_path)).recover()


# ---------------------------------------------------------------------------
# Graceful drain under live serving load
# ---------------------------------------------------------------------------


class TestGracefulDrain:
    def _tier(self, names):
        from svoc_tpu.fabric.scenario import deterministic_vectorizer
        from svoc_tpu.serving.frontend import AdmissionConfig
        from svoc_tpu.serving.scenario import VirtualClock
        from svoc_tpu.serving.tier import ServingTier
        from svoc_tpu.utils.slo import serving_slos

        metrics = MetricsRegistry()
        journal = EventJournal(registry=metrics)
        clock = VirtualClock()
        multi = make_multi(names, journal=journal, metrics=metrics)
        multi._clock = clock
        tier = ServingTier(
            multi,
            vectorizer=deterministic_vectorizer,
            admission=AdmissionConfig(queue_capacity=32, seed=0),
            clock=clock,
            slos=serving_slos(metrics),
        )
        return tier, multi, metrics, journal

    def test_drain_sheds_and_accounts_every_admitted_request(self):
        tier, multi, metrics, journal = self._tier(["alpha", "beta"])
        for i in range(6):
            tier.submit("alpha", f"drain load a{i}")
            tier.submit("beta", f"drain load b{i}")
        # Warm the request windows so commits can land post-cold-start.
        tier.step()
        for i in range(4):
            tier.submit("alpha", f"second wave {i}")
        # Pause beta AFTER admission so its queue cannot complete —
        # the drain must defer, not lose, anything still queued there.
        tier.submit("beta", "stuck request")
        multi.pause("beta")
        report = tier.drain()
        # Draining: new submissions shed with the typed reason.
        shed = tier.submit("alpha", "too late")
        assert shed["status"] == "shed" and shed["reason"] == "draining"
        shed_events = journal.recent(type="serving.shed")
        assert shed_events[-1].data["reason"] == "draining"
        # Every admitted request is answered or journaled deferred.
        admitted = metrics.family_total("serving_admitted")
        completed = metrics.family_total("serving_completed")
        dropped = metrics.family_total("serving_dropped")
        assert admitted == completed + dropped
        assert report["deferred"] >= 1
        deferred = journal.recent(type="serving.deferred")
        assert deferred and all(
            e.data["reason"] == "draining" for e in deferred
        )
        assert not any(tier.frontend.depths().values())

    def test_drain_is_idempotent_and_journals(self):
        from svoc_tpu.durability.recovery import GracefulDrain

        tier, multi, metrics, journal = self._tier(["alpha"])
        drainer = GracefulDrain(tier=tier, journal=journal)
        report = drainer.drain(reason="test")
        assert "flush" in report
        assert journal.recent(type="durability.drain")
        assert drainer.drain() == {"already_drained": True}


# ---------------------------------------------------------------------------
# Shutdown bundles (PostmortemMonitor satellites)
# ---------------------------------------------------------------------------


class TestShutdownBundles:
    def test_shutdown_bundle_classified_and_rate_limit_exempt(
        self, tmp_path
    ):
        from svoc_tpu.utils.postmortem import PostmortemMonitor

        reg = MetricsRegistry()
        journal = EventJournal(registry=reg)
        monitor = PostmortemMonitor(
            out_dir=str(tmp_path), registry=reg, journal=journal,
            min_interval_s=60.0,
        ).install()
        # An incident bundle just fired — the rate limiter is hot.
        journal.emit("crash", where="test")
        assert len(monitor.bundles) == 1
        # The shutdown bundle is EXEMPT from the 60 s window.
        path = monitor.shutdown("sigterm")
        assert path is not None and os.path.exists(path)
        bundle = json.load(open(path))
        assert bundle["trigger"] == "shutdown"  # not 'crash'
        assert bundle["trigger_event"]["reason"] == "sigterm"
        # Once: the atexit hook after a SIGTERM bundle is a no-op.
        assert monitor.shutdown("atexit") is None
        assert reg.counter(
            "postmortem_bundles", labels={"trigger": "shutdown"}
        ).count == 1

    def test_signal_hook_chains_previous_handler(self, tmp_path):
        import signal as _signal

        from svoc_tpu.utils.postmortem import PostmortemMonitor

        monitor = PostmortemMonitor(
            out_dir=str(tmp_path),
            registry=MetricsRegistry(),
            journal=EventJournal(registry=MetricsRegistry()),
        )
        calls = []
        prev = _signal.signal(_signal.SIGUSR1, lambda s, f: calls.append(s))
        try:
            monitor.install_shutdown_hooks(signals=(_signal.SIGUSR1,))
            os.kill(os.getpid(), _signal.SIGUSR1)
            assert calls == [_signal.SIGUSR1]  # previous handler ran
            assert monitor.bundles  # and the bundle was written first
        finally:
            monitor.uninstall_shutdown_hooks()
            _signal.signal(_signal.SIGUSR1, prev)

    def test_ignored_signal_stays_ignored(self, tmp_path):
        # Review fix: SIG_IGN must not be converted into process death
        # by the restore-default-and-rekill branch.
        import signal as _signal

        from svoc_tpu.utils.postmortem import PostmortemMonitor

        monitor = PostmortemMonitor(
            out_dir=str(tmp_path),
            registry=MetricsRegistry(),
            journal=EventJournal(registry=MetricsRegistry()),
        )
        prev = _signal.signal(_signal.SIGUSR2, _signal.SIG_IGN)
        try:
            monitor.install_shutdown_hooks(signals=(_signal.SIGUSR2,))
            os.kill(os.getpid(), _signal.SIGUSR2)  # survives = passes
            assert monitor.bundles  # bundled, did not die
        finally:
            monitor.uninstall_shutdown_hooks()
            _signal.signal(_signal.SIGUSR2, prev)


# ---------------------------------------------------------------------------
# Console surface
# ---------------------------------------------------------------------------


class TestConsoleCommands:
    def test_durability_and_drain_commands(self, tmp_path):
        from conftest import make_fake_console
        from svoc_tpu.durability.recovery import GracefulDrain, RecoveryManager

        console = make_fake_console()
        # Unattached: both commands explain themselves instead of
        # crashing.
        assert "no durability layer" in console.query("durability")[0]
        assert "no drain handler" in console.query("drain")[0]
        multi = make_multi(["alpha"])
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        multi.attach_wal(wal)
        manager = RecoveryManager(multi, out_dir=str(tmp_path), wal=wal)
        manager.attach(console)
        GracefulDrain(manager=manager).attach(console)
        out = console.query("durability")
        assert any("(none yet)" in line for line in out)
        out = console.query("durability snapshot")
        assert "snapshot written" in out[0]
        assert os.path.exists(manager.snapshot_path)
        status = manager.status()
        assert status["snapshot_exists"]
        assert status["wal_open_cycles"] == []
        out = console.query("drain")
        assert any(line.startswith("drained:") for line in out)
        assert console.query("drain") == ["already drained"]


# ---------------------------------------------------------------------------
# The full kill/restart scenario (in-process pieces; the subprocess
# SIGKILL matrix is `make crash-smoke`)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestDurableScenario:
    def test_fresh_run_is_clean_and_replayable(self, tmp_path):
        from svoc_tpu.durability.scenario import run_durable_scenario

        r1 = run_durable_scenario(str(tmp_path / "a"), seed=0, total_steps=4)
        r2 = run_durable_scenario(str(tmp_path / "b"), seed=0, total_steps=4)
        assert r1["duplicate_txs"] == 0
        assert not r1["wal_open_cycles"]
        assert r1["requests"]["unaccounted"] == 0
        assert {
            c: v["fingerprint"] for c, v in r1["claims"].items()
        } == {c: v["fingerprint"] for c, v in r2["claims"].items()}

    def test_restart_recovers_and_continues(self, tmp_path):
        from svoc_tpu.durability.scenario import run_durable_scenario

        d = str(tmp_path / "w")
        first = run_durable_scenario(d, seed=0, total_steps=3)
        assert first["steps"] == 3
        second = run_durable_scenario(d, seed=0, total_steps=6)
        assert second["recovered"]
        assert second["steps"] == 6
        assert second["duplicate_txs"] == 0
        assert second["requests"]["unaccounted"] == 0
