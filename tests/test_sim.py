"""Sim layer: generators, bootstrap oracle model, Monte-Carlo acceptance.

The Monte-Carlo assertions reproduce the published estimator-quality
tables (``documentation/README.md:248-341``, mirrored in BASELINE.md)
within sampling tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.sim.generators import (
    beta_mode,
    generate_beta_oracles,
    generate_gaussian_oracles,
    generate_kumaraswamy_oracles,
    kumaraswamy_mode,
)
from svoc_tpu.sim.montecarlo import (
    benchmark,
    benchmark_unconstrained,
    identify_failing_oracles,
    restricted_median,
    true_median,
)
from svoc_tpu.sim.oracle import gen_oracle_predictions


def test_beta_generator_shapes_and_failure_count():
    key = jax.random.PRNGKey(0)
    values, honest = generate_beta_oracles(key, 7, 2, 10.0, 10.0, dim=3)
    assert values.shape == (7, 3)
    assert honest.shape == (7,)
    assert int(jnp.sum(~honest)) == 2
    assert bool(jnp.all((values >= 0) & (values <= 1)))


def test_beta_honest_cluster_near_mode():
    # Beta(100, 100) concentrates at 0.5 (mode == mean == 0.5).
    key = jax.random.PRNGKey(1)
    values, honest = generate_beta_oracles(key, 200, 0, 100.0, 100.0, dim=1)
    assert abs(float(values.mean()) - beta_mode(100, 100)) < 0.02


def test_kumaraswamy_generator():
    key = jax.random.PRNGKey(2)
    values, honest = generate_kumaraswamy_oracles(key, 500, 0, 5.0, 5.0, dim=1)
    assert bool(jnp.all((values > 0) & (values < 1)))
    # empirical mode near analytic mode
    assert abs(float(jnp.median(values)) - kumaraswamy_mode(5.0, 5.0)) < 0.1


def test_gaussian_generator():
    key = jax.random.PRNGKey(3)
    values, honest = generate_gaussian_oracles(
        key, 400, 40, mu=[20.0, 12.0], sigma=[3.0, 2.0]
    )
    hv = values[honest]
    np.testing.assert_allclose(np.asarray(hv.mean(0)), [20.0, 12.0], atol=0.5)
    np.testing.assert_allclose(np.asarray(hv.std(0)), [3.0, 2.0], atol=0.5)


def test_bootstrap_oracle_model():
    key = jax.random.PRNGKey(4)
    window = jax.random.dirichlet(key, jnp.ones(6), shape=(30,))
    values, honest = gen_oracle_predictions(
        jax.random.PRNGKey(5), window, n_oracles=7, n_failing=2, subset_size=10
    )
    assert values.shape == (7, 6)
    assert int(jnp.sum(~honest)) == 2
    # honest oracles average normalized vectors -> components sum to ~1
    sums = jnp.sum(values, axis=-1)
    assert bool(jnp.all(jnp.abs(sums[honest] - 1.0) < 1e-5))
    # bootstrap means stay inside the window's convex hull
    lo, hi = window.min(axis=0), window.max(axis=0)
    assert bool(jnp.all(values[honest] >= lo[None, :] - 1e-6))
    assert bool(jnp.all(values[honest] <= hi[None, :] + 1e-6))


def test_bootstrap_is_vmappable_at_scale():
    window = jax.random.dirichlet(jax.random.PRNGKey(0), jnp.ones(6), shape=(50,))
    values, honest = gen_oracle_predictions(
        jax.random.PRNGKey(1), window, n_oracles=1024, n_failing=256
    )
    assert values.shape == (1024, 6)
    assert int(jnp.sum(~honest)) == 256


def test_true_and_restricted_median_match_numpy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(9, 3)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(true_median(jnp.array(x))), np.median(x, axis=0), atol=1e-6
    )
    x8 = x[:8]
    np.testing.assert_allclose(
        np.asarray(true_median(jnp.array(x8))), np.median(x8, axis=0), atol=1e-6
    )
    mask = np.array([True] * 5 + [False] * 4)
    np.testing.assert_allclose(
        np.asarray(restricted_median(jnp.array(x), jnp.array(mask), 5)),
        np.median(x[mask], axis=0),
        atol=1e-6,
    )


def test_identify_failing_matches_reference_rule():
    # reference rule: rank of ||pred - median||, worst n_failing flagged
    values = jnp.array([[0.5], [0.52], [0.48], [0.9], [0.1]])
    guess = identify_failing_oracles(values, 2)
    assert np.asarray(guess).tolist() == [True, True, True, False, False]


@pytest.mark.slow  # 3000-trial Monte-Carlo published-table cell (VERDICT r5 item 6; load-flaky under the full suite)
@pytest.mark.parametrize(
    "a,expected_success,expected_reliability,tol_s,tol_r",
    [
        (10.0, 40.33, 95.92, 6.0, 1.0),
        (100.0, 72.67, 99.44, 6.0, 0.5),
    ],
)
def test_montecarlo_matches_published_7_2(
    a, expected_success, expected_reliability, tol_s, tol_r
):
    """documentation/README.md:254 (a=10) and :272 (a=100), N=7/2."""
    r = benchmark(
        jax.random.PRNGKey(42), a, a, n_oracles=7, n_failing=2, k_trials=3000
    )
    assert r["identification_success_pct"] == pytest.approx(
        expected_success, abs=tol_s
    )
    assert r["reliability_pct"] == pytest.approx(expected_reliability, abs=tol_r)


@pytest.mark.slow  # 3000-trial Monte-Carlo published-table cell (VERDICT r5 item 6; load-flaky under the full suite)
@pytest.mark.parametrize(
    "a,expected_success,tol_s",
    [(10.0, 26.0, 6.0), (100.0, 78.33, 6.0)],
)
def test_montecarlo_matches_published_20_2(a, expected_success, tol_s):
    """documentation/README.md:285-307: N=20 with 2 failing — wider
    fleets make exact identification harder at low concentration
    (26 % at a=10) and easier at high (78 % at a=100)."""
    r = benchmark(
        jax.random.PRNGKey(21), a, a, n_oracles=20, n_failing=2, k_trials=3000
    )
    assert r["identification_success_pct"] == pytest.approx(
        expected_success, abs=tol_s
    )


@pytest.mark.slow  # 2000-trial Monte-Carlo (VERDICT r5 item 6)
def test_montecarlo_adversarial_75pct_stays_reliable():
    """documentation/README.md:318-319: N=20 with 15 failing (75%
    adversarial) keeps reliability ~90%."""
    r = benchmark(
        jax.random.PRNGKey(7), 10.0, 10.0, n_oracles=20, n_failing=15, k_trials=2000
    )
    assert r["reliability_pct"] == pytest.approx(90.2, abs=2.0)
    assert r["identification_success_pct"] < 10.0


def test_montecarlo_kernel_detection_close_to_reference_rule():
    """The on-chain two-pass detection (smooth median) should be in the
    same quality band as the notebook's true-median rule."""
    r = benchmark(
        jax.random.PRNGKey(9),
        100.0,
        100.0,
        n_oracles=7,
        n_failing=2,
        k_trials=2000,
        use_kernel=True,
    )
    assert r["identification_success_pct"] == pytest.approx(72.67, abs=8.0)
    assert r["reliability_pct"] > 98.5


GAUSS_FIXTURE = dict(mu=(20.0, 12.0), sigma=(3.0, 2.0))


@pytest.mark.slow  # 3000-trial Monte-Carlo published-table cell (VERDICT r5 item 6; load-flaky under the full suite)
@pytest.mark.parametrize(
    "use_kernel,expected_success,expected_reliability",
    [(False, 48.9, 91.5), (True, 48.1, 91.2)],
    ids=["notebook-rule", "onchain-kernel"],
)
def test_montecarlo_unconstrained_gaussian_7_2(
    use_kernel, expected_success, expected_reliability
):
    """Gaussian/unconstrained estimator quality at the Cairo fixture's
    configuration (mu=[20,12], sigma=[3,2], max_spread=10, N=7/2 —
    gaussian_distribution_for_tests.ipynb / test_contract.cairo:251-261).
    The reference never tabulated this case; these cells pin OUR
    recorded acceptance values (K=3000, key 0) as the regression
    contract, mirroring the published Beta tables' role."""
    r = benchmark_unconstrained(
        jax.random.PRNGKey(0),
        GAUSS_FIXTURE["mu"],
        GAUSS_FIXTURE["sigma"],
        n_oracles=7,
        n_failing=2,
        k_trials=3000,
        max_spread=10.0,
        use_kernel=use_kernel,
    )
    assert r["identification_success_pct"] == pytest.approx(
        expected_success, abs=4.0
    )
    assert r["reliability_pct"] == pytest.approx(expected_reliability, abs=1.0)
    if use_kernel:
        # On-chain second-pass reliability (essence1-centered quirk):
        # matches the fixture's recorded magnitude (0.647 for one draw).
        assert r["mean_onchain_reliability2_pct"] == pytest.approx(68.9, abs=3.0)


def test_montecarlo_unconstrained_tight_sigma_identifies_failures():
    """With a tight honest cloud the wide-uniform failing oracles are
    nearly always exactly identified, and the mean estimator tracks the
    honest mean closely."""
    r = benchmark_unconstrained(
        jax.random.PRNGKey(5),
        (0.0, 0.0),
        (0.1, 0.1),
        n_oracles=7,
        n_failing=2,
        k_trials=1000,
        max_spread=10.0,
        failing_spread=10.0,
    )
    assert r["identification_success_pct"] > 95.0
    assert r["mean_estimator_error"] < 0.05


@pytest.mark.slow  # N=1024 Monte-Carlo fleet-scale table (docs/ALGORITHM.md §5; the robustness cert gate covers breakdown in tier-1)
class TestFleetScale:
    """Fleet-scale (N=1024) acceptance — docs/ALGORITHM.md §5 table,
    at sampling tolerance (K=40 here vs the table's K=200)."""

    def test_fleet_sparse_adversaries_nearly_exact(self):
        from svoc_tpu.sim.montecarlo import fleet_benchmark

        r = fleet_benchmark(
            jax.random.PRNGKey(7), 1024, 2, k_trials=40
        )
        assert r["identification_success_pct"] >= 80.0
        assert r["mean_misclassified"] <= 0.5
        assert r["reliability_pct"] >= 99.9

    def test_fleet_75pct_adversaries_degrade_gracefully(self):
        """768/1024 uniform adversaries: exact-id collapses (harsh
        metric) but the per-oracle error stays under 2% and the
        recovered median within 2% of truth — the symmetric-adversary
        regime documented in ALGORITHM.md §5."""
        from svoc_tpu.sim.montecarlo import fleet_benchmark

        r = fleet_benchmark(
            jax.random.PRNGKey(8), 1024, 768, k_trials=40
        )
        assert r["misclassified_rate_pct"] <= 2.0
        assert r["reliability_pct"] >= 98.0
        assert 75.0 <= r["mean_onchain_reliability2_pct"] <= 95.0

    def test_breakdown_below_half_is_perfect(self):
        """40% COORDINATED biased adversaries: still exactly detected
        (docs/ALGORITHM.md §5 breakdown curve)."""
        from svoc_tpu.sim.montecarlo import fleet_benchmark

        r = fleet_benchmark(
            jax.random.PRNGKey(9), 1024, 410, k_trials=30, biased=True
        )
        assert r["misclassified_rate_pct"] <= 0.5
        assert r["reliability_pct"] >= 99.0

    def test_breakdown_above_half_inverts(self):
        """55% coordinated adversaries capture the median: the estimator
        inverts (masks the honest minority) while on-chain rel2 still
        reads healthy — the documented capture-invisibility property."""
        from svoc_tpu.sim.montecarlo import fleet_benchmark

        r = fleet_benchmark(
            jax.random.PRNGKey(10), 1024, 563, k_trials=30, biased=True
        )
        assert r["misclassified_rate_pct"] >= 60.0
        assert r["reliability_pct"] <= 0.0
        assert r["mean_onchain_reliability2_pct"] >= 70.0
