"""Real-weights accuracy parity (VERDICT r3 item 3).

Skip-with-reason when ``SamLowe/roberta-base-go_emotions`` is absent
from the local HF cache (the build image has no egress); the moment the
weights are present these tests prove the converter + every serving
path reproduce the reference pipeline's tracked sentiment vectors
(``client/oracle_scheduler.py:23-40``) on the committed 30-comment
fixture, and quantify the int8 accuracy cost against real weights.

The fixture-shaped machinery itself (fixture loads, harness wiring,
skip path) is tested unconditionally below via a tiny hermetic
checkpoint standing in for the real one.
"""

import json
import os

import numpy as np
import pytest

MODEL = "SamLowe/roberta-base-go_emotions"
FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "comments_30.json"
)


def _have_real_weights() -> bool:
    try:
        from transformers import AutoModelForSequenceClassification

        AutoModelForSequenceClassification.from_pretrained(
            MODEL, local_files_only=True
        )
        return True
    except Exception:
        return False


HAVE_WEIGHTS = _have_real_weights()
needs_weights = pytest.mark.skipif(
    not HAVE_WEIGHTS,
    reason=(
        f"{MODEL} not in the local HF cache (no egress in this image) — "
        "tools/weights_parity.py proves parity the moment it is"
    ),
)


def test_fixture_is_committed_and_sane():
    with open(FIXTURE) as f:
        fx = json.load(f)
    assert len(fx["comments"]) == 30
    assert all(isinstance(c, str) and c.strip() for c in fx["comments"])


@needs_weights
def test_all_paths_match_hf_reference():
    """Float/packed/flash paths within 2e-3 of the HF pipeline vectors;
    int8 within the 0.05 accuracy budget — on REAL weights."""
    from tools.weights_parity import main

    assert main(["--out", "/tmp/weights_parity_test.json"]) == 0
    with open("/tmp/weights_parity_test.json") as f:
        report = json.load(f)
    assert report["ok"]
    assert set(report["paths"]) == {
        "float", "packed_dense", "packed_flash", "int8_packed",
    }


def test_harness_machinery_on_hermetic_checkpoint(tmp_path):
    """Without the real cache, prove the harness MATH end to end on a
    tiny locally-saved HF model: save → reference vectors via torch →
    convert → float/packed paths agree with the torch reference."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = transformers.RobertaConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=66,
        num_labels=28,
        pad_token_id=1,
        bos_token_id=0,
        eos_token_id=2,
    )
    torch.manual_seed(0)
    hf_model = transformers.RobertaForSequenceClassification(cfg)
    hf_model.eval()

    from svoc_tpu.models.convert import config_from_hf, convert_roberta_state_dict
    from svoc_tpu.models.sentiment import (
        TRACKED_INDICES,
        SentimentPipeline,
    )

    enc_cfg = config_from_hf(cfg)
    import jax.numpy as jnp
    from dataclasses import replace

    enc_cfg = replace(enc_cfg, dtype=jnp.float32)
    params = convert_roberta_state_dict(hf_model.state_dict(), enc_cfg)

    with open(FIXTURE) as f:
        comments = json.load(f)["comments"][:8]

    seq = 32
    pipe = SentimentPipeline(
        cfg=enc_cfg, params=params, seq_len=seq, batch_size=8,
        tokenizer_name=None,
    )
    packed = SentimentPipeline(
        cfg=enc_cfg, params=params, seq_len=seq, batch_size=8,
        tokenizer_name=None, packed=True,
    )

    # Torch reference over the SAME token ids (the hashing tokenizer —
    # no HF tokenizer for a from-scratch config).
    ids, mask = pipe.tokenizer(comments, seq)
    with torch.no_grad():
        logits = hf_model(
            input_ids=torch.tensor(np.asarray(ids), dtype=torch.long),
            attention_mask=torch.tensor(np.asarray(mask), dtype=torch.long),
        ).logits
        scores = torch.sigmoid(logits).numpy()
    sel = scores[:, list(TRACKED_INDICES)]
    ref = sel / sel.sum(axis=1, keepdims=True)

    np.testing.assert_allclose(pipe(comments), ref, atol=2e-5, rtol=2e-4)
    np.testing.assert_allclose(packed(comments), ref, atol=2e-5, rtol=2e-4)
