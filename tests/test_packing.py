"""Sequence packing: packer invariants + packed-vs-unpacked parity.

The packed encoder must reproduce the unpacked per-comment logits to
float tolerance (same position ids, same per-segment softmax support —
``svoc_tpu/models/packing.py`` docstring), and the host packer must
cover every input exactly once with in-bounds gather indices.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from svoc_tpu.models.configs import TINY_TEST, EncoderConfig
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.models.packing import (
    PackedSentimentEncoder,
    pack_tokens,
    strip_padding,
)
from svoc_tpu.models.sentiment import SentimentPipeline


SEQ = 32


def _texts(n=12, seed=0):
    rng = np.random.default_rng(seed)
    vocab = "alpha beta gamma delta epsilon zeta eta theta iota kappa".split()
    return [
        " ".join(rng.choice(vocab, size=int(rng.integers(2, 12))))
        for _ in range(n)
    ]


@pytest.fixture(scope="module")
def pipe():
    return SentimentPipeline(
        cfg=TINY_TEST, seq_len=SEQ, batch_size=4, tokenizer_name=None
    )


# -- packer invariants ------------------------------------------------------


def test_pack_covers_every_input_once(pipe):
    ids, mask = pipe.tokenizer(_texts(20), SEQ)
    lists = strip_padding(ids, mask)
    batch, n = pack_tokens(lists, SEQ, max_segments=4, pad_id=pipe.tokenizer.pad_id)
    assert n == 20
    owners = batch.owner[batch.seg_valid > 0]
    assert sorted(owners.tolist()) == list(range(20))
    # every cls_pos points at the segment's first token
    for r in range(batch.ids.shape[0]):
        for s in range(batch.cls_pos.shape[1]):
            if batch.seg_valid[r, s]:
                p = batch.cls_pos[r, s]
                assert batch.seg[r, p] == s + 1
                assert p == 0 or batch.seg[r, p - 1] != s + 1


def test_pack_factor_beats_one_row_per_comment(pipe):
    ids, mask = pipe.tokenizer(_texts(30), SEQ)
    lists = strip_padding(ids, mask)
    batch, _ = pack_tokens(lists, SEQ, max_segments=8, pad_id=pipe.tokenizer.pad_id)
    assert batch.ids.shape[0] < 30  # strictly fewer rows than comments
    assert batch.n_segments == 30


def test_pack_respects_row_budget_and_resumes(pipe):
    ids, mask = pipe.tokenizer(_texts(30), SEQ)
    lists = strip_padding(ids, mask)
    first, n1 = pack_tokens(
        lists, SEQ, max_segments=2, pad_id=pipe.tokenizer.pad_id, rows=3
    )
    assert first.ids.shape[0] == 3 and 0 < n1 < 30
    rest, n2 = pack_tokens(
        lists[n1:], SEQ, max_segments=2, pad_id=pipe.tokenizer.pad_id
    )
    assert n1 + n2 == 30
    # resumed owners are relative to the sliced list
    owners = rest.owner[rest.seg_valid > 0]
    assert sorted(owners.tolist()) == list(range(30 - n1))


def test_pack_truncates_overlong(pipe):
    long = [list(range(2, SEQ + 40))]  # way past seq_len
    batch, n = pack_tokens(long, SEQ, max_segments=4, pad_id=1)
    assert n == 1
    assert (batch.seg[0] == 1).sum() == SEQ


def test_positions_restart_per_segment(pipe):
    lists = [[5, 6, 7], [8, 9]]
    batch, _ = pack_tokens(lists, SEQ, max_segments=4, pad_id=1)
    # both segments in one row; positions restart at pad_id + 1 = 2
    assert batch.pos[0, :5].tolist() == [2, 3, 4, 2, 3]


# -- numerical parity -------------------------------------------------------


def test_packed_logits_match_unpacked(pipe):
    texts = _texts(10, seed=3)
    ids, mask = pipe.tokenizer(texts, SEQ)
    lists = strip_padding(ids, mask)
    batch, _ = pack_tokens(lists, SEQ, max_segments=4, pad_id=pipe.tokenizer.pad_id)

    model = SentimentEncoder(TINY_TEST)
    packed_model = PackedSentimentEncoder(TINY_TEST)
    ref = model.apply(pipe.params, jnp.asarray(ids), jnp.asarray(mask))
    got = packed_model.apply(
        pipe.params,
        jnp.asarray(batch.ids),
        jnp.asarray(batch.pos),
        jnp.asarray(batch.seg),
        jnp.asarray(batch.cls_pos),
    )
    valid = batch.seg_valid > 0
    np.testing.assert_allclose(
        np.asarray(got)[valid][np.argsort(batch.owner[valid])],
        np.asarray(ref),
        rtol=2e-4,
        atol=2e-5,
    )


def test_packed_param_tree_is_identical(pipe):
    packed_model = PackedSentimentEncoder(TINY_TEST)
    batch, _ = pack_tokens([[5, 6], [7]], SEQ, max_segments=2, pad_id=1)
    packed_params = packed_model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(batch.ids),
        jnp.asarray(batch.pos),
        jnp.asarray(batch.seg),
        jnp.asarray(batch.cls_pos),
    )
    ref_tree = jax.tree_util.tree_structure(pipe.params)
    assert jax.tree_util.tree_structure(packed_params) == ref_tree
    ref_shapes = jax.tree_util.tree_map(lambda a: a.shape, pipe.params)
    got_shapes = jax.tree_util.tree_map(lambda a: a.shape, packed_params)
    assert ref_shapes == got_shapes


def test_packed_flash_matches_dense(pipe):
    """The flash segment-tag path must reproduce the dense block-diagonal
    bias path's logits on every REAL segment (padding rows legitimately
    differ: flash's dead-row convention emits 0 where dense emits the
    degenerate uniform average — neither is ever gathered)."""
    from dataclasses import replace

    texts = _texts(11, seed=21)
    ids, mask = pipe.tokenizer(texts, SEQ)
    batch, _ = pack_tokens(
        strip_padding(ids, mask), SEQ, max_segments=4, pad_id=pipe.tokenizer.pad_id
    )
    args = (
        jnp.asarray(batch.ids),
        jnp.asarray(batch.pos),
        jnp.asarray(batch.seg),
        jnp.asarray(batch.cls_pos),
    )
    dense_logits = PackedSentimentEncoder(TINY_TEST).apply(pipe.params, *args)
    flash_logits = PackedSentimentEncoder(
        replace(TINY_TEST, attention="flash")
    ).apply(pipe.params, *args)
    valid = batch.seg_valid > 0
    np.testing.assert_allclose(
        np.asarray(flash_logits)[valid],
        np.asarray(dense_logits)[valid],
        rtol=2e-4,
        atol=2e-5,
    )


def test_packed_rejects_unknown_attention():
    cfg = EncoderConfig(
        vocab_size=64, hidden=16, n_layers=1, n_heads=2, intermediate=32,
        max_len=32, dtype=jnp.float32, attention="ring",
    )
    packed_model = PackedSentimentEncoder(cfg)
    batch, _ = pack_tokens([[5, 6]], 16, max_segments=2, pad_id=1)
    with pytest.raises(ValueError, match="dense"):
        packed_model.init(
            jax.random.PRNGKey(0),
            jnp.asarray(batch.ids),
            jnp.asarray(batch.pos),
            jnp.asarray(batch.seg),
            jnp.asarray(batch.cls_pos),
        )


# -- pipeline round trip ----------------------------------------------------


def test_call_packed_matches_call(pipe):
    texts = _texts(11, seed=7)
    ref = pipe(texts)
    got = pipe.call_packed(texts)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_call_packed_empty(pipe):
    out = pipe.call_packed([])
    assert out.shape == (0, pipe.dimension)


def test_pipeline_packed_flag_routes_call():
    p = SentimentPipeline(
        cfg=TINY_TEST, seq_len=SEQ, batch_size=4, tokenizer_name=None, packed=True
    )
    ref = SentimentPipeline(
        cfg=TINY_TEST, seq_len=SEQ, batch_size=4, tokenizer_name=None
    )
    texts = _texts(9, seed=11)
    np.testing.assert_allclose(p(texts), ref(texts), rtol=2e-4, atol=2e-5)


def test_pipeline_packed_flash_matches_dense_pipeline():
    """End to end: packed×flash pipeline == packed×dense pipeline ==
    the plain unpacked pipeline, on the same texts."""
    from dataclasses import replace

    flash = SentimentPipeline(
        cfg=replace(TINY_TEST, attention="flash"),
        seq_len=SEQ,
        batch_size=4,
        tokenizer_name=None,
        packed=True,
    )
    ref = SentimentPipeline(
        cfg=TINY_TEST, seq_len=SEQ, batch_size=4, tokenizer_name=None
    )
    texts = _texts(9, seed=13)
    np.testing.assert_allclose(flash(texts), ref(texts), rtol=2e-4, atol=2e-5)


def test_pipeline_packed_rejects_unknown_attention():
    from dataclasses import replace

    with pytest.raises(ValueError, match="dense"):
        SentimentPipeline(
            cfg=replace(TINY_TEST, attention="ring"),
            seq_len=SEQ,
            batch_size=4,
            tokenizer_name=None,
            packed=True,
        )
