"""Checkpoint/resume: train state via orbax, simulation state via JSON."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.train.trainer import Batch, init_state, make_train_step
from svoc_tpu.utils.checkpoint import (
    contract_from_dict,
    contract_to_dict,
    restore_simulation,
    restore_train_state,
    save_simulation,
    save_train_state,
)


class TestTrainStateCheckpoint:
    def test_roundtrip_resumes_identically(self, tmp_path):
        model = SentimentEncoder(TINY_TEST)
        params = init_params(model, seed=0)
        tx = optax.adamw(1e-3)
        step = make_train_step(model, tx)
        state = init_state(model, params, tx)
        batch = Batch(
            ids=jnp.ones((2, 16), jnp.int32),
            mask=jnp.ones((2, 16), jnp.int32),
            labels=jnp.zeros((2, TINY_TEST.n_labels), jnp.float32),
        )
        state, _ = step(state, batch)

        path = str(tmp_path / "ckpt")
        save_train_state(path, state)
        template = init_state(model, params, tx)
        restored = restore_train_state(path, template)
        assert int(restored.step) == int(state.step)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            state.params,
            restored.params,
        )

        # The restored state must continue training bit-compatibly.
        s1, m1 = step(state, batch)
        s2, m2 = step(restored, batch)
        assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)


class TestSimulationCheckpoint:
    def make_session(self, **cfg_kwargs):
        from tests.test_apps import make_session

        return make_session(**cfg_kwargs)

    def test_contract_dict_roundtrip_mid_vote(self):
        from svoc_tpu.consensus.state import OracleConsensusContract

        c = OracleConsensusContract(
            admins=["a0", "a1", "a2"],
            oracles=[f"o{i}" for i in range(7)],
            dimension=2,
        )
        rng = np.random.default_rng(0)
        for i in range(7):
            c.update_prediction(f"o{i}", rng.uniform(0.01, 0.99, 2))
        c.update_proposition("a0", (6, "o_new"))  # one vote collected

        c2 = contract_from_dict(contract_to_dict(c))
        assert c2.consensus_active
        assert c2.get_consensus_value() == c.get_consensus_value()
        assert c2.get_skewness() == c.get_skewness()
        assert c2.replacement_propositions == [(6, "o_new"), None, None]
        # The pending vote survives: one more vote completes the swap.
        c2.vote_for_a_proposition("a1", 0, True)
        assert c2.get_oracle_list()[6] == "o_new"

    def test_session_save_restore(self, tmp_path):
        s = self.make_session()
        s.fetch()
        s.commit()
        cursor = s.simulation_step
        consensus = s.adapter.call_consensus()

        path = str(tmp_path / "sim.json")
        save_simulation(path, s)

        s2 = self.make_session()
        restore_simulation(path, s2)
        assert s2.simulation_step == cursor
        assert s2.adapter.call_consensus_active() is True
        assert s2.adapter.call_consensus() == consensus

    def test_restore_rehydrates_resilience_wiring(self, tmp_path):
        """asdict flattens the nested RetryPolicy/SupervisorConfig to
        dicts in the JSON; a restored session must get real dataclasses
        back (its resilient commit path calls policy.delays()), and the
        supervisor must be rebound to the RESTORED adapter, not keep
        watching the discarded pre-restore contract."""
        from svoc_tpu.resilience.retry import RetryPolicy
        from svoc_tpu.resilience.supervisor import SupervisorConfig

        s = self.make_session()
        s.fetch()
        s.commit()
        path = str(tmp_path / "sim.json")
        save_simulation(path, s)

        s2 = self.make_session()
        restore_simulation(path, s2)
        assert isinstance(s2.config.commit_retry, RetryPolicy)
        assert isinstance(s2.config.supervisor, SupervisorConfig)
        assert s2.supervisor.adapter is s2.adapter
        # the whole resilient loop works post-restore
        s2.fetch()
        assert s2.commit_resilient().complete
        assert s2.supervisor_step()["replaced"] == []

    def test_restore_rehydrates_claim_scoped_state(self, tmp_path):
        """Claim-derived session state (docs/FABRIC.md) is computed at
        construction; restoring a claim session's checkpoint into a
        plain Session() must keep minting claim-partitioned lineage ids
        and claim-labeled supervisor events — a stale prefix would
        silently split the claim's audit trail across two families."""
        s = self.make_session(claim="btc", lineage_scope="ck")
        s.fetch()
        path = str(tmp_path / "sim.json")
        save_simulation(path, s)

        s2 = self.make_session()
        restore_simulation(path, s2)
        assert s2.config.claim == "btc"
        assert s2.lineage_prefix == "blkck-btc"
        assert s2.supervisor.claim == "btc"
        s2.fetch()
        assert s2.last_lineage.startswith("blkck-btc-")
        # And the reverse: a claim checkpoint is authoritative — a
        # plain (claimless) checkpoint restored into a claim session
        # drops the claim segment, keeping its own process scope.
        s3 = self.make_session()
        s3.fetch()
        plain = str(tmp_path / "plain.json")
        save_simulation(plain, s3)
        s4 = self.make_session(claim="eth", lineage_scope="ck")
        restore_simulation(plain, s4)
        assert s4.supervisor.claim is None
        s4.fetch()
        assert s4.last_lineage.startswith("blk")
        assert "-eth-" not in s4.last_lineage


def test_fleet_scale_simulation_roundtrip(tmp_path):
    """A 1024-oracle session (batched-commit state) snapshots and
    rehydrates exactly — fleet-size contract storage is just more rows
    for the JSON path, and the restored adapter keeps batching."""
    import numpy as np

    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.consensus.state import OracleConsensusContract
    from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend
    from svoc_tpu.utils.checkpoint import restore_simulation, save_simulation

    n = 1024
    contract = OracleConsensusContract(
        [0xA0 + i for i in range(3)],
        [0x10 + i for i in range(n)],
        n_failing_oracles=256,
        constrained=True,
        dimension=6,
    )
    adapter = ChainAdapter(LocalChainBackend(contract))
    rng = np.random.default_rng(0)
    adapter.update_all_the_predictions(rng.uniform(0.05, 0.95, (n, 6)))
    assert contract.consensus_active

    session = Session(
        config=SessionConfig(n_oracles=n, n_failing=256),
        adapter=adapter,
        vectorizer=lambda texts: None,
    )
    session.simulation_step = 17
    path = tmp_path / "fleet.json"
    save_simulation(str(path), session)

    fresh = Session(vectorizer=lambda texts: None)
    restore_simulation(str(path), fresh)
    restored = fresh.adapter.backend.contract
    assert restored.n_active_oracles == n
    assert restored.get_consensus_value() == contract.get_consensus_value()
    assert fresh.simulation_step == 17
    assert fresh.config.n_oracles == n
    # The restored adapter still takes the batched path at fleet scale
    # (batch=True raises if the rehydrated backend lost the batched
    # capability instead of silently degrading to the per-tx loop).
    committed = fresh.adapter.update_all_the_predictions(
        rng.uniform(0.05, 0.95, (n, 6)), batch=True
    )
    assert committed == n
    assert restored.consensus_active
