"""Resilience layer: fault plans, retry/resume, breaker, supervisor,
and the deterministic chaos replay (ISSUE 3 acceptance scenario)."""

import threading

import numpy as np
import pytest

from svoc_tpu.apps.session import Session, SessionConfig
from svoc_tpu.consensus.state import OracleConsensusContract
from svoc_tpu.io.chain import ChainAdapter, ChainCommitError, LocalChainBackend
from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.scraper import SyntheticSource
from svoc_tpu.resilience import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    CircuitOpenError,
    FaultInjectingBackend,
    FaultPlan,
    FaultSpec,
    FleetHealthSupervisor,
    InjectedFault,
    InjectedTimeout,
    RetryPolicy,
    SupervisorConfig,
    call_with_retry,
    commit_fleet_with_resume,
)
from svoc_tpu.resilience.chaos import RecordingBackend, run_chaos_scenario
from svoc_tpu.utils.metrics import MetricsRegistry

from conftest import fake_sentiment_vectorizer  # noqa: E402

ADMINS = [0xA0, 0xA1, 0xA2]
ORACLES = [0x10 + i for i in range(7)]


def make_contract(**kwargs):
    defaults = dict(
        admins=ADMINS,
        oracles=ORACLES,
        required_majority=2,
        n_failing_oracles=2,
        constrained=True,
        dimension=6,
    )
    defaults.update(kwargs)
    return OracleConsensusContract(**defaults)


def fleet_predictions(seed=0, n=7, dim=6):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.05, 0.95, size=(n, dim))


def fast_policy(**kwargs):
    defaults = dict(max_attempts=4, base_s=0.0, cap_s=0.0, jitter_seed=0)
    defaults.update(kwargs)
    return RetryPolicy(**defaults)


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_same_seed_same_schedule(self):
        specs = [
            FaultSpec(op="invoke:update_prediction", target=1, probability=0.4),
            FaultSpec(op="invoke:update_prediction", target=2, probability=0.4),
        ]

        def drive(plan):
            decisions = []
            for count in range(50):
                for target in (1, 2):
                    decisions.append(
                        plan.decide("invoke:update_prediction", target)
                        is not None
                    )
            return decisions

        reg = MetricsRegistry()
        a = drive(FaultPlan(11, specs, registry=reg))
        b = drive(FaultPlan(11, specs, registry=reg))
        c = drive(FaultPlan(12, specs, registry=reg))
        assert a == b
        assert a != c  # a different seed reshuffles the schedule
        assert any(a) and not all(a)  # fractional probability both ways

    def test_schedule_independent_of_target_interleaving(self):
        """Per-(spec, target) counters: another target's traffic must
        not shift this target's schedule — the property that makes
        threaded chaos runs replayable."""
        spec = FaultSpec(
            op="invoke:update_prediction", target=1, probability=0.5
        )
        reg = MetricsRegistry()
        solo = FaultPlan(3, [spec], registry=reg)
        solo_seq = [
            solo.decide("invoke:update_prediction", 1) is not None
            for _ in range(30)
        ]
        mixed = FaultPlan(3, [spec], registry=reg)
        mixed_seq = []
        for i in range(30):
            # interleave unrelated traffic
            mixed.decide("invoke:update_prediction", 99)
            mixed_seq.append(
                mixed.decide("invoke:update_prediction", 1) is not None
            )
        assert solo_seq == mixed_seq

    def test_after_and_max_fires(self):
        plan = FaultPlan(
            0,
            [FaultSpec(op="op", after=2, max_fires=3)],
            registry=MetricsRegistry(),
        )
        fired = [plan.decide("op") is not None for _ in range(10)]
        assert fired == [False, False, True, True, True] + [False] * 5

    def test_wildcard_op_and_kinds(self):
        reg = MetricsRegistry()
        plan = FaultPlan(
            0,
            [FaultSpec(op="call:*", kind="timeout", max_fires=1)],
            registry=reg,
        )
        with pytest.raises(InjectedTimeout):
            plan.fire("call:get_consensus_value")
        assert plan.decide("invoke:update_prediction") is None
        assert (
            reg.counter("faults_injected", labels={"kind": "timeout"}).count
            == 1
        )

    def test_stall_sleeps_instead_of_raising(self):
        slept = []
        plan = FaultPlan(
            0,
            [FaultSpec(op="op", kind="stall", stall_s=1.5, max_fires=1)],
            registry=MetricsRegistry(),
        )
        plan.fire("op", sleep=slept.append)
        assert slept == [1.5]

    def test_fingerprint_replays(self):
        specs = [FaultSpec(op="op", probability=0.5)]
        reg = MetricsRegistry()

        def drive(plan):
            for _ in range(40):
                plan.decide("op")
            return plan.fingerprint()

        assert drive(FaultPlan(5, specs, registry=reg)) == drive(
            FaultPlan(5, specs, registry=reg)
        )
        assert drive(FaultPlan(5, specs, registry=reg)) != drive(
            FaultPlan(6, specs, registry=reg)
        )


class TestFaultInjectingBackend:
    def test_injects_on_invoke_and_passes_through(self):
        contract = make_contract()
        reg = MetricsRegistry()
        plan = FaultPlan(
            0,
            [
                FaultSpec(
                    op="invoke:update_prediction",
                    target=ORACLES[0],
                    max_fires=1,
                )
            ],
            registry=reg,
        )
        backend = FaultInjectingBackend(LocalChainBackend(contract), plan)
        adapter = ChainAdapter(backend)
        with pytest.raises(ChainCommitError) as e:
            adapter.update_all_the_predictions(fleet_predictions())
        assert e.value.committed == 0
        assert isinstance(e.value.cause, InjectedFault)
        # second pass: the max_fires budget is spent, the fleet commits
        assert adapter.update_all_the_predictions(fleet_predictions()) == 7
        assert contract.consensus_active

    def test_reads_faultable_too(self):
        plan = FaultPlan(
            0,
            [FaultSpec(op="call:get_admin_list", max_fires=1)],
            registry=MetricsRegistry(),
        )
        adapter = ChainAdapter(
            FaultInjectingBackend(LocalChainBackend(make_contract()), plan)
        )
        with pytest.raises(InjectedFault):
            adapter.call_admin_list()
        assert adapter.call_admin_list() == ADMINS


# ---------------------------------------------------------------------------
# Retry / resume
# ---------------------------------------------------------------------------


class TestCallWithRetry:
    def test_succeeds_after_transients_and_counts(self):
        reg = MetricsRegistry()
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise ValueError("transient")
            return "ok"

        out = call_with_retry(
            flaky,
            fast_policy(),
            op="probe",
            sleep=lambda s: None,
            registry=reg,
        )
        assert out == "ok" and len(attempts) == 3
        assert reg.counter("retries", labels={"op": "probe"}).count == 2

    def test_exhaustion_reraises_original(self):
        with pytest.raises(ValueError, match="always"):
            call_with_retry(
                lambda: (_ for _ in ()).throw(ValueError("always")),
                fast_policy(max_attempts=3),
                sleep=lambda s: None,
                registry=MetricsRegistry(),
            )

    def test_overall_deadline_cuts_retries_short(self):
        clock_now = [0.0]

        def clock():
            return clock_now[0]

        def sleep(s):
            clock_now[0] += s

        calls = []

        def failing():
            calls.append(1)
            clock_now[0] += 1.0  # each attempt costs 1s
            raise ValueError("down")

        with pytest.raises(ValueError):
            call_with_retry(
                failing,
                RetryPolicy(
                    max_attempts=50,
                    base_s=1.0,
                    cap_s=1.0,
                    overall_deadline_s=3.0,
                    jitter_seed=0,
                ),
                sleep=sleep,
                clock=clock,
                registry=MetricsRegistry(),
            )
        assert len(calls) < 50  # deadline, not attempts, stopped it

    def test_decorrelated_jitter_bounded_and_seeded(self):
        policy = RetryPolicy(base_s=0.1, cap_s=2.0, jitter_seed=42)
        gen = policy.delays()
        seq = [next(gen) for _ in range(64)]
        assert all(0.1 <= d <= 2.0 for d in seq)
        gen2 = RetryPolicy(base_s=0.1, cap_s=2.0, jitter_seed=42).delays()
        assert seq == [next(gen2) for _ in range(64)]


class FlakyOracleBackend:
    """LocalChainBackend wrapper failing specific oracles a fixed
    number of times (simpler than a plan when the test wants exact
    failure counts)."""

    def __init__(self, contract, fail_counts):
        self.inner = LocalChainBackend(contract)
        self.remaining = dict(fail_counts)

    def call(self, fn):
        return self.inner.call(fn)

    def call_as(self, caller, fn):
        return self.inner.call_as(caller, fn)

    def invoke(self, caller, fn, /, **kwargs):
        left = self.remaining.get(caller, 0)
        if fn == "update_prediction" and left:
            self.remaining[caller] = left - 1
            raise RuntimeError(f"rpc down for {caller:#x}")
        return self.inner.invoke(caller, fn, **kwargs)


class TestCommitFleetWithResume:
    def test_resume_resends_only_stranded_suffix(self):
        contract = make_contract()
        # the flake sits INSIDE the recorder so only landed txs count
        recorder = RecordingBackend(
            FlakyOracleBackend(contract, {ORACLES[3]: 2})
        )
        adapter = ChainAdapter(recorder)
        reg = MetricsRegistry()
        recorder.begin_cycle(0)
        outcome = commit_fleet_with_resume(
            adapter,
            fleet_predictions(),
            fast_policy(),
            sleep=lambda s: None,
            registry=reg,
        )
        assert outcome.complete and outcome.sent == 7
        assert outcome.attempts == 3  # two failures at oracle 3
        assert reg.counter("commit_resumes").count == 2
        # no oracle's tx landed twice
        assert recorder.duplicate_txs == 0
        assert contract.consensus_active

    def test_persistent_offender_is_stranded_not_fatal(self):
        contract = make_contract()
        backend = FlakyOracleBackend(contract, {ORACLES[6]: 10**9})
        adapter = ChainAdapter(backend)
        reg = MetricsRegistry()
        outcome = commit_fleet_with_resume(
            adapter,
            fleet_predictions(),
            fast_policy(max_attempts=3),
            sleep=lambda s: None,
            registry=reg,
        )
        assert not outcome.complete
        assert outcome.sent == 6
        assert outcome.stranded == (ORACLES[6],)
        assert reg.counter("commit_stranded").count == 1
        # activation gate: 6/7 committed, consensus must stay inactive
        assert not contract.consensus_active

    def test_mid_fleet_offender_does_not_starve_tail(self):
        contract = make_contract()
        adapter = ChainAdapter(
            FlakyOracleBackend(contract, {ORACLES[2]: 10**9})
        )
        outcome = commit_fleet_with_resume(
            adapter,
            fleet_predictions(),
            fast_policy(max_attempts=2),
            sleep=lambda s: None,
            registry=MetricsRegistry(),
        )
        assert outcome.stranded == (ORACLES[2],)
        assert outcome.sent == 6  # oracles 3..6 still committed

    def test_resume_roundtrip_matches_clean_run(self):
        """Partial-commit + resume must land the EXACT contract state a
        clean uninterrupted run produces."""
        predictions = fleet_predictions(seed=9)
        clean = make_contract()
        ChainAdapter(LocalChainBackend(clean)).update_all_the_predictions(
            predictions
        )
        chaotic = make_contract()
        adapter = ChainAdapter(
            FlakyOracleBackend(
                chaotic, {ORACLES[1]: 1, ORACLES[4]: 2, ORACLES[6]: 3}
            )
        )
        outcome = commit_fleet_with_resume(
            adapter,
            predictions,
            fast_policy(max_attempts=5),
            sleep=lambda s: None,
            registry=MetricsRegistry(),
        )
        assert outcome.complete
        assert chaotic.get_consensus_value() == clean.get_consensus_value()
        assert (
            chaotic.get_second_pass_consensus_reliability()
            == clean.get_second_pass_consensus_reliability()
        )
        assert [o.reliable for o in chaotic.oracles] == [
            o.reliable for o in clean.oracles
        ]
        assert [o.value for o in chaotic.oracles] == [
            o.value for o in clean.oracles
        ]

    def test_flaky_signers_do_not_open_the_backend_breaker(self):
        """Progress credit: a persistent offender plus transient flakes
        must never trip the BACKEND breaker while other txs land —
        otherwise a degraded fleet becomes a total commit outage
        (code-review finding, reproduced pre-fix with session defaults:
        threshold 5 + max_attempts 4 opened the breaker at cycle 3)."""
        contract = make_contract()
        breaker = CircuitBreaker(
            "chain", failure_threshold=5, reset_timeout_s=1e9,
            registry=MetricsRegistry(),
        )
        for cycle in range(6):
            backend = FlakyOracleBackend(
                contract, {ORACLES[1]: 1, ORACLES[6]: 10**9}
            )
            outcome = commit_fleet_with_resume(
                ChainAdapter(backend),
                fleet_predictions(seed=cycle),
                fast_policy(max_attempts=4),
                breaker=breaker,
                sleep=lambda s: None,
                registry=MetricsRegistry(),
            )
            assert outcome.sent == 6, f"cycle {cycle} wedged"
            assert breaker.state() == BREAKER_CLOSED

    def test_open_breaker_short_circuits_with_accounting(self):
        contract = make_contract()
        adapter = ChainAdapter(
            FlakyOracleBackend(contract, {ORACLES[0]: 10**9})
        )
        breaker = CircuitBreaker(
            "t", failure_threshold=2, reset_timeout_s=1e9,
            registry=MetricsRegistry(),
        )
        with pytest.raises(CircuitOpenError) as e:
            commit_fleet_with_resume(
                adapter,
                fleet_predictions(),
                fast_policy(max_attempts=10),
                breaker=breaker,
                sleep=lambda s: None,
                registry=MetricsRegistry(),
            )
        assert breaker.state() == BREAKER_OPEN
        assert e.value.sent == 0

    def test_read_failure_records_on_breaker(self):
        """A transport outage surfaces as a READ failure (the commit's
        first RPC is the oracle-list fetch) — it must count toward the
        breaker trip, and a claimed half-open probe must be resolved."""

        class DeadBackend:
            def call(self, fn):
                raise ConnectionError("rpc down")

            def call_as(self, caller, fn):
                raise ConnectionError("rpc down")

            def invoke(self, caller, fn, /, **kwargs):
                raise ConnectionError("rpc down")

        breaker = CircuitBreaker(
            "t", failure_threshold=2, reset_timeout_s=1e9,
            registry=MetricsRegistry(),
        )
        for _ in range(2):
            with pytest.raises(ConnectionError):
                commit_fleet_with_resume(
                    ChainAdapter(DeadBackend()),
                    fleet_predictions(),
                    fast_policy(),
                    breaker=breaker,
                    sleep=lambda s: None,
                    registry=MetricsRegistry(),
                )
        assert breaker.state() == BREAKER_OPEN

    def test_chain_adapter_start_offset_accounting(self):
        """`start=` slices the suffix and keeps ChainCommitError's
        committed count ABSOLUTE — the resume invariant."""
        contract = make_contract()
        adapter = ChainAdapter(
            FlakyOracleBackend(contract, {ORACLES[5]: 1})
        )
        predictions = fleet_predictions()
        with pytest.raises(ChainCommitError) as e:
            adapter.update_all_the_predictions(predictions, start=2)
        assert e.value.committed == 5  # absolute index, not 3
        assert e.value.total == 7
        # resume from the absolute index commits the rest
        assert adapter.update_all_the_predictions(
            predictions, start=e.value.committed
        ) == 2
        committed = [o.enabled for o in contract.oracles]
        assert committed == [False, False, True, True, True, True, True]


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def make(self, **kwargs):
        self.now = [0.0]
        reg = MetricsRegistry()
        defaults = dict(
            failure_threshold=3,
            reset_timeout_s=10.0,
            clock=lambda: self.now[0],
            registry=reg,
        )
        defaults.update(kwargs)
        return CircuitBreaker("t", **defaults), reg

    def test_opens_after_threshold_and_half_opens_after_reset(self):
        b, reg = self.make()
        assert b.state() == BREAKER_CLOSED
        for _ in range(3):
            assert b.allow()
            b.record_failure()
        assert b.state() == BREAKER_OPEN
        assert not b.allow()
        assert b.retry_after_s() == pytest.approx(10.0)
        gauge = reg.gauge("circuit_breaker_state", labels={"backend": "t"})
        assert gauge.get() == 1
        self.now[0] = 10.0
        assert b.allow()  # the half-open probe
        assert b.state() == BREAKER_HALF_OPEN
        assert gauge.get() == 2
        assert not b.allow()  # probe budget is 1
        b.record_success()
        assert b.state() == BREAKER_CLOSED
        assert gauge.get() == 0

    def test_half_open_failure_reopens(self):
        b, _ = self.make()
        for _ in range(3):
            b.record_failure()
        self.now[0] = 10.0
        assert b.allow()
        b.record_failure()
        assert b.state() == BREAKER_OPEN
        assert not b.allow()
        self.now[0] = 20.0
        assert b.allow()  # fresh reset window from the re-open

    def test_success_resets_consecutive_count(self):
        b, _ = self.make()
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state() == BREAKER_CLOSED

    def test_half_open_probe_slot_heals_after_a_lost_verdict(self):
        """A probe claimed by a caller that died without recording a
        verdict must not wedge the breaker half-open forever — after a
        full reset window the probe budget reopens."""
        b, _ = self.make()
        for _ in range(3):
            b.record_failure()
        self.now[0] = 10.0
        assert b.allow()  # probe claimed... and the caller vanishes
        assert not b.allow()
        self.now[0] = 20.0  # a whole reset window with no verdict
        assert b.allow()
        b.record_success()
        assert b.state() == BREAKER_CLOSED

    def test_guard_context(self):
        b, _ = self.make(failure_threshold=1)
        with pytest.raises(ValueError):
            with b.guard():
                raise ValueError("boom")
        assert b.state() == BREAKER_OPEN
        with pytest.raises(CircuitOpenError):
            with b.guard():
                pass


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------


class TestSupervisor:
    def test_commit_failures_quarantine_and_replace(self):
        contract = make_contract()
        adapter = ChainAdapter(LocalChainBackend(contract))
        reg = MetricsRegistry()
        sup = FleetHealthSupervisor(adapter, registry=reg)
        offender = ORACLES[6]
        replaced = None
        for _step in range(6):
            for _ in range(4):  # a stranded cycle's failure volume
                sup.record_commit_failure(offender)
            report = sup.step()
            if report["replaced"]:
                replaced = report["replaced"][0]
                break
        assert replaced is not None, "supervisor never replaced the offender"
        assert replaced["old"] == hex(offender)
        assert replaced["slot"] == 6
        assert offender not in contract.get_oracle_list()
        new_addr = contract.get_oracle_list()[6]
        assert new_addr not in ORACLES
        assert reg.counter("oracle_replacements").count == 1
        # slot-keyed health gauges exist and the new identity is fresh
        assert reg.gauge("oracle_health", labels={"slot": "6"}).get() >= 0
        assert sup.health_snapshot()["6"] == 1.0

    def test_healthy_fleet_untouched(self):
        contract = make_contract()
        sup = FleetHealthSupervisor(
            ChainAdapter(LocalChainBackend(contract)),
            registry=MetricsRegistry(),
        )
        for _ in range(5):
            report = sup.step()
            assert report["quarantined"] == []
            assert report["replaced"] == []
        assert contract.get_oracle_list() == ORACLES
        assert all(v == 1.0 for v in sup.health_snapshot().values())

    def test_hysteresis_recovery_without_replacement(self):
        contract = make_contract()
        sup = FleetHealthSupervisor(
            ChainAdapter(LocalChainBackend(contract)),
            SupervisorConfig(auto_replace=False),
            registry=MetricsRegistry(),
        )
        target = ORACLES[2]
        for _ in range(4):
            sup.record_commit_failure(target)
            sup.record_commit_failure(target)
            sup.step()
        assert sup.quarantined_slots() == [2]
        assert contract.get_oracle_list() == ORACLES  # observe-only
        # clean steps: the score must climb past healthy_threshold and
        # clear the quarantine (hysteresis, not a single boundary)
        for _ in range(4):
            sup.step()
        assert sup.quarantined_slots() == []

    def test_replacement_disabled_contract_downgrades_gracefully(self):
        contract = make_contract(enable_oracle_replacement=False)
        sup = FleetHealthSupervisor(
            ChainAdapter(LocalChainBackend(contract)),
            registry=MetricsRegistry(),
        )
        for _ in range(5):
            sup.record_commit_failure(ORACLES[0])
            sup.record_commit_failure(ORACLES[0])
            sup.step()
        assert contract.get_oracle_list() == ORACLES
        assert sup.replacements == []
        assert sup._replace_disabled  # stopped trying

    def test_step_does_not_flood_the_rel2_trajectory_ring(self):
        """The supervisor reads rel₂ at auto-loop cadence (seconds);
        it must peek, not feed the ~1-per-minute operator trajectory
        ring the capture-slide alarm windows over."""
        contract = make_contract()
        adapter = ChainAdapter(LocalChainBackend(contract))
        sup = FleetHealthSupervisor(adapter, registry=MetricsRegistry())
        adapter.update_all_the_predictions(fleet_predictions())
        before = len(adapter.rel2_history)
        for _ in range(20):
            sup.step()
        assert len(adapter.rel2_history) == before

    def test_default_factory_refuses_non_local_backends(self):
        """The default replacement-address factory mints SYNTHETIC
        addresses — voting one onto a real chain would create a slot
        nobody can sign for.  A backend that doesn't bottom out in the
        local simulator downgrades the supervisor to observe-only."""

        class OpaqueBackend:
            # mimics a remote backend: no .backend/.inner chain to walk
            def __init__(self, b):
                self._b = b

            def call(self, fn):
                return self._b.call(fn)

            def call_as(self, caller, fn):
                return self._b.call_as(caller, fn)

            def invoke(self, caller, fn, /, **kwargs):
                return self._b.invoke(caller, fn, **kwargs)

        contract = make_contract()
        adapter = ChainAdapter(OpaqueBackend(LocalChainBackend(contract)))
        sup = FleetHealthSupervisor(adapter, registry=MetricsRegistry())
        for _ in range(5):
            for _ in range(4):
                sup.record_commit_failure(ORACLES[6])
            sup.step()
        assert contract.get_oracle_list() == ORACLES  # no synthetic vote
        assert sup.replacements == []
        assert sup._replace_disabled
        # ... while an explicit operator-supplied factory IS honored
        sup2 = FleetHealthSupervisor(
            ChainAdapter(OpaqueBackend(LocalChainBackend(make_contract()))),
            new_address_factory=lambda existing: 0xBEEF,
            registry=MetricsRegistry(),
        )
        for _ in range(5):
            for _ in range(4):
                sup2.record_commit_failure(ORACLES[6])
            if sup2.step()["replaced"]:
                break
        assert len(sup2.replacements) == 1
        assert sup2.replacements[0]["new"] == "0xbeef"

    def test_on_chain_unreliable_flags_feed_scores(self):
        """An oracle the consensus flags unreliable every cycle drifts
        below 1.0 even with perfect commit infrastructure."""
        contract = make_contract()
        adapter = ChainAdapter(LocalChainBackend(contract))
        sup = FleetHealthSupervisor(
            adapter,
            SupervisorConfig(auto_replace=False),
            registry=MetricsRegistry(),
        )
        # a fleet with one wild outlier: always flagged by the two-pass
        predictions = fleet_predictions(seed=1)
        predictions[3] = 0.99
        for _ in range(3):
            adapter.update_all_the_predictions(predictions)
            sup.step()
        snapshot = sup.health_snapshot()
        assert snapshot["3"] < 1.0
        # slot 1 stays reliable in this fleet (n_failing=2 masks the
        # two most deviant — slots 0 and 3 here)
        assert snapshot["1"] == 1.0
        assert snapshot["1"] > snapshot["3"]


# ---------------------------------------------------------------------------
# Session integration
# ---------------------------------------------------------------------------


def make_resilient_session(backend_wrap=None, **cfg_kwargs):
    cfg_kwargs.setdefault(
        "commit_retry",
        RetryPolicy(max_attempts=3, base_s=0.0, cap_s=0.0, jitter_seed=0),
    )
    config = SessionConfig(**cfg_kwargs)
    contract = make_contract()
    backend = LocalChainBackend(contract)
    if backend_wrap is not None:
        backend = backend_wrap(contract, backend)
    store = CommentStore()
    store.save(SyntheticSource(batch=200)())
    session = Session(
        config=config,
        store=store,
        vectorizer=fake_sentiment_vectorizer,
        adapter=ChainAdapter(backend),
    )
    return session, contract


class TestSessionResilience:
    def test_set_auto_flags_bumps_state_version(self):
        session, _ = make_resilient_session()
        v0 = session.state_version
        session.set_auto_flags(commit=True)
        assert session.auto_commit and session.state_version == v0 + 1
        session.set_auto_flags(resume=True, fetch=True)
        assert session.auto_resume and session.auto_fetch
        assert session.state_version == v0 + 2

    def test_console_flag_commands_bump_state_version(self):
        from svoc_tpu.apps.commands import CommandConsole

        session, _ = make_resilient_session()
        console = CommandConsole(session)
        v0 = session.state_version
        assert console.query("auto_commit on") == ["Auto-Commit: ENABLED"]
        assert console.query("auto_resume on") == ["Auto-Resume: ENABLED"]
        assert session.state_version >= v0 + 2
        console.query("auto_commit off")
        assert not session.auto_commit

    def test_commit_resilient_resumes_and_completes(self):
        session, contract = make_resilient_session(
            backend_wrap=lambda contract, backend: FlakyOracleBackend(
                contract, {ORACLES[2]: 1, ORACLES[5]: 1}
            )
        )
        session.fetch()
        outcome = session.commit_resilient()
        assert outcome.complete and outcome.sent == 7
        assert contract.consensus_active

    def test_commit_resilient_strands_then_supervisor_replaces(self):
        session, contract = make_resilient_session(
            backend_wrap=lambda contract, backend: FlakyOracleBackend(
                contract, {ORACLES[6]: 10**9}
            )
        )
        replaced = False
        for _cycle in range(6):
            session.fetch()
            outcome = session.commit_resilient()
            report = session.supervisor_step()
            if report and report["replaced"]:
                replaced = True
                break
            assert outcome.stranded == (ORACLES[6],)
        assert replaced
        assert ORACLES[6] not in contract.get_oracle_list()
        # the replacement address signs cleanly: next cycle completes
        session.fetch()
        assert session.commit_resilient().complete
        assert contract.consensus_active

    def test_resilience_snapshot_shape(self):
        session, _ = make_resilient_session()
        snap = session.resilience_snapshot()
        assert snap["breaker"] == BREAKER_CLOSED
        assert snap["replacements"] == 0
        assert snap["quarantined"] == []
        assert isinstance(snap["health"], dict)

    def test_console_resilience_command(self):
        from svoc_tpu.apps.commands import CommandConsole

        session, _ = make_resilient_session()
        console = CommandConsole(session)
        out = console.query("resilience")
        assert out[0] == "breaker: closed"
        assert "replacements: 0" in out
        # PR 4: the gate verdict line (no fetch has run yet).
        assert out[-1] == "input quarantine: no gated fetch yet"

    def test_console_resilience_quarantine_line(self):
        from svoc_tpu.apps.commands import CommandConsole

        session, _ = make_resilient_session()
        console = CommandConsole(session)
        session.fetch()
        out = console.query("resilience")
        assert out[-1].startswith("input quarantine: clean (")


# ---------------------------------------------------------------------------
# Chaos replay (the acceptance scenario)
# ---------------------------------------------------------------------------


class TestChaosReplay:
    def test_same_seed_bit_identical_and_converged(self):
        first = run_chaos_scenario(4, registry=MetricsRegistry())
        second = run_chaos_scenario(4, registry=MetricsRegistry())
        assert first["fingerprint"] == second["fingerprint"]
        assert first["faults_fired"] == second["faults_fired"] > 0
        assert first["consensus_active"]
        assert first["final_cycle_complete"]
        assert first["offender_replaced"]
        assert first["replacements"] == 1
        assert first["duplicate_txs"] == 0

    def test_different_seed_differs(self):
        a = run_chaos_scenario(4, cycles=6, registry=MetricsRegistry())
        b = run_chaos_scenario(5, cycles=6, registry=MetricsRegistry())
        assert a["fingerprint"] != b["fingerprint"]

    def test_resume_only_resends_stranded(self):
        """Transient faults fired, yet every cycle's landed txs are
        unique per oracle — the no-duplicate invariant under chaos."""
        result = run_chaos_scenario(4, registry=MetricsRegistry())
        assert result["faults_fired"] > 12  # transients beyond offender
        assert result["duplicate_txs"] == 0


# ---------------------------------------------------------------------------
# Threaded sanity: shared supervisor state under concurrent reports
# ---------------------------------------------------------------------------


def test_concurrent_failure_reports_do_not_corrupt_scores():
    contract = make_contract()
    sup = FleetHealthSupervisor(
        ChainAdapter(LocalChainBackend(contract)),
        SupervisorConfig(auto_replace=False),
        registry=MetricsRegistry(),
    )
    n_threads, n_reports = 8, 200
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()
        for i in range(n_reports):
            sup.record_commit_failure(ORACLES[i % len(ORACLES)])

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(sup._pending_failures.values())
    assert total == n_threads * n_reports
    sup.step()  # folds without blowing up
    assert sup._pending_failures == {}
