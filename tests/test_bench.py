"""bench.py harness behavior (subprocess; the one-line JSON contract).

Runs the cheapest config end to end in a child process with the CPU
platform pinned — fast, hermetic, and exercising the REAL main() path
including backend resolution, the CPU auto-shrink, and the result-line
format the driver and tools/hw_queue.py parse.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(args, env_extra):
    env = dict(os.environ)
    # Hermetic against the caller's own bench knobs — an exported
    # SVOC_BENCH_SMALL would suppress auto-shrink, FORCE_FULL would run
    # the unbounded full-size workload.
    for knob in (
        "SVOC_BENCH_SMALL",
        "SVOC_BENCH_FORCE_FULL",
        "SVOC_BENCH_SECONDS",
        "SVOC_BENCH_MAX_STEPS",
        "SVOC_BENCH_NO_PIPELINE",
    ):
        env.pop(knob, None)
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, BENCH, *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, (proc.stdout, proc.stderr[-1500:])
    return proc.returncode, json.loads(lines[-1])


def test_cpu_platform_auto_shrinks_and_labels():
    """On a CPU backend the full-size workload auto-shrinks (it cannot
    finish in bounded time) with the reason stamped in detail — the
    round-end bench must emit an honest line, never wedge."""
    rc, result = _run_bench(
        ["--config", "2", "--seconds", "1"],
        {"JAX_PLATFORMS": "cpu"},
    )
    assert rc == 0
    assert result["unit"] == "consensus-updates/sec"  # config 2's metric
    assert result["value"] > 0
    assert result["detail"]["backend"] == "cpu"
    assert result["detail"]["small_mode"] is True
    assert "auto-shrunk" in result["detail"]["small_mode_auto"]


def test_explicit_small_mode_is_not_labeled_auto():
    rc, result = _run_bench(
        ["--config", "2", "--seconds", "1"],
        {"JAX_PLATFORMS": "cpu", "SVOC_BENCH_SMALL": "1"},
    )
    assert rc == 0
    assert result["detail"]["small_mode"] is True
    assert "small_mode_auto" not in result["detail"]


def test_perf_decision_precedence(tmp_path, monkeypatch):
    """Routing decisions resolve env > committed record > default, and
    report their source (the flagship/consensus paths route on this)."""
    import bench

    record = tmp_path / "PERF_DECISIONS.json"
    monkeypatch.setattr(bench, "PERF_DECISIONS_PATH", str(record))
    monkeypatch.delenv("SVOC_FLAGSHIP_VARIANT", raising=False)

    # no env, no record -> default
    assert bench.perf_decision(
        "flagship_variant", "dense", "SVOC_FLAGSHIP_VARIANT"
    ) == ("dense", "default")
    # record wins over default
    record.write_text(json.dumps({"flagship_variant": "packed_flash"}))
    assert bench.perf_decision(
        "flagship_variant", "dense", "SVOC_FLAGSHIP_VARIANT"
    ) == ("packed_flash", "PERF_DECISIONS.json")
    # env wins over record
    monkeypatch.setenv("SVOC_FLAGSHIP_VARIANT", "packed")
    assert bench.perf_decision(
        "flagship_variant", "dense", "SVOC_FLAGSHIP_VARIANT"
    ) == ("packed", "env:SVOC_FLAGSHIP_VARIANT")
    # a corrupt record degrades to the default, never raises —
    # including JSON-valid non-object content
    monkeypatch.delenv("SVOC_FLAGSHIP_VARIANT")
    for bad in ("{not json", "null", "[]", '"dense"'):
        record.write_text(bad)
        assert bench.perf_decision(
            "flagship_variant", "dense", "SVOC_FLAGSHIP_VARIANT"
        ) == ("dense", "default"), bad


def test_flagship_routes_packed_variant():
    """config 0 with a variant override runs the packed body and labels
    the emitted line as the flagship with variant + source stamped."""
    rc, result = _run_bench(
        ["--config", "0", "--seconds", "1"],
        {
            "JAX_PLATFORMS": "cpu",
            "SVOC_BENCH_SMALL": "1",
            "SVOC_FLAGSHIP_VARIANT": "packed",
        },
    )
    assert rc == 0
    assert result["metric"].startswith("flagship (packed):")
    assert result["unit"] == "comments/sec"
    assert result["detail"]["flagship_variant"] == "packed"
    assert result["detail"]["flagship_variant_source"] == "env:SVOC_FLAGSHIP_VARIANT"
    assert result["detail"]["attention"] == "dense"


def test_campaign_replay_prefers_routed_tpu_capture(tmp_path, monkeypatch):
    """A CPU *fallback* at snapshot time must replay the campaign's
    last on-TPU capture for the config (round-4 BENCH_r04 postmortem):
    config 0 prefers the routed capture, non-TPU/failed results are
    skipped, and provenance is stamped."""
    import bench

    journal = tmp_path / "HW_CAMPAIGN.json"
    monkeypatch.setattr(bench, "HW_CAMPAIGN_PATH", str(journal))
    monkeypatch.delenv("SVOC_BENCH_NO_REPLAY", raising=False)

    def capture(value, backend="tpu", rc=0, at="2026-07-31 02:30:00", **detail):
        return {
            "rc": rc,
            "captured_at": at,
            "result": {
                "metric": "m",
                "value": value,
                "unit": "comments/sec",
                "vs_baseline": value / 6.0,
                "detail": {"backend": backend, **detail},
            },
        }

    # no journal -> no replay
    assert bench.campaign_replay(0, "probe timed out") is None

    journal.write_text(json.dumps({
        "updated_at": "2026-07-31 04:00:00",
        "items": [
            {"name": "bench_config0", "done": True,
             "results": [capture(4515.7)]},
            {"name": "bench_config0_routed", "done": True,
             "results": [capture(111.0, backend="cpu"),  # skipped
                         capture(9582.95),
                         # a recycled replay and malformed entries must
                         # be skipped, never re-replayed or crash
                         capture(8000.0, replayed_from="HW_CAMPAIGN.json"),
                         "not-a-dict"]},
            {"name": "bench_config10", "done": False,    # not done
             "results": [capture(11471.0)]},
            {"name": "bench_config11", "done": True, "results": None},
        ],
    }))
    out = bench.campaign_replay(0, "probe timed out")
    assert out["value"] == 9582.95
    assert out["detail"]["replayed_from"] == "HW_CAMPAIGN.json"
    assert out["detail"]["replay_item"] == "bench_config0_routed"
    assert out["detail"]["replay_captured_at"] == "2026-07-31 02:30:00"
    assert out["detail"]["fresh_probe_failure"] == "probe timed out"
    # EVERY replayed line says so in its top-level metric string, the
    # routed-config0 capture included — not only the variant-routed
    # relabel path (r5 satellite).
    assert "replayed capture of bench_config0_routed" in out["metric"]
    assert out["detail"]["replayed_metric"] == "m"
    # a pre-captured_at-era capture must NOT inherit the journal's
    # liveness-poll updated_at as its provenance (code-review r5)
    journal.write_text(json.dumps({
        "updated_at": "2026-07-31 05:31:43",
        "items": [{"name": "bench_config0", "done": True,
                   "results": [{"rc": 0, "result": {
                       "metric": "m", "value": 4515.7, "unit": "c/s",
                       "vs_baseline": 1, "detail": {"backend": "tpu"}}}]}],
    }))
    legacy = bench.campaign_replay(0, "x")
    assert legacy["value"] == 4515.7
    assert "replay_captured_at" not in legacy["detail"]
    assert "replayed capture of bench_config0" in legacy["metric"]
    # config with only a not-done item -> no replay
    assert bench.campaign_replay(10, "x") is None
    # a NON-config-0 replay carries the provenance marker too (the r5
    # satellite: previously only routed config-0 relabeled its metric)
    journal.write_text(json.dumps({
        "items": [{"name": "bench_config8", "done": True,
                   "results": [capture(9271.0)]}],
    }))
    replay8 = bench.campaign_replay(8, "probe timed out")
    assert replay8["value"] == 9271.0
    assert replay8["metric"] == "(replayed capture of bench_config8) m"
    assert replay8["detail"]["replayed_metric"] == "m"
    # Without the routed re-capture, config 0 follows the COMMITTED
    # routing to the variant's own capture (the same bench body config
    # 0 executes) — the round-4 journal shape, where falling back to
    # the dense line would misreport the flagship by 2x.
    monkeypatch.setenv("SVOC_FLAGSHIP_VARIANT", "packed_flash")
    journal.write_text(json.dumps({
        "items": [
            {"name": "bench_config0", "done": True,
             "results": [capture(4515.7)]},
            {"name": "bench_config12", "done": True,
             "results": [capture(9582.95)]},
        ],
    }))
    routed = bench.campaign_replay(0, "probe timed out")
    assert routed["value"] == 9582.95
    assert routed["detail"]["replay_item"] == "bench_config12"
    monkeypatch.delenv("SVOC_FLAGSHIP_VARIANT")
    # an unknown routing fails loudly (same law as the live flagship
    # body) instead of silently replaying the wrong capture
    monkeypatch.setenv("SVOC_FLAGSHIP_VARIANT", "flash")
    with pytest.raises(ValueError, match="flagship_variant"):
        bench.campaign_replay(0, "x")
    monkeypatch.delenv("SVOC_FLAGSHIP_VARIANT")
    # kill switch
    monkeypatch.setenv("SVOC_BENCH_NO_REPLAY", "1")
    assert bench.campaign_replay(0, "x") is None


def test_driver_snapshot_replays_tpu_capture_on_dead_tunnel(tmp_path):
    """The round-artifact path end to end: `python bench.py` with a
    dead/unreachable device backend and a campaign journal holding a
    real TPU capture must emit THAT capture (provenance stamped), not
    a CPU fallback line — the exact round-4 failure BENCH_r04.json
    recorded."""
    journal = tmp_path / "HW_CAMPAIGN.json"
    journal.write_text(json.dumps({
        "items": [{
            "name": "bench_config0_routed", "done": True,
            "results": [{
                "rc": 0,
                "captured_at": "2026-07-31 02:59:00",
                "result": {
                    "metric": "flagship (packed x flash): ...",
                    "value": 9582.95,
                    "unit": "comments/sec",
                    "vs_baseline": 1597.16,
                    "detail": {"backend": "tpu", "mfu_estimate": 0.3586},
                },
            }],
        }],
    }))
    env = dict(os.environ)
    for knob in ("SVOC_BENCH_SMALL", "SVOC_BENCH_NO_REPLAY"):
        env.pop(knob, None)
    env.update({
        # No JAX_PLATFORMS=cpu: the probe must RUN and fail, like the
        # driver's snapshot on a dead tunnel.
        "JAX_PLATFORMS": "",
        "SVOC_BENCH_PROBE_ATTEMPTS": "1",
        "SVOC_BENCH_PROBE_TIMEOUT": "0.05",
        "SVOC_BENCH_CAMPAIGN_JOURNAL": str(journal),
    })
    proc = subprocess.run(
        [sys.executable, BENCH],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-1500:])
    out = json.loads(lines[-1])
    assert out["value"] == 9582.95
    assert out["detail"]["backend"] == "tpu"
    assert out["detail"]["replayed_from"] == "HW_CAMPAIGN.json"
    assert out["detail"]["replay_captured_at"] == "2026-07-31 02:59:00"
    assert "timed out" in out["detail"]["fresh_probe_failure"]


def test_pipelined_packed_step_is_lossless():
    """config 8 with and without the software pipeline must produce the
    SAME final consensus (key-for-key: batch k's consensus consumes the
    key chained at step k in both modes) — the pipelined throughput
    number is only comparable because the computation is identical."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "SVOC_BENCH_SMALL": "1",
        # Deterministic step budget: both runs must cover the SAME
        # batches of the seed-0 stream or the comparison is vacuous.
        "SVOC_BENCH_MAX_STEPS": "6",
    }
    rc_a, a = _run_bench(["--config", "8", "--seconds", "60"], env)
    rc_b, b = _run_bench(
        ["--config", "8", "--seconds", "60"],
        {**env, "SVOC_BENCH_NO_PIPELINE": "1"},
    )
    assert rc_a == 0 and rc_b == 0
    assert a["detail"]["pipelined"] is True
    assert b["detail"]["pipelined"] is False
    assert a["detail"]["steps"] == b["detail"]["steps"] == 6
    # Same batches, same chained keys: the final batch's consensus
    # must match exactly.
    assert a["detail"]["consensus_reliability2"] == (
        b["detail"]["consensus_reliability2"]
    )


def test_pipelined_dense_flagship_is_lossless():
    """The dense flagship body's pipelined loop, same A/B law."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "SVOC_BENCH_SMALL": "1",
        "SVOC_BENCH_MAX_STEPS": "5",
        "SVOC_FLAGSHIP_VARIANT": "dense",
    }
    rc_a, a = _run_bench(["--config", "0", "--seconds", "60"], env)
    rc_b, b = _run_bench(
        ["--config", "0", "--seconds", "60"],
        {**env, "SVOC_BENCH_NO_PIPELINE": "1"},
    )
    assert rc_a == 0 and rc_b == 0
    assert a["detail"]["pipelined"] is True
    assert b["detail"]["pipelined"] is False
    assert a["detail"]["steps"] == b["detail"]["steps"] == 5
    assert a["detail"]["consensus_reliability2"] == (
        b["detail"]["consensus_reliability2"]
    )


def test_pipelined_dp_serving_is_lossless():
    """The config 9 mesh-level pipelined loop: same A/B law as
    config 8 — identical batches (fixed step budget), identical final
    consensus between the pipelined and plain step."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "SVOC_BENCH_SMALL": "1",
        "SVOC_BENCH_MAX_STEPS": "4",
    }
    rc_a, a = _run_bench(["--config", "9", "--seconds", "60"], env)
    rc_b, b = _run_bench(
        ["--config", "9", "--seconds", "60"],
        {**env, "SVOC_BENCH_NO_PIPELINE": "1"},
    )
    assert rc_a == 0 and rc_b == 0
    assert a["detail"]["pipelined"] is True
    assert b["detail"]["pipelined"] is False
    assert a["detail"]["steps"] == b["detail"]["steps"] == 4
    assert a["detail"]["reliability2"] == b["detail"]["reliability2"]


def test_soak_recovered_reads_snapshot_series():
    """Recovery = a commit SUCCEEDED after the last panic; commit
    attempts and dedup'd console lines must not fool it (code-review
    r4)."""
    from tools.soak import soak_recovered

    def snap(commits, failures, active=True):
        return {
            "commits": commits,
            "chain_commit_failures": failures,
            "consensus_active": active,
        }

    # healthy run, no panics
    assert soak_recovered([snap(5, 0), snap(10, 0)])
    # panic then recovery (successes 4 -> 8)
    assert soak_recovered([snap(5, 1), snap(9, 1)])
    # every later commit fails: attempts grow, successes don't
    assert not soak_recovered([snap(5, 1), snap(30, 26)])
    # consensus lost at the end
    assert not soak_recovered([snap(5, 0), snap(10, 0, active=False)])
    # empty run
    assert not soak_recovered([])
