"""bench.py harness behavior (subprocess; the one-line JSON contract).

Runs the cheapest config end to end in a child process with the CPU
platform pinned — fast, hermetic, and exercising the REAL main() path
including backend resolution, the CPU auto-shrink, and the result-line
format the driver and tools/hw_queue.py parse.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(args, env_extra):
    env = dict(os.environ)
    # Hermetic against the caller's own bench knobs — an exported
    # SVOC_BENCH_SMALL would suppress auto-shrink, FORCE_FULL would run
    # the unbounded full-size workload.
    for knob in ("SVOC_BENCH_SMALL", "SVOC_BENCH_FORCE_FULL", "SVOC_BENCH_SECONDS"):
        env.pop(knob, None)
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, BENCH, *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, (proc.stdout, proc.stderr[-1500:])
    return proc.returncode, json.loads(lines[-1])


def test_cpu_platform_auto_shrinks_and_labels():
    """On a CPU backend the full-size workload auto-shrinks (it cannot
    finish in bounded time) with the reason stamped in detail — the
    round-end bench must emit an honest line, never wedge."""
    rc, result = _run_bench(
        ["--config", "2", "--seconds", "1"],
        {"JAX_PLATFORMS": "cpu"},
    )
    assert rc == 0
    assert result["unit"] == "consensus-updates/sec"  # config 2's metric
    assert result["value"] > 0
    assert result["detail"]["backend"] == "cpu"
    assert result["detail"]["small_mode"] is True
    assert "auto-shrunk" in result["detail"]["small_mode_auto"]


def test_explicit_small_mode_is_not_labeled_auto():
    rc, result = _run_bench(
        ["--config", "2", "--seconds", "1"],
        {"JAX_PLATFORMS": "cpu", "SVOC_BENCH_SMALL": "1"},
    )
    assert rc == 0
    assert result["detail"]["small_mode"] is True
    assert "small_mode_auto" not in result["detail"]


def test_soak_recovered_reads_snapshot_series():
    """Recovery = a commit SUCCEEDED after the last panic; commit
    attempts and dedup'd console lines must not fool it (code-review
    r4)."""
    from tools.soak import soak_recovered

    def snap(commits, failures, active=True):
        return {
            "commits": commits,
            "chain_commit_failures": failures,
            "consensus_active": active,
        }

    # healthy run, no panics
    assert soak_recovered([snap(5, 0), snap(10, 0)])
    # panic then recovery (successes 4 -> 8)
    assert soak_recovered([snap(5, 1), snap(9, 1)])
    # every later commit fails: attempts grow, successes don't
    assert not soak_recovered([snap(5, 1), snap(30, 26)])
    # consensus lost at the end
    assert not soak_recovered([snap(5, 0), snap(10, 0, active=False)])
    # empty run
    assert not soak_recovered([])
