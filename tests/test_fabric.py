"""Multi-claim consensus fabric (docs/FABRIC.md).

Covers: the claim-cube kernels' parity against a Python loop of the
single-claim kernels (gated and ungated, both consensus configs,
including degenerate claims), the router's pow2 bucketing/padding, the
fair weighted scheduler, per-claim seed derivation, and the two-claim
end-to-end isolation contract (lineage families never merge, one
claim's poison never crosses the claim axis).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from svoc_tpu.consensus.batch import (  # noqa: E402
    claims_consensus,
    claims_consensus_gated,
    claims_consensus_sanitized,
    pad_claim_cube,
    pow2_bucket,
)
from svoc_tpu.consensus.kernel import (  # noqa: E402
    ConsensusConfig,
    consensus_step,
    consensus_step_gated,
)
from svoc_tpu.fabric.registry import ClaimRegistry, ClaimSpec, ClaimState  # noqa: E402
from svoc_tpu.fabric.router import ClaimRouter  # noqa: E402
from svoc_tpu.sim.generators import claim_seed  # noqa: E402

CONFIGS = [
    ConsensusConfig(),  # constrained (the contract default)
    ConsensusConfig(constrained=False, max_spread=10.0),
]


def _cube(c=5, n=7, m=6, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.0, 1.0, size=(c, n, m)).astype(np.float32)


def _assert_output_close(batched, reference, i):
    """Claim ``i`` of a batched output vs a single-claim reference."""
    np.testing.assert_allclose(
        np.asarray(batched.essence)[i], np.asarray(reference.essence),
        atol=1e-6, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(batched.essence_first_pass)[i],
        np.asarray(reference.essence_first_pass),
        atol=1e-6, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(batched.reliability_first_pass)[i],
        np.asarray(reference.reliability_first_pass),
        atol=1e-6, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(batched.reliability_second_pass)[i],
        np.asarray(reference.reliability_second_pass),
        atol=1e-6, rtol=0,
    )
    assert np.array_equal(
        np.asarray(batched.reliable)[i], np.asarray(reference.reliable)
    )
    assert bool(np.asarray(batched.interval_valid)[i]) == bool(
        np.asarray(reference.interval_valid)
    )


class TestClaimCubeBatching:
    def test_pow2_bucket(self):
        assert [pow2_bucket(n) for n in (0, 1, 2, 3, 4, 5, 9, 64, 65)] == [
            1, 1, 2, 4, 4, 8, 16, 64, 128,
        ]
        assert pow2_bucket(3, floor=8) == 8
        with pytest.raises(ValueError):
            pow2_bucket(-1)

    def test_pad_claim_cube_pads_to_bucket_and_masks(self):
        values = _cube(c=5)
        ok = np.ones((5, 7), dtype=bool)
        ok[1, 3] = False
        padded, ok_padded, claim_mask = pad_claim_cube(values, ok)
        assert padded.shape == (8, 7, 6)
        assert ok_padded.shape == (8, 7)
        assert claim_mask.tolist() == [True] * 5 + [False] * 3
        np.testing.assert_array_equal(padded[:5], values)
        np.testing.assert_array_equal(ok_padded[:5], ok)
        assert ok_padded[5:].all()  # padding claims are all-admitted

    def test_pad_claim_cube_exact_bucket_is_identity(self):
        values = _cube(c=4)
        padded, _ok, claim_mask = pad_claim_cube(values)
        assert padded.shape[0] == 4 and claim_mask.all()

    def test_pad_claim_cube_rejects_bad_shapes(self):
        with pytest.raises(ValueError):
            pad_claim_cube(np.zeros((3, 7)))
        with pytest.raises(ValueError):
            pad_claim_cube(np.zeros((3, 7, 6)), ok=np.ones((2, 7), dtype=bool))


class TestClaimBatchedParity:
    """Acceptance: the claim-batched kernels are numerically identical
    to a per-claim Python loop of the existing single-claim kernels."""

    @pytest.mark.parametrize("cfg", CONFIGS, ids=["constrained", "unconstrained"])
    def test_ungated_matches_per_claim_loop(self, cfg):
        values = _cube(c=5, seed=1)
        padded, _ok, claim_mask = pad_claim_cube(values)
        out = claims_consensus(
            jnp.asarray(padded), jnp.asarray(claim_mask), cfg
        )
        for i in range(values.shape[0]):
            ref = consensus_step(jnp.asarray(values[i]), cfg)
            _assert_output_close(out, ref, i)

    @pytest.mark.parametrize("cfg", CONFIGS, ids=["constrained", "unconstrained"])
    def test_gated_matches_per_claim_loop(self, cfg):
        values = _cube(c=6, seed=2)
        ok = np.ones((6, 7), dtype=bool)
        ok[1, 0] = False  # one quarantined slot
        ok[2, :5] = False  # degenerate: n_ok == 2 (boundary-valid)
        ok[3, :6] = False  # degenerate: n_ok == 1 -> no consensus
        ok[4, :] = False  # degenerate: n_ok == 0 -> no consensus
        padded, ok_padded, claim_mask = pad_claim_cube(values, ok)
        out = claims_consensus_gated(
            jnp.asarray(padded), jnp.asarray(ok_padded),
            jnp.asarray(claim_mask), cfg,
        )
        for i in range(values.shape[0]):
            ref = consensus_step_gated(
                jnp.asarray(values[i]), jnp.asarray(ok[i]), cfg
            )
            _assert_output_close(out, ref, i)

    def test_degenerate_claims_invalid_but_finite_and_isolated(self):
        """A claim below 2 admitted oracles reports interval_valid=False
        with finite essences, and its siblings in the same micro-batch
        stay valid — sentinel leakage across the claim axis is the bug
        this pins."""
        cfg = ConsensusConfig()
        values = _cube(c=3, seed=3)
        ok = np.ones((3, 7), dtype=bool)
        ok[1, :6] = False
        padded, ok_padded, claim_mask = pad_claim_cube(values, ok)
        out = claims_consensus_gated(
            jnp.asarray(padded), jnp.asarray(ok_padded),
            jnp.asarray(claim_mask), cfg,
        )
        valid = np.asarray(out.interval_valid)
        assert not valid[1]
        assert valid[0] and valid[2]
        assert np.isfinite(np.asarray(out.essence)[:3]).all()

    def test_padding_claims_read_as_no_consensus(self):
        cfg = ConsensusConfig()
        values = _cube(c=5, seed=4)
        padded, ok_padded, claim_mask = pad_claim_cube(
            values, np.ones((5, 7), dtype=bool)
        )
        out = claims_consensus_gated(
            jnp.asarray(padded), jnp.asarray(ok_padded),
            jnp.asarray(claim_mask), cfg,
        )
        assert not np.asarray(out.interval_valid)[5:].any()
        assert not np.asarray(out.reliable)[5:].any()
        np.testing.assert_array_equal(np.asarray(out.essence)[5:], 0.0)

    def test_sanitized_fuses_gate_and_kernel(self):
        """The fused gate+consensus dispatch must agree with the host
        gate's admission mask and the gated kernel."""
        from svoc_tpu.robustness.sanitize import QuarantineGate, SanitizeConfig

        cfg = ConsensusConfig()
        sanitize = SanitizeConfig.for_consensus(constrained=True)
        values = _cube(c=4, seed=5).astype(np.float64)
        values[0, 2, 0] = np.nan
        values[1, 4, :] = 7.5  # out of the constrained [0, 1] domain
        padded, _ok, claim_mask = pad_claim_cube(values.astype(np.float32))
        out, ok = claims_consensus_sanitized(
            jnp.asarray(padded), jnp.asarray(claim_mask), cfg,
            sanitize.lo, sanitize.hi,
        )
        gate = QuarantineGate(sanitize)
        for i in range(4):
            report = gate.inspect(values[i], count=False)
            np.testing.assert_array_equal(np.asarray(ok)[i], report.ok)
            ref = consensus_step_gated(
                jnp.asarray(values[i], dtype=jnp.float32),
                jnp.asarray(report.ok), cfg,
            )
            _assert_output_close(out, ref, i)


class TestClaimSeed:
    def test_deterministic_and_distinct(self):
        assert claim_seed(0, "alpha") == claim_seed(0, "alpha")
        seeds = {claim_seed(0, f"claim{i}") for i in range(64)}
        assert len(seeds) == 64  # no collisions across nearby ids
        assert claim_seed(0, "alpha") != claim_seed(1, "alpha")
        for s in seeds:
            assert 0 <= s < 2**32  # PRNGKey/word-sized

    def test_base_seed_mixes_even_at_zero(self):
        # The crc is folded with the base seed, not OR'd into the low
        # word: base_seed=0 must still shift every claim's stream.
        assert claim_seed(0, "x") != claim_seed(7, "x")


class TestClaimSpec:
    def test_rejects_separator_ids(self):
        for bad in ("", "a-b", "a/b"):
            with pytest.raises(ValueError):
                ClaimSpec(claim_id=bad)

    def test_rejects_bad_weight_and_spread(self):
        with pytest.raises(ValueError):
            ClaimSpec(claim_id="a", weight=0)
        with pytest.raises(ValueError):
            ClaimSpec(claim_id="a", constrained=False, max_spread=0.0)

    def test_consensus_config_groups_identical_claims(self):
        a = ClaimSpec(claim_id="a").consensus_config()
        b = ClaimSpec(claim_id="b").consensus_config()
        assert a == b  # same config -> same micro-batch group


class TestRouterScheduling:
    def _registry_with(self, specs):
        registry = ClaimRegistry()
        for spec in specs:
            registry.add(spec, session=None, evaluator=None)
        return registry

    def test_weighted_rotation_is_fair_and_deterministic(self):
        registry = self._registry_with(
            [ClaimSpec(claim_id="a", weight=2), ClaimSpec(claim_id="b")]
        )
        router = ClaimRouter(registry, max_claims_per_batch=1)
        order = [router.select()[0].spec.claim_id for _ in range(6)]
        # Weight-2 "a" holds two rotation slots: served twice per full
        # rotation, deterministically.
        assert order == ["a", "a", "b", "a", "a", "b"]

    def test_select_returns_distinct_claims_up_to_cap(self):
        registry = self._registry_with(
            [ClaimSpec(claim_id=c, weight=3) for c in ("a", "b", "c")]
        )
        router = ClaimRouter(registry, max_claims_per_batch=8)
        picked = [s.spec.claim_id for s in router.select()]
        assert sorted(picked) == ["a", "b", "c"]  # distinct despite weights

    def test_paused_claims_are_skipped_and_resume(self):
        registry = self._registry_with(
            [ClaimSpec(claim_id="a"), ClaimSpec(claim_id="b")]
        )
        router = ClaimRouter(registry, max_claims_per_batch=8)
        registry.get("a").paused = True
        assert [s.spec.claim_id for s in router.select()] == ["b"]
        registry.get("a").paused = False
        assert sorted(s.spec.claim_id for s in router.select()) == ["a", "b"]

    def test_membership_changes_keep_rotation_position(self):
        registry = self._registry_with(
            [ClaimSpec(claim_id="a"), ClaimSpec(claim_id="b")]
        )
        router = ClaimRouter(registry, max_claims_per_batch=1)
        assert router.select()[0].spec.claim_id == "a"
        registry.add(ClaimSpec(claim_id="c"), session=None, evaluator=None)
        # b keeps its pending turn across the rebuild, and the next
        # full rotation serves every claim exactly once — a membership
        # change must not starve or double-serve anyone.
        assert router.select()[0].spec.claim_id == "b"
        next_round = [router.select()[0].spec.claim_id for _ in range(2)]
        assert sorted(["b"] + next_round) == ["a", "b", "c"]

    def test_rejects_bad_batch_cap(self):
        with pytest.raises(ValueError):
            ClaimRouter(ClaimRegistry(), max_claims_per_batch=0)

    def test_registry_rejects_duplicates_and_unknown(self):
        registry = self._registry_with([ClaimSpec(claim_id="a")])
        with pytest.raises(ValueError):
            registry.add(ClaimSpec(claim_id="a"), session=None, evaluator=None)
        with pytest.raises(KeyError):
            registry.get("nope")
        assert "a" in registry and len(registry) == 1


def _two_claim_multi(journal, metrics):
    """A deterministic two-claim MultiSession on synthetic stores."""
    from svoc_tpu.fabric.scenario import deterministic_vectorizer
    from svoc_tpu.fabric.session import MultiSession
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    def store_factory(claim_id):
        store = CommentStore()
        store.save(SyntheticSource(batch=80, seed=claim_seed(0, claim_id))())
        return store

    multi = MultiSession(
        base_seed=0,
        vectorizer=deterministic_vectorizer,
        store_factory=store_factory,
        journal=journal,
        metrics=metrics,
        lineage_scope="t",
    )
    multi.add_claim(ClaimSpec(claim_id="alpha"))
    multi.add_claim(ClaimSpec(claim_id="beta"))
    return multi


class TestMultiSessionEndToEnd:
    def test_two_claims_lineage_families_never_merge(self):
        """ISSUE 6 satellite: a two-claim end-to-end run whose journal
        lineage ids partition cleanly per claim — every event's lineage
        belongs to exactly one claim's family."""
        from svoc_tpu.utils.events import EventJournal
        from svoc_tpu.utils.metrics import MetricsRegistry

        journal = EventJournal(MetricsRegistry())
        multi = _two_claim_multi(journal, MetricsRegistry())
        reports = multi.run(3)
        assert all(sorted(r["served"]) == ["alpha", "beta"] for r in reports)

        alpha = multi.get("alpha").session
        beta = multi.get("beta").session
        assert alpha.lineage_prefix == "blkt-alpha"
        assert beta.lineage_prefix == "blkt-beta"
        assert alpha.last_lineage.startswith("blkt-alpha-")
        assert beta.last_lineage.startswith("blkt-beta-")
        prefixes = ("blkt-alpha-", "blkt-beta-")
        for event in journal.recent():
            if event.lineage is not None:
                assert sum(event.lineage.startswith(p) for p in prefixes) == 1
        # Both claims produced full per-block event sets on their own
        # lineage, and the audit record resolves per claim.
        for session in (alpha, beta):
            types = {
                e.type for e in journal.recent(lineage=session.last_lineage)
            }
            assert {"block.fetched", "consensus.result"} <= types
            record = multi.audit(session.last_lineage)
            assert record["found"]

    def test_per_claim_fingerprints_differ_and_compose(self):
        from svoc_tpu.utils.events import EventJournal
        from svoc_tpu.utils.metrics import MetricsRegistry

        journal = EventJournal(MetricsRegistry())
        multi = _two_claim_multi(journal, MetricsRegistry())
        multi.run(2)
        fp_a = multi.claim_fingerprint("alpha")
        fp_b = multi.claim_fingerprint("beta")
        assert fp_a != fp_b
        # The filter is a partition: an unknown prefix digests empty.
        assert journal.fingerprint(lineage_prefix="blkt-gamma-") != fp_a

    def test_snapshot_and_claims_state_shape(self):
        from svoc_tpu.utils.events import EventJournal
        from svoc_tpu.utils.metrics import MetricsRegistry

        multi = _two_claim_multi(
            EventJournal(MetricsRegistry()), MetricsRegistry()
        )
        multi.step()
        snapshot = multi.snapshot()
        assert snapshot["n_claims"] == 2 and snapshot["steps"] == 1
        for claim_id in ("alpha", "beta"):
            c = snapshot["claims"][claim_id]
            assert c["claim"] == claim_id
            assert c["cycles"] == 1
            assert c["consensus"]["interval_valid"] is True
            assert c["commit"]["complete"]
            assert c["lineage"].startswith(f"blkt-{claim_id}-")
        import json

        json.dumps(snapshot)  # /api/state ships this verbatim

    def test_raising_tamper_skips_claim_never_the_batch(self):
        """Isolation contract: a claim whose (user-supplied) tamper
        hook raises is skipped and counted as an anomaly — its
        siblings are served, the loop survives."""
        from svoc_tpu.utils.events import EventJournal
        from svoc_tpu.utils.metrics import MetricsRegistry

        metrics = MetricsRegistry()
        multi = _two_claim_multi(EventJournal(MetricsRegistry()), metrics)

        def explode(cycle, block):
            raise IndexError("bad hook")

        multi.add_claim(
            ClaimSpec(claim_id="gamma", tamper=explode),
            store=multi.get("alpha").session.store,
        )
        report = multi.step()
        assert sorted(report["served"]) == ["alpha", "beta"]
        assert report["skipped"]["gamma"] == "fetch_error:IndexError"
        assert (
            metrics.counter(
                "fabric_claim_errors",
                labels={"claim": "gamma", "stage": "fetch"},
            ).count
            == 1
        )

    def test_pause_drains_without_removing(self):
        from svoc_tpu.utils.events import EventJournal
        from svoc_tpu.utils.metrics import MetricsRegistry

        multi = _two_claim_multi(
            EventJournal(MetricsRegistry()), MetricsRegistry()
        )
        multi.pause("alpha")
        report = multi.step()
        assert report["served"] == ["beta"]
        multi.pause("alpha", paused=False)
        assert sorted(multi.step()["served"]) == ["alpha", "beta"]

    def test_claims_console_command(self):
        from svoc_tpu.apps.commands import CommandConsole
        from svoc_tpu.utils.events import EventJournal
        from svoc_tpu.utils.metrics import MetricsRegistry

        multi = _two_claim_multi(
            EventJournal(MetricsRegistry()), MetricsRegistry()
        )
        multi.step()
        console = CommandConsole(multi.get("alpha").session)
        assert any(
            "no claim fabric" in line for line in console.query("claims")
        )
        multi.attach(console)
        lines = console.query("claims")
        assert any("fabric: 2 claims" in line for line in lines)
        assert any(line.strip().startswith("alpha:") for line in lines)
        assert any(line.strip().startswith("beta:") for line in lines)


class TestFabricScenario:
    def test_seeded_scenario_replays_per_claim_identical(self):
        from svoc_tpu.fabric.scenario import run_fabric_scenario

        first = run_fabric_scenario(0, cycles=6)
        second = run_fabric_scenario(0, cycles=6)
        assert first["journal_fingerprint"] == second["journal_fingerprint"]
        for claim_id, c in first["claims"].items():
            assert (
                c["fingerprint"] == second["claims"][claim_id]["fingerprint"]
            )
        assert first["injection_count"] > 0
        assert first["siblings_clean"]
        offender = first["claims"][first["offender_claim"]]
        assert offender["quarantine_verdicts"] == first["injection_count"]
