"""Compile plane (docs/PARALLELISM.md §compile-plane): shape-universe
enumeration, AOT prewarm, the persistent compilation cache, warmth
accounting, and the serving tier's cold-shape deferral."""

from __future__ import annotations

import os

import numpy as np
import pytest

from svoc_tpu.compile.cache import (
    DEFAULT_MAX_BYTES,
    cache_salt,
    cache_stats,
    enable_persistent_cache,
    evict_cache,
    kernel_revision,
    persistent_cache_dir,
)
from svoc_tpu.compile.prewarm import PrewarmConfig, PrewarmWorker
from svoc_tpu.compile.universe import (
    CompileKey,
    bucket_ladder,
    dispatch_key,
    enumerate_universe,
    registry_groups,
    universe_summary,
)
from svoc_tpu.consensus.dispatch import (
    CompilePlaneError,
    resolve_compilation_cache,
    resolve_warmup_mode,
)
from svoc_tpu.consensus.kernel import ConsensusConfig
from svoc_tpu.fabric.registry import ClaimRegistry, ClaimSpec
from svoc_tpu.fabric.router import ClaimRouter
from svoc_tpu.utils.metrics import MetricsRegistry

CFG = ConsensusConfig(n_failing=2, constrained=True)


def bare_registry(n_claims=3, n_oracles=7, dimension=6) -> ClaimRegistry:
    reg = ClaimRegistry()
    for i in range(n_claims):
        reg.add(
            ClaimSpec(
                claim_id=f"c{i}", n_oracles=n_oracles, dimension=dimension
            ),
            None,
            None,
        )
    return reg


# ---------------------------------------------------------------------------
# Universe enumeration
# ---------------------------------------------------------------------------


class TestUniverse:
    def test_registry_groups_counts_unpaused_claims_per_group(self):
        reg = bare_registry(3)
        reg.add(ClaimSpec(claim_id="big", n_oracles=16, dimension=6), None, None)
        groups = registry_groups(reg)
        assert groups[(7, 6, CFG)] == 3
        assert groups[(16, 6, CFG)] == 1
        reg.get("c0").paused = True
        assert registry_groups(reg)[(7, 6, CFG)] == 2

    def test_serving_critical_bucket_first_then_ladder_then_twins(self):
        keys = enumerate_universe(
            {(7, 6, CFG): 3},
            max_claims_per_batch=8,
            sanitized_dispatch=True,
            donate=True,
            impl="xla",
        )
        # Head: the bucket 3 live claims dispatch (pow2 -> 4), in the
        # router's own variant (sanitized + donate).
        assert keys[0] == CompileKey(
            kind="sanitized", bucket=4, n_oracles=7, dimension=6,
            cfg=CFG, donate=True,
        )
        # The primary-variant ladder comes before any twin.
        first_twin = next(
            i for i, k in enumerate(keys)
            if k.kind == "gated" or not k.donate
        )
        primaries = keys[:first_twin]
        assert {k.bucket for k in primaries} == {1, 2, 4, 8}
        assert all(k.kind == "sanitized" and k.donate for k in primaries)
        # Twins cover the other gate fusion and the donate flip.
        kinds = {(k.kind, k.donate) for k in keys}
        assert kinds == {
            ("sanitized", True), ("sanitized", False),
            ("gated", True), ("gated", False),
        }
        # No duplicates; order deterministic.
        assert len(keys) == len(set(keys))
        assert keys == enumerate_universe(
            {(7, 6, CFG): 3},
            max_claims_per_batch=8,
            sanitized_dispatch=True,
            donate=True,
            impl="xla",
        )

    def test_mesh_universe_is_sharded_without_twins(self):
        keys = enumerate_universe(
            {(8, 6, CFG): 2},
            max_claims_per_batch=4,
            sanitized_dispatch=False,
            donate=True,  # sharded programs never donate
            impl="xla",
            mesh="2x4",
            mesh_claim_size=2,
        )
        assert all(k.kind == "sharded_gated" for k in keys)
        assert all(not k.donate for k in keys)
        assert all(k.mesh == "2x4" for k in keys)
        assert all(k.bucket % 2 == 0 for k in keys)

    def test_bucket_ladder_mesh_rounding(self):
        assert bucket_ladder(8) == [1, 2, 4, 8]
        # pow2 buckets rounded UP to the mesh claim-axis multiple,
        # deduplicated: 1,2 -> 3; 4 -> 6; 8 -> 9.
        assert bucket_ladder(8, multiple_of=3) == [3, 6, 9]

    def test_dispatch_key_matches_enumerated_identity(self):
        key = dispatch_key(
            sanitized=True, sharded=False, bucket=4, n_oracles=7,
            dimension=6, cfg=CFG, donate=False, impl="xla", mesh=None,
        )
        keys = enumerate_universe(
            {(7, 6, CFG): 4},
            max_claims_per_batch=4,
            sanitized_dispatch=True,
            donate=False,
            impl="xla",
        )
        assert key in keys

    def test_compile_key_validation_and_summary(self):
        with pytest.raises(ValueError):
            CompileKey(kind="nope", bucket=1, n_oracles=7, dimension=6, cfg=CFG)
        with pytest.raises(ValueError):
            CompileKey(kind="gated", bucket=0, n_oracles=7, dimension=6, cfg=CFG)
        keys = enumerate_universe(
            {(7, 6, CFG): 1},
            max_claims_per_batch=2,
            sanitized_dispatch=False,
            donate=False,
            impl="xla",
        )
        summary = universe_summary(keys)
        assert summary["keys"] == len(keys)
        assert summary["groups"] == 1
        assert set(summary["kinds"]) == {"gated", "sanitized"}


# ---------------------------------------------------------------------------
# Persistent cache: salt versioning + eviction
# ---------------------------------------------------------------------------


class TestPersistentCache:
    def test_salt_covers_jax_version_and_kernel_revision(self):
        import jax

        salt = cache_salt()
        assert jax.__version__ in salt
        assert kernel_revision()[:12] in salt

    def test_salt_change_invalidates_old_entries(self, tmp_path, monkeypatch):
        base = str(tmp_path)
        monkeypatch.setattr(
            "svoc_tpu.compile.cache.cache_salt", lambda: "saltA"
        )
        dir_a = enable_persistent_cache(base, metrics=MetricsRegistry())
        assert dir_a and dir_a.endswith("saltA")
        stale = os.path.join(dir_a, "old-cache")
        with open(stale, "w") as f:
            f.write("x" * 100)
        # A new salt (jax upgrade / kernel edit) gets a DIFFERENT dir
        # and deletes the stale one — old entries can never be read.
        monkeypatch.setattr(
            "svoc_tpu.compile.cache.cache_salt", lambda: "saltB"
        )
        reg = MetricsRegistry()
        dir_b = enable_persistent_cache(base, metrics=reg)
        assert dir_b != dir_a
        assert not os.path.exists(dir_a)
        assert (
            reg.counter(
                "compile_cache_invalidated", labels={"salt": "saltA"}
            ).count
            == 1
        )

    def test_eviction_drops_least_recently_used_until_under_cap(
        self, tmp_path
    ):
        cache_dir = str(tmp_path)
        for i, age in [(0, 100), (1, 50), (2, 10)]:
            payload = os.path.join(cache_dir, f"k{i}-cache")
            atime = os.path.join(cache_dir, f"k{i}-atime")
            with open(payload, "w") as f:
                f.write("x" * 1000)
            with open(atime, "w") as f:
                f.write("")
            now = os.path.getmtime(payload)
            os.utime(atime, (now - age, now - age))
        reg = MetricsRegistry()
        stats = evict_cache(cache_dir, 2500, metrics=reg)
        assert stats["evicted"] == 1
        # Oldest-used (k0) evicted, payload AND atime twin.
        assert not os.path.exists(os.path.join(cache_dir, "k0-cache"))
        assert not os.path.exists(os.path.join(cache_dir, "k0-atime"))
        assert os.path.exists(os.path.join(cache_dir, "k2-cache"))
        assert reg.counter("compile_cache_evictions").count == 1
        assert reg.gauge("compile_cache_bytes").get() == 2000.0
        assert cache_stats(cache_dir) == {"entries": 2.0, "bytes": 2000.0}

    def test_cache_module_imports_jax_free(self):
        # The RecoveryManager constructor path (reachable from jax-free
        # durable-plane consumers — the PR 14 fuzz-child discipline)
        # imports compile.cache; the package __init__ re-exports are
        # PEP 562 lazy so this import must never pull jax.
        import subprocess
        import sys

        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import sys; "
                "from svoc_tpu.compile.cache import enable_persistent_cache; "
                "assert 'jax' not in sys.modules, 'jax leaked'",
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr[-1000:]

    def test_persistent_cache_dir_is_salted_subdir(self, tmp_path):
        d = persistent_cache_dir(str(tmp_path))
        assert d.startswith(os.path.join(str(tmp_path), "xla_cache"))

    def test_enable_is_idempotent_and_capped(self, tmp_path):
        reg = MetricsRegistry()
        d1 = enable_persistent_cache(
            str(tmp_path), max_bytes=DEFAULT_MAX_BYTES, metrics=reg
        )
        d2 = enable_persistent_cache(
            str(tmp_path), max_bytes=DEFAULT_MAX_BYTES, metrics=reg
        )
        assert d1 == d2 and os.path.isdir(d1)


# ---------------------------------------------------------------------------
# Resolution (env > PERF_DECISIONS.json > default, SVOC011 pinning)
# ---------------------------------------------------------------------------


class TestResolution:
    def test_warmup_mode_env_beats_record_beats_default(
        self, tmp_path, monkeypatch
    ):
        record = tmp_path / "decisions.json"
        record.write_text('{"warmup_mode": "prewarm"}')
        monkeypatch.delenv("SVOC_WARMUP", raising=False)
        assert resolve_warmup_mode(str(record)) == "prewarm"
        monkeypatch.setenv("SVOC_WARMUP", "none")
        assert resolve_warmup_mode(str(record)) == "none"
        monkeypatch.delenv("SVOC_WARMUP", raising=False)
        assert resolve_warmup_mode(str(tmp_path / "absent.json")) == "none"

    def test_compilation_cache_resolution_and_typed_errors(
        self, tmp_path, monkeypatch
    ):
        record = tmp_path / "decisions.json"
        record.write_text('{"compilation_cache": "persistent"}')
        monkeypatch.delenv("SVOC_COMPILATION_CACHE", raising=False)
        assert resolve_compilation_cache(str(record)) == "persistent"
        assert (
            resolve_compilation_cache(str(tmp_path / "absent.json")) == "off"
        )
        monkeypatch.setenv("SVOC_COMPILATION_CACHE", "bogus")
        with pytest.raises(CompilePlaneError) as e:
            resolve_compilation_cache(str(record))
        assert "SVOC_COMPILATION_CACHE" in str(e.value)
        monkeypatch.setenv("SVOC_WARMUP", "bogus")
        with pytest.raises(CompilePlaneError):
            resolve_warmup_mode(str(record))

    def test_router_pins_warmup_mode_at_construction(self, monkeypatch):
        monkeypatch.setenv("SVOC_WARMUP", "prewarm")
        router = ClaimRouter(bare_registry(), metrics=MetricsRegistry())
        monkeypatch.setenv("SVOC_WARMUP", "none")
        assert router.warmup_mode == "prewarm"  # pinned, no re-read
        explicit = ClaimRouter(
            bare_registry(), metrics=MetricsRegistry(), warmup_mode="none"
        )
        assert explicit.warmup_mode == "none"


# ---------------------------------------------------------------------------
# Prewarm worker + warmth accounting
# ---------------------------------------------------------------------------


class TestPrewarm:
    def test_warm_all_compiles_universe_and_marks_warm(self):
        reg = MetricsRegistry()
        registry = bare_registry(2)
        router = ClaimRouter(
            registry,
            max_claims_per_batch=2,
            metrics=reg,
            warmup_mode="prewarm",
        )
        worker = PrewarmWorker(
            router, registry, metrics=reg,
            config=PrewarmConfig(include_twins=False),
        )
        report = worker.warm_all()
        assert report["outcomes"].get("compiled", 0) > 0
        assert not report["outcomes"].get("error")
        assert worker.stats()["warmed"] == report["warmed"]
        for key in worker.universe():
            assert worker.is_warm(key)
        # Compile latency histogram observed per AOT key.
        assert (
            reg.histogram("prewarm_compile_seconds").count
            >= report["outcomes"]["compiled"]
        )
        # Finished walk: nothing is cold.
        assert not worker.group_cold(7, 6, CFG)

    def test_budget_exhaustion_is_counted_and_cuts_the_tail(self):
        reg = MetricsRegistry()
        registry = bare_registry(2)
        router = ClaimRouter(
            registry, max_claims_per_batch=4, metrics=reg,
            warmup_mode="prewarm",
        )
        clock = {"t": 0.0}

        def fake_clock():
            clock["t"] += 10.0  # every step "costs" 10s
            return clock["t"]

        worker = PrewarmWorker(
            router, registry, metrics=reg, clock=fake_clock,
            config=PrewarmConfig(budget_s=15.0, include_twins=False),
        )
        report = worker.warm_all()
        assert report["outcomes"].get("budget_exhausted", 0) > 0
        assert (
            reg.counter(
                "compile_prewarm", labels={"outcome": "budget_exhausted"}
            ).count
            == report["outcomes"]["budget_exhausted"]
        )
        # The cut universe still warmed its head (priority order).
        assert report["warmed"] >= 1

    def test_prewarmed_numerics_match_fresh_jit_bitwise(self):
        import jax
        from functools import partial

        from svoc_tpu.consensus.batch import claims_consensus_gated
        from svoc_tpu.consensus.kernel import consensus_step_gated_claims
        import jax.numpy as jnp

        reg = MetricsRegistry()
        registry = bare_registry(2)
        router = ClaimRouter(
            registry, max_claims_per_batch=2, metrics=reg,
            warmup_mode="prewarm",
        )
        worker = PrewarmWorker(
            router, registry, metrics=reg,
            config=PrewarmConfig(include_twins=False),
        )
        worker.warm_all()
        rng = np.random.default_rng(3)
        values = rng.uniform(0.05, 0.95, size=(2, 7, 6)).astype(np.float32)
        ok = np.ones((2, 7), dtype=bool)
        mask = np.ones(2, dtype=bool)
        warm = claims_consensus_gated(
            jnp.asarray(values), jnp.asarray(ok), jnp.asarray(mask), CFG,
            consensus_impl="xla", metrics=reg,
        )
        # The reference is a FRESH jit of the same body: the eager
        # trace differs by one ulp in rel₂ (the XLA CPU fusion finding
        # of docs/PARALLELISM.md §sharded-claims), so bitwise identity
        # is only owed between identically-compiled programs.
        fresh = partial(
            jax.jit(consensus_step_gated_claims, static_argnames=("cfg",))
        )
        ref = fresh(
            jnp.asarray(values), jnp.asarray(ok), jnp.asarray(mask), CFG
        )
        # Prewarming (AOT compile + dummy priming) must never change
        # results: the warmed dispatch is bitwise the fresh program.
        np.testing.assert_array_equal(
            np.asarray(warm.essence), np.asarray(ref.essence)
        )
        np.testing.assert_array_equal(
            np.asarray(warm.reliability_second_pass),
            np.asarray(ref.reliability_second_pass),
        )
        np.testing.assert_array_equal(
            np.asarray(warm.reliable), np.asarray(ref.reliable)
        )

    def test_defer_gate_closes_on_primary_keys_not_twins(self):
        # The serving-critical head of the walk (the pinned variant's
        # bucket ladder) is what the router can dispatch; the twin
        # variants at the tail are restart insurance.  The defer gate
        # must open as soon as the PRIMARY keys are warm — a gate held
        # by twins would defer for the whole walk, worse than the
        # inline compile it exists to avoid (review finding).
        reg = MetricsRegistry()
        registry = bare_registry(2)
        router = ClaimRouter(
            registry, max_claims_per_batch=2, metrics=reg,
            warmup_mode="prewarm",
        )
        worker = PrewarmWorker(
            router, registry, metrics=reg,
            config=PrewarmConfig(include_twins=True),
        )
        worker.universe(refresh=True)
        worker._started = True  # mid-walk: active, nothing warm yet
        assert worker.group_cold(7, 6, CFG)
        for key in worker._primary_keys(7, 6, CFG):
            assert worker.step(key) in ("compiled", "primed")
        # Primary surface warm -> the gate opens, twins still pending.
        assert not worker.group_cold(7, 6, CFG)
        pending_twins = [
            k for k in worker.universe() if not worker.is_warm(k)
        ]
        assert pending_twins, "twins should still be unwarmed here"
        worker._done.set()

    def test_prime_less_walk_never_fakes_warmth_for_unaot_keys(self):
        # prime=False only does AOT work, which covers the unsharded
        # XLA twins — a pallas-routed key gets NO work and must be
        # counted skipped, not marked warm (review finding).
        reg = MetricsRegistry()
        registry = bare_registry(1)
        router = ClaimRouter(
            registry, max_claims_per_batch=1, metrics=reg,
            warmup_mode="prewarm", consensus_impl="pallas",
        )
        worker = PrewarmWorker(
            router, registry, metrics=reg,
            config=PrewarmConfig(prime=False, include_twins=False),
        )
        key = worker.universe(refresh=True)[0]
        assert key.impl == "pallas"
        assert worker.step(key) == "skipped"
        assert not worker.is_warm(key)
        assert (
            reg.counter(
                "compile_prewarm", labels={"outcome": "skipped"}
            ).count
            == 1
        )

    def test_prime_less_walk_still_aot_compiles_xla_keys(self):
        reg = MetricsRegistry()
        registry = bare_registry(1)
        router = ClaimRouter(
            registry, max_claims_per_batch=1, metrics=reg,
            warmup_mode="prewarm",
        )
        worker = PrewarmWorker(
            router, registry, metrics=reg,
            config=PrewarmConfig(prime=False, include_twins=False),
        )
        key = worker.universe(refresh=True)[0]
        assert worker.step(key) == "compiled"
        assert worker.is_warm(key)

    def test_worker_never_touches_a_journal(self):
        import svoc_tpu.compile.prewarm as prewarm_mod
        import inspect

        # The worker must be invisible to replay fingerprints: no
        # journal resolution, no event emission, no events import —
        # its only traces are metrics and compiled code.  (The word
        # "journal" may appear in prose; the APIs may not.)
        source = inspect.getsource(prewarm_mod)
        for forbidden in (
            "resolve_journal",
            ".emit(",
            "svoc_tpu.utils.events",
            "EventJournal",
        ):
            assert forbidden not in source, forbidden

    def test_router_warmth_accounting_cold_then_warm(self):
        reg = MetricsRegistry()

        def count(warmth):
            return reg.counter(
                "consensus_dispatch", labels={"warmth": warmth}
            ).count

        registry = bare_registry(2)
        router = ClaimRouter(
            registry, max_claims_per_batch=2, metrics=reg,
            warmup_mode="none",
        )
        values = np.full((2, 7, 6), 0.5, dtype=np.float32)
        # Drive the accounting contract _dispatch_group implements:
        # count, dispatch, THEN mark seen — so first sight is cold,
        # a retry after a raising dispatch is cold AGAIN, and only a
        # successful dispatch flips the key to warm.
        key, warmth = router._account_warmth(values, CFG)
        assert warmth == "cold"
        assert (count("cold"), count("warm")) == (1.0, 0.0)
        router._account_warmth(values, CFG)  # dispatch raised: still cold
        assert (count("cold"), count("warm")) == (2.0, 0.0)
        router._warmth_seen.add(key)  # the post-dispatch commit
        _key, warmth = router._account_warmth(values, CFG)
        assert warmth == "warm"
        assert (count("cold"), count("warm")) == (2.0, 1.0)

    def test_router_counts_prewarmed_first_dispatch(self):
        reg = MetricsRegistry()
        registry = bare_registry(2)
        router = ClaimRouter(
            registry, max_claims_per_batch=2, metrics=reg,
            warmup_mode="prewarm",
        )
        worker = PrewarmWorker(
            router, registry, metrics=reg,
            config=PrewarmConfig(include_twins=False),
        )
        router.attach_prewarmer(worker)
        worker.warm_all()
        values = np.full((2, 7, 6), 0.5, dtype=np.float32)
        router._account_warmth(values, CFG)
        assert (
            reg.counter(
                "consensus_dispatch", labels={"warmth": "prewarmed"}
            ).count
            == 1.0
        )


# ---------------------------------------------------------------------------
# Serving: defer-then-serve (cold shapes wait, nothing is lost)
# ---------------------------------------------------------------------------


def _tier(vectorizer=None, **kwargs):
    from svoc_tpu.fabric.session import MultiSession
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.serving.tier import ServingTier
    from svoc_tpu.utils.events import EventJournal

    def vec(texts):
        rng = np.random.default_rng(
            [abs(hash(t)) % 2**31 for t in texts] or [0]
        )
        v = rng.uniform(0.05, 0.95, size=(len(texts), 6))
        return v / v.sum(axis=1, keepdims=True)

    def store_factory(cid):
        return CommentStore()

    multi = MultiSession(
        base_seed=0,
        vectorizer=vec,
        store_factory=store_factory,
        journal=EventJournal(),
        metrics=MetricsRegistry(),
        lineage_scope="cp",
        sanitized_dispatch=True,
        warmup_mode="none",
    )
    for name in ("alpha", "beta"):
        multi.add_claim(ClaimSpec(claim_id=name, n_oracles=7, dimension=6))
    tier = ServingTier(multi, vectorizer=vectorizer or vec, **kwargs)
    return multi, tier


class _FakeWorker:
    """A controllable prewarmer double: active + per-group coldness."""

    def __init__(self):
        self.active = True
        self.cold_groups = set()

    def claim_cold(self, spec):
        return (
            spec.n_oracles, spec.dimension, spec.consensus_config()
        ) in self.cold_groups

    def is_warm(self, key):
        return False

    def stats(self):
        return {"active": self.active, "warmed": 0, "universe": 0,
                "report": None}


class TestColdShapeDeferral:
    def test_defer_then_serve_accounting(self):
        multi, tier = _tier()
        worker = _FakeWorker()
        worker.cold_groups = {(7, 6, CFG)}
        tier._prewarmer = worker
        reg = multi.metrics
        out = tier.submit("alpha", "first comment while cold")
        assert out["status"] == "deferred"
        assert out["reason"] == "cold_shape"
        # Deferred ≠ shed: the request is queued, counted admitted AND
        # deferred, and journaled serving.deferred{cold_shape}.
        assert tier.frontend.depth("alpha") == 1
        assert reg.family_total("serving_admitted") == 1
        assert reg.family_total("serving_shed") == 0
        assert (
            reg.counter(
                "serving_deferred",
                labels={"claim": "alpha", "reason": "cold_shape"},
            ).count
            == 1
        )
        events = multi._resolve_journal().recent(type="serving.deferred")
        assert events and events[-1].data["reason"] == "cold_shape"
        # A cold claim's queue is not drained: the step serves nothing.
        report = tier.step()
        assert report["requests"] == 0
        assert tier.frontend.depth("alpha") == 1
        # Warmup reaches the shape -> the deferred request serves.
        worker.cold_groups = set()
        report = tier.step()
        assert report["requests"] == 1
        assert "alpha" in report["served"]
        assert tier.frontend.depth("alpha") == 0
        # End-state accounting: every submission is served or queued —
        # deferral lost nothing and shed nothing.
        assert reg.family_total("serving_completed") == 1
        assert reg.family_total("serving_dropped") == 0

    def test_warm_claims_serve_while_sibling_defers(self):
        multi, tier = _tier()
        worker = _FakeWorker()
        worker.cold_groups = {(7, 6, CFG)}
        tier._prewarmer = worker
        # beta's group differs -> not cold.
        multi.add_claim(
            ClaimSpec(claim_id="gamma", n_oracles=9, dimension=6)
        )
        cold = tier.submit("alpha", "cold-path text")
        warm = tier.submit("gamma", "warm-path text")
        assert cold["status"] == "deferred"
        assert warm["status"] == "admitted"
        report = tier.step()
        assert report["served"] == ["gamma"]
        assert tier.frontend.depth("alpha") == 1

    def test_finished_worker_defers_nothing(self):
        multi, tier = _tier()
        worker = _FakeWorker()
        worker.cold_groups = {(7, 6, CFG)}
        worker.active = False  # walk done (or budget spent)
        tier._prewarmer = worker
        out = tier.submit("alpha", "text after warmup finished")
        assert out["status"] == "admitted"

    def test_cold_gate_errors_degrade_open(self):
        multi, tier = _tier()

        class Broken:
            active = True

            def claim_cold(self, spec):
                raise RuntimeError("warmth probe broke")

            def stats(self):
                return {}

        tier._prewarmer = Broken()
        out = tier.submit("alpha", "gate failure must still serve")
        assert out["status"] == "admitted"
        assert multi.metrics.counter("serving_cold_gate_errors").count == 1

    def test_run_loop_activates_the_committed_prewarm_routing(self):
        # The live deployment's entry point (run_loop) must activate
        # warmup_mode="prewarm" — the PR 13 precedent: a committed
        # decision that nothing in the serving path consumes is dead
        # routing (review finding).
        from svoc_tpu.fabric.session import MultiSession
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.serving.tier import ServingTier
        from svoc_tpu.utils.events import EventJournal

        def vec(texts):
            return np.full((len(texts), 6), 1 / 6)

        multi = MultiSession(
            base_seed=0,
            vectorizer=vec,
            store_factory=lambda cid: CommentStore(),
            journal=EventJournal(),
            metrics=MetricsRegistry(),
            lineage_scope="rl",
            warmup_mode="prewarm",
        )
        multi.add_claim(ClaimSpec(claim_id="alpha", n_oracles=7))
        tier = ServingTier(multi, vectorizer=vec)
        assert tier.prewarmer is None
        stop = tier.run_loop(period_s=10.0)
        try:
            assert tier.prewarmer is not None
            assert multi.router.prewarmer is tier.prewarmer
            assert tier.prewarmer.wait(120)
        finally:
            stop.set()
            tier.stop_loop()

    def test_queue_full_still_sheds_even_when_cold(self):
        from svoc_tpu.serving.frontend import AdmissionConfig

        multi, tier = _tier(admission=AdmissionConfig(queue_capacity=1))
        worker = _FakeWorker()
        worker.cold_groups = {(7, 6, CFG)}
        tier._prewarmer = worker
        assert tier.submit("alpha", "one")["status"] == "deferred"
        out = tier.submit("alpha", "two")
        assert out["status"] == "shed"
        assert out["reason"] == "queue_full"


# ---------------------------------------------------------------------------
# Recovery integration: the cache is durable state, restarts are warm
# ---------------------------------------------------------------------------


class TestRecoveryIntegration:
    def _multi(self, n_oracles: int = 7):
        from svoc_tpu.fabric.session import MultiSession
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.utils.events import EventJournal

        multi = MultiSession(
            base_seed=0,
            vectorizer=lambda texts: np.full((len(texts), 6), 1 / 6),
            store_factory=lambda cid: CommentStore(),
            journal=EventJournal(),
            metrics=MetricsRegistry(),
            lineage_scope="rw",
            warmup_mode="prewarm",
        )
        multi.add_claim(ClaimSpec(claim_id="alpha", n_oracles=n_oracles))
        return multi

    def test_manager_enables_salted_cache_under_out_dir(self, tmp_path):
        from svoc_tpu.durability.recovery import RecoveryManager

        manager = RecoveryManager(
            self._multi(),
            out_dir=str(tmp_path),
            compilation_cache="persistent",
        )
        assert manager.compile_cache_dir is not None
        assert manager.compile_cache_dir.startswith(
            os.path.join(str(tmp_path), "xla_cache")
        )
        status = manager.status()
        assert status["compilation_cache"] == "persistent"
        assert status["compile_cache_dir"] == manager.compile_cache_dir

    def test_manager_off_mode_leaves_cache_disabled(self, tmp_path):
        from svoc_tpu.durability.recovery import RecoveryManager

        manager = RecoveryManager(
            self._multi(), out_dir=str(tmp_path), compilation_cache="off"
        )
        assert manager.compile_cache_dir is None
        assert not os.path.exists(os.path.join(str(tmp_path), "xla_cache"))

    def test_recover_prewarm_restarts_warm(self, tmp_path):
        from svoc_tpu.durability.recovery import RecoveryManager

        # A fleet shape no other test compiles: an in-process jit reuse
        # of an already-compiled program skips the backend compile and
        # would write nothing into THIS manager's cache dir.
        multi = self._multi(n_oracles=11)
        manager = RecoveryManager(
            multi, out_dir=str(tmp_path), compilation_cache="persistent"
        )
        report = manager.recover(prewarm=True)
        assert report["prewarm"] is not None
        assert report["prewarm"]["warmed"] > 0
        assert multi.router.prewarmer is not None
        # The blocking recovery walk is PRIMARY-only: every key is the
        # router's pinned variant (twins are background work) — here an
        # unsanitized, undonated router, so gated/no-donate throughout.
        assert all(
            k.kind == "gated" and not k.donate
            for k in multi.router.prewarmer.universe()
        )
        # The cache dir survived and holds the compiled programs — the
        # restart-warm witness at the unit level (the full
        # kill/restart matrix is make coldstart-smoke).
        assert cache_stats(manager.compile_cache_dir)["entries"] > 0

    def test_recover_honors_warmup_mode_none(self, tmp_path):
        from svoc_tpu.durability.recovery import RecoveryManager
        from svoc_tpu.fabric.session import MultiSession
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.utils.events import EventJournal

        multi = MultiSession(
            base_seed=0,
            vectorizer=lambda texts: np.full((len(texts), 6), 1 / 6),
            store_factory=lambda cid: CommentStore(),
            journal=EventJournal(),
            metrics=MetricsRegistry(),
            lineage_scope="rn",
            warmup_mode="none",
        )
        multi.add_claim(ClaimSpec(claim_id="alpha", n_oracles=7))
        manager = RecoveryManager(
            multi, out_dir=str(tmp_path), compilation_cache="off"
        )
        report = manager.recover(prewarm=True)
        assert report["prewarm"] is None
        assert multi.router.prewarmer is None

    def test_snapshot_runs_cache_eviction(self, tmp_path):
        from svoc_tpu.durability.recovery import RecoveryManager

        manager = RecoveryManager(
            self._multi(),
            out_dir=str(tmp_path),
            compilation_cache="persistent",
            compile_cache_max_bytes=1500,
        )
        for i in range(3):
            with open(
                os.path.join(manager.compile_cache_dir, f"k{i}-cache"), "w"
            ) as f:
                f.write("x" * 1000)
        manager.snapshot()
        assert cache_stats(manager.compile_cache_dir)["bytes"] <= 1500


# ---------------------------------------------------------------------------
# Monitoring satellite: real histogram + cache events
# ---------------------------------------------------------------------------


class TestCompileMonitoring:
    def test_backend_compiles_land_in_histogram_and_counter(self):
        import jax
        import jax.numpy as jnp

        from svoc_tpu.utils.metrics import (
            compile_snapshot,
            install_compile_listener,
            registry as process_registry,
        )

        assert install_compile_listener()
        before = process_registry.counter("xla_compiles_total").count
        hist_before = process_registry.histogram("xla_compile_seconds").count

        @jax.jit
        def fresh(x):
            return x * 3.25 + 1.5

        fresh(jnp.arange(13, dtype=jnp.float32)).block_until_ready()
        assert process_registry.counter("xla_compiles_total").count > before
        assert (
            process_registry.histogram("xla_compile_seconds").count
            > hist_before
        )
        snap = compile_snapshot()
        assert snap["xla_compiles_total"] >= 1
        assert snap["xla_compile_seconds_sum"] > 0
        assert "prewarm_outcomes" in snap

    def test_cache_events_counted_hit_and_miss(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from svoc_tpu.utils.metrics import (
            install_compile_listener,
            registry as process_registry,
        )

        install_compile_listener()
        enable_persistent_cache(str(tmp_path), metrics=MetricsRegistry())

        def miss_count():
            return process_registry.counter(
                "xla_cache_events", labels={"event": "miss"}
            ).count

        def hit_count():
            return process_registry.counter(
                "xla_cache_events", labels={"event": "hit"}
            ).count

        # Two separately-jitted but IDENTICAL lambdas (the cache key
        # covers the computation name, so the twins must share it).
        program = jax.jit(lambda x: (x + 7.125) * 0.375)
        program2 = jax.jit(lambda x: (x + 7.125) * 0.375)
        misses0 = miss_count()
        program(jnp.arange(11, dtype=jnp.float32)).block_until_ready()
        assert miss_count() > misses0  # fresh compile = a counted miss
        hits0 = hit_count()
        # Second wrapper: traces again, but the backend compile is a
        # persistent-cache HIT.
        program2(jnp.arange(11, dtype=jnp.float32)).block_until_ready()
        assert hit_count() > hits0
