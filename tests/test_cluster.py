"""Multi-replica serving fleet (ISSUE 18, docs/CLUSTER.md): placement
determinism, typed degraded routing, drain→ship→adopt migration with
lineage continuity, orphan quarantine, seeded failover replay identity,
and the cluster chaos-corpus pinning entry."""

import json
import os

import pytest

from svoc_tpu.cluster import (
    ClusterRouter,
    PlacementDirectory,
    PlacementError,
    Replica,
)
from svoc_tpu.durability import faultspace
from svoc_tpu.durability.faultspace import FaultEvent
from svoc_tpu.fabric.registry import ClaimSpec
from svoc_tpu.resilience.retry import RetryPolicy

CORPUS_DIR = os.path.join(
    os.path.dirname(__file__), "fixtures", "chaos_corpus", "cluster"
)

CLUSTER_POINTS = (
    "cluster.forward.pre_send",
    "cluster.migrate.pre_drain",
    "cluster.migrate.post_ship",
    "cluster.migrate.pre_adopt",
    "replica.kill",
)


# ---------------------------------------------------------------------------
# placement directory
# ---------------------------------------------------------------------------


def test_placement_deterministic_across_instances():
    claims = [f"c{i}" for i in range(20)]
    roster = ["r0", "r1", "r2"]
    first = PlacementDirectory(roster)
    second = PlacementDirectory(list(reversed(roster)))
    owners = {c: first.owner(c) for c in claims}
    assert owners == {c: second.owner(c) for c in claims}
    # Every owner is on the roster and the map is non-degenerate for a
    # 20-claim spread (HRW over crc32 — not all on one replica).
    assert set(owners.values()) <= set(roster)
    assert len(set(owners.values())) > 1


def test_placement_epoch_monotone_and_explicit_wins(tmp_path):
    directory = PlacementDirectory(
        ["r0", "r1"], path=str(tmp_path / "placement.json")
    )
    epoch0 = directory.epoch
    hashed = directory.owner("c0")
    target = "r0" if hashed != "r0" else "r1"
    epoch1 = directory.assign("c0", target)
    assert epoch1 == epoch0 + 1
    assert directory.owner("c0") == target
    epoch2 = directory.add_replica("r2")
    assert epoch2 == epoch1 + 1
    # Removing the pinned replica drops the explicit entry: the claim
    # falls back to the rendezvous hash over the survivors.
    epoch3 = directory.remove_replica(target)
    assert epoch3 == epoch2 + 1
    assert directory.owner("c0") in directory.replicas()
    assert "c0" not in directory.assignments()


def test_placement_persist_roundtrip(tmp_path):
    path = str(tmp_path / "placement.json")
    directory = PlacementDirectory(["r0", "r1", "r2"], path=path)
    directory.assign("c3", "r1")
    loaded = PlacementDirectory.load(path)
    assert loaded.epoch == directory.epoch
    assert loaded.fingerprint() == directory.fingerprint()
    assert loaded.owner("c3") == "r1"
    assert all(
        loaded.owner(f"c{i}") == directory.owner(f"c{i}") for i in range(8)
    )


def test_placement_error_paths():
    with pytest.raises(PlacementError):
        PlacementDirectory([]).owner("c0")
    with pytest.raises(PlacementError):
        PlacementDirectory(["r0"]).assign("c0", "rZ")
    with pytest.raises(PlacementError):
        PlacementDirectory(["r0"], explicit={"c0": "rZ"})


def test_cluster_fault_points_declared_for_cluster_smoke():
    surface = faultspace.surface()
    for point in CLUSTER_POINTS:
        assert point in surface, point
        assert surface[point].smokes == (faultspace.SMOKE_CLUSTER,), point


# ---------------------------------------------------------------------------
# router: typed degraded paths (no serving cycles needed — cheap)
# ---------------------------------------------------------------------------


def build_fleet(tmp_path, *, n_replicas=2, claims=("c0",), seed=0):
    from svoc_tpu.serving.scenario import VirtualClock
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry

    metrics = MetricsRegistry()
    journal = EventJournal(registry=metrics)
    chain_dir = str(tmp_path / "chain")
    placement = PlacementDirectory(
        [], path=str(tmp_path / "placement.json")
    )

    def replica_factory(rid):
        return Replica(
            rid,
            str(tmp_path / f"replica-{rid}"),
            chain_dir=chain_dir,
            seed=seed,
            clock=VirtualClock(),
            lineage_scope="clu",
        )

    router = ClusterRouter(
        placement,
        journal=journal,
        metrics=metrics,
        clock=VirtualClock(),
        retry=RetryPolicy(max_attempts=2, base_s=0.0, cap_s=0.0, jitter_seed=0),
        replica_factory=replica_factory,
        lineage_scope="clu",
        unclaimed_path=str(tmp_path / "unclaimed.json"),
    )
    for i in range(n_replicas):
        router.add_replica(replica_factory(f"r{i}"))
    for cid in claims:
        router.add_claim(ClaimSpec(claim_id=cid, n_oracles=7, dimension=6))
    return router, placement, metrics


def test_stale_epoch_submit_redirects(tmp_path):
    router, placement, metrics = build_fleet(tmp_path)
    response = router.submit("c0", "text", epoch=placement.epoch - 1)
    assert response["status"] == "redirect"
    assert response["reason"] == "stale_epoch"
    assert response["epoch"] == placement.epoch
    assert response["owner"] == placement.owner("c0")
    assert metrics.family_total("cluster_redirects") == 1.0
    # A current-epoch caller is forwarded, not redirected.
    assert router.submit("c0", "text", epoch=placement.epoch)["status"] != "redirect"


def test_down_replica_submit_sheds_typed(tmp_path):
    router, placement, metrics = build_fleet(tmp_path)
    owner = placement.owner("c0")
    router.replica(owner).kill()
    response = router.submit("c0", "text")
    assert response["status"] == "unavailable"
    assert response["reason"] == "replica_down"
    assert response["replica"] == owner
    assert metrics.family_total("cluster_unavailable") == 1.0


def test_unknown_claim_is_a_caller_error_not_a_shed(tmp_path):
    router, _, metrics = build_fleet(tmp_path)
    with pytest.raises(KeyError):
        router.submit("nope", "text")
    assert metrics.family_total("cluster_unavailable") == 0.0


def test_forward_faults_open_the_breaker(tmp_path):
    router, placement, metrics = build_fleet(tmp_path)
    # Retry absorbs one fault per submit (max_attempts=2), so 6 error
    # events = 3 submits that exhaust their budget; failure_threshold=3
    # opens the breaker and the 4th submit sheds without forwarding.
    controller = faultspace.arm(
        faultspace.FaultController(
            [
                FaultEvent(
                    point="cluster.forward.pre_send", nth=n, action="error"
                )
                for n in range(1, 7)
            ]
        )
    )
    try:
        for _ in range(3):
            response = router.submit("c0", "text")
            assert response["status"] == "unavailable"
            assert response["reason"] == "forward_error"
        response = router.submit("c0", "text")
        assert response["status"] == "unavailable"
        assert response["reason"] == "breaker_open"
    finally:
        faultspace.disarm()
    assert controller.counts()["cluster.forward.pre_send"] >= 6
    assert metrics.family_total("cluster_unavailable") == 4.0


def test_orphan_quarantine_on_missing_target(tmp_path):
    router, placement, _ = build_fleet(tmp_path)
    report = router.migrate("c0", "rZ", reason="test")
    assert report["status"] == "quarantined"
    assert report["reason"] == "missing_target"
    assert "c0" in report["unclaimed"]
    # The slice is durable in unclaimed.json, not dropped, and the
    # claim is no longer live on any replica.
    with open(str(tmp_path / "unclaimed.json")) as f:
        unclaimed = json.load(f)
    assert "c0" in unclaimed
    assert not any(
        router.replica(rid).has_claim("c0") for rid in router.replica_ids()
    )


def test_migrate_roundtrip_preserves_lineage_cursor(tmp_path):
    from svoc_tpu.cluster.replica import lineage_cursor

    router, placement, _ = build_fleet(tmp_path)
    source = placement.owner("c0")
    target = next(r for r in router.replica_ids() if r != source)
    for i in range(3):
        assert router.submit("c0", f"comment {i}")["status"] == "admitted"
    router.step_all()
    cursor_before = lineage_cursor(
        router.replica(source).multi.get("c0").session
    )
    assert cursor_before >= 1
    report = router.migrate("c0", target, reason="test")
    assert report["status"] == "migrated"
    assert report["continuity"] is True
    assert report["cursor"] >= cursor_before
    assert placement.owner("c0") == target
    # The new owner serves the claim and the next mint continues the
    # lineage family — no re-mint, no skip.
    assert router.replica(target).has_claim("c0")
    assert not router.replica(source).has_claim("c0")
    assert router.submit("c0", "after migration")["status"] == "admitted"
    router.step_all()
    cursor_after = lineage_cursor(
        router.replica(target).multi.get("c0").session
    )
    assert cursor_after > report["cursor"]


def test_console_cluster_command(tmp_path):
    from svoc_tpu.apps.commands import CommandConsole

    router, placement, _ = build_fleet(tmp_path)
    console = CommandConsole.__new__(CommandConsole)
    console.cluster = None
    router.attach(console)
    assert console.cluster is router
    snap = router.snapshot()
    assert snap["epoch"] == placement.epoch
    assert snap["claims"]["c0"] == placement.owner("c0")
    assert set(snap["replicas"]) == set(router.replica_ids())


# ---------------------------------------------------------------------------
# seeded failover scenario (three small fleet runs, module-cached)
# ---------------------------------------------------------------------------


def load_corpus_entry():
    with open(os.path.join(CORPUS_DIR, "kill-failover-fleet.json")) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def failover_runs(tmp_path_factory):
    from svoc_tpu.cluster.scenario import run_cluster_scenario

    plan = load_corpus_entry()["plan"]
    runs = []
    for tag in ("a", "b"):
        workdir = str(tmp_path_factory.mktemp(f"fleet-{tag}"))
        runs.append(
            run_cluster_scenario(
                workdir,
                seed=load_corpus_entry()["seed"],
                n_replicas=plan["n_replicas"],
                n_claims=plan["n_claims"],
                total_steps=plan["total_steps"],
                arrivals_per_step=plan["arrivals_per_step"],
                kill_replica=plan["kill"]["replica"],
                kill_at_step=plan["kill"]["at_step"],
                fail_over_at_step=plan["kill"]["fail_over_at"],
            )
        )
    return runs


def test_failover_replay_identity(failover_runs):
    first, second = failover_runs
    assert first["fleet_fingerprint"] == second["fleet_fingerprint"]
    for cid, claim in first["claims"].items():
        assert claim["fingerprint"] == second["claims"][cid]["fingerprint"]


def test_failover_exactly_once_and_accounted(failover_runs):
    first, _ = failover_runs
    assert first["duplicate_txs"] == 0
    assert first["requests"]["unaccounted"] == 0.0
    moved = first["failover"]["claims"]
    assert moved, "the killed replica owned no claims — bad fixture"
    for report in moved.values():
        assert report["status"] == "migrated"
        assert report["continuity"] is True
    # Migrated claims keep serving on the survivors.
    for cid in moved:
        assert first["claims"][cid]["owner"] != "r1"
        assert first["chain"][cid]["predictions"] > 0
    # The death and every migration boundary hit their fault points.
    for point in ("replica.kill", "cluster.migrate.pre_drain",
                  "cluster.migrate.post_ship", "cluster.migrate.pre_adopt"):
        assert first["fault_points_fired"].get(point, 0) > 0, point


def test_cluster_corpus_entry_replays_pinned(tmp_path, failover_runs):
    from svoc_tpu.cluster.scenario import replay_corpus_entry

    entry = load_corpus_entry()
    result = replay_corpus_entry(entry, str(tmp_path / "corpus"))
    assert result["duplicate_txs"] == 0
    assert result["requests"]["unaccounted"] == 0.0
    # Same seed + same plan as the fixture runs → the corpus replay is
    # byte-identical to them (the regression pin).
    assert result["fleet_fingerprint"] == failover_runs[0]["fleet_fingerprint"]


def test_corpus_entry_invisible_to_durable_fuzzer():
    """The cluster subdirectory must not leak into the durable-plane
    fuzzer's corpus (its scenario cannot reach cluster points)."""
    from svoc_tpu.durability.fuzz import load_corpus

    corpus_root = os.path.dirname(CORPUS_DIR)
    for entry in load_corpus(corpus_root):
        assert entry.get("format") != "svoc-cluster-corpus-v1"
