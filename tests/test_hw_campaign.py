"""Campaign/probe measurement-infrastructure logic.

These pin the behaviors that decide whether a flapping-tunnel round
captures its hardware numbers: attempt refunds vs caps, retirement,
busy-flag self-healing, value ordering, and the probe bisect's
stop-at-first-hang rule.  All drives use fakes — no TPU, no bench
subprocesses."""

import json
import os
import sys

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)

import hw_campaign  # noqa: E402
import hw_queue  # noqa: E402
import tpu_probe  # noqa: E402


def run_campaign(
    monkeypatch,
    tmp_path,
    run_item,
    alive=lambda py: True,
    decide=lambda py: (0, "packed_flash"),
    argv=("--seconds", "1"),
):
    monkeypatch.setattr(hw_campaign, "run_item", run_item)
    monkeypatch.setattr(hw_campaign, "tunnel_alive", alive)
    monkeypatch.setattr(hw_campaign, "run_decide_perf", decide)
    monkeypatch.setattr(hw_campaign, "OUT", str(tmp_path / "HW_CAMPAIGN.json"))
    monkeypatch.setattr(hw_campaign, "BUSY_FLAG", str(tmp_path / "busy"))
    monkeypatch.setattr(hw_campaign, "DEAD_SLEEP_S", 0.0)
    rc = hw_campaign.main(list(argv))
    state = json.loads((tmp_path / "HW_CAMPAIGN.json").read_text())
    return rc, {i["name"]: i for i in state["items"]}


def ok(value=1.0):
    return {"rc": 0, "seconds": 0.1, "result": {"value": value, "detail": {}}}


def test_flagship_runs_first_and_fallbacks_are_refunded(
    monkeypatch, tmp_path
):
    order = []
    calls = {"n": 0}

    def fake(name, cmd, timeout):
        order.append(name)
        calls["n"] += 1
        if name == "bench_config0" and calls["n"] <= 2:
            return {"rc": "cpu-fallback", "seconds": 0.1}
        return ok()

    rc, items = run_campaign(monkeypatch, tmp_path, fake)
    assert rc == 0
    assert order[0] == "bench_config0"  # value order: flagship first
    assert order[-2:] == ["tpu_probe", "flash_probe"]  # probes last
    # decision items ride right after the lossless trio (VERDICT r4
    # item 6): flash numerics parity, then the pallas-consensus config 6,
    # then the routed flagship re-capture — the headline number —
    # before int8 + DP serving.
    dedup = list(dict.fromkeys(order))
    assert dedup[1:7] == [
        "bench_config8",
        "bench_config12",
        "flash_parity",
        "bench_config6",
        "bench_config0_routed",
        "bench_config10",
    ]
    flagship = items["bench_config0"]
    assert flagship["done"]
    assert flagship["attempts"] == 1  # both fallbacks refunded
    assert flagship["fallbacks"] == 2


def test_timeouts_retire_after_max_attempts(monkeypatch, tmp_path):
    def fake(name, cmd, timeout):
        if name == "bench_config8":
            return {"rc": "timeout", "seconds": 0.1}
        return ok()

    rc, items = run_campaign(monkeypatch, tmp_path, fake)
    assert rc == 1  # not everything captured
    retired = items["bench_config8"]
    assert not retired["done"]
    assert retired["attempts"] == hw_campaign.MAX_ATTEMPTS
    # retirement must not block later items
    assert items["bench_config12"]["done"]


def test_persistent_fallbacks_cannot_livelock(monkeypatch, tmp_path):
    """The 2026-07-30 pattern: liveness passes while bench's deeper
    backend probe always falls back — the head item must retire at the
    fallback cap instead of spinning forever."""

    def fake(name, cmd, timeout):
        if name == "bench_config12":
            return {"rc": "cpu-fallback", "seconds": 0.1}
        return ok()

    rc, items = run_campaign(monkeypatch, tmp_path, fake)
    assert rc == 1
    half_dead = items["bench_config12"]
    assert not half_dead["done"]
    assert half_dead["fallbacks"] == hw_campaign.MAX_FALLBACKS
    assert len(half_dead["results"]) <= (
        hw_campaign.MAX_ATTEMPTS + hw_campaign.MAX_FALLBACKS
    )
    assert items["bench_config6"]["done"]  # later items still ran


def test_stale_busy_flag_cleared_live_flag_refused(monkeypatch, tmp_path):
    flag = tmp_path / "busy"

    # dead pid -> stale, cleared, campaign proceeds.  A reaped child's
    # pid is PROVEN dead (hard-coded large pids can be live under a
    # raised kernel.pid_max).
    dead_pid = os.fork()
    if dead_pid == 0:
        os._exit(0)
    os.waitpid(dead_pid, 0)
    flag.write_text(f"{dead_pid} bench_config0")
    rc, items = run_campaign(monkeypatch, tmp_path, lambda n, c, t: ok())
    assert rc == 0 and not flag.exists()

    # corrupt flag -> stale by definition, cleared
    flag.write_text("")
    rc, _ = run_campaign(monkeypatch, tmp_path, lambda n, c, t: ok())
    assert rc == 0 and not flag.exists()

    # live pid -> another campaign is measuring: refuse to start
    flag.write_text(f"{os.getpid()} bench_config0")
    monkeypatch.setattr(hw_campaign, "BUSY_FLAG", str(flag))
    assert hw_campaign.main(["--seconds", "1"]) == 2
    assert flag.exists()


def test_campaign_shares_bench_cmd_with_queue(monkeypatch, tmp_path):
    rc, items = run_campaign(monkeypatch, tmp_path, lambda n, c, t: ok())
    assert items["bench_config0"]["cmd"] == hw_queue.bench_cmd(0, 1.0)
    assert (
        items["bench_config0"]["timeout"]
        == 1.0 + hw_queue.BENCH_TIMEOUT_MARGIN_S
    )


def test_resume_keeps_captured_results(monkeypatch, tmp_path):
    """A campaign killed mid-round (session restart) must resume from
    its journal: captured measurements survive, done items never
    re-run, pending items continue.  2026-07-31 pattern — four bench
    results captured, session died, remaining items still pending."""
    import pytest

    first_ran = []

    def die_after_flagship(name, cmd, timeout):
        first_ran.append(name)
        if name != "bench_config0":
            raise RuntimeError("session killed mid-campaign")
        return ok(42.0)

    with pytest.raises(RuntimeError):
        run_campaign(monkeypatch, tmp_path, die_after_flagship)
    journal = json.loads((tmp_path / "HW_CAMPAIGN.json").read_text())
    flagship = {i["name"]: i for i in journal["items"]}["bench_config0"]
    assert flagship["done"] and flagship["results"][0]["result"]["value"] == 42.0

    second_ran = []

    def finish(name, cmd, timeout):
        second_ran.append(name)
        return ok(7.0)

    rc, items = run_campaign(monkeypatch, tmp_path, finish)
    assert rc == 0
    assert "bench_config0" not in second_ran  # captured result kept
    assert items["bench_config0"]["results"][0]["result"]["value"] == 42.0
    assert items["bench_config8"]["done"]  # pending items completed

    # --fresh discards the journal and re-runs everything
    third_ran = []

    def fresh(name, cmd, timeout):
        third_ran.append(name)
        return ok(9.0)

    monkeypatch.setattr(hw_campaign, "run_item", fresh)
    monkeypatch.setattr(hw_campaign, "tunnel_alive", lambda py: True)
    monkeypatch.setattr(hw_campaign, "run_decide_perf", lambda py: (0, None))
    assert hw_campaign.main(["--seconds", "1", "--fresh"]) == 0
    assert "bench_config0" in third_ran


def test_resume_refunds_in_flight_attempt_and_keeps_done_cmd():
    """ADVICE r4: (a) a kill mid-item burned an attempt with no recorded
    result — resume refunds it; (b) a done item resumed under a
    different --seconds keeps the cmd/timeout that produced its
    results."""
    items = hw_campaign.build_items(20.0)
    prior = [
        # killed mid-item twice: 2 attempts, 1 recorded failure result
        {"name": "bench_config8", "attempts": 2, "fallbacks": 0,
         "done": False, "results": [{"rc": "timeout"}]},
        # done under the old 10 s window
        {"name": "bench_config0", "attempts": 1, "fallbacks": 0,
         "done": True, "cmd": hw_queue.bench_cmd(0, 10.0),
         "timeout": 10.0 + hw_queue.BENCH_TIMEOUT_MARGIN_S,
         "results": [{"rc": 0, "result": {"value": 4515.7}}]},
        # null counters must not crash the merge
        {"name": "bench_config12", "attempts": None, "fallbacks": None,
         "done": False, "results": []},
    ]
    merged = {i["name"]: i for i in hw_campaign.resume_items(items, prior)}
    assert merged["bench_config8"]["attempts"] == 1  # in-flight refunded
    assert merged["bench_config0"]["cmd"] == hw_queue.bench_cmd(0, 10.0)
    assert merged["bench_config0"]["timeout"] == 10.0 + hw_queue.BENCH_TIMEOUT_MARGIN_S
    assert merged["bench_config12"]["attempts"] == 0
    # not-done items DO get the new window
    assert merged["bench_config8"]["cmd"] == hw_queue.bench_cmd(8, 20.0)


def test_corrupt_journal_starts_fresh(monkeypatch, tmp_path):
    """A journal whose top level is a list, or whose counters are null,
    must start fresh instead of crashing main (ADVICE r4)."""
    ran = []

    def fake(name, cmd, timeout):
        ran.append(name)
        return ok()

    for corrupt in ("[1, 2]", '{"items": null, "liveness_checks": null}',
                    '{"items": [["not", "a", "dict"]]}'):
        (tmp_path / "HW_CAMPAIGN.json").write_text(corrupt)
        ran.clear()
        rc, items = run_campaign(monkeypatch, tmp_path, fake)
        assert rc == 0, corrupt
        assert "bench_config0" in ran, corrupt


def test_routed_item_refreshes_decide_perf(monkeypatch, tmp_path):
    """The campaign derives the routing right before the routed
    flagship capture and records the resolved variant (ADVICE r4)."""
    decided = []

    def decide(py):
        decided.append("called")
        return 0, "packed"

    rc, items = run_campaign(monkeypatch, tmp_path, lambda n, c, t: ok(),
                             decide=decide)
    assert rc == 0
    assert decided == ["called"]  # exactly once, for the routed item
    routed = items["bench_config0_routed"]
    assert routed["decide_perf_rc"] == 0
    assert routed["decided_variant"] == "packed"


def test_flash_parity_only_full_path_writes_verdict(monkeypatch, tmp_path):
    """The campaign's flash_parity decision item, end to end on a
    simulated TPU platform (interpret-mode kernels, real adjudication
    math): writes FLASH_PARITY.json with a rounding-equivalent verdict
    that decide_perf accepts, and exits 0."""
    import json as _json

    import flash_probe

    class FakeDev:
        platform = "tpu"

    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(flash_probe.jax, "devices", lambda: [FakeDev()])
    monkeypatch.setattr(flash_probe, "PARITY_SHAPES", ((16, 64),))
    assert flash_probe.parity_only() == 0
    data = _json.loads((tmp_path / "FLASH_PARITY.json").read_text())
    assert data["platform"] == "tpu"
    assert data["verdict"] == "rounding-equivalent"
    assert all(e["flash_within_bound"] for e in data["entries"])
    entry = data["entries"][0]
    # the adjudication's substance, not just its plumbing: flash is no
    # less accurate than the dense reference against the f32 truth
    assert entry["err_flash_vs_f32_truth"] <= entry["bound"]

    import decide_perf

    assert decide_perf.load_flash_verdict(str(tmp_path)) == "rounding-equivalent"


def test_probe_bisect_stops_at_first_hang(monkeypatch, tmp_path):
    """The consensus size-bisect walks 128/256/512/1024 ascending and
    stops at the first hang — larger sizes would only burn the alive
    window; results persist incrementally."""
    ran = []

    def fake_probe(name, timeout, extra_env=None):
        env = extra_env or {}
        n = env.get("SVOC_PROBE_N_ORACLES")
        ran.append((name, n, env.get("SVOC_PROBE_ATTENTION")))
        if name == "consensus1024" and n == "512":
            return {"probe": name, "ok": False, "timeout": True}
        return {"probe": name, "ok": True}

    monkeypatch.setattr(tpu_probe, "run_probe", fake_probe)
    monkeypatch.setattr(tpu_probe, "REPO", str(tmp_path))
    rc = tpu_probe.main(["--only", "consensus1024"])
    sizes = [n for name, n, _ in ran if name == "consensus1024"]
    assert sizes == ["128", "256", "512"]  # stopped before 1024
    assert rc == 1  # the hang keeps the run marked not-ok
    recorded = json.loads((tmp_path / "TPU_PROBE.json").read_text())
    assert [r["probe"] for r in recorded] == [
        "consensus128",
        "consensus256",
        "consensus512",
    ]
    assert recorded[-1]["timeout"] is True
