"""Fixture-parity tests against the reference Cairo test scenarios.

The prediction vectors are the hard-coded wsad calldata from
``contract/tests/test_contract.cairo`` (constrained M=2 at ``:150-158``,
unconstrained M=2 Gaussian at ``:253-261``, constrained M=6 at
``:364-372``), generated offline by the reference's Beta/Gaussian
notebooks.  The scenarios mirror the Cairo tests step by step: deploy →
assert inactive zero state → feed all 7 predictions (impersonating each
oracle) → consensus checks → replacement-vote flow.

The Cairo tests assert state-machine behavior and record the numeric
outcomes only as comments (μ=(20.714, 10.4) for the unconstrained run at
``test_contract.cairo:285-288``); here the numeric path is asserted
three ways: exact wsad-int golden model, recorded expectations, and
float-kernel agreement within fixed-point tolerance.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.consensus.state import ContractError, OracleConsensusContract
from svoc_tpu.ops.fixedpoint import from_wsad

ADMINS = ["Akashi", "Ozu", "Higuchi"]
ORACLES = [f"oracle_{i:02d}" for i in range(7)]

# test_contract.cairo:150-158 — Beta notebook, essence=[0.4, 0.2].
CONSTRAINED_2D = [
    [492954, 334814],
    [437692, 410445],
    [967794, 564219],
    [431029, 387225],
    [487609, 337990],
    [284178, 485072],
    [990059, 558600],
]

# test_contract.cairo:253-261 — Gaussian notebook, mu=[20,12], sigma=[3,2].
UNCONSTRAINED_2D = [
    [20202804, 16401132],
    [25630344, 13501687],
    [22210028, 7472938],
    [18138928, 16619949],
    [19527275, 10116085],
    [22084988, 7901585],
    [19549281, 10104796],
]

# test_contract.cairo:364-372 — Beta notebook, M=6.
CONSTRAINED_6D = [
    [444545, 54331, 321181, 93574, 58452, 27915],
    [650669, 423808, 458776, 619552, 867737, 117888],
    [360849, 61583, 445841, 66219, 44810, 20695],
    [442049, 38888, 420748, 44428, 30533, 23350],
    [260736, 619146, 110294, 505377, 699358, 584216],
    [267262, 48987, 551858, 74674, 26617, 30598],
    [268500, 45379, 495298, 145887, 22256, 22678],
]


def deploy(dimension, constrained=True, max_spread=0.0):
    """deploy_constrained_contract / deploy_unconstrained_contract
    calldata (test_contract.cairo:28-93)."""
    return OracleConsensusContract(
        admins=ADMINS,
        oracles=list(ORACLES),
        enable_oracle_replacement=True,
        required_majority=2,
        n_failing_oracles=2,
        constrained=constrained,
        unconstrained_max_spread=max_spread,
        dimension=dimension,
    )


def fill_predictions(contract, predictions):
    """fill_oracle_predictions (test_contract.cairo:98-113): each oracle
    commits its own vector; consensus activates on the last one."""
    for oracle, pred in zip(ORACLES, predictions):
        assert not contract.consensus_active
        contract.update_prediction(oracle, pred, encoding="wsad")


def float_consensus(predictions, constrained, max_spread=10.0):
    values = jnp.asarray(np.array(predictions, dtype=np.float64) / 1e6)
    cfg = ConsensusConfig(
        n_failing=2, constrained=constrained, max_spread=max_spread
    )
    return consensus_step(values, cfg)


def assert_zero_state(c, dim):
    """The pre-activation asserts (test_contract.cairo:140-143, :341-342)."""
    assert not c.consensus_active
    assert c.get_consensus_value() == [0] * dim
    assert c.get_skewness() == [0] * dim
    assert c.get_kurtosis() == [0] * dim
    assert c.get_first_pass_consensus_reliability() == 0
    assert c.get_second_pass_consensus_reliability() == 0


def run_replacement_flow(c):
    """The replacement scenario (test_contract.cairo:192-213): propose
    swapping oracle 6 for 'oracle_XX'; one vote is not a majority, the
    second admin's vote triggers the in-place address swap."""
    old = 6
    c.update_proposition("Akashi", (old, "oracle_XX"))
    assert c.get_oracle_list()[old] == "oracle_06"
    c.vote_for_a_proposition("Akashi", 0, True)
    assert c.get_oracle_list()[old] == "oracle_06"
    c.vote_for_a_proposition("Ozu", 0, True)
    assert c.get_oracle_list()[old] == "oracle_XX"
    assert c.get_replacement_propositions() == [None, None, None]


class TestConstrainedBasic:
    """test_constrained_basic_execution (test_contract.cairo:116-215)."""

    def test_scenario(self):
        c = deploy(dimension=2)
        assert_zero_state(c, 2)
        fill_predictions(c, CONSTRAINED_2D)

        assert c.consensus_active
        consensus = c.get_consensus_value(as_floats=True)
        # Beta notebook ground truth essence = [0.4, 0.2]
        # (test_contract.cairo:148): the robust estimate must land near
        # it despite the two adversarial vectors (oracles 2 and 6).
        assert consensus[0] == pytest.approx(0.44, abs=0.05)
        assert consensus[1] == pytest.approx(0.36, abs=0.05)

        # The two planted outliers carry the largest risk and must be
        # the masked pair.
        assert [o.reliable for o in c.oracles] == [
            True, True, False, True, True, True, False,
        ]

        rel1 = c.get_first_pass_consensus_reliability(as_floats=True)
        rel2 = c.get_second_pass_consensus_reliability(as_floats=True)
        assert 0.0 < rel1 < 1.0 and 0.0 < rel2 < 1.0
        # Masking the outliers must improve the score.
        assert rel2 > rel1

        run_replacement_flow(c)

    def test_float_kernel_parity(self):
        c = deploy(dimension=2)
        fill_predictions(c, CONSTRAINED_2D)
        out = float_consensus(CONSTRAINED_2D, constrained=True)
        np.testing.assert_allclose(
            np.asarray(out.essence),
            c.get_consensus_value(as_floats=True),
            atol=2e-6,
        )
        assert np.asarray(out.reliable).tolist() == [
            o.reliable for o in c.oracles
        ]
        assert float(out.reliability_first_pass) == pytest.approx(
            c.get_first_pass_consensus_reliability(as_floats=True), abs=2e-5
        )
        assert float(out.reliability_second_pass) == pytest.approx(
            c.get_second_pass_consensus_reliability(as_floats=True), abs=2e-5
        )


class TestUnconstrainedBasic:
    """test_unconstrained_basic_execution (test_contract.cairo:218-313)."""

    def test_scenario(self):
        c = deploy(dimension=2, constrained=False, max_spread=10.0)
        assert_zero_state(c, 2)
        fill_predictions(c, UNCONSTRAINED_2D)

        assert c.consensus_active
        consensus = c.get_consensus_value(as_floats=True)
        # Recorded results (test_contract.cairo:285-288): mu=(20.714, 10.4).
        assert consensus[0] == pytest.approx(20.714, abs=1e-3)
        assert consensus[1] == pytest.approx(10.4, abs=1e-3)

        rel1 = c.get_first_pass_consensus_reliability(as_floats=True)
        rel2 = c.get_second_pass_consensus_reliability(as_floats=True)
        # The "first pass std : 0.533 / second pass std : 0.647" comment
        # (test_contract.cairo:286-288) actually records the RELIABILITY
        # getters printed right above it (:277-281) — pin them exactly.
        assert rel1 == pytest.approx(0.533, abs=1e-3)
        assert rel2 == pytest.approx(0.647, abs=1e-3)

        run_replacement_flow(c)

    def test_float_kernel_parity(self):
        c = deploy(dimension=2, constrained=False, max_spread=10.0)
        fill_predictions(c, UNCONSTRAINED_2D)
        out = float_consensus(UNCONSTRAINED_2D, constrained=False)
        np.testing.assert_allclose(
            np.asarray(out.essence),
            c.get_consensus_value(as_floats=True),
            atol=2e-6,
        )
        assert np.asarray(out.reliable).tolist() == [
            o.reliable for o in c.oracles
        ]
        assert float(out.reliability_second_pass) == pytest.approx(
            c.get_second_pass_consensus_reliability(as_floats=True), abs=5e-6
        )


class TestConstrainedHighDimension:
    """test_constrained_high_dimension_execution
    (test_contract.cairo:315-396)."""

    def test_scenario(self):
        c = deploy(dimension=6)
        assert_zero_state(c, 6)
        fill_predictions(c, CONSTRAINED_6D)

        assert c.consensus_active
        # The planted outliers (oracles 1 and 4 — large in every
        # dimension) must be masked.
        assert [o.reliable for o in c.oracles] == [
            True, False, True, True, False, True, True,
        ]
        skew = c.get_skewness(as_floats=True)
        kurt = c.get_kurtosis(as_floats=True)
        assert len(skew) == 6 and len(kurt) == 6
        assert any(abs(s) > 0 for s in skew)

    def test_float_kernel_parity(self):
        c = deploy(dimension=6)
        fill_predictions(c, CONSTRAINED_6D)
        out = float_consensus(CONSTRAINED_6D, constrained=True)
        np.testing.assert_allclose(
            np.asarray(out.essence),
            c.get_consensus_value(as_floats=True),
            atol=2e-6,
        )
        # The wsad engine quantizes the per-dimension variance at 1e-6;
        # dims with var ~1e-5 amplify that ~1% std error into the cubed
        # and fourth-power z-sums, so moments agree only to a few
        # percent — an inherent property of the reference's fixed-point
        # arithmetic, not of this kernel.
        np.testing.assert_allclose(
            np.asarray(out.skewness),
            c.get_skewness(as_floats=True),
            rtol=0.05,
            atol=1e-3,
        )
        np.testing.assert_allclose(
            np.asarray(out.kurtosis),
            c.get_kurtosis(as_floats=True),
            rtol=0.25,
            atol=5e-3,
        )


class TestAccessControl:
    """The contract's caller asserts (contract.cairo:595-602, :775)."""

    def test_stranger_cannot_predict(self):
        c = deploy(dimension=2)
        with pytest.raises(ContractError):
            c.update_prediction("stranger", [100, 100], encoding="wsad")

    def test_constrained_rejects_out_of_interval(self):
        c = deploy(dimension=2)
        with pytest.raises(Exception):
            c.update_prediction(
                "oracle_00", [2_000_000, 0], encoding="wsad"
            )

    def test_non_admin_cannot_read_raw_values(self):
        c = deploy(dimension=2)
        with pytest.raises(ContractError):
            c.get_oracle_value_list("oracle_00")
