"""Fused Pallas consensus vs the XLA kernel (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.ops.pallas_consensus import fused_consensus


def fleets(key, n, dim, constrained=True):
    if constrained:
        return jax.random.uniform(key, (n, dim), minval=0.01, maxval=0.99)
    return 20.0 + 3.0 * jax.random.normal(key, (n, dim))


CASES = [
    (7, 2, 2, True),
    (7, 2, 6, True),
    (7, 2, 2, False),
    (16, 4, 3, True),
    (64, 16, 6, True),
    (256, 64, 6, True),  # multi-block rank loop (2 blocks of 128)
    (192, 16, 2, True),  # not a multiple of _RANK_BLOCK: XLA fallback
    (1024, 256, 6, True),  # flagship fleet, 8-block rank loop
]


@pytest.mark.parametrize("n,f,dim,constrained", CASES)
def test_matches_xla_kernel(n, f, dim, constrained):
    cfg = ConsensusConfig(
        n_failing=f, constrained=constrained, max_spread=10.0
    )
    values = fleets(jax.random.PRNGKey(n * dim), n, dim, constrained)
    ref = consensus_step(values, cfg)
    out = fused_consensus(values, cfg)

    np.testing.assert_allclose(
        np.asarray(out.essence), np.asarray(ref.essence), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out.essence_first_pass),
        np.asarray(ref.essence_first_pass),
        atol=1e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref.reliable)
    )
    np.testing.assert_allclose(
        np.asarray(out.quadratic_risk),
        np.asarray(ref.quadratic_risk),
        atol=1e-5,
    )
    assert float(out.reliability_first_pass) == pytest.approx(
        float(ref.reliability_first_pass), abs=1e-5
    )
    assert float(out.reliability_second_pass) == pytest.approx(
        float(ref.reliability_second_pass), abs=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out.skewness), np.asarray(ref.skewness), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out.kurtosis), np.asarray(ref.kurtosis), atol=1e-3
    )


def test_tie_order_matches_cairo_sort():
    """Duplicate risk values: the stable index tiebreak must pick the
    same unreliable set as the host merge sort."""
    cfg = ConsensusConfig(n_failing=2, constrained=True)
    # Three identical outliers — only two may be masked, lowest indices
    # first in the stable order.
    values = jnp.array(
        [[0.5], [0.5], [0.9], [0.9], [0.9], [0.5], [0.5]], jnp.float32
    )
    ref = consensus_step(values, cfg)
    out = fused_consensus(values, cfg)
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref.reliable)
    )


def test_compiled_size_is_constant_in_fleet_size():
    """The round-4 N=1024 Mosaic hang was compiled-CODE-SIZE blowup:
    the rank computation statically unrolled N/128 bodies per rank
    call.  Since the fori_loop rework the traced kernel must be the
    same size at every fleet size — this pins the law the fix rests on
    (a regression shows up as eqn counts growing with N long before
    anyone hangs a real chip on it)."""

    def eqn_count(n):
        cfg = ConsensusConfig(n_failing=n // 8, constrained=True)
        vals = jnp.zeros((n, 6), jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda v: fused_consensus(v, cfg, interpret=True)
        )(vals)
        total, stack = 0, [jaxpr.jaxpr]
        while stack:
            jx = stack.pop()
            for e in jx.eqns:
                total += 1
                for p in e.params.values():
                    cand = getattr(p, "jaxpr", p)
                    if hasattr(cand, "eqns"):
                        stack.append(cand)
                    elif hasattr(cand, "jaxpr") and hasattr(cand.jaxpr, "eqns"):
                        stack.append(cand.jaxpr)
        return total

    counts = {n: eqn_count(n) for n in (256, 512, 1024)}
    assert len(set(counts.values())) == 1, counts
