"""Fused Pallas consensus vs the XLA kernels (interpret mode on CPU).

This file is the ``make pallas-parity`` gate (CPU interpret-mode
parity + fallback-path smoke, budget < 60 s): single-claim ungated
parity, gated claim-cube parity on both configs — degenerate claims,
quarantine-all rows, pow2 padding rows, the ``n_failing >= N-1``
guard — plus the no-silent-fallback counter and the typed env-knob
errors."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svoc_tpu.consensus.kernel import (
    ConsensusConfig,
    consensus_step,
    consensus_step_gated_claims,
)
from svoc_tpu.ops.pallas_consensus import (
    fused_consensus,
    fused_consensus_gated_claims,
)


def fleets(key, n, dim, constrained=True):
    if constrained:
        return jax.random.uniform(key, (n, dim), minval=0.01, maxval=0.99)
    return 20.0 + 3.0 * jax.random.normal(key, (n, dim))


CASES = [
    (7, 2, 2, True),
    (7, 2, 6, True),
    (7, 2, 2, False),
    (16, 4, 3, True),
    (64, 16, 6, True),
    (256, 64, 6, True),  # multi-block rank loop (2 blocks of 128)
    (192, 16, 2, True),  # not a multiple of _RANK_BLOCK: XLA fallback
    (1024, 256, 6, True),  # flagship fleet, 8-block rank loop
]


@pytest.mark.parametrize("n,f,dim,constrained", CASES)
def test_matches_xla_kernel(n, f, dim, constrained):
    cfg = ConsensusConfig(
        n_failing=f, constrained=constrained, max_spread=10.0
    )
    values = fleets(jax.random.PRNGKey(n * dim), n, dim, constrained)
    ref = consensus_step(values, cfg)
    out = fused_consensus(values, cfg)

    np.testing.assert_allclose(
        np.asarray(out.essence), np.asarray(ref.essence), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out.essence_first_pass),
        np.asarray(ref.essence_first_pass),
        atol=1e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref.reliable)
    )
    np.testing.assert_allclose(
        np.asarray(out.quadratic_risk),
        np.asarray(ref.quadratic_risk),
        atol=1e-5,
    )
    assert float(out.reliability_first_pass) == pytest.approx(
        float(ref.reliability_first_pass), abs=1e-5
    )
    assert float(out.reliability_second_pass) == pytest.approx(
        float(ref.reliability_second_pass), abs=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out.skewness), np.asarray(ref.skewness), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out.kurtosis), np.asarray(ref.kurtosis), atol=1e-3
    )


def test_tie_order_matches_cairo_sort():
    """Duplicate risk values: the stable index tiebreak must pick the
    same unreliable set as the host merge sort."""
    cfg = ConsensusConfig(n_failing=2, constrained=True)
    # Three identical outliers — only two may be masked, lowest indices
    # first in the stable order.
    values = jnp.array(
        [[0.5], [0.5], [0.9], [0.9], [0.9], [0.5], [0.5]], jnp.float32
    )
    ref = consensus_step(values, cfg)
    out = fused_consensus(values, cfg)
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref.reliable)
    )


def test_compiled_size_is_constant_in_fleet_size():
    """The round-4 N=1024 Mosaic hang was compiled-CODE-SIZE blowup:
    the rank computation statically unrolled N/128 bodies per rank
    call.  Since the fori_loop rework the traced kernel must be the
    same size at every fleet size — this pins the law the fix rests on
    (a regression shows up as eqn counts growing with N long before
    anyone hangs a real chip on it)."""

    def eqn_count(n):
        cfg = ConsensusConfig(n_failing=n // 8, constrained=True)
        vals = jnp.zeros((n, 6), jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda v: fused_consensus(v, cfg, interpret=True)
        )(vals)
        total, stack = 0, [jaxpr.jaxpr]
        while stack:
            jx = stack.pop()
            for e in jx.eqns:
                total += 1
                for p in e.params.values():
                    cand = getattr(p, "jaxpr", p)
                    if hasattr(cand, "eqns"):
                        stack.append(cand)
                    elif hasattr(cand, "jaxpr") and hasattr(cand.jaxpr, "eqns"):
                        stack.append(cand.jaxpr)
        return total

    counts = {n: eqn_count(n) for n in (256, 512, 1024)}
    assert len(set(counts.values())) == 1, counts


# ---------------------------------------------------------------------------
# Gated claim-cube kernel (docs/FABRIC.md §consensus_impl)
# ---------------------------------------------------------------------------


def _assert_claims_parity(out, ref, atol=2e-5):
    """Field-for-field parity of two claim-batched ConsensusOutputs:
    reliable/interval_valid EXACT, floats within interpret-mode float
    tolerance (inf risks of all-quarantined claims compare equal)."""
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref.reliable)
    )
    np.testing.assert_array_equal(
        np.asarray(out.interval_valid), np.asarray(ref.interval_valid)
    )
    for field in (
        "essence",
        "essence_first_pass",
        "reliability_first_pass",
        "reliability_second_pass",
        "quadratic_risk",
    ):
        np.testing.assert_allclose(
            np.asarray(getattr(out, field)),
            np.asarray(getattr(ref, field)),
            atol=atol,
            err_msg=field,
        )
    np.testing.assert_allclose(
        np.asarray(out.skewness), np.asarray(ref.skewness), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out.kurtosis), np.asarray(ref.kurtosis), atol=1e-3
    )


def _claim_cube(key, c, n, dim, constrained):
    if constrained:
        return jax.random.uniform(key, (c, n, dim), minval=0.01, maxval=0.99)
    return 20.0 + 3.0 * jax.random.normal(key, (c, n, dim))


GATED_CASES = [
    # (C, N, n_failing, dim, constrained)
    (4, 7, 2, 6, True),  # reference fleet, both pad-free
    (4, 7, 2, 6, False),
    (3, 16, 4, 3, True),  # C=3 exercises explicit padding below
    (2, 256, 64, 6, True),  # multi-block rank loop (2 blocks of 128)
]


@pytest.mark.parametrize("c,n,f,dim,constrained", GATED_CASES)
def test_gated_claims_matches_xla(c, n, f, dim, constrained):
    """The full degenerate spectrum in ONE cube: a clean claim, a
    partially quarantined claim (with a poisoned NaN row), an
    all-quarantined claim (n_ok=0), and a single-survivor claim
    (n_ok=1) — per-claim isolation means one cube covers them all."""
    cfg = ConsensusConfig(
        n_failing=f, constrained=constrained, max_spread=10.0
    )
    values = np.asarray(
        _claim_cube(jax.random.PRNGKey(c * n + dim), c, n, dim, constrained)
    ).astype(np.float32)
    ok = np.ones((c, n), dtype=bool)
    if c > 1:
        ok[1, : max(1, n // 4)] = False  # partially quarantined
        values[1, 0, :] = np.nan  # poisoned quarantined row
    if c > 2:
        ok[2, :] = False  # all quarantined: n_ok = 0
    if c > 3:
        ok[3, : n - 1] = False  # single survivor: n_ok = 1
    claim_mask = np.ones(c, dtype=bool)
    v, o, m = jnp.asarray(values), jnp.asarray(ok), jnp.asarray(claim_mask)
    ref = consensus_step_gated_claims(v, o, m, cfg)
    out = fused_consensus_gated_claims(v, o, m, cfg, interpret=True)
    _assert_claims_parity(out, ref)
    # The degenerate claims really are degenerate (guards the test).
    valid = np.asarray(ref.interval_valid)
    if c > 2:
        assert not valid[2]
    if c > 3:
        assert not valid[3]


def test_gated_claims_padding_rows_forced_inactive():
    """pad_claim_cube's pow2 filler rows must come back invalid with
    zero essence from the pallas path exactly as from XLA."""
    from svoc_tpu.consensus.batch import pad_claim_cube

    cfg = ConsensusConfig(n_failing=2, constrained=True)
    rng = np.random.default_rng(7)
    values = rng.uniform(0.01, 0.99, (3, 8, 4)).astype(np.float32)
    padded, ok, claim_mask = pad_claim_cube(values)
    assert padded.shape[0] == 4 and not claim_mask[3]
    v, o, m = jnp.asarray(padded), jnp.asarray(ok), jnp.asarray(claim_mask)
    ref = consensus_step_gated_claims(v, o, m, cfg)
    out = fused_consensus_gated_claims(v, o, m, cfg, interpret=True)
    _assert_claims_parity(out, ref)
    assert not np.asarray(out.interval_valid)[3]
    np.testing.assert_array_equal(np.asarray(out.essence)[3], 0.0)
    assert not np.asarray(out.reliable)[3].any()


def test_gated_claims_n_failing_guard():
    """``n_failing >= N-1`` leaves < 2 reliable oracles: no consensus —
    interval_valid False with a FINITE essence, on both impls."""
    n = 8
    cfg = ConsensusConfig(n_failing=n - 1, constrained=True)
    values = jnp.asarray(
        np.random.default_rng(1).uniform(0.1, 0.9, (2, n, 3)).astype(
            np.float32
        )
    )
    ok = jnp.ones((2, n), dtype=bool)
    claim_mask = jnp.ones(2, dtype=bool)
    ref = consensus_step_gated_claims(values, ok, claim_mask, cfg)
    out = fused_consensus_gated_claims(
        values, ok, claim_mask, cfg, interpret=True
    )
    _assert_claims_parity(out, ref)
    assert not np.asarray(out.interval_valid).any()
    assert np.isfinite(np.asarray(out.essence)).all()


def test_gated_claims_tie_order_matches_cairo():
    """Duplicate risks across the gated ranking: the stable
    descending-index tiebreak must pick the same reliable sets as the
    XLA lexsort, per claim."""
    cfg = ConsensusConfig(n_failing=2, constrained=True)
    base = np.array(
        [[0.5], [0.5], [0.9], [0.9], [0.9], [0.5], [0.5]], np.float32
    )
    values = jnp.asarray(np.stack([base, base[::-1]]))
    ok = jnp.asarray(np.ones((2, 7), dtype=bool))
    claim_mask = jnp.ones(2, dtype=bool)
    ref = consensus_step_gated_claims(values, ok, claim_mask, cfg)
    out = fused_consensus_gated_claims(
        values, ok, claim_mask, cfg, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref.reliable)
    )


# ---------------------------------------------------------------------------
# No silent fallback (consensus_pallas_fallback{reason=}) and the
# dispatch layer's impl routing
# ---------------------------------------------------------------------------


def _fallback_counts(registry):
    return {
        labels.get("reason"): count
        for labels, count in registry.family_series(
            "consensus_pallas_fallback"
        )
    }


def test_fallback_counter_fleet_too_large(monkeypatch):
    """Over the oracle cap the fused entry points serve XLA results AND
    count the fallback — the bench subprocess must not stay the only
    place a fallback is visible."""
    from svoc_tpu.utils.metrics import registry as default_registry

    monkeypatch.setenv("SVOC_PALLAS_MAX_ORACLES", "8")
    before = _fallback_counts(default_registry).get("fleet_too_large", 0)
    cfg = ConsensusConfig(n_failing=2, constrained=True)
    values = jnp.asarray(
        np.random.default_rng(2).uniform(0.1, 0.9, (2, 16, 3)).astype(
            np.float32
        )
    )
    ok = jnp.ones((2, 16), dtype=bool)
    out = fused_consensus_gated_claims(
        values, ok, jnp.ones(2, dtype=bool), cfg
    )
    ref = consensus_step_gated_claims(
        values, ok, jnp.ones(2, dtype=bool), cfg
    )
    _assert_claims_parity(out, ref)
    after = _fallback_counts(default_registry).get("fleet_too_large", 0)
    assert after == before + 1


def test_dispatch_pallas_route_counts_non_tpu(monkeypatch):
    """A pallas-routed dispatch on a non-TPU backend without the
    interpret opt-in serves XLA and counts reason=non_tpu into the
    CALLER's registry (the router passes its own).  The backend is
    pinned via monkeypatch so the assertion holds on a TPU host too."""
    from svoc_tpu.consensus.batch import claims_consensus_gated
    from svoc_tpu.utils.metrics import MetricsRegistry

    monkeypatch.delenv("SVOC_PALLAS_INTERPRET", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    reg = MetricsRegistry()
    cfg = ConsensusConfig(n_failing=2, constrained=True)
    values = jnp.asarray(
        np.random.default_rng(3).uniform(0.1, 0.9, (2, 8, 3)).astype(
            np.float32
        )
    )
    ok = jnp.ones((2, 8), dtype=bool)
    mask = jnp.ones(2, dtype=bool)
    out = claims_consensus_gated(
        values, ok, mask, cfg, consensus_impl="pallas", metrics=reg
    )
    assert _fallback_counts(reg) == {"non_tpu": 1}
    ref = consensus_step_gated_claims(values, ok, mask, cfg)
    _assert_claims_parity(out, ref)


def test_dispatch_pallas_route_with_interpret_opt_in(monkeypatch):
    """With SVOC_PALLAS_INTERPRET=1 the pallas route actually runs the
    kernel on CPU: no fallback counted, parity holds — this is the
    `make pallas-parity` dispatch path.  The backend is pinned to CPU
    so a TPU host exercises the same interpret path (a compiled-TPU
    dispatch here would re-risk the known Mosaic compile hang inside
    tier-1)."""
    from svoc_tpu.consensus.batch import (
        claims_consensus,
        claims_consensus_gated,
    )
    from svoc_tpu.utils.metrics import MetricsRegistry

    monkeypatch.setenv("SVOC_PALLAS_INTERPRET", "1")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    reg = MetricsRegistry()
    cfg = ConsensusConfig(n_failing=2, constrained=True)
    values = jnp.asarray(
        np.random.default_rng(4).uniform(0.1, 0.9, (2, 8, 3)).astype(
            np.float32
        )
    )
    ok = jnp.asarray(np.array([[True] * 8, [True] * 5 + [False] * 3]))
    mask = jnp.ones(2, dtype=bool)
    out = claims_consensus_gated(
        values, ok, mask, cfg, consensus_impl="pallas", metrics=reg
    )
    ref = consensus_step_gated_claims(values, ok, mask, cfg)
    _assert_claims_parity(out, ref)
    # The ungated wrapper routes through the gated kernel with
    # all-admitted masks — same outputs as the ungated XLA claims path
    # on finite cubes.
    from svoc_tpu.consensus.kernel import consensus_step_claims

    out_u = claims_consensus(
        values, mask, cfg, consensus_impl="pallas", metrics=reg
    )
    ref_u = consensus_step_claims(values, mask, cfg)
    _assert_claims_parity(out_u, ref_u)
    assert _fallback_counts(reg) == {}


def test_router_resolves_impl_once(monkeypatch):
    """ClaimRouter pins consensus_impl at construction (replay rule:
    the impl choice is part of a seeded run's config)."""
    from svoc_tpu.fabric.registry import ClaimRegistry
    from svoc_tpu.fabric.router import ClaimRouter

    monkeypatch.setenv("SVOC_CONSENSUS_IMPL", "pallas")
    router = ClaimRouter(ClaimRegistry())
    assert router.consensus_impl == "pallas"
    monkeypatch.setenv("SVOC_CONSENSUS_IMPL", "xla")
    assert router.consensus_impl == "pallas"  # pinned, not re-resolved
    explicit = ClaimRouter(ClaimRegistry(), consensus_impl="xla")
    assert explicit.consensus_impl == "xla"


# ---------------------------------------------------------------------------
# Typed env-knob parsing (no ValueError-at-import)
# ---------------------------------------------------------------------------


def test_env_knobs_raise_typed_errors(monkeypatch):
    from svoc_tpu.consensus.dispatch import PallasConfigError, env_float
    from svoc_tpu.ops import pallas_consensus as pc

    monkeypatch.setenv("SVOC_PALLAS_MAX_ORACLES", "not-a-number")
    with pytest.raises(PallasConfigError, match="SVOC_PALLAS_MAX_ORACLES"):
        pc.pallas_max_oracles()
    with pytest.raises(PallasConfigError, match="SVOC_PALLAS_MAX_ORACLES"):
        _ = pc.PALLAS_MAX_ORACLES  # lazy module attr, same validation
    monkeypatch.setenv("SVOC_PALLAS_MAX_ORACLES", "0")
    with pytest.raises(PallasConfigError, match="minimum"):
        pc.pallas_max_oracles()
    monkeypatch.setenv("SVOC_PALLAS_MAX_ORACLES", "512")
    assert pc.PALLAS_MAX_ORACLES == 512

    monkeypatch.setenv("SVOC_PALLAS_TIMEOUT", "soon")
    with pytest.raises(PallasConfigError, match="SVOC_PALLAS_TIMEOUT"):
        env_float("SVOC_PALLAS_TIMEOUT", 300.0, minimum=1e-3)


def test_resolve_consensus_impl_rejection_names_allowed_values(monkeypatch):
    from svoc_tpu.consensus.dispatch import (
        ConsensusImplError,
        resolve_consensus_impl,
    )

    monkeypatch.setenv("SVOC_CONSENSUS_IMPL", "cuda")
    with pytest.raises(ConsensusImplError) as err:
        resolve_consensus_impl()
    message = str(err.value)
    assert "'xla'" in message and "'pallas'" in message
    assert "SVOC_CONSENSUS_IMPL" in message
