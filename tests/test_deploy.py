"""Constructor calldata layout parity with the reference deployments."""

import pytest

from svoc_tpu.io.deploy import (
    DeployConfig,
    constructor_calldata,
    parse_constructor_calldata,
    simulator_from_calldata,
)

# Short-string felts for 'Akashi', 'Ozu', 'Higuchi', 'oracle_00'...
# (test_contract.cairo:28-49 uses these as addresses).
AKASHI = int.from_bytes(b"Akashi", "big")
OZU = int.from_bytes(b"Ozu", "big")
HIGUCHI = int.from_bytes(b"Higuchi", "big")
ORACLES = [int.from_bytes(f"oracle_{i:02d}".encode(), "big") for i in range(7)]


def reference_constrained_calldata(dimension: int):
    """deploy_constrained_contract (test_contract.cairo:28-59):
    3 admins, replacement on, majority 2, 2 failing, constrained,
    spread 0, 7 oracles."""
    return [
        3, AKASHI, OZU, HIGUCHI,
        1, 2, 2, 1, 0, dimension,
        7, *ORACLES,
    ]


class TestCalldata:
    def test_matches_reference_constrained_layout(self):
        cfg = DeployConfig(
            admins=[AKASHI, OZU, HIGUCHI],
            oracles=ORACLES,
            dimension=2,
        )
        assert constructor_calldata(cfg) == reference_constrained_calldata(2)

    def test_unconstrained_spread_encodes_wsad(self):
        """deploy_unconstrained_contract uses wsad()*10 (test_contract
        .cairo:73): max_spread 10.0 -> felt 10_000_000."""
        cfg = DeployConfig(
            admins=[AKASHI, OZU, HIGUCHI],
            oracles=ORACLES,
            constrained=False,
            unconstrained_max_spread=10.0,
        )
        calldata = constructor_calldata(cfg)
        assert calldata[8] == 10_000_000

    def test_roundtrip(self):
        cfg = DeployConfig(
            admins=[1, 2, 3],
            oracles=[10, 11, 12, 13],
            enable_oracle_replacement=False,
            required_majority=3,
            n_failing_oracles=1,
            constrained=False,
            unconstrained_max_spread=5.5,
            dimension=6,
        )
        parsed = parse_constructor_calldata(constructor_calldata(cfg))
        assert parsed == DeployConfig(
            admins=[1, 2, 3],
            oracles=[10, 11, 12, 13],
            enable_oracle_replacement=False,
            required_majority=3,
            n_failing_oracles=1,
            constrained=False,
            unconstrained_max_spread=5.5,
            dimension=6,
        )

    def test_trailing_garbage_rejected(self):
        calldata = reference_constrained_calldata(2) + [99]
        with pytest.raises(ValueError, match="consumed"):
            parse_constructor_calldata(calldata)

    def test_simulator_from_calldata_runs(self):
        sim = simulator_from_calldata(reference_constrained_calldata(2))
        assert sim.get_admin_list() == [AKASHI, OZU, HIGUCHI]
        assert sim.get_oracle_list() == ORACLES
        assert sim.get_predictions_dimension() == 2
        sim.update_prediction(ORACLES[0], [0.4, 0.2])
        assert not sim.consensus_active
