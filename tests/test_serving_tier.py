"""Continuous-batching serving tier (docs/SERVING.md).

Covers: the dedup cache's hit/miss/evict semantics and capacity
bounds, deterministic admission control (queue bounds + the forced
burn-rate flip + the seeded shed draw), frontend lineage/journal
accounting, fair cross-claim micro-batch assembly, assembler parity
(the packed cross-claim batch against a per-request loop, and the
batched request-driven fabric cycle against a claim-at-a-time loop),
request-driven per-claim isolation (ISSUE 7 satellite: one claim's
overflow or malformed feed never stalls a sibling), seeded replay
determinism of the whole serving scenario, and the ``POST /api/submit``
web path.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from svoc_tpu.fabric.registry import ClaimSpec  # noqa: E402
from svoc_tpu.fabric.scenario import deterministic_vectorizer  # noqa: E402
from svoc_tpu.fabric.session import MultiSession  # noqa: E402
from svoc_tpu.serving.batcher import MicroBatcher  # noqa: E402
from svoc_tpu.serving.cache import ResultCache, content_key  # noqa: E402
from svoc_tpu.serving.frontend import (  # noqa: E402
    AdmissionConfig,
    AdmissionController,
)
from svoc_tpu.serving.scenario import VirtualClock, run_serving_scenario  # noqa: E402
from svoc_tpu.serving.tier import ServingTier  # noqa: E402
from svoc_tpu.utils.events import EventJournal  # noqa: E402
from svoc_tpu.utils.metrics import MetricsRegistry  # noqa: E402
from svoc_tpu.utils.slo import REQUEST_LATENCY_HISTOGRAM, serving_slos  # noqa: E402


def _multi(journal, metrics, claims=("alpha", "beta"), **kw):
    multi = MultiSession(
        base_seed=0,
        vectorizer=deterministic_vectorizer,
        journal=journal,
        metrics=metrics,
        lineage_scope="t",
        sanitized_dispatch=True,
        **kw,
    )
    for cid in claims:
        multi.add_claim(ClaimSpec(claim_id=cid, n_oracles=7, dimension=6))
    return multi


def _tier(claims=("alpha", "beta"), *, admission=None, clock=None, **kw):
    journal = EventJournal(MetricsRegistry())
    metrics = MetricsRegistry()
    clock = clock or VirtualClock()
    multi = _multi(journal, metrics, claims)
    tier = ServingTier(
        multi,
        vectorizer=kw.pop("vectorizer", deterministic_vectorizer),
        admission=admission,
        clock=clock,
        slos=serving_slos(
            metrics, latency_target_s=0.25, fast_window_s=1.0, slow_window_s=5.0
        ),
        **kw,
    )
    return tier, multi, journal, metrics, clock


class TestResultCache:
    def test_miss_then_hit_counts_and_copies(self):
        reg = MetricsRegistry()
        cache = ResultCache(4, metrics=reg)
        key = content_key("alpha", "hello")
        assert cache.get(key) is None
        cache.put(key, np.array([1.0, 2.0]))
        got = cache.get(key)
        np.testing.assert_array_equal(got, [1.0, 2.0])
        got[0] = 99.0  # a copy: caller mutation never pollutes the cache
        np.testing.assert_array_equal(cache.get(key), [1.0, 2.0])
        stats = cache.stats()
        assert stats["hits"] == 2 and stats["misses"] == 1
        assert stats["size"] == 1 and stats["evictions"] == 0

    def test_lru_eviction_hit_refreshes_recency(self):
        reg = MetricsRegistry()
        cache = ResultCache(2, metrics=reg)
        ka, kb, kc = (content_key("c", t) for t in ("a", "b", "c"))
        cache.put(ka, np.zeros(2))
        cache.put(kb, np.ones(2))
        cache.get(ka)  # refresh: 'a' is now most recent
        cache.put(kc, np.full(2, 2.0))  # evicts 'b', not 'a'
        assert ka in cache and kc in cache and kb not in cache
        assert len(cache) == 2
        assert cache.stats()["evictions"] == 1

    def test_capacity_bound_holds_under_churn(self):
        cache = ResultCache(8, metrics=MetricsRegistry())
        for i in range(50):
            cache.put(content_key("c", f"t{i}"), np.array([float(i)]))
        assert len(cache) == 8
        assert cache.stats()["evictions"] == 42

    def test_keys_partition_by_claim(self):
        # Same text, different claims: distinct entries (an eviction in
        # one claim must not dent another's hit rate).
        assert content_key("alpha", "same") != content_key("beta", "same")

    def test_rejects_bad_capacity(self):
        with pytest.raises(ValueError):
            ResultCache(0)


class TestAdmissionController:
    def test_queue_bound_sheds_first(self):
        reg = MetricsRegistry()
        ctrl = AdmissionController(
            AdmissionConfig(queue_capacity=2), metrics=reg
        )
        assert ctrl.decide("alpha", 0, 1).action == "admit"
        assert ctrl.decide("alpha", 1, 2).action == "admit"
        decision = ctrl.decide("alpha", 2, 3)
        assert (decision.action, decision.reason) == ("shed", "queue_full")

    def test_burn_flip_sheds_misses_and_recovers(self):
        """ISSUE 7: admission flips at a forced burn-rate threshold."""
        reg = MetricsRegistry()
        cfg = AdmissionConfig(burn_threshold=4.0, shed_fraction=1.0)
        ctrl = AdmissionController(cfg, metrics=reg)
        gauge = reg.gauge(
            "slo_burn_rate",
            labels={"slo": "request_latency", "window": "fast"},
        )
        assert ctrl.decide("alpha", 0, 1).action == "admit"  # cold: admit
        gauge.set(10.0)
        decision = ctrl.decide("alpha", 0, 2)
        assert (decision.action, decision.reason) == ("shed", "slo_burn")
        gauge.set(1.0)  # back under: the brownout lifts immediately
        assert ctrl.decide("alpha", 0, 3).action == "admit"

    def test_fractional_shed_draw_is_seeded_and_deterministic(self):
        reg = MetricsRegistry()
        cfg = AdmissionConfig(burn_threshold=4.0, shed_fraction=0.5, seed=7)
        reg.gauge(
            "slo_burn_rate",
            labels={"slo": "request_latency", "window": "fast"},
        ).set(10.0)
        a = AdmissionController(cfg, metrics=reg)
        b = AdmissionController(cfg, metrics=reg)
        seq_a = [a.decide("alpha", 0, s).action for s in range(40)]
        seq_b = [b.decide("alpha", 0, s).action for s in range(40)]
        assert seq_a == seq_b  # replayable across instances
        assert {"admit", "shed"} == set(seq_a)  # the fraction really splits

    def test_rejects_bad_config(self):
        with pytest.raises(ValueError):
            AdmissionConfig(queue_capacity=0)
        with pytest.raises(ValueError):
            AdmissionConfig(shed_fraction=1.5)
        with pytest.raises(ValueError):
            AdmissionConfig(burn_threshold=0.0)


class TestFrontend:
    def test_submit_admits_with_claim_family_lineage(self):
        tier, _multi, journal, metrics, _clock = _tier()
        response = tier.submit("alpha", "first comment")
        assert response["status"] == "admitted"
        assert response["lineage"].startswith("blkt-alpha-rq")
        assert tier.frontend.depth("alpha") == 1
        events = [e for e in journal.recent() if e.type == "serving.admitted"]
        assert len(events) == 1
        assert events[0].lineage == response["lineage"]
        assert metrics.counter(
            "serving_admitted", labels={"claim": "alpha"}
        ).count == 1

    def test_unknown_claim_raises_keyerror(self):
        tier, *_ = _tier()
        with pytest.raises(KeyError):
            tier.submit("nope", "text")

    def test_cached_repeat_answers_immediately(self):
        tier, _multi, journal, metrics, _clock = _tier()
        tier.submit("alpha", "viral take")
        tier.step()  # completes the request and fills the cache
        response = tier.submit("alpha", "viral take")
        assert response["status"] == "cached"
        assert len(response["vector"]) == 6
        assert tier.frontend.depth("alpha") == 0  # no queue slot used
        assert metrics.counter(
            "serving_cache", labels={"event": "hit"}
        ).count == 1

    def test_queue_overflow_sheds_on_own_lineage_siblings_fine(self):
        """ISSUE 7 satellite: a claim whose submit queue overflows gets
        shed events on its own lineage and counters, and never stalls
        a sibling claim."""
        tier, _multi, journal, metrics, _clock = _tier(
            admission=AdmissionConfig(queue_capacity=2)
        )
        for i in range(5):
            tier.submit("alpha", f"flood {i}")
        response = tier.submit("beta", "calm")
        assert response["status"] == "admitted"
        shed_alpha = metrics.counter(
            "serving_shed", labels={"claim": "alpha", "reason": "queue_full"}
        ).count
        assert shed_alpha == 3
        assert metrics.family_total("serving_shed") == 3  # none on beta
        shed_events = [e for e in journal.recent() if e.type == "serving.shed"]
        assert len(shed_events) == 3
        assert all(
            e.lineage.startswith("blkt-alpha-rq") for e in shed_events
        )
        # The flooded claim still serves what it admitted, and the
        # sibling is served in the SAME step — no stall.
        report = tier.step()
        assert sorted(report["served"]) == ["alpha", "beta"]
        assert metrics.family_total("serving_completed") == 3

    def test_drain_is_fifo_and_refreshes_depth(self):
        tier, *_ = _tier()
        for i in range(3):
            tier.submit("alpha", f"c{i}")
        got = tier.frontend.drain("alpha", 2)
        assert [r.text for r in got] == ["c0", "c1"]
        assert tier.frontend.depth("alpha") == 1


class TestMicroBatcher:
    def test_round_robin_is_fair_across_claims(self):
        """A deep queue cannot monopolize a micro-batch: assembly takes
        one request per claim per round."""
        tier, *_ = _tier(("alpha", "beta"), max_requests_per_step=4)
        for i in range(6):
            tier.submit("alpha", f"a{i}")
        tier.submit("beta", "b0")
        tier.submit("beta", "b1")
        picked = tier.batcher.assemble()
        order = [r.claim for r in picked]
        assert order == ["alpha", "beta", "alpha", "beta"]
        assert tier.frontend.depth("alpha") == 4  # the rest stay queued

    def test_group_by_claim_requires_vectors(self):
        tier, *_ = _tier()
        tier.submit("alpha", "x")
        (request,) = tier.batcher.assemble()
        with pytest.raises(ValueError, match="no vector"):
            MicroBatcher.group_by_claim([request])

    def test_assembler_packed_parity_vs_per_request_loop(self):
        """ISSUE 7 acceptance: the packed cross-claim batch produces
        the same vectors as a per-request loop through the model."""
        from svoc_tpu.models.configs import TINY_TEST
        from svoc_tpu.models.sentiment import SentimentPipeline

        pipe = SentimentPipeline(
            cfg=TINY_TEST, seq_len=32, batch_size=4, tokenizer_name=None
        )
        tier, *_ = _tier(("alpha", "beta", "gamma"), vectorizer=pipe)
        texts = [
            "short",
            "a somewhat longer comment with more tokens in it",
            "medium length remark",
            "another take entirely",
            "yet more words to pack",
            "final thought",
        ]
        batched = tier.batcher.vectorize(texts)  # one packed forward
        loop = np.stack([pipe([t])[0] for t in texts])  # per-request loop
        assert batched.shape == (6, 6)
        np.testing.assert_allclose(batched, loop, atol=1e-4)

    def test_vectorize_dedups_in_batch_duplicates(self):
        """Duplicates of one hot comment inside a single micro-batch
        are forwarded once and fanned back out — repeats never occupy
        packed segments (the cache only answers across steps)."""
        calls = []

        def counting_vectorizer(texts):
            calls.append(list(texts))
            return np.stack([deterministic_vectorizer([t])[0] for t in texts])

        tier, *_ = _tier(vectorizer=counting_vectorizer)
        texts = ["viral take", "fresh a", "viral take", "fresh b", "viral take"]
        out = tier.batcher.vectorize(texts)
        assert calls == [["viral take", "fresh a", "fresh b"]]
        expected = np.stack([deterministic_vectorizer([t])[0] for t in texts])
        np.testing.assert_array_equal(out, expected)

    def test_removed_claim_queue_is_purged_and_dropped(self):
        """Requests stranded by ``remove_claim`` must be accounted as
        dropped on the next step (counting against serving_admission),
        not sit queued forever reading as served."""
        tier, multi, _journal, metrics, _clock = _tier()
        for i in range(3):
            assert tier.submit("beta", f"b{i}")["status"] == "admitted"
        tier.submit("alpha", "a0")
        multi.remove_claim("beta")
        report = tier.step()
        assert report["dropped"] == 3
        assert report["served"] == ["alpha"]
        assert (
            metrics.counter("serving_dropped", labels={"claim": "beta"}).count
            == 3
        )
        assert "beta" not in tier.frontend.depths()  # no ghost queue


class TestRequestDrivenFabric:
    def _feeds(self, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "alpha": rng.uniform(0.1, 0.9, (3, 6)).astype(np.float32),
            "beta": rng.uniform(0.1, 0.9, (2, 6)).astype(np.float32),
        }

    def test_batched_step_matches_claim_at_a_time_loop(self):
        """ISSUE 7 acceptance: micro-batched cross-claim consensus is
        parity-exact against feeding each claim on its own."""
        feeds = self._feeds()
        multi_a = _multi(EventJournal(MetricsRegistry()), MetricsRegistry())
        report = multi_a.step(feeds=feeds)
        assert sorted(report["served"]) == ["alpha", "beta"]

        multi_b = _multi(EventJournal(MetricsRegistry()), MetricsRegistry())
        multi_b.step(feeds={"alpha": feeds["alpha"]})
        multi_b.step(feeds={"beta": feeds["beta"]})

        for cid in ("alpha", "beta"):
            batched = multi_a.get(cid).last_consensus
            looped = multi_b.get(cid).last_consensus
            assert batched["essence"] == looped["essence"]
            assert batched["reliable"] == looped["reliable"]
            assert batched["interval_valid"] == looped["interval_valid"]
            assert (
                batched["reliability_second_pass"]
                == looped["reliability_second_pass"]
            )

    def test_request_fed_block_audits_like_a_scraped_one(self):
        journal = EventJournal(MetricsRegistry())
        multi = _multi(journal, MetricsRegistry())
        multi.step(feeds=self._feeds())
        session = multi.get("alpha").session
        assert session.last_lineage.startswith("blkt-alpha-")
        types = {e.type for e in journal.recent(lineage=session.last_lineage)}
        assert {"block.fetched", "consensus.result"} <= types
        fetched = [
            e
            for e in journal.recent(lineage=session.last_lineage)
            if e.type == "block.fetched"
        ]
        assert fetched[0].data["source"] == "serving"
        assert fetched[0].data["n_comments"] == 3

    def test_cold_start_single_request_defers_commit_then_recovers(self):
        """A 1-request cold start yields a zero-variance fleet block —
        the on-chain skewness recompute would revert the final tx
        (docs/SERVING.md §degeneracy), so the commit defers on a typed
        ``commit.deferred`` instead of stranding the last signer; the
        rolling request window restores diversity and the next cycle
        commits for real."""
        journal = EventJournal(MetricsRegistry())
        metrics = MetricsRegistry()
        multi = _multi(journal, metrics, claims=("alpha",))
        rng = np.random.default_rng(7)
        lone = rng.uniform(0.1, 0.9, (1, 6)).astype(np.float32)
        report = multi.step(feeds={"alpha": lone})
        assert report["served"] == ["alpha"]
        labels = {"claim": "alpha"}
        assert metrics.counter("claim_commit_deferred", labels=labels).count == 1
        assert metrics.counter("claim_commit_failures", labels=labels).count == 0
        state = multi.get("alpha")
        assert state.last_commit == {"deferred": True}
        deferred = [e for e in journal.recent(40) if e.type == "commit.deferred"]
        assert deferred and deferred[0].data["reason"] == "degenerate"
        assert deferred[0].lineage.startswith("blkt-alpha-")
        # More traffic → the rolling window regains diversity → commit.
        more = rng.uniform(0.1, 0.9, (3, 6)).astype(np.float32)
        multi.step(feeds={"alpha": more})
        assert state.last_commit.get("complete") is True
        assert metrics.counter("claim_commit_deferred", labels=labels).count == 1

    def test_malformed_feed_isolated_to_its_claim(self):
        """ISSUE 7 satellite: a malformed feed lands in that claim's
        ``fabric_claim_errors{stage="fetch"}``; siblings are served."""
        metrics = MetricsRegistry()
        multi = _multi(EventJournal(MetricsRegistry()), metrics)
        feeds = self._feeds()
        feeds["alpha"] = np.zeros((2, 3), dtype=np.float32)  # wrong dim
        report = multi.step(feeds=feeds)
        assert report["served"] == ["beta"]
        assert report["skipped"]["alpha"].startswith("fetch_error:")
        assert metrics.counter(
            "fabric_claim_errors", labels={"claim": "alpha", "stage": "fetch"}
        ).count == 1

    def test_empty_feed_window_is_isolated_not_fatal(self):
        multi = _multi(EventJournal(MetricsRegistry()), MetricsRegistry())
        feeds = self._feeds()
        feeds["alpha"] = np.zeros((0, 6), dtype=np.float32)
        report = multi.step(feeds=feeds)
        assert report["served"] == ["beta"]
        assert report["skipped"]["alpha"] == "empty_store"

    def test_unknown_and_paused_claims_are_reported_not_served(self):
        multi = _multi(EventJournal(MetricsRegistry()), MetricsRegistry())
        multi.pause("beta")
        feeds = self._feeds()
        feeds["ghost"] = feeds.pop("beta")
        report = multi.step(feeds=feeds)
        assert report["served"] == ["alpha"]
        assert report["skipped"]["ghost"] == "unknown_claim"
        report = multi.step(feeds={"beta": self._feeds()["beta"]})
        assert report["skipped"]["beta"] == "paused"

    def test_pull_mode_unchanged_without_feeds(self):
        """feeds=None keeps the PR 6 pull cycle: claims read their own
        stores (here empty → the routine empty_store skip)."""
        multi = _multi(EventJournal(MetricsRegistry()), MetricsRegistry())
        report = multi.step()
        assert report["served"] == []
        assert set(report["skipped"].values()) == {"empty_store"}


class TestServingTierEndToEnd:
    def test_step_completes_requests_and_observes_latency(self):
        clock = VirtualClock()
        tier, multi, journal, metrics, _ = _tier(clock=clock)
        tier.submit("alpha", "one")
        tier.submit("beta", "two")
        clock.advance(0.05)
        report = tier.step()
        assert report["requests"] == 2
        assert sorted(report["served"]) == ["alpha", "beta"]
        assert report["latencies_s"] == [0.05, 0.05]
        hist = metrics.histogram(REQUEST_LATENCY_HISTOGRAM).snapshot()
        assert hist["count"] == 2
        assert metrics.family_total("serving_completed") == 2
        # Completion fills the dedup cache for both texts.
        assert tier.cache.stats()["size"] == 2
        steps = [e for e in journal.recent() if e.type == "serving.step"]
        assert len(steps) == 1 and steps[0].data["requests"] == 2

    def test_skipped_claim_requests_drop_not_complete(self):
        """A claim the fabric skips mid-cycle (paused after admission)
        must not have its drained requests counted as completed — that
        would read a blackholed claim as green on both serving SLOs."""
        clock = VirtualClock()
        tier, multi, _journal, metrics, _ = _tier(clock=clock)
        tier.submit("alpha", "one")
        tier.submit("beta", "two")
        multi.pause("beta")
        clock.advance(0.05)
        report = tier.step()
        assert report["served"] == ["alpha"]
        assert report["skipped"] == {"beta": "paused"}
        assert report["dropped"] == 1
        assert report["latencies_s"] == [0.05]
        assert metrics.family_total("serving_completed") == 1
        assert metrics.counter(
            "serving_dropped", labels={"claim": "beta"}
        ).count == 1
        assert metrics.histogram(REQUEST_LATENCY_HISTOGRAM).snapshot()[
            "count"
        ] == 1
        assert tier.snapshot()["dropped"] == 1

    def test_poison_text_drops_only_its_request(self):
        """A text that makes the shared packed forward raise must not
        lose the whole drained cross-claim micro-batch: the step falls
        back to per-request vectorize and drops only the poison."""

        def poisoned(texts):
            if any(t == "poison" for t in texts):
                raise RuntimeError("tokenizer exploded")
            return deterministic_vectorizer(texts)

        clock = VirtualClock()
        tier, _multi, _journal, metrics, _ = _tier(
            clock=clock, vectorizer=poisoned
        )
        tier.submit("alpha", "a perfectly fine comment")
        tier.submit("beta", "poison")
        clock.advance(0.05)
        report = tier.step()
        assert report["requests"] == 2
        assert report["dropped"] == 1
        assert report["served"] == ["alpha"]
        assert metrics.counter("serving_vectorize_errors").count == 1
        assert metrics.counter(
            "serving_dropped", labels={"claim": "beta"}
        ).count == 1
        assert metrics.family_total("serving_completed") == 1

    def test_idle_step_still_evaluates_slos(self):
        tier, _multi, _journal, metrics, _ = _tier()
        report = tier.step()
        assert report["requests"] == 0
        # The evaluator ran: the burn gauges exist (0.0 on a cold tier).
        assert tier.frontend.controller.burn_rate() == 0.0

    def test_snapshot_shape(self):
        tier, *_ = _tier()
        tier.submit("alpha", "x")
        tier.step()
        snap = tier.snapshot()
        assert snap["steps"] == 1
        assert snap["submitted"] == 1 and snap["completed"] == 1
        assert snap["cache"]["size"] == 1
        assert "p99" in snap["latency"]
        assert isinstance(snap["queues"], dict)


class TestServingScenarioReplay:
    # Short phases: determinism is phase-shape-independent, and tier-1
    # budget matters more than saturation realism here (the full-shape
    # run is make serving-smoke / bench_serving.py).
    PHASES = ((4, 3), (30, 4), (4, 3))

    def test_seeded_replay_is_fingerprint_identical(self):
        a = run_serving_scenario(seed=3, phases=self.PHASES)
        b = run_serving_scenario(seed=3, phases=self.PHASES)
        assert a["journal_fingerprint"] == b["journal_fingerprint"]
        assert a["per_claim_fingerprints"] == b["per_claim_fingerprints"]
        assert a["shed_by_reason"] == b["shed_by_reason"]
        assert a["journal_events"] > 0

    def test_different_seeds_diverge(self):
        a = run_serving_scenario(seed=3, phases=self.PHASES)
        b = run_serving_scenario(seed=4, phases=self.PHASES)
        assert a["journal_fingerprint"] != b["journal_fingerprint"]

    def test_overload_sheds_and_cache_serves(self):
        r = run_serving_scenario(seed=0, phases=self.PHASES)
        warm, overload, _recovery = r["phases"]
        assert warm["shed"] == 0
        assert overload["shed"] > 0
        assert r["cache"]["hits"] > 0
        assert r["completed"] > 0
        assert r["latency"]["count"] > 0


class TestSubmitEndpoint:
    @staticmethod
    def _submit(base, payload):
        req = urllib.request.Request(
            f"{base}/api/submit",
            data=json.dumps(payload).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())

    def _served_console(self, **tier_kw):
        from tests.conftest import make_fake_console

        console = make_fake_console()
        tier, *_ = _tier(**tier_kw)
        tier.attach(console)
        return console, tier

    def test_submit_happy_and_cached_paths(self):
        from svoc_tpu.apps.web import serve

        console, tier = self._served_console()
        srv, _ = serve(console, port=0, block=False)
        try:
            base = f"http://127.0.0.1:{srv.server_address[1]}"
            status, body = self._submit(
                base, {"claim": "alpha", "text": "hello world"}
            )
            assert status == 200 and body["status"] == "admitted"
            assert body["lineage"].startswith("blkt-alpha-rq")
            tier.step()
            status, body = self._submit(
                base, {"claim": "alpha", "text": "hello world"}
            )
            assert status == 200 and body["status"] == "cached"
            assert len(body["vector"]) == 6
            # /api/state grows the serving section.
            with urllib.request.urlopen(f"{base}/api/state", timeout=10) as r:
                state = json.loads(r.read())
            assert state["serving"]["submitted"] == 2
            assert state["serving"]["completed"] == 1
        finally:
            srv.shutdown()

    def test_submit_shed_is_429_unknown_404_malformed_400(self):
        from svoc_tpu.apps.web import serve

        console, _tier = self._served_console(
            admission=AdmissionConfig(queue_capacity=1)
        )
        srv, _ = serve(console, port=0, block=False)
        try:
            base = f"http://127.0.0.1:{srv.server_address[1]}"
            status, _ = self._submit(base, {"claim": "alpha", "text": "a"})
            assert status == 200
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._submit(base, {"claim": "alpha", "text": "b"})
            assert exc_info.value.code == 429
            assert json.loads(exc_info.value.read())["reason"] == "queue_full"
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._submit(base, {"claim": "ghost", "text": "x"})
            assert exc_info.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._submit(base, {"wrong": "shape"})
            assert exc_info.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._submit(base, {"claim": 3, "text": "x"})
            assert exc_info.value.code == 400
        finally:
            srv.shutdown()

    def test_submit_without_tier_is_503(self):
        from svoc_tpu.apps.web import serve
        from tests.conftest import make_fake_console

        srv, _ = serve(make_fake_console(), port=0, block=False)
        try:
            base = f"http://127.0.0.1:{srv.server_address[1]}"
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._submit(base, {"claim": "alpha", "text": "x"})
            assert exc_info.value.code == 503
        finally:
            srv.shutdown()


class TestServingConsole:
    def _console_with_tier(self):
        from tests.conftest import make_fake_console

        console = make_fake_console()
        tier, multi, *_ = _tier()
        tier.attach(console)
        multi.attach(console)
        return console, tier

    def test_serving_command_status_submit_step(self):
        console, _tier = self._console_with_tier()
        out = console.query("serving")
        assert any("serving: 0 steps" in line for line in out)
        out = console.query("serving submit alpha a hot take")
        assert any("admitted: alpha:1" in line for line in out)
        out = console.query("serving step")
        assert any("step 1: 1 requests over 1 claims" in line for line in out)
        out = console.query("serving")
        assert any("hit rate" in line for line in out)

    def test_serving_command_errors(self):
        console, _tier = self._console_with_tier()
        out = console.query("serving submit ghost hi")
        assert any("unknown claim" in line for line in out)
        out = console.query("serving bogus")
        assert any("usage:" in line for line in out)

    def test_serving_command_without_tier(self):
        from tests.conftest import make_fake_console

        out = make_fake_console().query("serving")
        assert any("no serving tier attached" in line for line in out)

    def test_slo_command_includes_serving_objectives(self):
        console, tier = self._console_with_tier()
        tier.submit("alpha", "x")
        tier.step()
        out = console.query("slo")
        joined = "\n".join(out)
        assert "request_latency" in joined
        assert "serving_admission" in joined
        # The fabric's per-claim objectives ride along (ISSUE 7
        # satellite: per-claim burn rates in the slo output).
        assert "claim_commit_success" in joined or "commit_success" in joined


class TestPerClaimPrometheus:
    def test_claim_counters_render_from_registration(self):
        """ISSUE 7 satellite: per-claim SLO counters and
        fabric_claim_errors render on /metrics from claim registration
        onward, before any traffic."""
        metrics = MetricsRegistry()
        _multi(EventJournal(MetricsRegistry()), metrics)
        text = metrics.render_prometheus()
        for cid in ("alpha", "beta"):
            assert f'svoc_claim_commit_cycles_total{{claim="{cid}"}} 0' in text
            assert (
                f'svoc_fabric_claim_errors_total{{claim="{cid}",stage="fetch"}} 0'
                in text
            )
            assert (
                f'svoc_fabric_claim_errors_total{{claim="{cid}",stage="commit"}} 0'
                in text
            )


class TestPackingFillRatio:
    def test_fill_ratios_and_gauges_from_pack_path(self):
        """ISSUE 7 satellite: the pack path's segment/token occupancy is
        observable — ``fill_ratios`` math plus the
        ``packing_fill_ratio{kind=}`` gauges on the registry."""
        from svoc_tpu.models.packing import (
            fill_ratios,
            observe_fill_ratios,
            pack_tokens_auto,
        )

        token_lists = [[5, 6, 7], [8, 9], [10, 11, 12, 13], [14, 15]]
        batch, n = pack_tokens_auto(token_lists, 32, 4, 0)
        assert n == len(token_lists)
        ratios = fill_ratios(batch)
        rows, slots = batch.seg_valid.shape
        assert ratios["rows"] == rows
        assert ratios["segments_used"] == int(batch.seg_valid.sum())
        assert ratios["segments"] == pytest.approx(
            ratios["segments_used"] / (rows * slots)
        )
        assert 0.0 < ratios["tokens"] <= 1.0

        metrics = MetricsRegistry()
        observed = observe_fill_ratios(batch, metrics)
        assert observed == ratios
        text = metrics.render_prometheus()
        assert 'packing_fill_ratio{kind="segments"}' in text
        assert 'packing_fill_ratio{kind="tokens"}' in text
