"""Web UI server: page, query endpoint, state endpoint."""

import json
import urllib.request

import pytest

from svoc_tpu.apps.commands import CommandConsole
from svoc_tpu.apps.web import serve
from tests.test_apps import make_session


@pytest.fixture()
def server():
    console = CommandConsole(make_session())
    srv, thread = serve(console, port=0, block=False)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, console
    srv.shutdown()


def post(base, text):
    req = urllib.request.Request(
        f"{base}/api/query", data=text.encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(f"{base}{path}", timeout=10) as r:
        return r.read()


class TestWebUI:
    def test_page_served(self, server):
        base, _ = server
        page = get(base, "/").decode()
        assert "svoc" in page and "drawScatter" in page

    def test_query_endpoint_runs_commands(self, server):
        base, _ = server
        assert post(base, "dimension") == ["Dimension: 6"]
        out = post(base, "fetch")
        assert any("fetched 30 comments" in line for line in out)
        assert post(base, "commit")[-1] == "Done (7 transactions)."

    def test_state_endpoint_reflects_session(self, server):
        base, _ = server
        state = json.loads(get(base, "/api/state"))
        assert state["preview"] is None
        post(base, "fetch")
        post(base, "commit")
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["consensus_active"] is True
        assert len(state["preview"]["values"]) == 7
        assert 0 < state["reliability_second_pass"] <= 1
        # trajectory surface (ALGORITHM.md §5): resume fed the history
        assert state["rel2_history"]
        assert state["rel2_falling"] is False

    def test_unknown_path_404(self, server):
        base, _ = server
        with pytest.raises(urllib.error.HTTPError):
            get(base, "/nope")

    def test_page_has_replacement_menu_and_labels(self, server):
        """The oracle-replacement modal (reference
        oracle_management.js:23-62) and the per-pair axis label names
        (oracle_scheduler.py:113-118) must be in the served page."""
        base, _ = server
        page = get(base, "/").decode()
        for element in (
            "replace-menu", "rp-admin", "rp-old", "rp-new",
            "vt-admin", "vt-which", "update_proposition",
            "vote_for_a_proposition",
        ):
            assert element in page, f"missing {element}"
        assert "names[0]" in page  # axis name rendering in drawScatter

    def test_state_exposes_labels_and_chain_lists(self, server):
        base, _ = server
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["labels"][:2] == ["optimism", "anger"]
        assert len(state["admin_list"]) == 3
        assert len(state["oracle_list"]) == 7
        # Addresses rendered in hex like the reference's to_hex.
        assert all(a.startswith("0x") for a in state["admin_list"])
        assert len(state["replacement_propositions"]) == 3

    def test_replacement_flow_via_query_endpoint(self, server):
        """The modal's buttons issue console commands — drive the same
        commands and verify the address swap lands in /api/state."""
        base, _ = server
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert "0xbeef" not in state["oracle_list"]
        post(base, "update_proposition 0 3 0xbeef")
        post(base, "vote_for_a_proposition 1 0 yes")
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["oracle_list"][3] == "0xbeef"

    def test_cross_origin_post_rejected(self, server):
        """CSRF guard: a POST whose Origin names another host is
        rejected; same-origin and header-free clients pass."""
        base, _ = server
        req = urllib.request.Request(
            f"{base}/api/query",
            data=b"dimension",
            method="POST",
            headers={"Origin": "http://evil.example"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 403

        host = base.split("://", 1)[1]
        req = urllib.request.Request(
            f"{base}/api/query",
            data=b"dimension",
            method="POST",
            headers={"Origin": f"http://{host}"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read()) == ["Dimension: 6"]

    def test_dns_rebinding_host_rejected(self, server):
        """Origin == Host is not enough: a rebound domain sends a
        matching pair naming the attacker's host — the Host header must
        itself be loopback/the bound address."""
        base, _ = server
        port = base.rsplit(":", 1)[1]
        req = urllib.request.Request(
            f"{base}/api/query",
            data=b"dimension",
            method="POST",
            headers={
                "Origin": f"http://evil.example:{port}",
                "Host": f"evil.example:{port}",
            },
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 403

    def test_non_loopback_bind_warns(self):
        console = CommandConsole(make_session())
        with pytest.warns(UserWarning, match="non-loopback"):
            srv, _ = serve(console, host="0.0.0.0", port=0, block=False)
        srv.shutdown()


class TestLiveRefresh:
    def test_state_surfaces_without_a_command(self, server):
        """Round-3 VERDICT item 8: with auto_fetch driving the session in
        the background, /api/state must surface the new preview and a
        bumped state_version WITHOUT any /api/query call — the page's
        poll loop (setInterval in the HTML) redraws on version change."""
        base, console = server
        s0 = json.loads(get(base, "/api/state"))
        assert s0["preview"] is None and s0["state_version"] == 0

        # background activity: what the auto_fetch thread does, no
        # command goes through the query endpoint
        console.session.fetch()
        s1 = json.loads(get(base, "/api/state"))
        assert s1["state_version"] == 1
        assert s1["preview"] is not None
        assert len(s1["preview"]["values"]) == 7

        console.session.fetch()
        s2 = json.loads(get(base, "/api/state"))
        assert s2["state_version"] == 2

    def test_page_has_poll_loop(self, server):
        base, _ = server
        page = get(base, "/").decode()
        assert "setInterval" in page
        assert "state_version" in page

    def test_state_reports_auto_fetch_flag(self, server):
        base, console = server
        assert json.loads(get(base, "/api/state"))["auto_fetch"] is False
        console.session.auto_fetch = True
        assert json.loads(get(base, "/api/state"))["auto_fetch"] is True

    def test_events_stream_pushes_state_changes(self, server):
        """/api/events is the push channel (eel-websocket parity): the
        current version arrives immediately, and a session change pushes
        a new frame without the client asking."""
        base, console = server
        with urllib.request.urlopen(f"{base}/api/events", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")

            def next_frame():
                while True:
                    line = r.readline().decode()
                    if line.startswith("data: "):
                        return json.loads(line[6:])

            first = next_frame()
            v0 = first["state_version"]
            console.session.fetch()  # state change -> push
            assert next_frame()["state_version"] == v0 + 1

    def test_page_is_push_first_with_poll_fallback(self, server):
        base, _ = server
        page = get(base, "/").decode()
        assert "EventSource('/api/events')" in page
        assert "pushAlive" in page  # poll loop gated off while push is up
