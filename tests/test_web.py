"""Web UI server: page, query endpoint, state endpoint."""

import json
import urllib.request

import pytest

from svoc_tpu.apps.commands import CommandConsole
from svoc_tpu.apps.web import serve
from tests.test_apps import make_session


@pytest.fixture()
def server():
    console = CommandConsole(make_session())
    srv, thread = serve(console, port=0, block=False)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, console
    srv.shutdown()


def post(base, text):
    req = urllib.request.Request(
        f"{base}/api/query", data=text.encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(f"{base}{path}", timeout=10) as r:
        return r.read()


class TestWebUI:
    def test_page_served(self, server):
        base, _ = server
        page = get(base, "/").decode()
        assert "svoc" in page and "drawScatter" in page

    def test_query_endpoint_runs_commands(self, server):
        base, _ = server
        assert post(base, "dimension") == ["Dimension: 6"]
        out = post(base, "fetch")
        assert any("fetched 30 comments" in line for line in out)
        assert post(base, "commit")[-1] == "Done (7 transactions)."

    def test_state_endpoint_reflects_session(self, server):
        base, _ = server
        state = json.loads(get(base, "/api/state"))
        assert state["preview"] is None
        post(base, "fetch")
        post(base, "commit")
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["consensus_active"] is True
        assert len(state["preview"]["values"]) == 7
        assert 0 < state["reliability_second_pass"] <= 1

    def test_unknown_path_404(self, server):
        base, _ = server
        with pytest.raises(urllib.error.HTTPError):
            get(base, "/nope")
