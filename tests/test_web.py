"""Web UI server: page, query endpoint, state endpoint."""

import json
import urllib.request

import pytest

from svoc_tpu.apps.commands import CommandConsole
from svoc_tpu.apps.web import serve
from tests.test_apps import make_session


@pytest.fixture()
def server():
    console = CommandConsole(make_session())
    srv, thread = serve(console, port=0, block=False)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, console
    srv.shutdown()


def post(base, text):
    req = urllib.request.Request(
        f"{base}/api/query", data=text.encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(f"{base}{path}", timeout=10) as r:
        return r.read()


class TestWebUI:
    def test_page_served(self, server):
        base, _ = server
        page = get(base, "/").decode()
        assert "svoc" in page and "drawScatter" in page

    def test_query_endpoint_runs_commands(self, server):
        base, _ = server
        assert post(base, "dimension") == ["Dimension: 6"]
        out = post(base, "fetch")
        assert any("fetched 30 comments" in line for line in out)
        assert post(base, "commit")[-1] == "Done (7 transactions)."

    def test_state_endpoint_reflects_session(self, server):
        base, _ = server
        state = json.loads(get(base, "/api/state"))
        assert state["preview"] is None
        post(base, "fetch")
        post(base, "commit")
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["consensus_active"] is True
        assert len(state["preview"]["values"]) == 7
        assert 0 < state["reliability_second_pass"] <= 1
        # trajectory surface (ALGORITHM.md §5): resume fed the history
        assert state["rel2_history"]
        assert state["rel2_falling"] is False

    def test_unknown_path_404(self, server):
        base, _ = server
        with pytest.raises(urllib.error.HTTPError):
            get(base, "/nope")

    def test_page_has_replacement_menu_and_labels(self, server):
        """The oracle-replacement modal (reference
        oracle_management.js:23-62) and the per-pair axis label names
        (oracle_scheduler.py:113-118) must be in the served page."""
        base, _ = server
        page = get(base, "/").decode()
        for element in (
            "replace-menu", "rp-admin", "rp-old", "rp-new",
            "vt-admin", "vt-which", "update_proposition",
            "vote_for_a_proposition",
        ):
            assert element in page, f"missing {element}"
        assert "names[0]" in page  # axis name rendering in drawScatter

    def test_state_exposes_labels_and_chain_lists(self, server):
        base, _ = server
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["labels"][:2] == ["optimism", "anger"]
        assert len(state["admin_list"]) == 3
        assert len(state["oracle_list"]) == 7
        # Addresses rendered in hex like the reference's to_hex.
        assert all(a.startswith("0x") for a in state["admin_list"])
        assert len(state["replacement_propositions"]) == 3

    def test_replacement_flow_via_query_endpoint(self, server):
        """The modal's buttons issue console commands — drive the same
        commands and verify the address swap lands in /api/state."""
        base, _ = server
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert "0xbeef" not in state["oracle_list"]
        post(base, "update_proposition 0 3 0xbeef")
        post(base, "vote_for_a_proposition 1 0 yes")
        post(base, "resume")
        state = json.loads(get(base, "/api/state"))
        assert state["oracle_list"][3] == "0xbeef"

    def test_cross_origin_post_rejected(self, server):
        """CSRF guard: a POST whose Origin names another host is
        rejected; same-origin and header-free clients pass."""
        base, _ = server
        req = urllib.request.Request(
            f"{base}/api/query",
            data=b"dimension",
            method="POST",
            headers={"Origin": "http://evil.example"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 403

        host = base.split("://", 1)[1]
        req = urllib.request.Request(
            f"{base}/api/query",
            data=b"dimension",
            method="POST",
            headers={"Origin": f"http://{host}"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read()) == ["Dimension: 6"]

    def test_dns_rebinding_host_rejected(self, server):
        """Origin == Host is not enough: a rebound domain sends a
        matching pair naming the attacker's host — the Host header must
        itself be loopback/the bound address."""
        base, _ = server
        port = base.rsplit(":", 1)[1]
        req = urllib.request.Request(
            f"{base}/api/query",
            data=b"dimension",
            method="POST",
            headers={
                "Origin": f"http://evil.example:{port}",
                "Host": f"evil.example:{port}",
            },
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 403

    def test_non_loopback_bind_warns(self):
        console = CommandConsole(make_session())
        with pytest.warns(UserWarning, match="non-loopback"):
            srv, _ = serve(console, host="0.0.0.0", port=0, block=False)
        srv.shutdown()


class TestLiveRefresh:
    def test_state_surfaces_without_a_command(self, server):
        """Round-3 VERDICT item 8: with auto_fetch driving the session in
        the background, /api/state must surface the new preview and a
        bumped state_version WITHOUT any /api/query call — the page's
        poll loop (setInterval in the HTML) redraws on version change."""
        base, console = server
        s0 = json.loads(get(base, "/api/state"))
        assert s0["preview"] is None and s0["state_version"] == 0

        # background activity: what the auto_fetch thread does, no
        # command goes through the query endpoint
        console.session.fetch()
        s1 = json.loads(get(base, "/api/state"))
        assert s1["state_version"] == 1
        assert s1["preview"] is not None
        assert len(s1["preview"]["values"]) == 7

        console.session.fetch()
        s2 = json.loads(get(base, "/api/state"))
        assert s2["state_version"] == 2

    def test_page_has_poll_loop(self, server):
        base, _ = server
        page = get(base, "/").decode()
        assert "setInterval" in page
        assert "state_version" in page

    def test_state_reports_resilience_and_auto_flags(self, server):
        """The resilience surface (ISSUE 3): all three auto flags, the
        breaker state, and fleet-health live in /api/state, and the
        page renders the status line from them."""
        base, console = server
        state = json.loads(get(base, "/api/state"))
        assert state["auto_commit"] is False
        assert state["auto_resume"] is False
        assert state["resilience"]["breaker"] == "closed"
        assert state["resilience"]["replacements"] == 0
        assert state["resilience"]["quarantined"] == []
        v0 = state["state_version"]
        # toggling a flag is a LIVE state change (bumps state_version)
        post(base, "auto_commit on")
        state = json.loads(get(base, "/api/state"))
        assert state["auto_commit"] is True
        assert state["state_version"] > v0
        page = get(base, "/").decode()
        assert "resil" in page and "breaker" in page

    def test_metrics_exposes_breaker_gauge(self, server):
        """circuit_breaker_state exists from session start — before any
        incident (acceptance: breaker state in GET /metrics)."""
        base, _ = server
        text = get(base, "/metrics").decode()
        assert 'svoc_circuit_breaker_state{backend="chain"} 0' in text

    def test_state_reports_auto_fetch_flag(self, server):
        base, console = server
        assert json.loads(get(base, "/api/state"))["auto_fetch"] is False
        console.session.auto_fetch = True
        assert json.loads(get(base, "/api/state"))["auto_fetch"] is True

    def test_state_carries_claims_when_fabric_attached(self, server):
        """Multi-claim mode (docs/FABRIC.md): /api/state grows a
        ``claims`` section — per-claim consensus slice, commit outcome,
        and block lineage — once a MultiSession is attached; the
        single-claim payload has no such key."""
        base, console = server
        assert "claims" not in json.loads(get(base, "/api/state"))
        from svoc_tpu.fabric.registry import ClaimSpec
        from svoc_tpu.fabric.scenario import deterministic_vectorizer
        from svoc_tpu.fabric.session import MultiSession
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.io.scraper import SyntheticSource

        def store_factory(claim_id):
            store = CommentStore()
            store.save(SyntheticSource(batch=80)())
            return store

        multi = MultiSession(
            vectorizer=deterministic_vectorizer,
            store_factory=store_factory,
            lineage_scope="w",
        )
        multi.add_claim(ClaimSpec(claim_id="alpha"))
        multi.add_claim(ClaimSpec(claim_id="beta"))
        multi.step()
        multi.attach(console)
        claims = json.loads(get(base, "/api/state"))["claims"]
        assert sorted(claims) == ["alpha", "beta"]
        for claim_id, c in claims.items():
            assert c["consensus"]["interval_valid"] is True
            assert c["lineage"].startswith(f"blkw-{claim_id}-")

    def test_events_stream_pushes_state_changes(self, server):
        """/api/events is the push channel (eel-websocket parity): the
        current version arrives immediately, and a session change pushes
        a new frame without the client asking."""
        base, console = server
        with urllib.request.urlopen(f"{base}/api/events", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")

            def next_frame():
                while True:
                    line = r.readline().decode()
                    if line.startswith("data: "):
                        return json.loads(line[6:])

            first = next_frame()
            v0 = first["state_version"]
            console.session.fetch()  # state change -> push
            assert next_frame()["state_version"] == v0 + 1

    def test_page_is_push_first_with_poll_fallback(self, server):
        base, _ = server
        page = get(base, "/").decode()
        # The page opts into the flight recorder's typed frames (PR 5
        # gotcha closed by the fabric PR): named 'journal' frames land
        # in their own listener, unnamed state_version frames drive the
        # redraw loop unchanged.
        assert "EventSource('/api/events?journal=1')" in page
        assert "addEventListener('journal'" in page
        assert "pushAlive" in page  # poll loop gated off while push is up

    def test_page_catch_up_loop_paces_and_resets_on_reconnect(self, server):
        """The SSE catch-up loop must not busy-spin: a successful
        refresh that still trails the pushed target sleeps before the
        next /api/state fetch, and a reconnect resets the stale pushed
        version from the previous server process."""
        base, _ = server
        page = get(base, "/").decode()
        assert "pushedVersion = null; };" in page  # onopen/onerror reset
        assert "setTimeout(res, 250)" in page  # pacing between fetches

    def test_sse_streams_capped(self, server):
        """Beyond MAX_SSE_STREAMS concurrent /api/events connections the
        server answers 503 + Retry-After instead of parking one handler
        thread per abandoned tab; closing a stream frees its slot."""
        import urllib.error

        from svoc_tpu.apps.web import _Handler

        base, console = server
        streams = []
        try:
            for _ in range(_Handler.MAX_SSE_STREAMS):
                streams.append(
                    urllib.request.urlopen(f"{base}/api/events", timeout=10)
                )
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(f"{base}/api/events", timeout=10)
            assert exc_info.value.code == 503
            assert exc_info.value.headers["Retry-After"]
            # Non-SSE endpoints still have threads to serve them.
            assert json.loads(get(base, "/api/state"))["state_version"] == 0
        finally:
            for s in streams:
                s.close()
        # Released slots admit new streams.  A dead socket is only
        # observed when the handler next WRITES — bump the state each
        # poll so the push loops write immediately instead of idling
        # until the 15 s keepalive.
        import time

        deadline = time.time() + 15
        while time.time() < deadline:
            console.session.bump_state()
            try:
                with urllib.request.urlopen(
                    f"{base}/api/events", timeout=10
                ) as r:
                    assert r.status == 200
                break
            except urllib.error.HTTPError:
                time.sleep(0.3)
        else:
            pytest.fail("SSE slot never freed after client disconnect")


class TestMetricsEndpoint:
    def test_metrics_scrape_returns_prometheus_text(self, server):
        base, _ = server
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        # exposition parses line-wise: comments or name{labels} value
        for line in text.strip().splitlines():
            assert line.startswith("#") or " " in line

    def test_metrics_surface_session_stages(self, server):
        """After a fetch + commit the scrape must expose the fleet /
        consensus / commit stage histograms the session's spans feed
        (bucket series from which p50/p95/p99 are derivable) and the
        fetch/commit counters-of-record.  The registry is process-wide,
        so the assertion is on the DELTA between two scrapes."""

        def stage_counts(text):
            out = {}
            for line in text.splitlines():
                if line.startswith("svoc_stage_seconds_count{stage="):
                    stage = line.split('stage="', 1)[1].split('"', 1)[0]
                    out[stage] = int(line.rsplit(" ", 1)[1])
            return out

        base, _ = server
        before = stage_counts(get(base, "/metrics").decode())
        post(base, "fetch")
        post(base, "commit")
        text = get(base, "/metrics").decode()
        assert "# TYPE svoc_stage_seconds histogram" in text
        after = stage_counts(text)
        for stage in ("fetch", "vectorize", "fleet", "consensus", "commit"):
            assert after.get(stage, 0) == before.get(stage, 0) + 1, stage
            assert f'svoc_stage_seconds_bucket{{stage="{stage}",le="+Inf"}}' in text
        assert "svoc_comments_processed_total" in text
        assert "svoc_chain_transactions_total" in text
        assert "svoc_fetch_latency_seconds_count" in text

    @pytest.mark.slow  # tiny-but-real encoder: ~8 s of XLA compiles;
    # the tier-1 budget is razor-thin and the cheap twin below covers
    # the span/scrape plumbing on every run
    def test_end_to_end_stage_observability(self, tmp_path, monkeypatch):
        """The acceptance path: one serving step through a REAL (tiny)
        sentiment pipeline must (a) expose tokenize / forward / fleet /
        consensus / commit stage histograms on /metrics, and (b) with
        SVOC_TRACE_FILE set, write parseable JSONL spans covering every
        stage of the run, nested under the fetch span.  (The unpacked
        forward keeps the tier-1 wall clock affordable — the pack span
        rides the same stage_span code path and is exercised by the
        packed-pipeline tests in test_apps.)"""
        from svoc_tpu.apps.session import Session, SessionConfig
        from svoc_tpu.io.comment_store import CommentStore
        from svoc_tpu.io.scraper import SyntheticSource
        from svoc_tpu.models.configs import TINY_TEST
        from svoc_tpu.models.sentiment import SentimentPipeline
        from svoc_tpu.utils.metrics import registry, tracer

        trace_path = tmp_path / "trace.jsonl"
        monkeypatch.setenv("SVOC_TRACE_FILE", str(trace_path))
        store = CommentStore()
        store.save(SyntheticSource(batch=60)())
        session = Session(
            # Smallest real pipeline that still exercises every stage:
            # tiny encoder, short rows, 10-comment window — the span
            # coverage is shape-independent and tier-1 wall clock is
            # razor-thin (the suite budget is 870 s on a 2-core box).
            config=SessionConfig(window=10, fetch_limit=10),
            store=store,
            vectorizer=SentimentPipeline(
                cfg=TINY_TEST,
                seq_len=16,
                batch_size=16,
                tokenizer_name=None,
            ),
        )
        console = CommandConsole(session)
        srv, _ = serve(console, port=0, block=False)
        try:
            base = f"http://127.0.0.1:{srv.server_address[1]}"
            before = registry.stage_snapshot()
            post(base, "fetch")
            post(base, "commit")
            tracer.flush()
            text = get(base, "/metrics").decode()
            after = registry.stage_snapshot()
            stages = ("tokenize", "forward", "fleet", "consensus",
                      "commit", "fetch")
            for stage in stages:
                grew = after.get(stage, {}).get("count", 0) > before.get(
                    stage, {}
                ).get("count", 0)
                assert grew, f"stage {stage} not observed"
                assert f'svoc_stage_seconds_count{{stage="{stage}"}}' in text
                # p50 <= p95 <= p99 derivable from the scraped buckets
                snap = after[stage]
                assert snap["p50"] <= snap["p95"] <= snap["p99"]
            records = [
                rec
                for rec in (
                    json.loads(line)
                    for line in trace_path.read_text().strip().splitlines()
                )
                # PR 5: the flight-recorder file interleaves event
                # lines — spans are the ones keyed by `name`.
                if "name" in rec
            ]
            by_name = {}
            for rec in records:
                by_name.setdefault(rec["name"], rec)
            for stage in stages:
                assert stage in by_name, f"no JSONL span for {stage}"
            # nesting: tokenize ran inside vectorize inside fetch
            ids = {rec["span_id"]: rec for rec in records}
            tok = by_name["tokenize"]
            assert tok["parent_id"] is not None
            assert ids[tok["parent_id"]]["name"] == "vectorize"
            assert ids[ids[tok["parent_id"]]["parent_id"]]["name"] == "fetch"
        finally:
            srv.shutdown()

    def test_trace_jsonl_covers_session_stages(self, tmp_path, monkeypatch):
        """Cheap twin of the slow end-to-end test: a fetch+commit with
        SVOC_TRACE_FILE set writes parseable JSONL spans for every
        session stage, with vectorize nested under fetch."""
        from svoc_tpu.utils.metrics import tracer
        from tests.test_apps import make_session

        trace_path = tmp_path / "trace.jsonl"
        monkeypatch.setenv("SVOC_TRACE_FILE", str(trace_path))
        session = make_session()
        session.fetch()
        session.commit()
        tracer.flush()
        lines = [
            json.loads(line)
            for line in trace_path.read_text().strip().splitlines()
        ]
        # The flight-recorder file interleaves span lines (`name`) with
        # event lines (`event`) since PR 5 — both must parse; spans are
        # the subject here.
        records = [rec for rec in lines if "name" in rec]
        events = [rec for rec in lines if "event" in rec]
        assert any(e["event"] == "block.fetched" for e in events)
        names = {rec["name"] for rec in records}
        for stage in ("fetch", "vectorize", "fleet", "consensus", "commit"):
            assert stage in names, f"no JSONL span for {stage}"
        ids = {rec["span_id"]: rec for rec in records}
        vec = next(rec for rec in records if rec["name"] == "vectorize")
        assert ids[vec["parent_id"]]["name"] == "fetch"
        # lineage joins spans to the block's events
        assert vec["lineage"] == session.last_lineage

    def test_metrics_command_matches_endpoint(self, server):
        """The console's `metrics prom` dump and the /metrics scrape are
        the same exposition — live telemetry and the command surface
        can never disagree."""
        base, console = server
        post(base, "fetch")
        endpoint = get(base, "/metrics").decode()
        command = "\n".join(console.query("metrics prom")) + "\n"
        # Histogram/counter structure matches (rates/gauges resample
        # between the two calls; compare the stable series lines).
        for line in endpoint.splitlines():
            if line.startswith("svoc_stage_seconds_bucket"):
                assert line in command
