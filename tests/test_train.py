"""Fine-tune step: loss decreases; sharded == unsharded."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.parallel.mesh import MeshSpec, make_mesh
from svoc_tpu.train.trainer import (
    Batch,
    init_state,
    make_sharded_train_step,
    make_train_step,
)


def _toy_batch(key, b=8, t=16, n_labels=TINY_TEST.n_labels):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (b, t), 0, TINY_TEST.vocab_size)
    mask = jnp.ones((b, t), jnp.int32)
    labels = jax.random.bernoulli(k2, 0.2, (b, n_labels)).astype(jnp.float32)
    return Batch(ids=ids, mask=mask, labels=labels)


def test_train_step_reduces_loss():
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    tx = optax.adam(1e-3)
    state = init_state(model, params, tx)
    step = make_train_step(model, tx)
    batch = _toy_batch(jax.random.PRNGKey(0))
    losses = []
    for _ in range(20):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]
    assert int(state.step) == 20


def test_sharded_train_step_matches_unsharded():
    # SGD: updates are linear in the gradient, so cross-sharding
    # reduction-order noise stays at float-noise scale (adam's
    # grad/sqrt(v) normalization would amplify near-zero grads).
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    tx = optax.sgd(0.1)
    batch = _toy_batch(jax.random.PRNGKey(1))

    ref_state = init_state(model, params, tx)
    ref_step = make_train_step(model, tx)
    for _ in range(3):
        ref_state, ref_metrics = ref_step(ref_state, batch)

    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    step, shard_state, _ = make_sharded_train_step(
        model, tx, mesh, params_template=params
    )
    state = shard_state(init_state(model, params, tx))
    for _ in range(3):
        state, metrics = step(state, batch)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4
    )
    leaves_a = jax.tree_util.tree_leaves(state.params)
    leaves_b = jax.tree_util.tree_leaves(ref_state.params)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_zero1_matches_unsharded_and_shards_opt_state():
    """ZeRO-1 optimizer-state sharding (arXiv:2004.13336): the
    trajectory matches the unsharded step, and the at-rest moment
    leaves are physically 1/D per data replica.

    SGD+momentum, for the same reason as the plain sharded-parity test
    above: the momentum buffer is linear in the gradient (so
    cross-sharding reduction-order noise stays at float scale) while
    still giving a full non-scalar optimizer state tree to shard."""
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    tx = optax.sgd(0.1, momentum=0.9)
    batch = _toy_batch(jax.random.PRNGKey(1))

    ref_state = init_state(model, params, tx)
    ref_step = make_train_step(model, tx)
    for _ in range(3):
        ref_state, ref_metrics = ref_step(ref_state, batch)

    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    step, shard_state, _ = make_sharded_train_step(
        model, tx, mesh, params_template=params, zero1=True
    )
    state = shard_state(init_state(model, params, tx))
    for _ in range(3):
        state, metrics = step(state, batch)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)

    # at-rest memory: every non-scalar momentum leaf shards over "data"
    # — its largest addressable shard holds at most 1/4 of the elements
    # (modulo a dimension the leaf cannot split).
    from svoc_tpu.train.trainer import max_shard_fraction

    mu = state.opt_state[0].trace
    sharded = 0
    for leaf in jax.tree_util.tree_leaves(mu):
        if leaf.ndim == 0:
            continue
        frac = max_shard_fraction(leaf)
        if frac <= 0.25 + 1e-9:
            sharded += 1
        spec = leaf.sharding.spec
        assert "data" in tuple(spec) or frac == 1.0, (spec, frac)
    assert sharded >= 1  # the big kernels must actually shard


def test_zero1_packed_step_runs_and_shards():
    """The packed twin accepts zero1 too (shared factory wiring)."""
    from svoc_tpu.models.packing import pack_tokens_auto
    from svoc_tpu.train.trainer import (
        PackedTrainBatch,
        make_sharded_packed_train_step,
    )

    cfg = TINY_TEST
    model = SentimentEncoder(cfg)
    params = init_params(model)
    tx = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    step, shard_state, bshard = make_sharded_packed_train_step(
        cfg, tx, mesh, params_template=params, zero1=True
    )

    rng = np.random.default_rng(0)
    toks = [
        np.arange(4, 4 + L, dtype=np.int32) for L in rng.integers(3, 8, 64)
    ]
    packed, _ = pack_tokens_auto(toks, 16, 4, pad_id=1, rows=8)
    labels = (rng.random((8, 4, cfg.n_labels)) < 0.3).astype(np.float32)
    batch = jax.device_put(
        PackedTrainBatch(
            ids=jnp.asarray(packed.ids),
            pos=jnp.asarray(packed.pos),
            seg=jnp.asarray(packed.seg),
            cls_pos=jnp.asarray(packed.cls_pos),
            seg_valid=jnp.asarray(packed.seg_valid),
            labels=jnp.asarray(labels),
        ),
        bshard,
    )
    state = shard_state(init_state(model, params, tx))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    from svoc_tpu.train.trainer import max_shard_fraction

    trace_leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(state.opt_state[0].trace)
        if leaf.ndim > 0
    ]
    assert any(
        max_shard_fraction(leaf) <= 0.25 + 1e-9 for leaf in trace_leaves
    )


@pytest.mark.slow  # heavyweight trainer parity (VERDICT r5 item 6); tier-1 keeps the basic loss-reduction + sharded-parity steps
def test_flash_train_step_matches_dense():
    """attention='flash' now trains (FlashAttention-2 custom VJP):
    gradients through the flash encoder must match the dense encoder's
    to float tolerance on the same batch."""
    import dataclasses

    from svoc_tpu.train.trainer import _loss_fn

    dense_cfg = dataclasses.replace(TINY_TEST, max_len=32)
    flash_cfg = dataclasses.replace(dense_cfg, attention="flash")
    dense_model = SentimentEncoder(dense_cfg)
    flash_model = SentimentEncoder(flash_cfg)
    params = init_params(dense_model, seed=0)

    rng = np.random.default_rng(2)
    b, t = 4, 16
    ids = jnp.asarray(rng.integers(4, 1000, (b, t)), jnp.int32)
    mask = jnp.asarray((np.arange(t)[None, :] < rng.integers(6, t + 1, (b, 1))), jnp.int32)
    labels = jnp.asarray((rng.random((b, dense_cfg.n_labels)) < 0.3), jnp.float32)
    from svoc_tpu.train.trainer import Batch

    batch = Batch(ids=ids, mask=mask, labels=labels)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: _loss_fn(dense_model, p, batch)
    )(params)
    loss, grads = jax.value_and_grad(
        lambda p: _loss_fn(flash_model, p, batch)
    )(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b_ in zip(
        jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref_grads)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-3, atol=2e-5
        )


def _packed_pair(n_texts=12, seq=24, seed=5):
    """Matching (unpacked Batch, PackedTrainBatch) over the same texts
    and labels."""
    from svoc_tpu.models.packing import pack_labels, pack_tokens, strip_padding
    from svoc_tpu.models.tokenizer import HashingTokenizer
    from svoc_tpu.train.trainer import Batch, PackedTrainBatch

    cfg = TINY_TEST
    tok = HashingTokenizer(cfg.vocab_size, pad_id=cfg.pad_id, max_len=seq)
    rng = np.random.default_rng(seed)
    texts = [
        " ".join(rng.choice(["aa", "bb", "cc", "dd"], size=int(rng.integers(2, 8))))
        for _ in range(n_texts)
    ]
    ids, mask = tok(texts, seq)
    labels = (rng.random((n_texts, cfg.n_labels)) < 0.3).astype(np.float32)
    batch = Batch(
        ids=jnp.asarray(ids), mask=jnp.asarray(mask), labels=jnp.asarray(labels)
    )
    pk, n = pack_tokens(strip_padding(ids, mask), seq, 4, pad_id=cfg.pad_id)
    assert n == n_texts
    packed = PackedTrainBatch(
        ids=jnp.asarray(pk.ids),
        pos=jnp.asarray(pk.pos),
        seg=jnp.asarray(pk.seg),
        cls_pos=jnp.asarray(pk.cls_pos),
        seg_valid=jnp.asarray(pk.seg_valid),
        labels=jnp.asarray(pack_labels(pk, labels)),
    )
    return cfg, batch, packed


@pytest.mark.slow  # heavyweight trainer parity (VERDICT r5 item 6); tier-1 keeps the basic loss-reduction + sharded-parity steps
def test_packed_train_step_matches_unpacked():
    """A packed update must equal an unpacked update on the same
    comments+labels: the masked segment-mean loss IS the batch mean.

    Gradients are compared directly, and the optimizer step uses SGD
    (linear in the gradient) — one-step Adam equality is ill-
    conditioned: coordinates whose true gradient is ~0 get float-noise
    signs that Adam amplifies to ±lr."""
    from svoc_tpu.models.packing import PackedSentimentEncoder
    from svoc_tpu.train.trainer import (
        _loss_fn,
        _packed_loss_fn,
        make_packed_train_step,
    )

    cfg, batch, packed = _packed_pair()
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: _loss_fn(model, p, batch)
    )(params)
    loss, grads = jax.value_and_grad(
        lambda p: _packed_loss_fn(PackedSentimentEncoder(cfg), p, packed)
    )(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref_grads)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )

    tx = optax.sgd(0.1)
    ref_state, _ = make_train_step(model, tx)(init_state(model, params, tx), batch)
    state, _ = make_packed_train_step(cfg, tx)(
        init_state(model, params, tx), packed
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow  # heavyweight trainer parity (VERDICT r5 item 6); tier-1 keeps the basic loss-reduction + sharded-parity steps
def test_sharded_packed_train_step_matches_unsharded():
    from svoc_tpu.train.trainer import (
        make_packed_train_step,
        make_sharded_packed_train_step,
    )

    cfg, batch, packed = _packed_pair(n_texts=16)
    # pad rows to the 8-device mesh (repeat last row, zero validity)
    rows = packed.ids.shape[0]
    pad_to = -(-rows // 8) * 8
    if pad_to != rows:
        k = pad_to - rows

        def padrow(a, zero=False):
            tail = jnp.repeat(a[-1:], k, axis=0)
            if zero:
                tail = jnp.zeros_like(tail)
            return jnp.concatenate([a, tail], axis=0)

        from svoc_tpu.train.trainer import PackedTrainBatch

        packed = PackedTrainBatch(
            ids=padrow(packed.ids),
            pos=padrow(packed.pos),
            seg=padrow(packed.seg, zero=True),
            cls_pos=padrow(packed.cls_pos, zero=True),
            seg_valid=padrow(packed.seg_valid, zero=True),
            labels=padrow(packed.labels, zero=True),
        )
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    # SGD: linear in the gradient, so sharded-reduction float noise
    # stays at float scale instead of being amplified to ±lr by Adam.
    tx = optax.sgd(0.1)

    ref_state, ref_metrics = make_packed_train_step(cfg, tx)(
        init_state(model, params, tx), packed
    )
    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    step, shard_state, bshard = make_sharded_packed_train_step(
        cfg, tx, mesh, params_template=params
    )
    sbatch = jax.device_put(packed, bshard)
    state, metrics = step(shard_state(init_state(model, params, tx)), sbatch)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_packed_trainer_rejects_unknown_attention():
    import dataclasses

    import pytest

    from svoc_tpu.train.trainer import make_packed_train_step

    with pytest.raises(ValueError, match="dense"):
        make_packed_train_step(
            dataclasses.replace(TINY_TEST, attention="ring"), optax.adamw(1e-4)
        )


@pytest.mark.slow  # heavyweight trainer parity (VERDICT r5 item 6); tier-1 keeps the basic loss-reduction + sharded-parity steps
def test_sharded_flash_train_step_matches_unsharded():
    """attention='flash' trains SHARDED too: the flash VJP under GSPMD
    data x model shardings must match the unsharded step (the round-3
    'GSPMD flash hangs' diagnosis was a dead-TPU backend-init hang, not
    a real limitation)."""
    import dataclasses

    cfg = dataclasses.replace(TINY_TEST, max_len=32, attention="flash")
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    b, t = 8, 16
    batch = Batch(
        ids=jnp.asarray(rng.integers(4, 1000, (b, t)), jnp.int32),
        mask=jnp.ones((b, t), jnp.int32),
        labels=jnp.asarray((rng.random((b, cfg.n_labels)) < 0.3), jnp.float32),
    )
    ref_state, _ = make_train_step(model, tx)(init_state(model, params, tx), batch)
    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    step, shard_state, bshard = make_sharded_train_step(
        model, tx, mesh, params_template=params
    )
    state, _ = step(
        shard_state(init_state(model, params, tx)), jax.device_put(batch, bshard)
    )
    for a, b_ in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)


@pytest.mark.slow  # heavyweight trainer parity (VERDICT r5 item 6); tier-1 keeps the basic loss-reduction + sharded-parity steps
def test_sp_train_step_matches_dense():
    """Long-context sequence-parallel fine-tuning: one SP train step
    (ring-attention custom VJP over the 8-way seq mesh) must match the
    plain single-device step on the same batch."""
    from svoc_tpu.train.trainer import make_sp_train_step

    cfg = TINY_TEST
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(1)
    b, t = 2, 64  # T sharded 8 ways
    batch = Batch(
        ids=jnp.asarray(rng.integers(4, cfg.vocab_size, (b, t)), jnp.int32),
        mask=jnp.ones((b, t), jnp.int32),
        labels=jnp.asarray((rng.random((b, cfg.n_labels)) < 0.3), jnp.float32),
    )
    ref_state, ref_metrics = make_train_step(model, tx)(
        init_state(model, params, tx), batch
    )
    mesh = make_mesh(MeshSpec(("seq",), (8,)))
    step = make_sp_train_step(cfg, tx, mesh)
    state, metrics = step(init_state(model, params, tx), batch)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-4
    )
    for a, b_ in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5)


def test_sp_trainer_rejects_flash():
    import dataclasses

    import pytest

    from svoc_tpu.train.trainer import make_sp_train_step

    mesh = make_mesh(MeshSpec(("seq",), (8,)))
    with pytest.raises(ValueError, match="dense"):
        make_sp_train_step(
            dataclasses.replace(TINY_TEST, attention="flash"), optax.sgd(0.1), mesh
        )


def test_packed_flash_train_step_matches_unpacked():
    """packed × flash fine-tuning: the segment-tag kernel's custom VJP
    must deliver the same loss and gradients as the unpacked dense
    reference on the same comments+labels."""
    from dataclasses import replace

    from svoc_tpu.models.packing import PackedSentimentEncoder
    from svoc_tpu.train.trainer import _loss_fn, _packed_loss_fn

    cfg, batch, packed = _packed_pair()
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: _loss_fn(model, p, batch)
    )(params)
    flash_cfg = replace(cfg, attention="flash")
    loss, grads = jax.value_and_grad(
        lambda p: _packed_loss_fn(PackedSentimentEncoder(flash_cfg), p, packed)
    )(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref_grads)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        )
