"""Fine-tune step: loss decreases; sharded == unsharded."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.parallel.mesh import MeshSpec, make_mesh
from svoc_tpu.train.trainer import (
    Batch,
    init_state,
    make_sharded_train_step,
    make_train_step,
)


def _toy_batch(key, b=8, t=16, n_labels=TINY_TEST.n_labels):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (b, t), 0, TINY_TEST.vocab_size)
    mask = jnp.ones((b, t), jnp.int32)
    labels = jax.random.bernoulli(k2, 0.2, (b, n_labels)).astype(jnp.float32)
    return Batch(ids=ids, mask=mask, labels=labels)


def test_train_step_reduces_loss():
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    tx = optax.adam(1e-3)
    state = init_state(model, params, tx)
    step = make_train_step(model, tx)
    batch = _toy_batch(jax.random.PRNGKey(0))
    losses = []
    for _ in range(20):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]
    assert int(state.step) == 20


def test_sharded_train_step_matches_unsharded():
    # SGD: updates are linear in the gradient, so cross-sharding
    # reduction-order noise stays at float-noise scale (adam's
    # grad/sqrt(v) normalization would amplify near-zero grads).
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    tx = optax.sgd(0.1)
    batch = _toy_batch(jax.random.PRNGKey(1))

    ref_state = init_state(model, params, tx)
    ref_step = make_train_step(model, tx)
    for _ in range(3):
        ref_state, ref_metrics = ref_step(ref_state, batch)

    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    step, shard_state, _ = make_sharded_train_step(
        model, tx, mesh, params_template=params
    )
    state = shard_state(init_state(model, params, tx))
    for _ in range(3):
        state, metrics = step(state, batch)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4
    )
    leaves_a = jax.tree_util.tree_leaves(state.params)
    leaves_b = jax.tree_util.tree_leaves(ref_state.params)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_trainer_rejects_flash_attention():
    """The flash kernel is forward-only; BOTH trainer factories must
    fail with an actionable message instead of a deep tracing error."""
    import dataclasses

    import pytest

    cfg = dataclasses.replace(TINY_TEST, attention="flash")
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    with pytest.raises(ValueError, match="inference-only"):
        make_train_step(model, optax.adamw(1e-4))
    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    with pytest.raises(ValueError, match="inference-only"):
        make_sharded_train_step(
            model, optax.adamw(1e-4), mesh, params_template=params
        )
