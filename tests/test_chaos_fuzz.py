"""Deterministic fault-space fuzzer (ISSUE 14; docs/RESILIENCE.md
§fault-surface): the named registry, the seed-driven schedule explorer,
the invariant oracles, shrinking, and the committed regression corpus.

The subprocess tests here ride the deliberately jax-free durable-plane
child harness (~1 s per child) — tier-1 affordable; the full
fabric/serving kill matrix stays in ``make crash-smoke``.
"""

import dataclasses
import json
import os
import tempfile

import pytest

from svoc_tpu.durability import faultspace, fuzz
from svoc_tpu.durability.faultspace import (
    FaultController,
    FaultEvent,
    read_fired_log,
)
from svoc_tpu.resilience.faults import InjectedFault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_DIR = os.path.join(REPO, "tests", "fixtures", "chaos_corpus")
DOC = os.path.join(REPO, "docs", "RESILIENCE.md")

SURFACE = faultspace.load_surface()


# ---------------------------------------------------------------------------
# Registry + controller
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_surface_nonempty_and_sorted(self):
        names = list(SURFACE)
        assert names == sorted(names)
        assert len(names) >= 15

    def test_identical_redeclaration_is_idempotent(self):
        spec = SURFACE["wal.intent.pre_fsync"]
        assert (
            faultspace.declare(
                spec.name,
                owner=spec.owner,
                invariant=spec.invariant,
                actions=spec.actions,
                smokes=spec.smokes,
                modes=spec.modes,
                stage=spec.stage,
            )
            == spec.name
        )

    def test_conflicting_redeclaration_raises(self):
        spec = SURFACE["wal.intent.pre_fsync"]
        with pytest.raises(ValueError, match="different spec"):
            faultspace.declare(
                spec.name,
                owner=spec.owner,
                invariant="something else entirely",
                actions=spec.actions,
                smokes=spec.smokes,
            )

    def test_every_point_names_a_smoke(self):
        # The can't-silently-escape contract: a declared durable
        # boundary must name the harness that witnesses it.
        for name, spec in SURFACE.items():
            assert spec.smokes, f"{name} declares no reaching smoke"

    def test_every_owner_module_exists(self):
        for name, spec in SURFACE.items():
            assert os.path.exists(
                os.path.join(REPO, spec.owner)
            ), f"{name} owner {spec.owner} missing"

    def test_invalid_specs_rejected(self):
        with pytest.raises(ValueError):
            faultspace.FaultPointSpec(
                name="x", owner="y", invariant="z",
                actions=("explode",), smokes=("fuzz",),
            )
        with pytest.raises(ValueError):
            faultspace.FaultPointSpec(
                name="x", owner="y", invariant="z",
                actions=("kill",), smokes=("nope",),
            )
        with pytest.raises(ValueError):
            FaultEvent(point="p", nth=0)
        with pytest.raises(ValueError):
            FaultEvent(point="p", action="frobnicate")


class TestController:
    def _arm(self, events, tmp_path, die):
        ctl = FaultController(
            events,
            log_path=str(tmp_path / "fired.jsonl"),
            die=die,
        )
        faultspace.arm(ctl)
        return ctl

    def test_disarmed_fault_point_is_noop(self):
        assert not faultspace.armed()
        faultspace.fault_point("wal.intent.pre_fsync")  # no controller

    def test_nth_counting_and_kill(self, tmp_path):
        died = []
        ctl = self._arm(
            [FaultEvent(point="wal.intent.pre_fsync", nth=3,
                        action="kill")],
            tmp_path, die=lambda: died.append(True),
        )
        try:
            for _ in range(5):
                faultspace.fault_point("wal.intent.pre_fsync")
            # Fires exactly once, at the 3rd firing.
            assert died == [True]
            assert ctl.counts()["wal.intent.pre_fsync"] == 5
            log = read_fired_log(str(tmp_path / "fired.jsonl"))
            assert log["fired"] == ["wal.intent.pre_fsync"]
            assert log["actions"] == [
                {"kind": "action", "point": "wal.intent.pre_fsync",
                 "action": "kill", "n": 3}
            ]
        finally:
            faultspace.disarm()

    def test_match_is_payload_subset(self, tmp_path):
        died = []
        self._arm(
            [FaultEvent(point="chainlog.tx.post_fsync", nth=2,
                        action="kill",
                        match={"fn": "update_prediction"})],
            tmp_path, die=lambda: died.append(True),
        )
        try:
            fire = faultspace.fault_point
            fire("chainlog.tx.post_fsync", payload={"fn": "vote"})
            fire("chainlog.tx.post_fsync",
                 payload={"fn": "update_prediction"})
            assert not died  # one matching firing so far
            fire("chainlog.tx.post_fsync",
                 payload={"fn": "update_prediction"})
            assert died == [True]
        finally:
            faultspace.disarm()

    def test_error_action_raises_injected_fault(self, tmp_path):
        self._arm(
            [FaultEvent(point="chain.tx.pre_invoke", nth=1,
                        action="error")],
            tmp_path, die=lambda: pytest.fail("error must not die"),
        )
        try:
            with pytest.raises(InjectedFault, match="chain.tx.pre_invoke"):
                faultspace.fault_point("chain.tx.pre_invoke")
            # Spent: subsequent firings pass.
            faultspace.fault_point("chain.tx.pre_invoke")
        finally:
            faultspace.disarm()

    def test_torn_action_writes_then_dies(self, tmp_path):
        order = []
        self._arm(
            [FaultEvent(point="wal.intent.pre_fsync", nth=1,
                        action="torn")],
            tmp_path, die=lambda: order.append("die"),
        )
        try:
            faultspace.fault_point(
                "wal.intent.pre_fsync", torn=lambda: order.append("torn")
            )
            assert order == ["torn", "die"]
        finally:
            faultspace.disarm()

    def test_torn_without_writer_is_loud(self, tmp_path):
        self._arm(
            [FaultEvent(point="wal.intent.pre_fsync", nth=1,
                        action="torn")],
            tmp_path, die=lambda: None,
        )
        try:
            with pytest.raises(RuntimeError, match="no torn writer"):
                faultspace.fault_point("wal.intent.pre_fsync")
        finally:
            faultspace.disarm()

    def test_undeclared_point_raises_when_armed(self, tmp_path):
        self._arm([], tmp_path, die=lambda: None)
        try:
            with pytest.raises(KeyError, match="undeclared"):
                faultspace.fault_point("made.up.point")
        finally:
            faultspace.disarm()

    def test_event_on_undeclared_point_rejected_at_arm(self):
        with pytest.raises(KeyError):
            FaultController([FaultEvent(point="made.up.point")])

    def test_event_with_disallowed_action_rejected_at_arm(self):
        # serving.step.post declares kill only.
        with pytest.raises(ValueError, match="invalid at"):
            FaultController(
                [FaultEvent(point="serving.step.post", action="torn")]
            )

    def test_double_arm_refused(self, tmp_path):
        self._arm([], tmp_path, die=lambda: None)
        try:
            with pytest.raises(RuntimeError, match="already armed"):
                faultspace.arm(FaultController([]))
        finally:
            faultspace.disarm()

    def test_colliding_same_point_events_both_execute(self, tmp_path):
        # Two events sharing a point and an nth: one event acts per
        # firing, and the loser executes at the NEXT eligible firing
        # instead of being silently lost (review finding).
        acted = []
        self._arm(
            [FaultEvent(point="chain.tx.pre_invoke", nth=2,
                        action="error"),
             FaultEvent(point="chain.tx.pre_invoke", nth=2,
                        action="kill")],
            tmp_path, die=lambda: acted.append("kill"),
        )
        try:
            faultspace.fault_point("chain.tx.pre_invoke")
            with pytest.raises(InjectedFault):
                faultspace.fault_point("chain.tx.pre_invoke")
            faultspace.fault_point("chain.tx.pre_invoke")
            assert acted == ["kill"]
        finally:
            faultspace.disarm()

    def test_unfired_events_reported(self, tmp_path):
        ctl = self._arm(
            [FaultEvent(point="wal.intent.pre_fsync", nth=99,
                        action="kill")],
            tmp_path, die=lambda: None,
        )
        try:
            faultspace.fault_point("wal.intent.pre_fsync")
            assert [e.nth for e in ctl.unfired_events()] == [99]
        finally:
            faultspace.disarm()


# ---------------------------------------------------------------------------
# Plan drawing
# ---------------------------------------------------------------------------


class TestDrawPlan:
    def test_same_seed_same_plan(self):
        assert fuzz.draw_plan(17, SURFACE) == fuzz.draw_plan(17, SURFACE)

    def test_seeds_differ(self):
        plans = {s: fuzz.draw_plan(s, SURFACE) for s in range(40)}
        assert len({json.dumps(p.as_dict()) for p in plans.values()}) > 30

    def test_directed_pass_covers_every_fuzz_point(self):
        # Coverage by construction: seed i targets sorted point i.
        points = fuzz.fuzz_points(SURFACE)
        targeted = set()
        for seed, name in enumerate(points):
            plan = fuzz.draw_plan(seed, SURFACE)
            assert any(e.point == name for e in plan.events), (
                f"directed seed {seed} does not target {name}"
            )
            targeted.add(name)
        assert targeted == set(points)

    def test_drawn_events_always_valid(self):
        # Every drawn event arms cleanly: point declared, action
        # allowed, recovery-stage targets preceded by a phase-0 kill.
        for seed in range(64):
            plan = fuzz.draw_plan(seed, SURFACE)
            FaultController(plan.events)  # raises on invalid draw
            for e in plan.events:
                spec = SURFACE[e.point]
                if spec.stage == "recovery" and e.phase == 0:
                    pytest.fail(
                        f"seed {seed}: recovery-stage {e.point} drawn "
                        f"at phase 0"
                    )

    def test_mode_compatibility(self):
        for seed in range(64):
            plan = fuzz.draw_plan(seed, SURFACE)
            for e in plan.events:
                spec = SURFACE[e.point]
                if spec.stage == "run":
                    assert plan.commit_mode in spec.modes, (
                        f"seed {seed}: {e.point} unreachable in "
                        f"{plan.commit_mode}"
                    )

    def test_plan_round_trip(self):
        plan = fuzz.draw_plan(3, SURFACE)
        assert fuzz.FuzzPlan.from_dict(
            json.loads(json.dumps(plan.as_dict()))
        ) == plan


# ---------------------------------------------------------------------------
# Shrinking + corpus mechanics (no subprocesses)
# ---------------------------------------------------------------------------


class TestShrink:
    def _plan(self):
        return fuzz.FuzzPlan(
            seed=5, cycles=8,
            events=(
                FaultEvent(point="wal.intent.pre_fsync", nth=6,
                           action="torn"),
                FaultEvent(point="snapshot.pre_rename", nth=2,
                           action="kill"),
                FaultEvent(point="reconcile.mid_cycle", nth=1,
                           action="kill", phase=1),
            ),
        )

    def test_shrink_drops_irrelevant_events_and_cycles(self):
        # "Fails" iff the torn-intent event survives and cycles >= 3.
        def fails(p):
            return p.cycles >= 3 and any(
                e.point == "wal.intent.pre_fsync" for e in p.events
            )

        out = fuzz.shrink_plan(self._plan(), fails, budget=40)
        small = out["plan"]
        assert fails(small)
        assert [e.point for e in small.events] == ["wal.intent.pre_fsync"]
        assert small.cycles == 3
        # nth shrinks toward 1 too.
        assert small.events[0].nth == 1

    def test_shrink_respects_budget(self):
        calls = []

        def fails(p):
            calls.append(1)
            return True

        fuzz.shrink_plan(self._plan(), fails, budget=5)
        assert len(calls) <= 5

    def test_unshrinkable_plan_survives(self):
        plan = fuzz.FuzzPlan(seed=1, cycles=2, events=())
        out = fuzz.shrink_plan(plan, lambda p: True, budget=10)
        assert out["plan"].cycles == 2

    def test_corpus_round_trip(self, tmp_path):
        plan = self._plan()
        path = fuzz.write_corpus_entry(
            str(tmp_path), plan, ["duplicate_txs: 2"], notes="unit"
        )
        assert os.path.basename(path) == "duplicate-txs-s5.json"
        entries = fuzz.load_corpus(str(tmp_path))
        assert len(entries) == 1
        assert fuzz.FuzzPlan.from_dict(entries[0]["plan"]) == plan
        assert entries[0]["expect"] == "pass"


# ---------------------------------------------------------------------------
# The child harness + invariant oracles (in-process, no kills)
# ---------------------------------------------------------------------------


class TestChildHarness:
    def test_clean_run_invariants_and_determinism(self):
        plan = fuzz.FuzzPlan(seed=11, cycles=3)
        r1 = fuzz.run_fuzz_child(tempfile.mkdtemp(), plan, 0)
        r2 = fuzz.run_fuzz_child(tempfile.mkdtemp(), plan, 0)
        assert r1["duplicate_txs"] == 0
        assert r1["wal_open_cycles"] == []
        assert r1["lost_commits"] == []
        assert r1["codec_divergences"] == 0
        assert r1["final_unknown"] == 0
        # Same plan, fresh directories: byte-identical fingerprints.
        assert r1["fingerprint"] == r2["fingerprint"]
        # Both commit planes' run-stage surface fires even fault-free.
        assert "wal.intent.pre_fsync" in r1["fired"]
        assert "snapshot.pre_rename" in r1["fired"]
        assert "wal.rotate.pre_replace" in r1["fired"]

    def test_batched_run_uses_batch_family(self):
        plan = fuzz.FuzzPlan(seed=12, cycles=3, commit_mode="batched")
        r = fuzz.run_fuzz_child(tempfile.mkdtemp(), plan, 0)
        assert r["duplicate_txs"] == 0 and r["codec_divergences"] == 0
        assert "wal.intent_batch.pre_fsync" in r["fired"]
        assert "chain.batch.mid_fleet" in r["fired"]
        assert "wal.intent.pre_fsync" not in r["fired"]

    def test_check_invariants_flags_each_oracle(self):
        base = {
            "duplicate_txs": 0, "wal_open_cycles": [],
            "lost_commits": [], "final_unknown": 0,
            "final_unaccounted": 0, "codec_divergences": 0,
        }
        assert fuzz.check_invariants({"result": dict(base)}) == []
        for key, bad, expect in [
            ("duplicate_txs", 2, "duplicate_txs"),
            ("wal_open_cycles", ["fz-x"], "open_cycles"),
            ("lost_commits", [{"lineage": "x", "slot": 1}],
             "lost_commits"),
            ("final_unknown", 1, "unknown_slots"),
            ("final_unaccounted", 1, "unaccounted_slots"),
            ("codec_divergences", 3, "codec_divergences"),
        ]:
            result = dict(base)
            result[key] = bad
            violations = fuzz.check_invariants({"result": result})
            assert len(violations) == 1 and expect in violations[0]

    def test_codec_divergence_witness(self, tmp_path):
        # A synthetic chain log with one non-canonical felt (inside the
        # dead zone the codec refuses) must count as a divergence.
        from svoc_tpu.ops.fixedpoint import FELT_PRIME

        path = str(tmp_path / "chain-x.jsonl")
        good = {"caller": 1, "fn": "update_prediction",
                "prediction": [500000], "digest": "d"}
        bad = {"caller": 1, "fn": "update_prediction",
               "prediction": [FELT_PRIME - 10**40], "digest": "d"}
        with open(path, "w") as f:
            f.write(json.dumps(good) + "\n")
            f.write(json.dumps(bad) + "\n")
        assert fuzz._codec_divergences(path) == 1


class TestSupersession:
    """The fuzzer-captured stale-resend class (corpus entry
    duplicate-txs-reconcile-error): the reconciler's `superseded`
    verdict and the WAL's open-lineage guard."""

    def _wal_with_open_then_newer(self, tmp_path):
        from svoc_tpu.consensus.state import OracleConsensusContract
        from svoc_tpu.durability.chainlog import DurableLocalBackend
        from svoc_tpu.durability.wal import CommitIntentWAL
        from svoc_tpu.io.chain import ChainAdapter
        from svoc_tpu.ops.fixedpoint import encode_vector

        oracles = [0x10 + i for i in range(5)]
        contract = OracleConsensusContract(
            admins=[0xA0, 0xA1, 0xA2], oracles=oracles,
            required_majority=2, n_failing_oracles=1,
            constrained=True, dimension=2,
        )
        backend = DurableLocalBackend(
            contract, str(tmp_path / "chain.jsonl")
        )
        adapter = ChainAdapter(backend)
        wal = CommitIntentWAL(str(tmp_path / "wal.jsonl"))
        old = [
            encode_vector([0.10 + 0.01 * i, 0.20 + 0.01 * i])
            for i in range(5)
        ]
        new = [
            encode_vector([0.50 + 0.01 * i, 0.60 + 0.01 * i])
            for i in range(5)
        ]
        # Cycle A: opened, nothing landed, no done — a kill's leftovers.
        wal.cycle("lin-a", claim=None, oracles=oracles, payloads=old)
        # Cycle B: newer, fully landed on chain, cleanly done.
        cyc_b = wal.cycle(
            "lin-b", claim=None, oracles=oracles, payloads=new
        )
        for oracle, felts in zip(oracles, new):
            adapter._invoke_prediction_felts(oracle, felts)
        cyc_b.done(sent=5)
        return wal, adapter, old, new

    def test_reconciler_never_resends_superseded_slots(self, tmp_path):
        from svoc_tpu.durability.chainlog import duplicate_predictions
        from svoc_tpu.durability.reconcile import reconcile_wal

        wal, adapter, old, new = self._wal_with_open_then_newer(tmp_path)
        report = reconcile_wal(wal, lambda _c: adapter)
        (cyc,) = report.cycles
        assert cyc.lineage == "lin-a" and cyc.closed
        assert cyc.count("superseded") == 5
        assert report.resent == 0
        # The done record carries the superseded slots for the audits.
        done = [r for r in wal.records() if r.get("kind") == "done"
                and r["lineage"] == "lin-a"]
        assert done[-1]["superseded"] == [0, 1, 2, 3, 4]
        assert duplicate_predictions(str(tmp_path / "chain.jsonl")) == []
        # And the chain still holds the NEWER values (no stale-data
        # regression from a resend of cycle A).
        assert adapter.get_the_predictions() == new

    def test_open_lineages_cached_guard(self, tmp_path):
        from svoc_tpu.durability.reconcile import reconcile_wal

        wal, adapter, _old, _new = self._wal_with_open_then_newer(tmp_path)
        assert wal.open_lineages() == {"lin-a"}
        assert "lin-b" not in wal.open_lineages()
        # Failure-closed cycles are NOT open (outcome reported).
        cyc_c = wal.cycle("lin-c", claim=None, oracles=[0x10],
                          payloads=[[1]])
        cyc_c.done(sent=0, failed="transport")
        assert "lin-c" not in wal.open_lineages()
        # A reconcile close drops the open lineage incrementally.
        reconcile_wal(wal, lambda _c: adapter)
        assert wal.open_lineages() == set()


# ---------------------------------------------------------------------------
# Subprocess: the restart-storm regression + the committed corpus
# ---------------------------------------------------------------------------


CORPUS = fuzz.load_corpus(CORPUS_DIR)


class TestKillRestart:
    def test_restart_storm_idempotent(self, tmp_path):
        """ISSUE 14 satellite 3: SIGKILL during recovery — after the
        reconciler's resends landed but before the cycle closed — then
        a second recovery.  No duplicate resends (the chain witness),
        every cycle closed, fingerprint continuity across the full
        rerun."""
        plan = fuzz.FuzzPlan(
            seed=42, cycles=4,
            events=(
                FaultEvent(point="chainlog.tx.post_apply", nth=3,
                           action="kill", phase=0),
                FaultEvent(point="reconcile.mid_cycle", nth=1,
                           action="kill", phase=1),
            ),
        )
        checked = fuzz.run_and_check(plan, str(tmp_path))
        assert checked["violations"] == []
        assert checked["replay_identical"] is True
        result = checked["run"]["result"]
        # Three lives: crash, storming recovery, final recovery.
        assert [p["killed"] for p in checked["run"]["phases"]] == [
            True, True, False,
        ]
        # The second recovery saw the storm's resends as landed (chain
        # witness) — zero duplicate txs IS the no-double-resend proof.
        assert result["duplicate_txs"] == 0
        assert result["wal_open_cycles"] == []
        assert "reconcile.mid_cycle" in checked["fired"]["fired"]
        assert "recovery.post_restore" in checked["fired"]["fired"]

    def test_corpus_is_committed(self):
        names = {e["name"] for e in CORPUS}
        assert {
            "torn-intent-restart-storm.json",
            "batched-felt-mid-fleet.json",
            "open-cycles-s2.json",
        } <= names

    @pytest.mark.parametrize(
        "entry",
        [e for e in CORPUS if e.get("tier1", True)],
        ids=[e["name"] for e in CORPUS if e.get("tier1", True)],
    )
    def test_corpus_replays_green(self, entry, tmp_path):
        """The regression contract: every committed corpus entry —
        auto-shrunk minimal repros of past violations — replays with
        zero invariant violations and byte-identical rerun
        fingerprints."""
        assert entry["expect"] == "pass"
        violations = fuzz.replay_corpus_entry(entry, str(tmp_path))
        assert violations == [], (
            f"corpus entry {entry['name']} regressed: {violations}"
        )


@pytest.mark.slow
class TestCorpusSlow:
    _SLOW = [e for e in CORPUS if not e.get("tier1", True)]

    @pytest.mark.parametrize(
        "entry", _SLOW or [None],
        ids=[e["name"] for e in _SLOW] or ["none"],
    )
    def test_corpus_replays_green_slow(self, entry, tmp_path):
        if entry is None:
            pytest.skip("no slow corpus entries")
        violations = fuzz.replay_corpus_entry(entry, str(tmp_path))
        assert violations == []


# ---------------------------------------------------------------------------
# Crash-scenario mapping + docs inventory
# ---------------------------------------------------------------------------


class TestCrashMapping:
    def test_crash_events_target_declared_points(self):
        # The scenario's named-point mapping, without importing the
        # jax-heavy scenario module: tools/crash_smoke.py's LEG_POINT
        # must name declared points with crash-smoke witness metadata.
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "crash_smoke", os.path.join(REPO, "tools", "crash_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        for leg, point in mod.LEG_POINT.items():
            assert point in SURFACE, f"{leg} targets undeclared {point}"
            assert faultspace.SMOKE_CRASH in SURFACE[point].smokes, (
                f"{leg} targets {point} which does not name the crash "
                f"smoke as a witness"
            )
        assert set(mod.LEGS) == set(mod.LEG_POINT)

    def test_crash_witnessed_points_all_reachable(self):
        # Every point claiming the crash smoke as witness is targeted
        # by some leg (or fires on every recovery, like post_restore).
        crash_points = {
            n for n, s in SURFACE.items()
            if faultspace.SMOKE_CRASH in s.smokes
        }
        assert crash_points == {
            "wal.intent.pre_fsync", "chainlog.tx.post_fsync",
            "serving.step.post", "chain.batch.mid_fleet",
            "recovery.post_restore",
        }


class TestDocsInventory:
    def test_every_declared_point_in_resilience_doc(self):
        # The docs table and the registry are the same inventory: a
        # point added without a doc row fails here, a doc row without a
        # declaration is caught by the reverse scan.
        with open(DOC) as f:
            doc = f.read()
        for name in SURFACE:
            assert f"`{name}`" in doc, (
                f"fault point {name} missing from docs/RESILIENCE.md "
                f"fault-surface inventory"
            )
