"""Metrics utilities."""

from svoc_tpu.utils.metrics import Counter, LatencyTimer, MetricsRegistry


def test_counter_rate():
    c = Counter()
    c.add(10)
    c.add(5)
    assert c.count == 15
    assert c.rate() > 0
    c.reset()
    assert c.count == 0


def test_latency_timer():
    t = LatencyTimer()
    with t.time():
        pass
    t.observe(0.5)
    assert t.n == 2
    assert t.max_s >= 0.5
    assert 0 < t.mean_s <= 0.5
    assert t.ema_s is not None


def test_registry_report():
    r = MetricsRegistry()
    r.counter("comments").add(100)
    with r.timer("consensus").time():
        pass
    lines = r.report()
    assert any("comments" in line for line in lines)
    assert any("consensus" in line for line in lines)
