"""Observability layer: counters, histograms, spans, gauges, exposition."""

import json
import threading

import pytest

from svoc_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    LatencyTimer,
    MetricsRegistry,
    SpanRecord,
    Tracer,
    log_buckets,
    set_mfu_gauge,
)


def test_counter_rate():
    c = Counter()
    c.add(10)
    c.add(5)
    assert c.count == 15
    assert c.rate() > 0
    assert c.lifetime_rate() > 0
    c.reset()
    assert c.count == 0
    assert c.rate() == 0.0


def test_counter_rate_is_windowed_not_lifetime():
    """After an idle period the recent rate must drop to zero instead of
    decaying forever as a lifetime average (round-1 advisor finding)."""
    c = Counter(window_s=0.05)
    c.add(1000)
    import time

    time.sleep(0.1)  # idle past the window
    assert c.rate() == 0.0  # recent rate: no events in window
    assert c.lifetime_rate() > 0  # lifetime average still positive
    c.add(1)
    assert c.rate() >= 0.0  # single fresh sample doesn't blow up


def test_latency_timer():
    t = LatencyTimer()
    with t.time():
        pass
    t.observe(0.5)
    assert t.n == 2
    assert t.max_s >= 0.5
    assert 0 < t.mean_s <= 0.5
    assert t.ema_s is not None


def test_registry_report():
    r = MetricsRegistry()
    r.counter("comments").add(100)
    with r.timer("consensus").time():
        pass
    lines = r.report()
    assert any("comments" in line for line in lines)
    assert any("consensus" in line for line in lines)


# -- histograms --------------------------------------------------------------


def test_log_buckets_are_monotone_and_span_range():
    edges = log_buckets(1e-4, 120.0, per_decade=4)
    assert edges == tuple(sorted(edges))
    assert edges[0] == pytest.approx(1e-4)
    assert edges[-1] >= 60.0
    # ~1.78x steps: every edge strictly grows by the decade ratio.
    for lo, hi in zip(edges, edges[1:]):
        assert hi / lo == pytest.approx(10 ** 0.25, rel=1e-3)


class TestHistogram:
    def test_empty_percentiles_are_zero(self):
        h = Histogram()
        assert h.percentile(50) == 0.0
        assert h.snapshot()["count"] == 0

    def test_percentile_math_against_known_distribution(self):
        """1000 samples spread uniformly over [1ms, 100ms]: the bucket
        interpolation must land within one log-spaced bucket width of
        the exact percentile — the property that makes a p99 regression
        visible rather than bucket-quantized away."""
        h = Histogram()
        n = 1000
        samples = [0.001 + (0.099 * i / (n - 1)) for i in range(n)]
        for s in samples:
            h.observe(s)
        for q in (50, 95, 99):
            exact = samples[int(q / 100 * (n - 1))]
            got = h.percentile(q)
            # within a bucket step (x1.78 either way) of exact
            assert exact / 1.9 <= got <= exact * 1.9, (q, exact, got)
        snap = h.snapshot()
        assert snap["count"] == n
        assert snap["p50"] <= snap["p95"] <= snap["p99"]
        assert snap["min"] == pytest.approx(0.001)
        assert snap["max"] == pytest.approx(0.1)

    def test_overflow_bucket_reports_observed_max(self):
        h = Histogram(buckets=(0.001, 0.01))
        h.observe(5.0)  # beyond every bound
        assert h.percentile(99) == pytest.approx(5.0)
        buckets = h.cumulative_buckets()
        assert buckets[-1] == (float("inf"), 1)
        assert buckets[-2][1] == 0  # nothing below the finite bounds

    def test_cumulative_buckets_are_monotone(self):
        h = Histogram(buckets=(0.001, 0.01, 0.1))
        for v in (0.0005, 0.005, 0.005, 0.05, 2.0):
            h.observe(v)
        counts = [c for _, c in h.cumulative_buckets()]
        assert counts == sorted(counts)
        assert counts[-1] == 5

    def test_invalid_percentile_raises(self):
        with pytest.raises(ValueError):
            Histogram().percentile(101)


# -- spans / tracer ----------------------------------------------------------


class TestTracer:
    def test_span_nesting_records_parent_and_depth(self):
        r = MetricsRegistry()
        t = Tracer(r)
        with t.span("fetch") as fetch_id:
            with t.span("forward") as fwd_id:
                pass
        spans = {s.name: s for s in t.recent()}
        assert spans["forward"].parent_id == fetch_id
        assert spans["forward"].depth == 1
        assert spans["fetch"].parent_id is None
        assert spans["fetch"].depth == 0
        assert spans["forward"].span_id == fwd_id
        # inner completed first, outer covers it
        assert spans["fetch"].duration_s >= spans["forward"].duration_s

    def test_spans_feed_stage_histograms(self):
        r = MetricsRegistry()
        t = Tracer(r)
        with t.span("tokenize"):
            pass
        h = r.stage_histogram("tokenize")
        assert h.count == 1
        assert r.stage_snapshot()["tokenize"]["count"] == 1

    def test_jsonl_round_trip(self, tmp_path):
        """SVOC_TRACE_FILE-style export: every completed span is one
        parseable JSON line reconstructing the nesting tree."""
        path = tmp_path / "trace.jsonl"
        r = MetricsRegistry()
        t = Tracer(r)
        t.set_trace_file(str(path))
        with t.span("fetch"):
            with t.span("tokenize"):
                pass
            with t.span("forward"):
                pass
        t.flush()
        lines = path.read_text().strip().splitlines()
        records = [json.loads(line) for line in lines]
        assert [rec["name"] for rec in records] == [
            "tokenize", "forward", "fetch",  # completion order
        ]
        by_name = {rec["name"]: rec for rec in records}
        assert by_name["tokenize"]["parent_id"] == by_name["fetch"]["span_id"]
        assert by_name["forward"]["parent_id"] == by_name["fetch"]["span_id"]
        assert by_name["fetch"]["parent_id"] is None
        for rec in records:
            assert rec["duration_s"] >= 0
            assert rec["start_s"] > 0

    def test_env_var_export(self, tmp_path, monkeypatch):
        path = tmp_path / "env_trace.jsonl"
        monkeypatch.setenv(Tracer.TRACE_ENV, str(path))
        r = MetricsRegistry()
        t = Tracer(r)
        with t.span("commit"):
            pass
        t.flush()
        assert json.loads(path.read_text())["name"] == "commit"

    def test_bad_trace_path_never_breaks_spans(self, tmp_path):
        t = Tracer(MetricsRegistry())
        t.set_trace_file(str(tmp_path / "no" / "such" / "dir" / "t.jsonl"))
        with t.span("fetch"):
            pass  # must not raise
        assert len(t.recent()) == 1

    def test_ring_buffer_is_bounded(self):
        t = Tracer(MetricsRegistry(), capacity=8)
        for i in range(50):
            with t.span(f"s{i}"):
                pass
        spans = t.recent()
        assert len(spans) == 8
        assert spans[-1].name == "s49"

    def test_span_record_json_fields(self):
        rec = SpanRecord("x", 1.0, 0.5, 3, None, "main", 0)
        assert json.loads(rec.to_json())["duration_s"] == 0.5


# -- registry: labels, exposition, thread-safety -----------------------------


class TestRegistry:
    def test_labeled_series_are_distinct(self):
        r = MetricsRegistry()
        r.histogram("stage_seconds", labels={"stage": "a"}).observe(0.01)
        r.histogram("stage_seconds", labels={"stage": "b"}).observe(0.02)
        snap = r.stage_snapshot()
        assert set(snap) == {"a", "b"}
        assert snap["a"]["count"] == snap["b"]["count"] == 1

    def test_family_total_folds_labels(self):
        r = MetricsRegistry()
        r.counter("faults_injected", labels={"kind": "error"}).add(3)
        r.counter("faults_injected", labels={"kind": "timeout"}).add(2)
        r.counter("retries", labels={"op": "commit"}).add(5)
        assert r.family_total("faults_injected") == 5
        assert r.family_total("retries") == 5
        assert r.family_total("absent") == 0

    def test_render_prometheus_exposition(self):
        r = MetricsRegistry()
        r.counter("comments_processed").add(7)
        r.gauge("mfu_estimate").set(0.42)
        r.timer("fetch_latency").observe(0.25)
        r.stage_histogram("forward").observe(0.02)
        text = r.render_prometheus()
        assert text.endswith("\n")
        assert "# TYPE svoc_comments_processed_total counter" in text
        assert "svoc_comments_processed_total 7" in text
        assert "svoc_mfu_estimate 0.42" in text
        assert "svoc_fetch_latency_seconds_count 1" in text
        assert "svoc_fetch_latency_seconds_sum 0.25" in text
        assert "# TYPE svoc_stage_seconds histogram" in text
        assert 'svoc_stage_seconds_bucket{stage="forward",le="+Inf"} 1' in text
        assert 'svoc_stage_seconds_count{stage="forward"} 1' in text
        # cumulative le series: later bounds never decrease
        counts = [
            int(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("svoc_stage_seconds_bucket")
        ]
        assert counts == sorted(counts)

    def test_prometheus_name_sanitization(self):
        r = MetricsRegistry()
        r.counter("weird.name-with/chars").add(1)
        text = r.render_prometheus()
        assert "svoc_weird_name_with_chars_total 1" in text

    def test_thread_safety_under_concurrent_observers(self):
        """16 threads hammer one histogram + counter + spans; every
        observation must land (no lost updates, no double counts)."""
        r = MetricsRegistry()
        t = Tracer(r)
        n_threads, per_thread = 16, 200
        barrier = threading.Barrier(n_threads)

        def work():
            barrier.wait()
            for i in range(per_thread):
                r.counter("hits").add(1)
                r.histogram("lat").observe(0.001 * (i % 7 + 1))
                with t.span("stage_x"):
                    pass

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        total = n_threads * per_thread
        assert r.counter("hits").count == total
        assert r.histogram("lat").count == total
        assert r.stage_histogram("stage_x").count == total
        # exposition renders while nothing is mutating — and parses
        text = r.render_prometheus()
        assert f"svoc_hits_total {total}" in text

    def test_concurrent_series_creation_returns_one_object(self):
        """Racing first-use of the same name must converge on ONE
        histogram (a lost construction would drop observations)."""
        r = MetricsRegistry()
        results = []
        barrier = threading.Barrier(8)

        def grab():
            barrier.wait()
            h = r.histogram("contended")
            h.observe(0.01)
            results.append(id(h))

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(set(results)) == 1
        assert r.histogram("contended").count == 8


def test_set_mfu_gauge_uses_flop_model():
    r = MetricsRegistry()
    # 1 TFLOP step in 0.1 s on a 100-TFLOP/s chip => 10% MFU
    mfu = set_mfu_gauge(0.1, 1e12, 100e12, reg=r)
    assert mfu == pytest.approx(0.1)
    assert r.gauge("mfu_estimate").get() == pytest.approx(0.1)
    assert set_mfu_gauge(0.1, 1e12, None, reg=r) is None  # CPU: unknown peak


def test_gauge_set_add_get():
    g = Gauge()
    g.set(3.5)
    g.add(1.5)
    assert g.get() == 5.0


def test_sample_runtime_gauges_reports_live_device_bytes():
    """With a live backend and at least one device array, the sampler
    must fill per-device live-bytes gauges (and never raise)."""
    import jax.numpy as jnp

    from svoc_tpu.utils.metrics import sample_runtime_gauges

    keep = jnp.ones((16, 16), jnp.float32) + 1  # ensure a live array
    r = MetricsRegistry()
    out = sample_runtime_gauges(r)
    assert any(k.startswith("device_live_bytes") for k in out), out
    assert r.gauge("device_live_arrays").get() >= 1
    assert sum(
        g.get()
        for key, g in r.gauges.items()
        if key.startswith("device_live_bytes")
    ) >= keep.nbytes
    # rendering includes the device-labeled gauge family
    assert 'svoc_device_live_bytes{device="' in r.render_prometheus()


def test_sample_runtime_gauges_zeroes_vanished_devices():
    """A device whose live arrays were all freed must read 0 on the
    next sample — not its last-seen bytes forever (code-review: the
    phantom-leak contradiction with device_live_arrays)."""
    import jax.numpy as jnp

    from svoc_tpu.utils.metrics import sample_runtime_gauges

    jnp.zeros(1) + 1  # backend live so the sampler runs
    r = MetricsRegistry()
    stale = r.gauge("device_live_bytes", labels={"device": "FakeDevice(99)"})
    stale.set(1e9)
    out = sample_runtime_gauges(r)
    key = 'device_live_bytes{device="FakeDevice(99)"}'
    assert stale.get() == 0.0
    assert out[key] == 0.0
