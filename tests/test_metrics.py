"""Metrics utilities."""

from svoc_tpu.utils.metrics import Counter, LatencyTimer, MetricsRegistry


def test_counter_rate():
    c = Counter()
    c.add(10)
    c.add(5)
    assert c.count == 15
    assert c.rate() > 0
    assert c.lifetime_rate() > 0
    c.reset()
    assert c.count == 0
    assert c.rate() == 0.0


def test_counter_rate_is_windowed_not_lifetime():
    """After an idle period the recent rate must drop to zero instead of
    decaying forever as a lifetime average (round-1 advisor finding)."""
    c = Counter(window_s=0.05)
    c.add(1000)
    import time

    time.sleep(0.1)  # idle past the window
    assert c.rate() == 0.0  # recent rate: no events in window
    assert c.lifetime_rate() > 0  # lifetime average still positive
    c.add(1)
    assert c.rate() >= 0.0  # single fresh sample doesn't blow up


def test_latency_timer():
    t = LatencyTimer()
    with t.time():
        pass
    t.observe(0.5)
    assert t.n == 2
    assert t.max_s >= 0.5
    assert 0 < t.mean_s <= 0.5
    assert t.ema_s is not None


def test_registry_report():
    r = MetricsRegistry()
    r.counter("comments").add(100)
    with r.timer("consensus").time():
        pass
    lines = r.report()
    assert any("comments" in line for line in lines)
    assert any("consensus" in line for line in lines)
