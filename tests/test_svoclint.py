"""svoclint: per-rule fixtures, suppressions, baseline, CI contract.

Covers the docs/STATIC_ANALYSIS.md contract: one positive + one
negative fixture per rule, inline-suppression handling, baseline
round-trip (including stale-entry detection — baselines only shrink),
a whole-package run asserting zero non-baselined findings, and the CLI
exit codes the Makefile's ``lint`` target relies on.

Everything here runs without JAX (and asserts that importing the
analyzer cannot pull it in) — svoclint is the one tier-1 surface that
must stay cheap on a box with no accelerator stack.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from svoc_tpu.analysis import (  # noqa: E402
    Baseline,
    RULE_DOCS,
    analyze_paths,
    analyze_source,
)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def src(text):
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# SVOC001 — host-sync-in-hot-path
# ---------------------------------------------------------------------------


def test_svoc001_flags_host_sync_in_jit_body():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)
            """
        )
    )
    assert rules_of(findings) == ["SVOC001"]
    assert "np.asarray" in findings[0].message


def test_svoc001_flags_item_in_dispatch_span():
    findings = analyze_source(
        src(
            """
            from svoc_tpu.utils.metrics import stage_span

            def g(v):
                with stage_span("consensus"):
                    return v.item()
            """
        )
    )
    assert rules_of(findings) == ["SVOC001"]
    assert 'span "consensus"' in findings[0].message


def test_svoc001_negative_pure_jit_and_host_stage_span():
    findings = analyze_source(
        src(
            """
            import jax
            import jax.numpy as jnp
            import numpy as np
            from svoc_tpu.utils.metrics import stage_span

            @jax.jit
            def f(x):
                return jnp.sum(x) * 2.0

            def g(texts):
                # tokenize is a HOST stage — numpy there is the point
                with stage_span("tokenize"):
                    return np.asarray(texts)
            """
        )
    )
    assert findings == []


def test_svoc001_span_scan_skips_nested_defs_that_only_define():
    # a callback DEFINED (not called) inside a dispatch span runs
    # later, outside the span — not a span-body sync
    findings = analyze_source(
        src(
            """
            import numpy as np
            from svoc_tpu.utils.metrics import stage_span

            def g(v, schedule):
                with stage_span("forward"):
                    def cb(r):
                        return np.asarray(r)
                    schedule(cb)
            """
        )
    )
    assert findings == []


def test_svoc001_covers_jit_wrapper_call_and_lambda():
    findings = analyze_source(
        src(
            """
            import jax

            def body(x):
                return x.block_until_ready()

            step = jax.jit(body)
            other = jax.jit(lambda v: float(v))
            """
        )
    )
    assert rules_of(findings) == ["SVOC001"]
    assert len(findings) == 2


# ---------------------------------------------------------------------------
# SVOC002 — impure-jit-body
# ---------------------------------------------------------------------------


def test_svoc002_flags_print_metrics_and_self_mutation():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.metrics import registry as metrics

            @jax.jit
            def f(x):
                print("tracing", x)
                metrics.counter("steps").add(1)
                return x

            class Engine:
                def build(self):
                    @jax.jit
                    def step(x):
                        self.last = x
                        return x
                    return step
            """
        )
    )
    assert rules_of(findings) == ["SVOC002"]
    assert len(findings) == 3


def test_svoc002_bare_log_is_math_not_logging():
    # `from jax.numpy import log` — calling it inside jit is pure math;
    # only method calls on log/logger roots (or the logging module) are
    # logging.
    clean = analyze_source(
        src(
            """
            import jax
            from jax.numpy import log

            @jax.jit
            def f(x):
                return log(x) + 1
            """
        )
    )
    assert clean == []
    flagged = analyze_source(
        src(
            """
            import jax
            import logging

            logger = logging.getLogger(__name__)

            @jax.jit
            def f(x):
                logger.info("step %s", x)
                return x
            """
        )
    )
    assert rules_of(flagged) == ["SVOC002"]


def test_svoc002_negative_effects_outside_trace():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.metrics import registry as metrics

            @jax.jit
            def f(x):
                return x + 1

            def drive(x):
                out = f(x)
                metrics.counter("steps").add(1)
                print("done")
                return out
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC003 — recompile-hazard
# ---------------------------------------------------------------------------


def test_svoc003_flags_jit_in_loop():
    findings = analyze_source(
        src(
            """
            import jax

            def sweep(xs):
                outs = []
                for x in xs:
                    f = jax.jit(lambda v: v + 1)
                    outs.append(f(x))
                return outs
            """
        )
    )
    assert "SVOC003" in rules_of(findings)
    assert "inside a loop" in findings[0].message


def test_svoc003_flags_dotted_pjit_in_loop():
    findings = analyze_source(
        src(
            """
            import jax

            def sweep(xs):
                return [jax.experimental.pjit.pjit(lambda v: v)(x) for x in xs]
            """
        )
    )
    assert "SVOC003" in rules_of(findings)


def test_svoc003_flags_per_request_jit_construction():
    findings = analyze_source(
        src(
            """
            import jax

            def handle(request):
                return jax.jit(lambda v: v * 2)(request)
            """
        )
    )
    assert rules_of(findings) == ["SVOC003"]
    assert "per-request" in findings[0].message


def test_svoc003_negative_factory_and_module_level_invocation():
    findings = analyze_source(
        src(
            """
            import jax
            import jax.numpy as jnp

            def make_step(cfg):
                # the factory pattern: build once, return the callable
                return jax.jit(lambda v: v * cfg)

            # module level runs once at import — not per-request
            warmup = jax.jit(lambda v: v + 1)(jnp.zeros(4))
            """
        )
    )
    assert findings == []


def test_svoc003_flags_fstring_and_nonstatic_shape_arg():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("mode",))
            def f(x, mode):
                return x

            @jax.jit
            def g(x, n):
                return x[:2]

            def drive(v, k):
                a = f(v, mode=f"mode-{k}")
                b = g(v, v.shape[0])
                return a, b
            """
        )
    )
    assert rules_of(findings) == ["SVOC003"]
    msgs = " | ".join(f.message for f in findings)
    assert "f-string" in msgs and "shape-derived" in msgs
    assert len(findings) == 2


def test_svoc003_negative_static_declarations_match():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def g(x, n):
                return x[:n]

            @partial(jax.jit, static_argnums=(1,))
            def h(x, n):
                return x[:n]

            f = jax.jit(lambda v: v * 2)

            def drive(v):
                a = g(v, n=v.shape[0])   # declared static by name
                b = g(v, v.shape[0])     # static position via argnames
                c = h(v, v.shape[0])     # declared static by position
                return a, b, c, f(v)
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC004 — donation-reuse
# ---------------------------------------------------------------------------


def test_svoc004_flags_use_after_donation():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dx):
                out = step(state, dx)
                return state + out
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]
    assert "DONATED" in findings[0].message


def test_svoc004_flags_loop_without_rebind():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dxs):
                outs = []
                for dx in dxs:
                    outs.append(step(state, dx))
                return outs
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]
    assert "loop" in findings[0].message


def test_svoc004_flags_same_line_use_outside_the_call():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dx):
                return step(state, dx) + state
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]


def test_svoc004_flags_load_on_the_rebind_line_itself():
    # `x = x + 1` after donation: the load happens BEFORE the store, so
    # it reads the invalidated buffer — a rebind protects only lines
    # strictly after it.
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dx):
                out = step(state, dx)
                state = state + 1
                return out
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]


def test_svoc004_negative_rebind_over_donated_name():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dxs):
                for dx in dxs:
                    state = step(state, dx)
                return state
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC005 — fixed-point-contract
# ---------------------------------------------------------------------------


def test_svoc005_flags_float_div_and_foreign_scale():
    findings = analyze_source(
        src(
            """
            # svoclint: tag=fixedpoint-path

            def wsad_half(a: int) -> int:
                return int(a * 0.5)

            def wsad_ratio(a: int, b: int) -> int:
                return a / b

            def wsad_rescale(a: int) -> int:
                return a * 1000000000
            """
        )
    )
    assert rules_of(findings) == ["SVOC005"]
    msgs = " | ".join(f.message for f in findings)
    assert "float literal" in msgs
    assert "true division" in msgs
    assert "foreign Q-scale" in msgs


def test_svoc005_negative_boundary_functions_and_untagged_modules():
    clean = src(
        """
        WSAD = 1_000_000

        def wsad_mul(a: int, b: int) -> int:
            return (a * b + WSAD // 2) // WSAD

        def from_wsad(x: int) -> float:
            return float(x) * 1e-6
        """
    )
    # tagged: boundary (-> float) functions and int-clean Q-paths pass
    assert analyze_source("# svoclint: tag=fixedpoint-path\n" + clean) == []
    # untagged module: rule does not apply at all
    assert analyze_source("def wsad_x(a: int) -> int:\n    return int(a * 0.5)\n") == []


def test_svoc005_applies_to_real_fixedpoint_module_by_path():
    findings = analyze_source(
        "def wsad_x(a: int) -> int:\n    return int(a * 0.5)\n",
        path="svoc_tpu/ops/fixedpoint.py",
    )
    assert rules_of(findings) == ["SVOC005"]


# ---------------------------------------------------------------------------
# SVOC006 — unlocked-shared-state
# ---------------------------------------------------------------------------


def test_svoc006_flags_unlocked_mutation_in_thread_entry_module():
    findings = analyze_source(
        src(
            """
            # svoclint: tag=thread-entry
            _streams = {}

            def handler(key, value):
                _streams[key] = value
                _streams.pop(key, None)
            """
        )
    )
    assert rules_of(findings) == ["SVOC006"]
    assert len(findings) == 2


def test_svoc006_negative_locked_mutation_and_untagged_module():
    locked = src(
        """
        # svoclint: tag=thread-entry
        import threading

        _streams = {}
        _lock = threading.Lock()

        def handler(key, value):
            with _lock:
                _streams[key] = value
        """
    )
    assert analyze_source(locked) == []
    unguarded_elsewhere = src(
        """
        _cache = {}

        def remember(k, v):
            _cache[k] = v
        """
    )
    assert analyze_source(unguarded_elsewhere) == []


def test_svoc006_lock_match_is_identifier_segment_not_substring():
    # `with block:` is NOT a lock even though "block" contains "lock";
    # RLock()/sse_lock ARE.
    flagged = analyze_source(
        src(
            """
            # svoclint: tag=thread-entry
            import threading

            _streams = {}
            block = threading.Semaphore()

            def handler(key, value):
                with block:
                    _streams[key] = value
            """
        )
    )
    assert rules_of(flagged) == ["SVOC006"]
    clean = analyze_source(
        src(
            """
            # svoclint: tag=thread-entry
            import threading

            _streams = {}
            sse_lock = threading.RLock()

            def handler(key, value):
                with sse_lock:
                    _streams[key] = value
            """
        )
    )
    assert clean == []


def test_svoc006_applies_to_web_module_by_path():
    findings = analyze_source(
        "_streams = {}\n\ndef h(k, v):\n    _streams[k] = v\n",
        path="svoc_tpu/apps/web.py",
    )
    assert rules_of(findings) == ["SVOC006"]


# ---------------------------------------------------------------------------
# SVOC007 — event-in-traced-body
# ---------------------------------------------------------------------------


def test_svoc007_flags_emit_event_in_jit_body():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.events import emit_event

            @jax.jit
            def step(x):
                emit_event("consensus.result", n=1)
                return x + 1
            """
        )
    )
    assert rules_of(findings) == ["SVOC007"]
    assert "trace time" in findings[0].message
    assert "host" in findings[0].hint


def test_svoc007_flags_journal_emit_method_in_jit_body():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.events import journal

            @jax.jit
            def step(x):
                journal.emit("commit.sent", sent=1)
                return x * 2
            """
        )
    )
    assert rules_of(findings) == ["SVOC007"]


def test_svoc007_negative_emission_around_dispatch():
    """Host-side emission around the jitted call — the documented
    pattern — and unrelated `.emit()` methods on non-journal objects
    must not flag."""
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.events import emit_event

            @jax.jit
            def step(x):
                return x + 1

            def commit(x):
                y = step(x)
                emit_event("commit.sent", sent=1)
                return y

            def unrelated(sound):
                sound.emit("beep")  # not a journal root
            """
        )
    )
    assert rules_of(findings) == []


def test_inline_suppression_silences_one_rule_on_one_line():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                a = np.asarray(x)  # svoclint: disable=SVOC001
                b = np.asarray(x)
                return a + b
            """
        )
    )
    assert len(findings) == 1  # only the un-suppressed line remains
    assert findings[0].snippet == "b = np.asarray(x)"


def test_inline_suppression_tolerates_spaces_in_rule_list():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                print(np.asarray(x))  # svoclint: disable=SVOC001, SVOC002
                return x
            """
        )
    )
    assert findings == []


def test_inline_suppression_disable_all_and_multiple_rules():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                print(np.asarray(x))  # svoclint: disable=SVOC001,SVOC002
                return x

            @jax.jit
            def g(x):
                print(np.asarray(x))  # svoclint: disable=all
                return x
            """
        )
    )
    assert findings == []


def test_trailing_suppression_covers_interior_lines_of_the_statement():
    # findings can anchor on an interior line of a multi-line literal;
    # the trailing disable covers the whole logical statement
    findings = analyze_source(
        src(
            """
            import numpy as np
            from svoc_tpu.utils.metrics import stage_span

            def g(mean, median):
                with stage_span("consensus"):
                    return {
                        "mean": np.asarray(mean),
                        "median": np.asarray(median),
                    }  # svoclint: disable=SVOC001
            """
        )
    )
    assert findings == []


def test_jit_wrapping_does_not_contaminate_the_raw_function_name():
    # `fast = jax.jit(step, donate_argnums=(0,))`: only calls of `fast`
    # donate — a plain Python `step(...)` call does not.
    findings = analyze_source(
        src(
            """
            import jax

            def step(state, dx):
                return state + dx

            fast = jax.jit(step, donate_argnums=(0,))

            def raw(state, dx):
                out = step(state, dx)
                return state + out

            def jitted(state, dx):
                out = fast(state, dx)
                return state + out
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]
    assert len(findings) == 1
    assert "`fast`" in findings[0].message


def test_trailing_suppression_on_multiline_statement_covers_its_first_line():
    # The finding reports at the statement's first line; the disable
    # trails the closing paren — logical-line mapping must connect them.
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(
                    x,
                    dtype=np.float64,
                )  # svoclint: disable=SVOC001
            """
        )
    )
    assert findings == []


def test_file_level_suppression():
    findings = analyze_source(
        src(
            """
            # svoclint: disable-file=SVOC001
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)
            """
        )
    )
    assert findings == []


def test_suppression_comment_inside_string_is_not_honored():
    findings = analyze_source(
        src(
            '''
            import jax
            import numpy as np

            NOTE = """ svoclint: disable-file=SVOC001 """

            @jax.jit
            def f(x):
                return np.asarray(x)
            '''
        )
    )
    assert rules_of(findings) == ["SVOC001"]


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

_BASELINE_FIXTURE = """
import jax
import numpy as np

@jax.jit
def f(x):
    return np.asarray(x)
"""


def test_baseline_round_trip(tmp_path):
    findings = analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    assert len(findings) == 1
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings, reason="grandfathered in test").dump(bl_path)

    loaded = Baseline.load(bl_path)
    new, baselined, stale = loaded.split(
        analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    )
    assert new == [] and stale == []
    assert len(baselined) == 1
    # entries keep their reason through the round trip
    assert json.load(open(bl_path))["entries"][0]["reason"] == "grandfathered in test"


def test_baseline_is_line_drift_tolerant_but_edit_sensitive(tmp_path):
    findings = analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).dump(bl_path)
    loaded = Baseline.load(bl_path)

    # unrelated lines added above: same snippet, still baselined
    drifted = "import os\nimport sys\n" + _BASELINE_FIXTURE
    new, baselined, stale = loaded.split(analyze_source(drifted, path="pkg/mod.py"))
    assert new == [] and len(baselined) == 1 and stale == []

    # the flagged line itself edited: no longer covered, old entry stale
    edited = _BASELINE_FIXTURE.replace(
        "return np.asarray(x)", "return np.asarray(x * 2)"
    )
    new, baselined, stale = loaded.split(analyze_source(edited, path="pkg/mod.py"))
    assert len(new) == 1 and baselined == [] and len(stale) == 1


def test_baseline_context_blocks_lookalike_new_findings(tmp_path):
    # A dead grandfather entry must not absorb a NEW finding whose
    # flagged line happens to have identical text but different
    # surroundings — the next-line context disambiguates.
    original = src(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)
        """
    )
    findings = analyze_source(original, path="pkg/mod.py")
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).dump(bl_path)

    lookalike = src(
        """
        import jax
        import numpy as np

        @jax.jit
        def g(y):
            return np.asarray(x)
            # different statement, same flagged-line text
        """
    )
    new, baselined, stale = Baseline.load(bl_path).split(
        analyze_source(lookalike, path="pkg/mod.py")
    )
    assert len(new) == 1 and baselined == [] and len(stale) == 1


def test_write_baseline_preserves_curated_reasons(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    bl = tmp_path / "bl.json"
    proc = _run_cli([str(bad), "--baseline", str(bl), "--write-baseline"])
    assert proc.returncode == 0
    data = json.load(open(bl))
    data["entries"][0]["reason"] = "curated explanation"
    json.dump(data, open(bl, "w"))
    proc = _run_cli([str(bad), "--baseline", str(bl), "--write-baseline"])
    assert proc.returncode == 0
    assert json.load(open(bl))["entries"][0]["reason"] == "curated explanation"


def test_stale_baseline_entry_reported_when_finding_fixed(tmp_path):
    findings = analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).dump(bl_path)
    new, baselined, stale = Baseline.load(bl_path).split([])
    assert new == [] and baselined == []
    assert len(stale) == 1  # baselines only shrink — CI flags leftovers


# ---------------------------------------------------------------------------
# whole-package run + CLI contract
# ---------------------------------------------------------------------------


def test_whole_package_run_is_clean_and_fast():
    report = analyze_paths(
        [os.path.join(REPO_ROOT, "svoc_tpu"), os.path.join(REPO_ROOT, "tools")],
        root=REPO_ROOT,
    )
    assert report.parse_errors == []
    baseline = Baseline.load(os.path.join(REPO_ROOT, "tools", "svoclint_baseline.json"))
    new, _baselined, stale = baseline.split(report.all_findings)
    assert new == [], "non-baselined svoclint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert stale == [], f"stale baseline entries (remove them): {stale}"
    # acceptance: whole-package lint completes in < 10 s on CPU
    assert report.duration_s < 10.0


def test_every_documented_rule_has_a_registered_doc():
    # SVOC001–007 per-module + SVOC008–012 interprocedural
    # + SVOC013–017 contract plane
    assert sorted(RULE_DOCS) == [f"SVOC{i:03d}" for i in range(1, 18)]
    for doc in RULE_DOCS.values():
        assert doc["severity"] in ("error", "warning")


def _run_cli(args, cwd=REPO_ROOT):
    # Tests must never touch the repo's real findings cache: default to
    # --no-cache unless the test explicitly exercises caching.
    args = list(args)
    if "--cache" not in args and "--no-cache" not in args:
        args.append("--no-cache")
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "svoclint.py"), *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=120,
    )


def test_cli_repo_run_exits_zero_json():
    proc = _run_cli(["svoc_tpu", "tools", "--format", "json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["counts"]["new"] == 0
    assert payload["counts"]["files"] > 50


_INJECTED = {
    "SVOC001": "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n",
    "SVOC002": "import jax\n\n@jax.jit\ndef f(x):\n    print(x)\n    return x\n",
    "SVOC003": (
        "import jax\n\ndef sweep(xs):\n    return [jax.jit(lambda v: v)(x)"
        " for x in xs]\n"
    ),
    "SVOC004": (
        "import jax\nfrom functools import partial\n\n"
        "@partial(jax.jit, donate_argnums=(0,))\ndef step(s, d):\n"
        "    return s + d\n\ndef run(s, d):\n    out = step(s, d)\n"
        "    return s + out\n"
    ),
    "SVOC005": (
        "# svoclint: tag=fixedpoint-path\n\ndef wsad_bad(a: int) -> int:\n"
        "    return int(a * 0.5)\n"
    ),
    "SVOC006": (
        "# svoclint: tag=thread-entry\n_state = {}\n\ndef h(k, v):\n"
        "    _state[k] = v\n"
    ),
    "SVOC007": (
        "import jax\nfrom svoc_tpu.utils.events import emit_event\n\n"
        "@jax.jit\ndef f(x):\n    emit_event('x')\n    return x\n"
    ),
    "SVOC008": (
        "import time\nfrom svoc_tpu.utils.events import emit_event\n\n"
        "def report(n):\n"
        "    emit_event('consensus.result', n=n, at=time.time())\n"
    ),
    "SVOC009": (
        "def derive_seed(claim_id):\n    return hash(claim_id) & 0xFFFF\n"
    ),
    "SVOC010": (
        "import threading\nfrom svoc_tpu.utils.events import emit_event\n\n"
        "_lock = threading.Lock()\n\ndef commit(n):\n    with _lock:\n"
        "        emit_event('commit.sent', sent=n)\n"
    ),
    "SVOC011": (
        "import os\n\nclass Router:\n    def step(self):\n"
        "        return os.environ.get('SVOC_CONSENSUS_IMPL')\n"
    ),
    "SVOC012": (
        "import json, os\n\ndef publish(path, payload):\n"
        "    with open(path + '.tmp', 'w') as f:\n"
        "        json.dump(payload, f)\n"
        "    os.replace(path + '.tmp', path)\n"
    ),
    # a stale volatile annotation in a serializer module is SVOC013's
    # single-file form (uncovered-field findings need a two-module tree)
    "SVOC013": (
        "def save_state(session):\n"
        "    return {'cursor': session.cursor}\n"
        "\n"
        "SCRATCH = 1  # svoc: volatile(scratch buffer)\n"
    ),
    "SVOC014": (
        "def step(store):\n"
        "    try:\n"
        "        return store.fetch()\n"
        "    except Exception:\n"
        "        return None\n"
    ),
    "SVOC015": (
        "from svoc_tpu.utils.events import emit_event\n\n"
        "def notify(n):\n"
        "    emit_event('bogus.event_xyz', n=n)\n"
    ),
    "SVOC016": (
        "import time\n"
        "from svoc_tpu.utils.events import emit_event\n\n"
        "def report(n):\n"
        "    started = time.perf_counter()\n"
        "    took = 1.0 - started\n"
        "    emit_event('consensus.result', took=took)\n"
    ),
    "SVOC017": (
        "from jax.sharding import PartitionSpec\n\n"
        "CLAIM_AXIS = 'claims'\n\n"
        "def spec():\n"
        "    return PartitionSpec('oraclez')\n"
    ),
}

#: Rules whose single-file fixture only fires at a specific path (the
#: SVOC013 coverage walk roots on serializer-module suffixes).
_INJECTED_PATHS = {"SVOC013": os.path.join("utils", "checkpoint.py")}


@pytest.mark.parametrize("rule", sorted(_INJECTED))
def test_cli_exits_nonzero_on_injected_violation(rule, tmp_path):
    bad = tmp_path / _INJECTED_PATHS.get(rule, f"bad_{rule.lower()}.py")
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(_INJECTED[rule])
    proc = _run_cli([str(bad), "--no-baseline"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert rule in proc.stdout


def test_cli_honors_checked_in_baseline_from_any_cwd(tmp_path):
    # The default baseline + root are anchored to the repo, not the
    # CWD: the grandfathered flash_probe findings stay baselined.
    proc = _run_cli(
        [os.path.join(REPO_ROOT, "svoc_tpu"), os.path.join(REPO_ROOT, "tools")],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6 baselined" in proc.stdout


def test_overlapping_paths_analyze_each_file_once():
    # "tools tools/flash_probe.py" must not double-analyze the probe —
    # duplicate findings would exhaust the baseline multiset.
    proc = _run_cli(
        [
            os.path.join(REPO_ROOT, "tools"),
            os.path.join(REPO_ROOT, "tools", "flash_probe.py"),
        ]
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6 baselined" in proc.stdout


def test_cli_list_rules():
    proc = _run_cli(["--list-rules"])
    assert proc.returncode == 0
    for rule in _INJECTED:
        assert rule in proc.stdout


def test_cli_default_paths_work_from_any_cwd(tmp_path):
    proc = _run_cli([], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6 baselined" in proc.stdout


def test_cli_bad_path_is_usage_error():
    proc = _run_cli(["definitely/not/a/path"])
    assert proc.returncode == 2


def test_write_baseline_over_a_subset_keeps_other_paths_entries(tmp_path):
    # regenerating over one tree must not drop another tree's
    # grandfathered entries (or their curated reasons)
    sub_a = tmp_path / "a"
    sub_b = tmp_path / "b"
    sub_a.mkdir(), sub_b.mkdir()
    bad = "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    (sub_a / "mod_a.py").write_text(bad)
    (sub_b / "mod_b.py").write_text(bad)
    bl = tmp_path / "bl.json"
    proc = _run_cli(
        [str(sub_a), str(sub_b), "--baseline", str(bl), "--write-baseline",
         "--root", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.load(open(bl))
    assert len(data["entries"]) == 2
    for e in data["entries"]:
        e["reason"] = "curated " + e["path"]
    json.dump(data, open(bl, "w"))
    # rewrite analyzing ONLY sub_a: sub_b's entry must survive verbatim
    proc = _run_cli(
        [str(sub_a), "--baseline", str(bl), "--write-baseline",
         "--root", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.load(open(bl))["entries"]
    assert len(entries) == 2
    assert {e["reason"] for e in entries} == {
        "curated a/mod_a.py",
        "curated b/mod_b.py",
    }
    # and the full run is still green against the rewritten baseline
    proc = _run_cli(
        [str(sub_a), str(sub_b), "--baseline", str(bl),
         "--root", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_write_baseline_refuses_to_grandfather_parse_errors(tmp_path):
    # A file the linter cannot parse must never become permanently
    # green via the baseline.
    (tmp_path / "broken.py").write_text("def f(:\n")
    bl = tmp_path / "bl.json"
    proc = _run_cli(
        [str(tmp_path), "--baseline", str(bl), "--write-baseline"]
    )
    assert proc.returncode == 1
    assert "refused" in proc.stderr
    assert all(
        e["rule"] != "SVOC000" for e in json.load(open(bl))["entries"]
    )
    # and the next gated run still fails on the parse error
    proc = _run_cli([str(tmp_path), "--baseline", str(bl)])
    assert proc.returncode == 1
    assert "SVOC000" in proc.stdout


def test_syntax_error_becomes_svoc000_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    proc = _run_cli([str(bad), "--no-baseline"])
    assert proc.returncode == 1
    assert "SVOC000" in proc.stdout


def test_linting_never_imports_jax():
    """The CI gate must run on accelerator-free boxes: importing the
    analyzer and linting the whole package may not pull in jax."""
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            (
                "import sys; sys.path.insert(0, '.');"
                "from svoc_tpu.analysis import analyze_paths, RULE_DOCS;"
                "from svoc_tpu.analysis.sarif import to_sarif;"
                "r = analyze_paths(['svoc_tpu', 'tools']);"
                "assert r.files > 50;"
                "doc = to_sarif(r.all_findings, RULE_DOCS, root='.');"
                "assert doc['version'] == '2.1.0';"
                "assert 'jax' not in sys.modules, 'lint imported jax';"
                "assert 'numpy' not in sys.modules, 'lint imported numpy'"
            ),
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# SVOC008 — wall-clock-in-fingerprinted-path (interprocedural)
# ---------------------------------------------------------------------------


def test_svoc008_flags_wall_clock_inline_in_emit_data():
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event

            def report(n):
                emit_event("consensus.result", n=n, at=time.time())
            """
        )
    )
    assert rules_of(findings) == ["SVOC008"]
    assert findings[0].path_trace  # interprocedural findings carry a trace


def test_svoc008_flags_wall_clock_through_a_helper_with_path_trace():
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event

            def stamp():
                return time.time()

            def report(n):
                emit_event("consensus.result", n=n, at=stamp())
            """
        )
    )
    assert rules_of(findings) == ["SVOC008"]
    trace = " | ".join(findings[0].path_trace)
    assert "stamp" in trace and "time.time" in trace


def test_svoc008_flags_fingerprint_path_reaching_clock():
    findings = analyze_source(
        src(
            """
            import time

            def fingerprint_payload(data):
                return {"data": data, "at": time.time()}
            """
        )
    )
    assert rules_of(findings) == ["SVOC008"]


def test_svoc008_negative_clock_outside_emit_data_and_bare_time_method():
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event
            from svoc_tpu.utils.metrics import registry as metrics

            def report(n):
                t0 = time.perf_counter()
                emit_event("consensus.result", n=n)
                with metrics.timer("latency").time():
                    pass
                return time.perf_counter() - t0
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC009 — process-randomized-draw (interprocedural)
# ---------------------------------------------------------------------------


def test_svoc009_flags_hash_random_and_set_iteration_in_seed_paths():
    findings = analyze_source(
        src(
            """
            import random

            def derive_seed(claim_id):
                return hash(claim_id) & 0xFFFF

            def jitter_seed():
                return int(random.random() * 1e6)

            def mix_seed(ids):
                total = 0
                for i in set(ids):
                    total ^= i
                return total
            """
        )
    )
    assert rules_of(findings) == ["SVOC009"]
    assert len(findings) == 3


def test_svoc009_flags_draw_reached_through_a_helper():
    findings = analyze_source(
        src(
            """
            def _salt(x):
                return hash(x)

            def claim_seed(base, claim_id):
                return base ^ _salt(claim_id)
            """
        )
    )
    assert rules_of(findings) == ["SVOC009"]
    assert any("claim_seed" in h for f in findings for h in f.path_trace)


def test_svoc009_negative_crc32_seeded_random_and_sorted_set():
    findings = analyze_source(
        src(
            """
            import random
            import zlib

            def claim_seed(base, claim_id):
                return zlib.crc32(repr(claim_id).encode()) ^ base

            def jitter_seed(seed):
                return random.Random(seed).random()

            def mix_seed(ids):
                return sum(i for i in sorted(set(ids)))
            """
        )
    )
    assert findings == []


def test_svoc009_negative_outside_seed_paths():
    # hash()/set iteration in NON-derivation functions is ordinary code
    findings = analyze_source(
        src(
            """
            def bucket(x):
                return hash(x) % 8

            def union(ids):
                return [i for i in set(ids)]
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC010 — emit-under-lock / lock-order (interprocedural)
# ---------------------------------------------------------------------------

_LEAF_LOCK_VIOLATION = """
import threading
from svoc_tpu.utils.events import emit_event

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def _publish(self, n):
        emit_event("consensus.result", n=n)

    def commit(self, n):
        with self._lock:
            self._publish(n)
"""


def test_svoc010_flags_emit_reached_while_lock_held():
    findings = analyze_source(src(_LEAF_LOCK_VIOLATION))
    assert rules_of(findings) == ["SVOC010"]
    (f,) = findings
    assert "_lock" in f.message
    trace = " | ".join(f.path_trace)
    assert "_publish" in trace and "emit" in trace


def test_svoc010_flags_direct_emit_under_lock():
    findings = analyze_source(
        src(
            """
            import threading
            from svoc_tpu.utils.events import emit_event

            _lock = threading.Lock()

            def commit(n):
                with _lock:
                    emit_event("commit.sent", sent=n)
            """
        )
    )
    assert rules_of(findings) == ["SVOC010"]


def test_svoc010_negative_queue_and_flush_after_release():
    findings = analyze_source(
        src(
            """
            import threading
            from svoc_tpu.utils.events import emit_event

            class Breaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pending = []

                def record(self, n):
                    with self._lock:
                        self._pending.append(n)
                    for n in self._pending:
                        emit_event("breaker.transition", n=n)
            """
        )
    )
    assert findings == []


def test_svoc010_negative_journal_internal_locks_are_leaves():
    # The journal holding its OWN lock around the ring append is the
    # design — utils/events.py locks are exempt.
    findings = analyze_source(
        src(
            """
            import threading

            class EventJournal:
                def __init__(self):
                    self._lock = threading.Lock()

                def emit(self, event_type, **data):
                    with self._lock:
                        self._ring.append((event_type, data))
            """
        ),
        path="svoc_tpu/utils/events.py",
    )
    assert findings == []


def test_svoc010_flags_lock_acquisition_cycle():
    findings = analyze_source(
        src(
            """
            import threading

            a_lock = threading.Lock()
            b_lock = threading.Lock()

            def one():
                with a_lock:
                    with b_lock:
                        pass

            def two():
                with b_lock:
                    with a_lock:
                        pass
            """
        )
    )
    assert rules_of(findings) == ["SVOC010"]
    assert any("cycle" in f.message for f in findings)


def test_svoc010_negative_consistent_lock_order():
    findings = analyze_source(
        src(
            """
            import threading

            a_lock = threading.Lock()
            b_lock = threading.Lock()

            def one():
                with a_lock:
                    with b_lock:
                        pass

            def two():
                with a_lock:
                    with b_lock:
                        pass
            """
        )
    )
    assert findings == []


def test_svoc010_flags_interprocedural_lock_cycle():
    # f holds A and calls g which takes B; h holds B and calls k which
    # takes A — the cycle spans four functions.
    findings = analyze_source(
        src(
            """
            import threading

            a_lock = threading.Lock()
            b_lock = threading.Lock()

            def take_b():
                with b_lock:
                    pass

            def take_a():
                with a_lock:
                    pass

            def one():
                with a_lock:
                    take_b()

            def two():
                with b_lock:
                    take_a()
            """
        )
    )
    assert "SVOC010" in rules_of(findings)
    assert any("cycle" in f.message for f in findings)


# ---------------------------------------------------------------------------
# SVOC011 — unpinned-replay-knob (interprocedural)
# ---------------------------------------------------------------------------

_PER_STEP_ENV_READ = """
import os

class Router:
    def step(self):
        return os.environ.get("SVOC_CONSENSUS_IMPL")
"""

_PINNED_ENV_READ = """
import os

class Router:
    def __init__(self):
        self._impl = os.environ.get("SVOC_CONSENSUS_IMPL")

    def step(self):
        return self._impl
"""


def test_svoc011_pinned_vs_per_step_env_read_pair():
    flagged = analyze_source(src(_PER_STEP_ENV_READ))
    assert rules_of(flagged) == ["SVOC011"]
    assert "pinned" in flagged[0].message or "pinned" in flagged[0].hint
    assert analyze_source(src(_PINNED_ENV_READ)) == []


def test_svoc011_flags_knob_resolution_through_helpers():
    findings = analyze_source(
        src(
            """
            from svoc_tpu.consensus.dispatch import resolve_consensus_impl

            def _route():
                return resolve_consensus_impl()

            class Dispatcher:
                def dispatch_gated(self, values):
                    return _route()
            """
        )
    )
    assert rules_of(findings) == ["SVOC011"]
    trace = " | ".join(findings[0].path_trace)
    assert "dispatch_gated" in trace and "resolve_consensus_impl" in trace


def test_svoc011_prewarm_and_warmup_bodies_are_construction_time():
    # ISSUE 15 satellite: the compile plane's warmup worker names its
    # unit-of-work ``step()`` and deliberately walks knob-resolving jit
    # paths AHEAD of traffic — the entry heuristic must read any
    # prewarm/warmup-qualified body as construction-time, while the
    # same body under a non-warmup name keeps flagging.
    warm = """
    import os

    class PrewarmWorker:
        def step(self, key):
            return os.environ.get("SVOC_CONSENSUS_IMPL")

    def warmup_step():
        return os.environ.get("SVOC_CONSENSUS_IMPL")
    """
    assert analyze_source(src(warm)) == []
    hot = """
    import os

    class CubeWorker:
        def step(self, key):
            return os.environ.get("SVOC_CONSENSUS_IMPL")
    """
    assert rules_of(analyze_source(src(hot))) == ["SVOC011"]


def test_svoc011_negative_non_svoc_env_and_non_entry_functions():
    findings = analyze_source(
        src(
            """
            import os

            def configure():
                # not a step/dispatch/fetch body: resolution-time read
                return os.environ.get("SVOC_CONSENSUS_IMPL")

            class Router:
                def step(self):
                    return os.environ.get("HOME")  # not a replay knob
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC012 — durability-ordering
# ---------------------------------------------------------------------------


def test_svoc012_flags_replace_without_fsync():
    findings = analyze_source(
        src(
            """
            import json, os

            def publish(path, payload):
                with open(path + ".tmp", "w") as f:
                    json.dump(payload, f)
                os.replace(path + ".tmp", path)
            """
        )
    )
    assert rules_of(findings) == ["SVOC012"]
    assert "fsync" in findings[0].message


def test_svoc012_negative_fsynced_replace():
    findings = analyze_source(
        src(
            """
            import json, os
            from svoc_tpu.utils.events import fsync_dir

            def publish(path, payload):
                with open(path + ".tmp", "w") as f:
                    json.dump(payload, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(path + ".tmp", path)
                fsync_dir(path)
            """
        )
    )
    assert findings == []


def test_svoc012_flags_durability_path_write_without_fsync():
    findings = analyze_source(
        src(
            """
            import json

            class WAL:
                def append(self, record):
                    self._f.write(json.dumps(record) + "\\n")
                    self._f.flush()
            """
        ),
        path="svoc_tpu/durability/wal.py",
    )
    assert rules_of(findings) == ["SVOC012"]


def test_svoc012_negative_durability_write_with_fsync_and_non_durability_scope():
    fsynced = src(
        """
        import json, os

        class WAL:
            def append(self, record):
                self._f.write(json.dumps(record) + "\\n")
                self._f.flush()
                os.fsync(self._f.fileno())
        """
    )
    assert analyze_source(fsynced, path="svoc_tpu/durability/wal.py") == []
    # the same unfsynced write OUTSIDE durability scope is ordinary I/O
    plain = src(
        """
        import json

        class Exporter:
            def append(self, record):
                self._f.write(json.dumps(record) + "\\n")
        """
    )
    assert analyze_source(plain, path="svoc_tpu/utils/export.py") == []


# ---------------------------------------------------------------------------
# call-graph resolution units
# ---------------------------------------------------------------------------


def test_callgraph_resolution_local_imported_self_and_alias():
    import ast as _ast

    from svoc_tpu.analysis.callgraph import Program, summarize_module

    helpers = summarize_module(
        "pkg/helpers.py",
        _ast.parse(
            src(
                """
                def derive(x):
                    return x

                class Store:
                    def persist(self):
                        pass
                """
            )
        ),
    )
    main = summarize_module(
        "pkg/main.py",
        _ast.parse(
            src(
                """
                from pkg.helpers import derive
                from pkg import helpers as h

                def local():
                    pass

                class Engine:
                    def helper_method(self):
                        pass

                    def run(self, store):
                        local()
                        derive(1)
                        h.derive(2)
                        self.helper_method()
                        store.persist()
                        store.commit()
                """
            )
        ),
    )
    program = Program([helpers, main])
    run = next(f for f in main.functions if f.name == "run")
    calls = {c.name or c.leaf: c for c in run.calls}
    resolve = lambda c: program.resolve(main, c, run)
    assert resolve(calls["local"]) == "pkg/main.py::local"
    assert resolve(calls["derive"]) == "pkg/helpers.py::derive"
    assert resolve(calls["h.derive"]) == "pkg/helpers.py::derive"
    assert resolve(calls["self.helper_method"]) == "pkg/main.py::Engine.helper_method"
    # unique-method fallback: persist is defined by exactly one class
    assert resolve(calls["store.persist"]) == "pkg/helpers.py::Store.persist"
    # blacklisted common method: conn.commit must never cross-resolve
    assert resolve(calls["store.commit"]) is None


def test_cross_module_interprocedural_finding_via_analyze_paths(tmp_path):
    (tmp_path / "clocks.py").write_text(
        "import time\n\n\ndef stamp():\n    return time.time()\n"
    )
    (tmp_path / "reporter.py").write_text(
        "from clocks import stamp\n"
        "from svoc_tpu.utils.events import emit_event\n\n\n"
        "def report(n):\n"
        "    emit_event('consensus.result', n=n, at=stamp())\n"
    )
    report = analyze_paths([str(tmp_path)], root=str(tmp_path))
    rules = rules_of(report.all_findings)
    assert rules == ["SVOC008"]
    (f,) = report.all_findings
    assert f.path == "reporter.py"
    assert any("clocks.py" in hop for hop in f.path_trace)


def test_interprocedural_findings_respect_inline_suppressions():
    findings = analyze_source(
        src(
            """
            import threading
            from svoc_tpu.utils.events import emit_event

            _lock = threading.Lock()

            def commit(n):
                with _lock:
                    emit_event("commit.sent", sent=n)  # svoclint: disable=SVOC010 -- no subscriber re-enters
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# findings cache (.svoclint_cache.json)
# ---------------------------------------------------------------------------


def _make_tree(root, n=60):
    for i in range(n):
        body = "\n".join(
            f"def fn_{i}_{j}(x):\n    return x + {j}\n" for j in range(20)
        )
        (root / f"mod_{i:03d}.py").write_text(
            f'"""module {i}"""\nimport json\n\n{body}\n'
        )


def test_cache_cold_parses_warm_does_not_and_is_faster(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    _make_tree(tree, n=120)
    cache = str(tmp_path / "cache.json")
    cold = analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    assert cold.parsed == cold.files == 120
    assert cold.cache_hits == 0
    warm = analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    assert warm.parsed == 0
    assert warm.cache_hits == 120
    assert warm.all_findings == cold.all_findings
    # the cache exists to buy time: a warm run skips every parse.
    # Wall-clock on a loaded single-core box can stall any ONE run, so
    # the timing claim is best-of-3 warm vs the single cold run.
    warm_times = [warm.duration_s] + [
        analyze_paths(
            [str(tree)], root=str(tmp_path), cache_path=cache
        ).duration_s
        for _ in range(2)
    ]
    assert min(warm_times) < cold.duration_s


def test_cache_invalidates_only_the_edited_file(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    _make_tree(tree, n=10)
    cache = str(tmp_path / "cache.json")
    analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    (tree / "mod_003.py").write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    )
    r = analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    assert r.parsed == 1 and r.cache_hits == 9
    assert rules_of(r.all_findings) == ["SVOC001"]


def test_cache_subset_run_does_not_evict_other_entries(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    _make_tree(tree, n=8)
    cache = str(tmp_path / "cache.json")
    analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    # a one-file subset run rewrites the cache...
    analyze_paths(
        [str(tree / "mod_000.py")], root=str(tmp_path), cache_path=cache
    )
    # ...but the full tree is still warm afterwards
    r = analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    assert r.parsed == 0 and r.cache_hits == 8


def test_cache_version_mismatch_invalidates(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    _make_tree(tree, n=4)
    cache = str(tmp_path / "cache.json")
    analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    data = json.load(open(cache))
    data["ruleset"] = "older-ruleset"
    json.dump(data, open(cache, "w"))
    r = analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    assert r.parsed == 4 and r.cache_hits == 0


def test_cli_cache_flag_round_trip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    cache = str(tmp_path / "cache.json")
    first = _run_cli([str(bad), "--no-baseline", "--cache", cache])
    second = _run_cli([str(bad), "--no-baseline", "--cache", cache])
    assert first.returncode == second.returncode == 1
    assert "SVOC001" in second.stdout
    assert "0 parsed" in second.stdout  # warm run, same findings


# ---------------------------------------------------------------------------
# --changed mode
# ---------------------------------------------------------------------------


def _git(cwd, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=30,
    )


def test_changed_mode_lints_only_files_differing_from_main(tmp_path):
    if _git(tmp_path, "--version").returncode != 0:
        pytest.skip("git unavailable")
    repo = tmp_path / "repo"
    repo.mkdir()
    assert _git(repo, "init", "-q", "-b", "main").returncode == 0
    bad = "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    (repo / "committed_bad.py").write_text(bad)
    (repo / "touched.py").write_text("x = 1\n")
    _git(repo, "add", "-A")
    assert _git(repo, "commit", "-q", "-m", "seed").returncode == 0
    # committed_bad is UNCHANGED vs main; touched gains a violation
    (repo / "touched.py").write_text(bad)
    proc = _run_cli(
        [str(repo), "--changed", "--no-baseline", "--no-cache",
         "--root", str(repo)],
        cwd=str(repo),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "touched.py" in proc.stdout
    assert "committed_bad.py" not in proc.stdout


def test_changed_mode_clean_when_nothing_changed(tmp_path):
    if _git(tmp_path, "--version").returncode != 0:
        pytest.skip("git unavailable")
    repo = tmp_path / "repo"
    repo.mkdir()
    assert _git(repo, "init", "-q", "-b", "main").returncode == 0
    (repo / "mod.py").write_text("x = 1\n")
    _git(repo, "add", "-A")
    assert _git(repo, "commit", "-q", "-m", "seed").returncode == 0
    proc = _run_cli(
        [str(repo), "--changed", "--no-baseline", "--no-cache",
         "--root", str(repo)],
        cwd=str(repo),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no changed python files" in proc.stdout


def test_changed_mode_falls_back_to_full_tree_without_git(tmp_path):
    # --root points at a directory that is not a git repo (and has no
    # main ref): --changed must lint the FULL tree, loudly.
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    env_dir = tmp_path  # no .git anywhere up to /tmp... but the repo
    # itself is one; point --root at tmp_path so merge-base runs there
    proc = _run_cli(
        [str(bad), "--changed", "--no-baseline", "--no-cache",
         "--root", str(env_dir)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "SVOC001" in proc.stdout
    assert "full tree" in proc.stderr


# ---------------------------------------------------------------------------
# stale-entry rebase suggestions
# ---------------------------------------------------------------------------


def test_stale_baseline_entry_suggests_nearest_rebase(tmp_path):
    original = "import jax\n\n@jax.jit\ndef f(x):\n    return np.asarray(x)\n"
    edited = "import jax\n\n@jax.jit\ndef f(x):\n    return np.asarray(x * 2)\n"
    mod = tmp_path / "mod.py"
    mod.write_text("import numpy as np\n" + original)
    bl = tmp_path / "bl.json"
    proc = _run_cli([str(mod), "--baseline", str(bl), "--write-baseline",
                     "--no-cache", "--root", str(tmp_path)])
    assert proc.returncode == 0
    mod.write_text("import numpy as np\n" + edited)
    proc = _run_cli([str(mod), "--baseline", str(bl), "--no-cache",
                     "--root", str(tmp_path)])
    assert proc.returncode == 1
    assert "stale baseline entry" in proc.stdout
    assert "suggested rebase" in proc.stdout
    assert "np.asarray(x * 2)" in proc.stdout
    # ...and the JSON form carries the suggestion structurally
    proc = _run_cli([str(mod), "--baseline", str(bl), "--no-cache",
                     "--format", "json", "--root", str(tmp_path)])
    payload = json.loads(proc.stdout)
    (entry,) = payload["stale_baseline_entries"]
    assert entry["suggested_rebase"]["snippet"] == "return np.asarray(x * 2)"


def test_stale_entry_with_no_successor_suggests_nothing(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import jax\nimport numpy as np\n\n@jax.jit\ndef f(x):\n"
        "    return np.asarray(x)\n"
    )
    bl = tmp_path / "bl.json"
    _run_cli([str(mod), "--baseline", str(bl), "--write-baseline",
              "--no-cache", "--root", str(tmp_path)])
    mod.write_text("x = 1\n")  # finding truly fixed
    proc = _run_cli([str(mod), "--baseline", str(bl), "--no-cache",
                     "--root", str(tmp_path)])
    assert proc.returncode == 1
    assert "stale baseline entry" in proc.stdout
    assert "suggested rebase" not in proc.stdout


# ---------------------------------------------------------------------------
# JSON schema: path_trace
# ---------------------------------------------------------------------------


def test_json_findings_carry_path_trace_for_interprocedural_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_INJECTED["SVOC010"])
    proc = _run_cli([str(bad), "--no-baseline", "--no-cache",
                     "--format", "json"])
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    (finding,) = payload["findings"]
    assert finding["rule"] == "SVOC010"
    assert isinstance(finding["path_trace"], list) and finding["path_trace"]
    # per-module findings carry an EMPTY trace, same schema
    bad2 = tmp_path / "bad2.py"
    bad2.write_text(_INJECTED["SVOC001"])
    proc = _run_cli([str(bad2), "--no-baseline", "--no-cache",
                     "--format", "json"])
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["path_trace"] == []

# ---------------------------------------------------------------------------
# SVOC013 — snapshot-coverage (contract plane)
# ---------------------------------------------------------------------------


def _write(tree, rel, text):
    path = tree / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src(text))
    return path


_SVOC013_SERIALIZER = """
    from app import read_fields

    def save(session):
        return read_fields(session)
    """

_SVOC013_APP = """
    class Session:
        def step(self):
            self.cursor = 1
            self.backlog = []

    def read_fields(session):
        return {"cursor": session.cursor}
    """


def _svoc013(report):
    return [f for f in report.all_findings if f.rule == "SVOC013"]


def test_svoc013_flags_uncovered_replay_field_with_trace(tmp_path):
    tree = tmp_path / "tree"
    _write(tree, "utils/checkpoint.py", _SVOC013_SERIALIZER)
    _write(tree, "app.py", _SVOC013_APP)
    findings = _svoc013(analyze_paths([str(tree)], root=str(tree)))
    assert len(findings) == 1
    (f,) = findings
    # `cursor` is covered through the serializer's helper call;
    # `backlog` is the gap
    assert "self.backlog" in f.message and "Session" in f.message
    assert f.path == "app.py"
    trace = " | ".join(f.path_trace)
    assert "utils/checkpoint.py" in trace  # names the coverage roots


def test_svoc013_negative_serializer_coverage_through_helper(tmp_path):
    tree = tmp_path / "tree"
    _write(tree, "utils/checkpoint.py", _SVOC013_SERIALIZER)
    _write(
        tree,
        "app.py",
        """
        class Session:
            def step(self):
                self.cursor = 1

        def read_fields(session):
            return {"cursor": session.cursor}
        """,
    )
    assert _svoc013(analyze_paths([str(tree)], root=str(tree))) == []


def test_svoc013_volatile_annotation_suppresses_with_reason(tmp_path):
    tree = tmp_path / "tree"
    _write(tree, "utils/checkpoint.py", _SVOC013_SERIALIZER)
    _write(
        tree,
        "app.py",
        """
        class Session:
            def step(self):
                self.cursor = 1
                self.backlog = []  # svoc: volatile(rebuilt per step)

        def read_fields(session):
            return {"cursor": session.cursor}
        """,
    )
    assert _svoc013(analyze_paths([str(tree)], root=str(tree))) == []


def test_svoc013_stale_volatile_annotation_is_its_own_finding(tmp_path):
    # The annotated field got covered (or renamed): the claim is stale
    # and must fail exactly like a stale baseline entry.
    tree = tmp_path / "tree"
    _write(tree, "utils/checkpoint.py", _SVOC013_SERIALIZER)
    _write(
        tree,
        "app.py",
        """
        class Session:
            def step(self):
                self.cursor = 1  # svoc: volatile(obsolete claim)

        def read_fields(session):
            return {"cursor": session.cursor}
        """,
    )
    findings = _svoc013(analyze_paths([str(tree)], root=str(tree)))
    assert len(findings) == 1
    assert "stale" in findings[0].message
    assert "obsolete claim" in findings[0].message


def test_svoc013_skips_subset_runs_without_serializer_modules(tmp_path):
    # a --changed slice with no serializer module has no coverage
    # roots: flagging every field would be pure noise
    tree = tmp_path / "tree"
    _write(tree, "app.py", _SVOC013_APP)
    assert _svoc013(analyze_paths([str(tree)], root=str(tree))) == []


def test_svoc013_non_replay_classes_are_out_of_scope(tmp_path):
    tree = tmp_path / "tree"
    _write(tree, "utils/checkpoint.py", _SVOC013_SERIALIZER)
    _write(
        tree,
        "app.py",
        """
        class ScratchPad:
            def step(self):
                self.doodle = 1

        def read_fields(session):
            return {"cursor": session.cursor}
        """,
    )
    assert _svoc013(analyze_paths([str(tree)], root=str(tree))) == []


def test_svoc013_catches_seeded_regression_in_real_tier(tmp_path):
    """Acceptance: adding a mutable field to the REAL ServingTier that
    the durable serializers never read must produce a SVOC013 finding
    with a path_trace — the exact regression class PR 8 closed by hand."""
    tree = tmp_path / "tree"
    for rel in ("utils/checkpoint.py", "serving/tier.py"):
        with open(os.path.join(REPO_ROOT, "svoc_tpu", rel)) as fh:
            _write(tree, rel, fh.read())
    before = {
        (f.path, f.message)
        for f in _svoc013(analyze_paths([str(tree)], root=str(tree)))
    }
    with open(tree / "serving" / "tier.py", "a") as fh:
        fh.write(
            "\n\nclass ServingTier:\n"
            "    def _seeded_tick(self):\n"
            "        self._seeded_drift_window = {}\n"
        )
    after = _svoc013(analyze_paths([str(tree)], root=str(tree)))
    fresh = [f for f in after if (f.path, f.message) not in before]
    seeded = [f for f in fresh if "_seeded_drift_window" in f.message]
    assert seeded, "seeded uncovered field not caught:\n" + "\n".join(
        f.render() for f in after
    )
    assert seeded[0].path_trace


# ---------------------------------------------------------------------------
# SVOC014 — silent-fallback (contract plane)
# ---------------------------------------------------------------------------


def test_svoc014_flags_silent_handler_in_step_entry():
    findings = analyze_source(
        src(
            """
            def step(store):
                try:
                    return store.fetch()
                except Exception:
                    return None
            """
        )
    )
    assert "SVOC014" in rules_of(findings)
    f = next(f for f in findings if f.rule == "SVOC014")
    assert "silent fallback" in f.message
    assert f.path_trace


def test_svoc014_flags_silent_handler_reached_through_helper():
    findings = analyze_source(
        src(
            """
            def _quiet(store):
                try:
                    return store.fetch()
                except Exception:
                    return None

            def step(store):
                return _quiet(store)
            """
        )
    )
    hits = [f for f in findings if f.rule == "SVOC014"]
    assert hits
    trace = " | ".join(hits[0].path_trace)
    assert "step" in trace and "_quiet" in trace


def test_svoc014_negative_reraise_counter_and_exception_capture():
    findings = analyze_source(
        src(
            """
            from svoc_tpu.utils.metrics import registry

            def step(store):
                try:
                    return store.fetch()
                except Exception:
                    raise

            def submit(store):
                try:
                    return store.fetch()
                except Exception:
                    registry.counter("submit_fallback").add(1)
                    return None

            def drain(store, log):
                try:
                    return store.fetch()
                except Exception as e:
                    log.append(str(e))
                    return None
            """
        )
    )
    assert "SVOC014" not in rules_of(findings)


def test_svoc014_negative_handler_outside_entry_reachability():
    # not an entry name and never called from one: out of scope
    findings = analyze_source(
        src(
            """
            def helper(store):
                try:
                    return store.fetch()
                except Exception:
                    return None
            """
        )
    )
    assert "SVOC014" not in rules_of(findings)


def test_svoc014_inline_suppression_with_reason():
    findings = analyze_source(
        src(
            """
            def step(store):
                try:
                    return store.fetch()
                except Exception:  # svoclint: disable=SVOC014 -- counted upstream
                    return None
            """
        )
    )
    assert "SVOC014" not in rules_of(findings)


# ---------------------------------------------------------------------------
# SVOC015 — emission-taxonomy sync (contract plane)
# ---------------------------------------------------------------------------


def test_svoc015_docs_parser_round_trip():
    from svoc_tpu.analysis.emissions import parse_observability_tables

    lines = [
        "Prose mentioning `not.documented` and `svoc_not_a_row` does",
        "not count as documentation.",
        "",
        "| type | emitted by | data |",
        "|------|------------|------|",
        "| `a.b` | `app.py: run` | `n` |",
        "",
        "| series | type | meaning |",
        "|--------|------|---------|",
        "| `svoc_foo_total` | counter | things (`svoc_red_herring`) |",
        "| `svoc_cache_events_total{event=hit\\|miss}` | counter | raw |",
        "| `svoc_bar_seconds` | timer | wall time |",
        "",
        "| SLO | target | window |",
        "|-----|--------|--------|",
        "| `availability` | 99.9 | 30d |",
    ]
    doc_events, doc_series = parse_observability_tables(lines)
    assert doc_events == {"a.b": 6}
    # svoc_ prefix and {label=...} suffix stripped; the escaped pipe
    # inside the label set must not break the cell split; backticks in
    # NON-FIRST cells never count
    assert set(doc_series) == {"foo_total", "cache_events_total", "bar_seconds"}
    # a non-series, non-event table (the SLO table) parses as neither
    assert "availability" not in doc_series and "availability" not in doc_events


def test_svoc015_two_way_join_over_a_tree(tmp_path):
    tree = tmp_path / "tree"
    _write(
        tree,
        "docs/OBSERVABILITY.md",
        """
        | type | emitted by | data |
        |------|------------|------|
        | `a.b` | `app.py: run` | `n` |
        | `never.sent` | nobody | |

        | series | type | meaning |
        |--------|------|---------|
        | `svoc_foo_total` | counter | counted |
        | `svoc_ghost_total` | counter | never registered |
        """,
    )
    # completeness markers: the doc-side direction only runs when the
    # journal and metrics modules are in the analyzed set
    _write(tree, "utils/events.py", "def emit_event(t, **d):\n    return None")
    _write(tree, "utils/metrics.py", "class Registry:\n    pass")
    _write(
        tree,
        "app.py",
        """
        from utils.events import emit_event

        def run(reg, n):
            emit_event("a.b", n=n)
            emit_event("c.d", n=n)
            reg.counter("foo").add(1)
            reg.counter("undocumented_fam").add(1)
        """,
    )
    report = analyze_paths([str(tree)], root=str(tree))
    msgs = [f.message for f in report.all_findings if f.rule == "SVOC015"]
    assert any("`c.d`" in m and "absent" in m for m in msgs)
    assert any("`undocumented_fam`" in m for m in msgs)
    assert any("`never.sent`" in m and "never emitted" in m for m in msgs)
    assert any("`svoc_ghost_total`" in m for m in msgs)
    # the documented-and-emitted pairs are clean
    assert not any("`a.b`" in m for m in msgs)
    assert not any("`foo`" in m or "`svoc_foo_total`" in m for m in msgs)
    assert len(msgs) == 4


def test_svoc015_doc_side_requires_whole_package(tmp_path):
    # without utils/events.py + utils/metrics.py in the analyzed set, a
    # subset run cannot prove a documented name is NEVER emitted
    tree = tmp_path / "tree"
    _write(
        tree,
        "docs/OBSERVABILITY.md",
        """
        | type | emitted by | data |
        |------|------------|------|
        | `never.sent` | nobody | |
        """,
    )
    _write(
        tree,
        "app.py",
        """
        from utils.events import emit_event

        def run(n):
            emit_event("c.d", n=n)
        """,
    )
    report = analyze_paths([str(tree)], root=str(tree))
    msgs = [f.message for f in report.all_findings if f.rule == "SVOC015"]
    assert any("`c.d`" in m for m in msgs)  # code->docs still runs
    assert not any("never.sent" in m for m in msgs)


def test_svoc015_counter_render_matches_total_suffix(tmp_path):
    # family `f` may be documented under any metrics.py render:
    # svoc_f, svoc_f_total, svoc_f_seconds, svoc_f_seconds_max
    tree = tmp_path / "tree"
    _write(
        tree,
        "docs/OBSERVABILITY.md",
        """
        | series | type | meaning |
        |--------|------|---------|
        | `svoc_fetch_latency_seconds` | timer | wall time |
        """,
    )
    _write(
        tree,
        "app.py",
        """
        def run(reg):
            reg.timer("fetch_latency").time()
        """,
    )
    report = analyze_paths([str(tree)], root=str(tree))
    assert not [f for f in report.all_findings if f.rule == "SVOC015"]


# ---------------------------------------------------------------------------
# SVOC016 — fingerprint-taint (contract plane)
# ---------------------------------------------------------------------------


def test_svoc016_flags_clock_taint_through_variable_into_emit():
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event

            def report(n):
                started = time.perf_counter()
                took = 1.0 - started
                emit_event("consensus.result", n=n, took=took)
            """
        )
    )
    hits = [f for f in findings if f.rule == "SVOC016"]
    assert len(hits) == 1
    assert "`took`" in hits[0].message
    trace = " | ".join(hits[0].path_trace)
    assert "source" in trace and "sink" in trace


def test_svoc016_taint_propagates_through_containers_and_fstrings():
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event

            def report_list(n):
                t0 = time.monotonic()
                parts = [t0, n]
                emit_event("consensus.result", parts=parts)

            def report_fstring(n):
                t0 = time.monotonic()
                label = f"run-{t0}"
                emit_event("consensus.result", label=label)
            """
        )
    )
    hits = [f for f in findings if f.rule == "SVOC016"]
    assert len(hits) == 2
    assert any("`parts`" in f.message for f in hits)
    assert any("`label`" in f.message for f in hits)


def test_svoc016_flags_set_iteration_taint_in_fingerprint_return():
    findings = analyze_source(
        src(
            """
            def fingerprint_keys(d):
                acc = ""
                for k in set(d):
                    acc = acc + k
                return acc
            """
        )
    )
    hits = [f for f in findings if f.rule == "SVOC016"]
    assert len(hits) == 1
    assert "fingerprint_keys" in hits[0].message
    assert "set" in hits[0].message


def test_svoc016_negative_sorted_sanitizes_and_reassignment_clears():
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event

            def fingerprint_keys(d):
                acc = ""
                for k in sorted(set(d)):
                    acc = acc + k
                return acc

            def report(n):
                t0 = time.monotonic()
                t0 = 0.0
                emit_event("consensus.result", t0=t0)
            """
        )
    )
    assert "SVOC016" not in rules_of(findings)


def test_svoc016_direct_source_at_sink_is_svoc008_not_svoc016():
    # one hazard, one rule id: the direct form belongs to SVOC008
    findings = analyze_source(
        src(
            """
            import time
            from svoc_tpu.utils.events import emit_event

            def report(n):
                emit_event("consensus.result", at=time.time())
            """
        )
    )
    assert "SVOC008" in rules_of(findings)
    assert "SVOC016" not in rules_of(findings)


# ---------------------------------------------------------------------------
# SVOC017 — shard-spec consistency (contract plane)
# ---------------------------------------------------------------------------


def test_svoc017_flags_unknown_axis_in_partition_spec():
    findings = analyze_source(
        src(
            """
            from jax.sharding import PartitionSpec

            CLAIM_AXIS = "claims"

            def claims_spec():
                return PartitionSpec("oraclez", None)
            """
        )
    )
    hits = [f for f in findings if f.rule == "SVOC017"]
    assert len(hits) == 1
    assert "`oraclez`" in hits[0].message
    assert "claims" in hits[0].message  # names the known universe


def test_svoc017_negative_axes_resolved_through_constants():
    findings = analyze_source(
        src(
            """
            from jax.sharding import PartitionSpec

            CLAIM_AXIS = "claims"
            ORACLE_AXIS = "oracles"

            def claims_spec():
                return PartitionSpec(CLAIM_AXIS, ORACLE_AXIS)

            def literal_but_known():
                return PartitionSpec("claims")
            """
        )
    )
    assert "SVOC017" not in rules_of(findings)


def test_svoc017_flags_collective_over_unknown_axis():
    findings = analyze_source(
        src(
            """
            import jax

            CLAIM_AXIS = "claims"

            def reduce_scores(x):
                return jax.lax.psum(x, "oraclez")
            """
        )
    )
    hits = [f for f in findings if f.rule == "SVOC017"]
    assert len(hits) == 1
    assert "psum" in hits[0].message and "`oraclez`" in hits[0].message


def test_svoc017_any_collective_in_parity_body_is_an_error(tmp_path):
    # even over a KNOWN axis: the claim-cube bodies are the bit-exact
    # parity surface — cross-shard communication there is the bug class
    tree = tmp_path / "tree"
    _write(
        tree,
        "parallel/claim_shard.py",
        """
        import jax

        CLAIM_AXIS = "claims"

        def _host_cube_body(x):
            return jax.lax.psum(x, CLAIM_AXIS)

        def _fleet_cube_body(x):
            return jax.lax.psum(x, CLAIM_AXIS)
        """,
    )
    report = analyze_paths([str(tree)], root=str(tree))
    hits = [f for f in report.all_findings if f.rule == "SVOC017"]
    assert len(hits) == 1
    assert "_host_cube_body" in hits[0].message
    assert "parity" in hits[0].message


def test_svoc017_empty_axis_universe_skips():
    # a subset run without parallel/mesh.py (no *_AXIS constants in
    # sight) proves nothing — must not flag every axis
    findings = analyze_source(
        src(
            """
            from jax.sharding import PartitionSpec

            def claims_spec():
                return PartitionSpec("anything_goes")
            """
        )
    )
    assert "SVOC017" not in rules_of(findings)


# ---------------------------------------------------------------------------
# SARIF export
# ---------------------------------------------------------------------------


def test_sarif_document_shape_and_path_trace_related_locations(tmp_path):
    from svoc_tpu.analysis.sarif import to_sarif

    bad = tmp_path / "bad.py"
    bad.write_text(_INJECTED["SVOC010"])
    report = analyze_paths([str(bad)], root=str(tmp_path))
    doc = to_sarif(report.all_findings, RULE_DOCS, root=str(tmp_path))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == set(RULE_DOCS)
    res = next(r for r in run["results"] if r["ruleId"] == "SVOC010")
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "bad.py"
    assert loc["region"]["startLine"] >= 1
    # the interprocedural path_trace rides as relatedLocations, in
    # order; anchored hops get physical locations
    assert res["relatedLocations"]
    assert any("physicalLocation" in rl for rl in res["relatedLocations"])
    for rl in res["relatedLocations"]:
        if "physicalLocation" in rl:
            assert rl["physicalLocation"]["artifactLocation"]["uri"] == "bad.py"


def test_sarif_levels_follow_rule_severity(tmp_path):
    from svoc_tpu.analysis.sarif import to_sarif

    bad = tmp_path / "bad.py"
    bad.write_text(_INJECTED["SVOC001"])
    report = analyze_paths([str(bad)], root=str(tmp_path))
    doc = to_sarif(report.all_findings, RULE_DOCS, root=str(tmp_path))
    res = next(
        r for r in doc["runs"][0]["results"] if r["ruleId"] == "SVOC001"
    )
    assert res["level"] == RULE_DOCS["SVOC001"]["severity"]


def test_cli_sarif_flag_writes_document(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_INJECTED["SVOC001"])
    out = tmp_path / "findings.sarif"
    proc = _run_cli([str(bad), "--no-baseline", "--sarif", str(out)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"][0]["ruleId"] == "SVOC001"


def test_cli_sarif_clean_repo_run_exports_empty_results(tmp_path):
    # baselined findings are accepted debt — they must NOT surface as
    # annotations on every PR
    out = tmp_path / "clean.sarif"
    proc = _run_cli(["--sarif", str(out)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["results"] == []


# ---------------------------------------------------------------------------
# contract-plane cache + timing acceptance
# ---------------------------------------------------------------------------


def test_cache_rejects_pre_contract_ruleset_version(tmp_path):
    # the PR that added SVOC013-017 bumped RULESET_VERSION: a cache
    # written by the previous rule set must load as empty, or warm runs
    # would silently skip the new rules on unchanged files
    from svoc_tpu.analysis.cache import RULESET_VERSION

    assert RULESET_VERSION != "svoclint-2-interproc-1"
    tree = tmp_path / "tree"
    tree.mkdir()
    _make_tree(tree, n=3)
    cache = str(tmp_path / "cache.json")
    analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    data = json.load(open(cache))
    data["ruleset"] = "svoclint-2-interproc-1"
    json.dump(data, open(cache, "w"))
    r = analyze_paths([str(tree)], root=str(tmp_path), cache_path=cache)
    assert r.parsed == 3 and r.cache_hits == 0


def test_whole_repo_warm_cache_run_is_fast(tmp_path):
    # acceptance: whole-repo lint < 5 s warm (< 10 s cold is pinned by
    # test_whole_package_run_is_clean_and_fast)
    cache = str(tmp_path / "cache.json")
    paths = [
        os.path.join(REPO_ROOT, "svoc_tpu"),
        os.path.join(REPO_ROOT, "tools"),
    ]
    analyze_paths(paths, root=REPO_ROOT, cache_path=cache)
    warm = analyze_paths(paths, root=REPO_ROOT, cache_path=cache)
    assert warm.parsed == 0, "warm run re-parsed files"
    assert warm.duration_s < 5.0
