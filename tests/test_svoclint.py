"""svoclint: per-rule fixtures, suppressions, baseline, CI contract.

Covers the docs/STATIC_ANALYSIS.md contract: one positive + one
negative fixture per rule, inline-suppression handling, baseline
round-trip (including stale-entry detection — baselines only shrink),
a whole-package run asserting zero non-baselined findings, and the CLI
exit codes the Makefile's ``lint`` target relies on.

Everything here runs without JAX (and asserts that importing the
analyzer cannot pull it in) — svoclint is the one tier-1 surface that
must stay cheap on a box with no accelerator stack.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from svoc_tpu.analysis import (  # noqa: E402
    Baseline,
    RULE_DOCS,
    analyze_paths,
    analyze_source,
)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def src(text):
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# SVOC001 — host-sync-in-hot-path
# ---------------------------------------------------------------------------


def test_svoc001_flags_host_sync_in_jit_body():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)
            """
        )
    )
    assert rules_of(findings) == ["SVOC001"]
    assert "np.asarray" in findings[0].message


def test_svoc001_flags_item_in_dispatch_span():
    findings = analyze_source(
        src(
            """
            from svoc_tpu.utils.metrics import stage_span

            def g(v):
                with stage_span("consensus"):
                    return v.item()
            """
        )
    )
    assert rules_of(findings) == ["SVOC001"]
    assert 'span "consensus"' in findings[0].message


def test_svoc001_negative_pure_jit_and_host_stage_span():
    findings = analyze_source(
        src(
            """
            import jax
            import jax.numpy as jnp
            import numpy as np
            from svoc_tpu.utils.metrics import stage_span

            @jax.jit
            def f(x):
                return jnp.sum(x) * 2.0

            def g(texts):
                # tokenize is a HOST stage — numpy there is the point
                with stage_span("tokenize"):
                    return np.asarray(texts)
            """
        )
    )
    assert findings == []


def test_svoc001_span_scan_skips_nested_defs_that_only_define():
    # a callback DEFINED (not called) inside a dispatch span runs
    # later, outside the span — not a span-body sync
    findings = analyze_source(
        src(
            """
            import numpy as np
            from svoc_tpu.utils.metrics import stage_span

            def g(v, schedule):
                with stage_span("forward"):
                    def cb(r):
                        return np.asarray(r)
                    schedule(cb)
            """
        )
    )
    assert findings == []


def test_svoc001_covers_jit_wrapper_call_and_lambda():
    findings = analyze_source(
        src(
            """
            import jax

            def body(x):
                return x.block_until_ready()

            step = jax.jit(body)
            other = jax.jit(lambda v: float(v))
            """
        )
    )
    assert rules_of(findings) == ["SVOC001"]
    assert len(findings) == 2


# ---------------------------------------------------------------------------
# SVOC002 — impure-jit-body
# ---------------------------------------------------------------------------


def test_svoc002_flags_print_metrics_and_self_mutation():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.metrics import registry as metrics

            @jax.jit
            def f(x):
                print("tracing", x)
                metrics.counter("steps").add(1)
                return x

            class Engine:
                def build(self):
                    @jax.jit
                    def step(x):
                        self.last = x
                        return x
                    return step
            """
        )
    )
    assert rules_of(findings) == ["SVOC002"]
    assert len(findings) == 3


def test_svoc002_bare_log_is_math_not_logging():
    # `from jax.numpy import log` — calling it inside jit is pure math;
    # only method calls on log/logger roots (or the logging module) are
    # logging.
    clean = analyze_source(
        src(
            """
            import jax
            from jax.numpy import log

            @jax.jit
            def f(x):
                return log(x) + 1
            """
        )
    )
    assert clean == []
    flagged = analyze_source(
        src(
            """
            import jax
            import logging

            logger = logging.getLogger(__name__)

            @jax.jit
            def f(x):
                logger.info("step %s", x)
                return x
            """
        )
    )
    assert rules_of(flagged) == ["SVOC002"]


def test_svoc002_negative_effects_outside_trace():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.metrics import registry as metrics

            @jax.jit
            def f(x):
                return x + 1

            def drive(x):
                out = f(x)
                metrics.counter("steps").add(1)
                print("done")
                return out
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC003 — recompile-hazard
# ---------------------------------------------------------------------------


def test_svoc003_flags_jit_in_loop():
    findings = analyze_source(
        src(
            """
            import jax

            def sweep(xs):
                outs = []
                for x in xs:
                    f = jax.jit(lambda v: v + 1)
                    outs.append(f(x))
                return outs
            """
        )
    )
    assert "SVOC003" in rules_of(findings)
    assert "inside a loop" in findings[0].message


def test_svoc003_flags_dotted_pjit_in_loop():
    findings = analyze_source(
        src(
            """
            import jax

            def sweep(xs):
                return [jax.experimental.pjit.pjit(lambda v: v)(x) for x in xs]
            """
        )
    )
    assert "SVOC003" in rules_of(findings)


def test_svoc003_flags_per_request_jit_construction():
    findings = analyze_source(
        src(
            """
            import jax

            def handle(request):
                return jax.jit(lambda v: v * 2)(request)
            """
        )
    )
    assert rules_of(findings) == ["SVOC003"]
    assert "per-request" in findings[0].message


def test_svoc003_negative_factory_and_module_level_invocation():
    findings = analyze_source(
        src(
            """
            import jax
            import jax.numpy as jnp

            def make_step(cfg):
                # the factory pattern: build once, return the callable
                return jax.jit(lambda v: v * cfg)

            # module level runs once at import — not per-request
            warmup = jax.jit(lambda v: v + 1)(jnp.zeros(4))
            """
        )
    )
    assert findings == []


def test_svoc003_flags_fstring_and_nonstatic_shape_arg():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("mode",))
            def f(x, mode):
                return x

            @jax.jit
            def g(x, n):
                return x[:2]

            def drive(v, k):
                a = f(v, mode=f"mode-{k}")
                b = g(v, v.shape[0])
                return a, b
            """
        )
    )
    assert rules_of(findings) == ["SVOC003"]
    msgs = " | ".join(f.message for f in findings)
    assert "f-string" in msgs and "shape-derived" in msgs
    assert len(findings) == 2


def test_svoc003_negative_static_declarations_match():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def g(x, n):
                return x[:n]

            @partial(jax.jit, static_argnums=(1,))
            def h(x, n):
                return x[:n]

            f = jax.jit(lambda v: v * 2)

            def drive(v):
                a = g(v, n=v.shape[0])   # declared static by name
                b = g(v, v.shape[0])     # static position via argnames
                c = h(v, v.shape[0])     # declared static by position
                return a, b, c, f(v)
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC004 — donation-reuse
# ---------------------------------------------------------------------------


def test_svoc004_flags_use_after_donation():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dx):
                out = step(state, dx)
                return state + out
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]
    assert "DONATED" in findings[0].message


def test_svoc004_flags_loop_without_rebind():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dxs):
                outs = []
                for dx in dxs:
                    outs.append(step(state, dx))
                return outs
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]
    assert "loop" in findings[0].message


def test_svoc004_flags_same_line_use_outside_the_call():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dx):
                return step(state, dx) + state
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]


def test_svoc004_flags_load_on_the_rebind_line_itself():
    # `x = x + 1` after donation: the load happens BEFORE the store, so
    # it reads the invalidated buffer — a rebind protects only lines
    # strictly after it.
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dx):
                out = step(state, dx)
                state = state + 1
                return out
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]


def test_svoc004_negative_rebind_over_donated_name():
    findings = analyze_source(
        src(
            """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, dx):
                return state + dx

            def run(state, dxs):
                for dx in dxs:
                    state = step(state, dx)
                return state
            """
        )
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SVOC005 — fixed-point-contract
# ---------------------------------------------------------------------------


def test_svoc005_flags_float_div_and_foreign_scale():
    findings = analyze_source(
        src(
            """
            # svoclint: tag=fixedpoint-path

            def wsad_half(a: int) -> int:
                return int(a * 0.5)

            def wsad_ratio(a: int, b: int) -> int:
                return a / b

            def wsad_rescale(a: int) -> int:
                return a * 1000000000
            """
        )
    )
    assert rules_of(findings) == ["SVOC005"]
    msgs = " | ".join(f.message for f in findings)
    assert "float literal" in msgs
    assert "true division" in msgs
    assert "foreign Q-scale" in msgs


def test_svoc005_negative_boundary_functions_and_untagged_modules():
    clean = src(
        """
        WSAD = 1_000_000

        def wsad_mul(a: int, b: int) -> int:
            return (a * b + WSAD // 2) // WSAD

        def from_wsad(x: int) -> float:
            return float(x) * 1e-6
        """
    )
    # tagged: boundary (-> float) functions and int-clean Q-paths pass
    assert analyze_source("# svoclint: tag=fixedpoint-path\n" + clean) == []
    # untagged module: rule does not apply at all
    assert analyze_source("def wsad_x(a: int) -> int:\n    return int(a * 0.5)\n") == []


def test_svoc005_applies_to_real_fixedpoint_module_by_path():
    findings = analyze_source(
        "def wsad_x(a: int) -> int:\n    return int(a * 0.5)\n",
        path="svoc_tpu/ops/fixedpoint.py",
    )
    assert rules_of(findings) == ["SVOC005"]


# ---------------------------------------------------------------------------
# SVOC006 — unlocked-shared-state
# ---------------------------------------------------------------------------


def test_svoc006_flags_unlocked_mutation_in_thread_entry_module():
    findings = analyze_source(
        src(
            """
            # svoclint: tag=thread-entry
            _streams = {}

            def handler(key, value):
                _streams[key] = value
                _streams.pop(key, None)
            """
        )
    )
    assert rules_of(findings) == ["SVOC006"]
    assert len(findings) == 2


def test_svoc006_negative_locked_mutation_and_untagged_module():
    locked = src(
        """
        # svoclint: tag=thread-entry
        import threading

        _streams = {}
        _lock = threading.Lock()

        def handler(key, value):
            with _lock:
                _streams[key] = value
        """
    )
    assert analyze_source(locked) == []
    unguarded_elsewhere = src(
        """
        _cache = {}

        def remember(k, v):
            _cache[k] = v
        """
    )
    assert analyze_source(unguarded_elsewhere) == []


def test_svoc006_lock_match_is_identifier_segment_not_substring():
    # `with block:` is NOT a lock even though "block" contains "lock";
    # RLock()/sse_lock ARE.
    flagged = analyze_source(
        src(
            """
            # svoclint: tag=thread-entry
            import threading

            _streams = {}
            block = threading.Semaphore()

            def handler(key, value):
                with block:
                    _streams[key] = value
            """
        )
    )
    assert rules_of(flagged) == ["SVOC006"]
    clean = analyze_source(
        src(
            """
            # svoclint: tag=thread-entry
            import threading

            _streams = {}
            sse_lock = threading.RLock()

            def handler(key, value):
                with sse_lock:
                    _streams[key] = value
            """
        )
    )
    assert clean == []


def test_svoc006_applies_to_web_module_by_path():
    findings = analyze_source(
        "_streams = {}\n\ndef h(k, v):\n    _streams[k] = v\n",
        path="svoc_tpu/apps/web.py",
    )
    assert rules_of(findings) == ["SVOC006"]


# ---------------------------------------------------------------------------
# SVOC007 — event-in-traced-body
# ---------------------------------------------------------------------------


def test_svoc007_flags_emit_event_in_jit_body():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.events import emit_event

            @jax.jit
            def step(x):
                emit_event("consensus.result", n=1)
                return x + 1
            """
        )
    )
    assert rules_of(findings) == ["SVOC007"]
    assert "trace time" in findings[0].message
    assert "host" in findings[0].hint


def test_svoc007_flags_journal_emit_method_in_jit_body():
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.events import journal

            @jax.jit
            def step(x):
                journal.emit("commit.sent", sent=1)
                return x * 2
            """
        )
    )
    assert rules_of(findings) == ["SVOC007"]


def test_svoc007_negative_emission_around_dispatch():
    """Host-side emission around the jitted call — the documented
    pattern — and unrelated `.emit()` methods on non-journal objects
    must not flag."""
    findings = analyze_source(
        src(
            """
            import jax
            from svoc_tpu.utils.events import emit_event

            @jax.jit
            def step(x):
                return x + 1

            def commit(x):
                y = step(x)
                emit_event("commit.sent", sent=1)
                return y

            def unrelated(sound):
                sound.emit("beep")  # not a journal root
            """
        )
    )
    assert rules_of(findings) == []


def test_inline_suppression_silences_one_rule_on_one_line():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                a = np.asarray(x)  # svoclint: disable=SVOC001
                b = np.asarray(x)
                return a + b
            """
        )
    )
    assert len(findings) == 1  # only the un-suppressed line remains
    assert findings[0].snippet == "b = np.asarray(x)"


def test_inline_suppression_tolerates_spaces_in_rule_list():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                print(np.asarray(x))  # svoclint: disable=SVOC001, SVOC002
                return x
            """
        )
    )
    assert findings == []


def test_inline_suppression_disable_all_and_multiple_rules():
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                print(np.asarray(x))  # svoclint: disable=SVOC001,SVOC002
                return x

            @jax.jit
            def g(x):
                print(np.asarray(x))  # svoclint: disable=all
                return x
            """
        )
    )
    assert findings == []


def test_trailing_suppression_covers_interior_lines_of_the_statement():
    # findings can anchor on an interior line of a multi-line literal;
    # the trailing disable covers the whole logical statement
    findings = analyze_source(
        src(
            """
            import numpy as np
            from svoc_tpu.utils.metrics import stage_span

            def g(mean, median):
                with stage_span("consensus"):
                    return {
                        "mean": np.asarray(mean),
                        "median": np.asarray(median),
                    }  # svoclint: disable=SVOC001
            """
        )
    )
    assert findings == []


def test_jit_wrapping_does_not_contaminate_the_raw_function_name():
    # `fast = jax.jit(step, donate_argnums=(0,))`: only calls of `fast`
    # donate — a plain Python `step(...)` call does not.
    findings = analyze_source(
        src(
            """
            import jax

            def step(state, dx):
                return state + dx

            fast = jax.jit(step, donate_argnums=(0,))

            def raw(state, dx):
                out = step(state, dx)
                return state + out

            def jitted(state, dx):
                out = fast(state, dx)
                return state + out
            """
        )
    )
    assert rules_of(findings) == ["SVOC004"]
    assert len(findings) == 1
    assert "`fast`" in findings[0].message


def test_trailing_suppression_on_multiline_statement_covers_its_first_line():
    # The finding reports at the statement's first line; the disable
    # trails the closing paren — logical-line mapping must connect them.
    findings = analyze_source(
        src(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(
                    x,
                    dtype=np.float64,
                )  # svoclint: disable=SVOC001
            """
        )
    )
    assert findings == []


def test_file_level_suppression():
    findings = analyze_source(
        src(
            """
            # svoclint: disable-file=SVOC001
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)
            """
        )
    )
    assert findings == []


def test_suppression_comment_inside_string_is_not_honored():
    findings = analyze_source(
        src(
            '''
            import jax
            import numpy as np

            NOTE = """ svoclint: disable-file=SVOC001 """

            @jax.jit
            def f(x):
                return np.asarray(x)
            '''
        )
    )
    assert rules_of(findings) == ["SVOC001"]


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

_BASELINE_FIXTURE = """
import jax
import numpy as np

@jax.jit
def f(x):
    return np.asarray(x)
"""


def test_baseline_round_trip(tmp_path):
    findings = analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    assert len(findings) == 1
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings, reason="grandfathered in test").dump(bl_path)

    loaded = Baseline.load(bl_path)
    new, baselined, stale = loaded.split(
        analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    )
    assert new == [] and stale == []
    assert len(baselined) == 1
    # entries keep their reason through the round trip
    assert json.load(open(bl_path))["entries"][0]["reason"] == "grandfathered in test"


def test_baseline_is_line_drift_tolerant_but_edit_sensitive(tmp_path):
    findings = analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).dump(bl_path)
    loaded = Baseline.load(bl_path)

    # unrelated lines added above: same snippet, still baselined
    drifted = "import os\nimport sys\n" + _BASELINE_FIXTURE
    new, baselined, stale = loaded.split(analyze_source(drifted, path="pkg/mod.py"))
    assert new == [] and len(baselined) == 1 and stale == []

    # the flagged line itself edited: no longer covered, old entry stale
    edited = _BASELINE_FIXTURE.replace(
        "return np.asarray(x)", "return np.asarray(x * 2)"
    )
    new, baselined, stale = loaded.split(analyze_source(edited, path="pkg/mod.py"))
    assert len(new) == 1 and baselined == [] and len(stale) == 1


def test_baseline_context_blocks_lookalike_new_findings(tmp_path):
    # A dead grandfather entry must not absorb a NEW finding whose
    # flagged line happens to have identical text but different
    # surroundings — the next-line context disambiguates.
    original = src(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)
        """
    )
    findings = analyze_source(original, path="pkg/mod.py")
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).dump(bl_path)

    lookalike = src(
        """
        import jax
        import numpy as np

        @jax.jit
        def g(y):
            return np.asarray(x)
            # different statement, same flagged-line text
        """
    )
    new, baselined, stale = Baseline.load(bl_path).split(
        analyze_source(lookalike, path="pkg/mod.py")
    )
    assert len(new) == 1 and baselined == [] and len(stale) == 1


def test_write_baseline_preserves_curated_reasons(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    bl = tmp_path / "bl.json"
    proc = _run_cli([str(bad), "--baseline", str(bl), "--write-baseline"])
    assert proc.returncode == 0
    data = json.load(open(bl))
    data["entries"][0]["reason"] = "curated explanation"
    json.dump(data, open(bl, "w"))
    proc = _run_cli([str(bad), "--baseline", str(bl), "--write-baseline"])
    assert proc.returncode == 0
    assert json.load(open(bl))["entries"][0]["reason"] == "curated explanation"


def test_stale_baseline_entry_reported_when_finding_fixed(tmp_path):
    findings = analyze_source(_BASELINE_FIXTURE, path="pkg/mod.py")
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).dump(bl_path)
    new, baselined, stale = Baseline.load(bl_path).split([])
    assert new == [] and baselined == []
    assert len(stale) == 1  # baselines only shrink — CI flags leftovers


# ---------------------------------------------------------------------------
# whole-package run + CLI contract
# ---------------------------------------------------------------------------


def test_whole_package_run_is_clean_and_fast():
    report = analyze_paths(
        [os.path.join(REPO_ROOT, "svoc_tpu"), os.path.join(REPO_ROOT, "tools")],
        root=REPO_ROOT,
    )
    assert report.parse_errors == []
    baseline = Baseline.load(os.path.join(REPO_ROOT, "tools", "svoclint_baseline.json"))
    new, _baselined, stale = baseline.split(report.all_findings)
    assert new == [], "non-baselined svoclint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert stale == [], f"stale baseline entries (remove them): {stale}"
    # acceptance: whole-package lint completes in < 10 s on CPU
    assert report.duration_s < 10.0


def test_every_documented_rule_has_a_registered_doc():
    assert sorted(RULE_DOCS) == [f"SVOC00{i}" for i in range(1, 8)]
    for doc in RULE_DOCS.values():
        assert doc["severity"] in ("error", "warning")


def _run_cli(args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "svoclint.py"), *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=120,
    )


def test_cli_repo_run_exits_zero_json():
    proc = _run_cli(["svoc_tpu", "tools", "--format", "json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["counts"]["new"] == 0
    assert payload["counts"]["files"] > 50


_INJECTED = {
    "SVOC001": "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n",
    "SVOC002": "import jax\n\n@jax.jit\ndef f(x):\n    print(x)\n    return x\n",
    "SVOC003": (
        "import jax\n\ndef sweep(xs):\n    return [jax.jit(lambda v: v)(x)"
        " for x in xs]\n"
    ),
    "SVOC004": (
        "import jax\nfrom functools import partial\n\n"
        "@partial(jax.jit, donate_argnums=(0,))\ndef step(s, d):\n"
        "    return s + d\n\ndef run(s, d):\n    out = step(s, d)\n"
        "    return s + out\n"
    ),
    "SVOC005": (
        "# svoclint: tag=fixedpoint-path\n\ndef wsad_bad(a: int) -> int:\n"
        "    return int(a * 0.5)\n"
    ),
    "SVOC006": (
        "# svoclint: tag=thread-entry\n_state = {}\n\ndef h(k, v):\n"
        "    _state[k] = v\n"
    ),
    "SVOC007": (
        "import jax\nfrom svoc_tpu.utils.events import emit_event\n\n"
        "@jax.jit\ndef f(x):\n    emit_event('x')\n    return x\n"
    ),
}


@pytest.mark.parametrize("rule", sorted(_INJECTED))
def test_cli_exits_nonzero_on_injected_violation(rule, tmp_path):
    bad = tmp_path / f"bad_{rule.lower()}.py"
    bad.write_text(_INJECTED[rule])
    proc = _run_cli([str(bad), "--no-baseline"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert rule in proc.stdout


def test_cli_honors_checked_in_baseline_from_any_cwd(tmp_path):
    # The default baseline + root are anchored to the repo, not the
    # CWD: the grandfathered flash_probe findings stay baselined.
    proc = _run_cli(
        [os.path.join(REPO_ROOT, "svoc_tpu"), os.path.join(REPO_ROOT, "tools")],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6 baselined" in proc.stdout


def test_overlapping_paths_analyze_each_file_once():
    # "tools tools/flash_probe.py" must not double-analyze the probe —
    # duplicate findings would exhaust the baseline multiset.
    proc = _run_cli(
        [
            os.path.join(REPO_ROOT, "tools"),
            os.path.join(REPO_ROOT, "tools", "flash_probe.py"),
        ]
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6 baselined" in proc.stdout


def test_cli_list_rules():
    proc = _run_cli(["--list-rules"])
    assert proc.returncode == 0
    for rule in _INJECTED:
        assert rule in proc.stdout


def test_cli_default_paths_work_from_any_cwd(tmp_path):
    proc = _run_cli([], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "6 baselined" in proc.stdout


def test_cli_bad_path_is_usage_error():
    proc = _run_cli(["definitely/not/a/path"])
    assert proc.returncode == 2


def test_write_baseline_over_a_subset_keeps_other_paths_entries(tmp_path):
    # regenerating over one tree must not drop another tree's
    # grandfathered entries (or their curated reasons)
    sub_a = tmp_path / "a"
    sub_b = tmp_path / "b"
    sub_a.mkdir(), sub_b.mkdir()
    bad = "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    (sub_a / "mod_a.py").write_text(bad)
    (sub_b / "mod_b.py").write_text(bad)
    bl = tmp_path / "bl.json"
    proc = _run_cli(
        [str(sub_a), str(sub_b), "--baseline", str(bl), "--write-baseline",
         "--root", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.load(open(bl))
    assert len(data["entries"]) == 2
    for e in data["entries"]:
        e["reason"] = "curated " + e["path"]
    json.dump(data, open(bl, "w"))
    # rewrite analyzing ONLY sub_a: sub_b's entry must survive verbatim
    proc = _run_cli(
        [str(sub_a), "--baseline", str(bl), "--write-baseline",
         "--root", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.load(open(bl))["entries"]
    assert len(entries) == 2
    assert {e["reason"] for e in entries} == {
        "curated a/mod_a.py",
        "curated b/mod_b.py",
    }
    # and the full run is still green against the rewritten baseline
    proc = _run_cli(
        [str(sub_a), str(sub_b), "--baseline", str(bl),
         "--root", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_write_baseline_refuses_to_grandfather_parse_errors(tmp_path):
    # A file the linter cannot parse must never become permanently
    # green via the baseline.
    (tmp_path / "broken.py").write_text("def f(:\n")
    bl = tmp_path / "bl.json"
    proc = _run_cli(
        [str(tmp_path), "--baseline", str(bl), "--write-baseline"]
    )
    assert proc.returncode == 1
    assert "refused" in proc.stderr
    assert all(
        e["rule"] != "SVOC000" for e in json.load(open(bl))["entries"]
    )
    # and the next gated run still fails on the parse error
    proc = _run_cli([str(tmp_path), "--baseline", str(bl)])
    assert proc.returncode == 1
    assert "SVOC000" in proc.stdout


def test_syntax_error_becomes_svoc000_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    proc = _run_cli([str(bad), "--no-baseline"])
    assert proc.returncode == 1
    assert "SVOC000" in proc.stdout


def test_linting_never_imports_jax():
    """The CI gate must run on accelerator-free boxes: importing the
    analyzer and linting the whole package may not pull in jax."""
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            (
                "import sys; sys.path.insert(0, '.');"
                "from svoc_tpu.analysis import analyze_paths;"
                "r = analyze_paths(['svoc_tpu', 'tools']);"
                "assert r.files > 50;"
                "assert 'jax' not in sys.modules, 'lint imported jax';"
                "assert 'numpy' not in sys.modules, 'lint imported numpy'"
            ),
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
