"""Test harness config: force CPU with 8 virtual devices.

Must run before the first ``import jax`` anywhere in the test session so
the sharding tests (:mod:`tests.test_parallel`) see a multi-device mesh
without TPU hardware.
"""

import os

# Hard override: the outer environment may point JAX at real TPU hardware
# (e.g. JAX_PLATFORMS=axon); the test suite must be hermetic and see a
# deterministic 8-device virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize pins jax at the TPU platform regardless of the
# env var — override through jax.config as well (must happen before any
# backend is initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def fake_sentiment_vectorizer(texts):
    """Cheap deterministic stand-in for the sentiment pipeline —
    shared by the apps and property suites so the fake cannot drift."""
    import numpy as np

    rng = np.random.default_rng(len(texts))
    v = rng.uniform(0.05, 0.95, size=(len(texts), 6))
    return v / v.sum(axis=1, keepdims=True)


def make_fake_console(n_comments: int = 200):
    """A CommandConsole over a seeded in-memory session with the fake
    vectorizer (no transformer builds)."""
    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    store = CommentStore()
    store.save(SyntheticSource(batch=n_comments)())
    return CommandConsole(
        Session(
            config=SessionConfig(),
            store=store,
            vectorizer=fake_sentiment_vectorizer,
        )
    )
