"""Test harness config: force CPU with 8 virtual devices.

Must run before the first ``import jax`` anywhere in the test session so
the sharding tests (:mod:`tests.test_parallel`) see a multi-device mesh
without TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
