"""Test harness config: force CPU with 8 virtual devices.

Must run before the first ``import jax`` anywhere in the test session so
the sharding tests (:mod:`tests.test_parallel`) see a multi-device mesh
without TPU hardware.
"""

import os

# Hard override: the outer environment may point JAX at real TPU hardware
# (e.g. JAX_PLATFORMS=axon); the test suite must be hermetic and see a
# deterministic 8-device virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Compile-plane hermeticity (docs/PARALLELISM.md §compile-plane): the
# committed PERF_DECISIONS.json routes compilation_cache="persistent",
# which would make every default-constructed RecoveryManager re-point
# jax's PROCESS-GLOBAL compilation cache at a pytest tmp dir (deleted
# later while still configured) and delete sibling salt dirs —
# cross-test state leakage.  Pin both compile-plane knobs off; tests
# that exercise the plane pass explicit kwargs/env (monkeypatch.setenv
# overrides these) or record paths with monkeypatch-cleared env.
# Unconditional (not setdefault): an ambient export from a local bench
# run would silently defeat the pin; per-test monkeypatch.setenv still
# overrides these.
os.environ["SVOC_COMPILATION_CACHE"] = "off"
os.environ["SVOC_WARMUP"] = "none"

# The axon sitecustomize pins jax at the TPU platform regardless of the
# env var — override through jax.config as well (must happen before any
# backend is initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Derandomized hypothesis profile for CI (ISSUE 4 satellite): property
# failures must reproduce from the test id alone — a CI-only flake from
# a rotating random seed is unactionable.  Registered here (conftest
# imports before any test module) so module-level `settings(...)`
# objects inherit `derandomize` from the active profile.  Opt out for
# exploratory fuzzing with HYPOTHESIS_PROFILE=default.  Import-gated:
# the hermetic image may lack hypothesis (test_properties.py then skips
# collection under --continue-on-collection-errors, as seeded).
try:
    from hypothesis import HealthCheck, settings as _hyp_settings

    _hyp_settings.register_profile(
        "svoc-ci",
        derandomize=True,
        deadline=None,
        suppress_health_check=[HealthCheck.too_slow],
    )
    _hyp_settings.load_profile(
        os.environ.get("HYPOTHESIS_PROFILE", "svoc-ci")
    )
except ImportError:  # pragma: no cover — bare image
    pass


def fake_sentiment_vectorizer(texts):
    """Cheap deterministic stand-in for the sentiment pipeline —
    shared by the apps and property suites so the fake cannot drift."""
    import numpy as np

    rng = np.random.default_rng(len(texts))
    v = rng.uniform(0.05, 0.95, size=(len(texts), 6))
    return v / v.sum(axis=1, keepdims=True)


def make_fake_console(n_comments: int = 200):
    """A CommandConsole over a seeded in-memory session with the fake
    vectorizer (no transformer builds)."""
    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    store = CommentStore()
    store.save(SyntheticSource(batch=n_comments)())
    return CommandConsole(
        Session(
            config=SessionConfig(),
            store=store,
            vectorizer=fake_sentiment_vectorizer,
        )
    )
