"""Test harness config: force CPU with 8 virtual devices.

Must run before the first ``import jax`` anywhere in the test session so
the sharding tests (:mod:`tests.test_parallel`) see a multi-device mesh
without TPU hardware.
"""

import os

# Hard override: the outer environment may point JAX at real TPU hardware
# (e.g. JAX_PLATFORMS=axon); the test suite must be hermetic and see a
# deterministic 8-device virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize pins jax at the TPU platform regardless of the
# env var — override through jax.config as well (must happen before any
# backend is initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
