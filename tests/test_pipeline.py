"""Prefetch pipeline: ordering, backpressure, error propagation."""

import time

import numpy as np
import pytest

from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.pipeline import PrefetchPipeline, window_source
from svoc_tpu.io.scraper import SyntheticSource
from svoc_tpu.models.tokenizer import HashingTokenizer


def test_yields_all_batches_in_order():
    batches = [[f"text {i} {j}" for j in range(4)] for i in range(10)]
    tok = HashingTokenizer(1024)
    with PrefetchPipeline(batches, tok, seq_len=16) as pipe:
        out = list(pipe)
    assert len(out) == 10
    ref_ids, _ = tok(batches[3], 16)
    np.testing.assert_array_equal(out[3][0], ref_ids)


def test_overlaps_slow_consumer():
    """Producer keeps the queue warm while the consumer is busy."""
    produced = []

    def tok(texts, seq_len):
        produced.append(time.perf_counter())
        return np.zeros((len(texts), seq_len), np.int32), np.zeros(
            (len(texts), seq_len), np.int32
        )

    batches = [["a"] * 2 for _ in range(4)]
    with PrefetchPipeline(batches, tok, seq_len=8, depth=2) as pipe:
        it = iter(pipe)
        next(it)
        time.sleep(0.2)  # consumer busy; producer should have refilled
        assert len(produced) >= 3


def test_error_propagates():
    def bad_tok(texts, seq_len):
        raise ValueError("boom")

    with PrefetchPipeline([["a"]], bad_tok, seq_len=8) as pipe:
        with pytest.raises(ValueError, match="boom"):
            next(iter(pipe))


def test_window_source_reads_store():
    store = CommentStore()
    store.save(SyntheticSource(batch=120)())
    windows = list(
        window_source(store, window=50, limit=30, max_windows=3)
    )
    assert len(windows) == 3
    assert all(len(w) == 30 for w in windows)


def test_empty_store_ends_pipeline():
    store = CommentStore()
    tok = HashingTokenizer(1024)
    src = window_source(store, window=50, limit=30)
    with PrefetchPipeline(src, tok, seq_len=16) as pipe:
        assert list(pipe) == []
