"""Prefetch pipeline: ordering, backpressure, error propagation."""

import time

import numpy as np
import pytest

from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.pipeline import PrefetchPipeline, window_source
from svoc_tpu.io.scraper import SyntheticSource
from svoc_tpu.models.tokenizer import HashingTokenizer


def test_yields_all_batches_in_order():
    batches = [[f"text {i} {j}" for j in range(4)] for i in range(10)]
    tok = HashingTokenizer(1024)
    with PrefetchPipeline(batches, tok, seq_len=16) as pipe:
        out = list(pipe)
    assert len(out) == 10
    ref_ids, _ = tok(batches[3], 16)
    np.testing.assert_array_equal(out[3][0], ref_ids)


def test_overlaps_slow_consumer():
    """Producer keeps the queue warm while the consumer is busy."""
    produced = []

    def tok(texts, seq_len):
        produced.append(time.perf_counter())
        return np.zeros((len(texts), seq_len), np.int32), np.zeros(
            (len(texts), seq_len), np.int32
        )

    batches = [["a"] * 2 for _ in range(4)]
    with PrefetchPipeline(batches, tok, seq_len=8, depth=2) as pipe:
        it = iter(pipe)
        next(it)
        time.sleep(0.2)  # consumer busy; producer should have refilled
        assert len(produced) >= 3


def test_error_propagates():
    def bad_tok(texts, seq_len):
        raise ValueError("boom")

    with PrefetchPipeline([["a"]], bad_tok, seq_len=8) as pipe:
        with pytest.raises(ValueError, match="boom"):
            next(iter(pipe))


def test_close_is_idempotent_and_flags_leaked_producer():
    """A producer wedged in a blocking tokenizer past the join timeout
    is RECORDED (stats + metric), not silently leaked; a later close
    that reaps it clears the flag (ISSUE 3 hardening)."""
    import threading

    from svoc_tpu.utils.metrics import registry

    release = threading.Event()
    entered = threading.Event()

    def blocking_tok(texts, seq_len):
        entered.set()
        release.wait(10)  # ignores the pipeline's stop event
        return np.zeros((len(texts), 8), np.int32), np.zeros(
            (len(texts), 8), np.int32
        )

    pipe = PrefetchPipeline(
        [["a"], ["b"]], blocking_tok, seq_len=8, join_timeout_s=0.1
    )
    try:
        assert entered.wait(5)
        before = registry.counter("pipeline_producer_leaks").count
        pipe.close()
        s = pipe.stats()
        assert s["closed"] and s["producer_leaked"]
        assert registry.counter("pipeline_producer_leaks").count == before + 1
        pipe.close()  # idempotent; the still-wedged leak counts once
        assert pipe.stats()["producer_leaked"]
        assert registry.counter("pipeline_producer_leaks").count == before + 1
    finally:
        release.set()
    pipe._thread.join(timeout=5)
    pipe.close()  # producer reaped now — the leak flag clears
    assert not pipe.stats()["producer_leaked"]


def test_close_idempotent_on_clean_pipeline():
    batches = [["a"] * 2]
    tok = HashingTokenizer(1024)
    pipe = PrefetchPipeline(batches, tok, seq_len=8)
    list(pipe)
    pipe.close()
    pipe.close()
    s = pipe.stats()
    assert s["closed"] and not s["producer_leaked"]
    assert s["producer_error"] is None


def test_stats_surface_producer_error():
    """A crashed producer is visible in stats() even when nothing
    iterates far enough to re-raise it."""

    def bad_tok(texts, seq_len):
        raise ValueError("tokenizer died")

    pipe = PrefetchPipeline([["a"]], bad_tok, seq_len=8)
    pipe._thread.join(timeout=5)
    assert "tokenizer died" in pipe.stats()["producer_error"]
    with pytest.raises(ValueError, match="tokenizer died"):
        next(iter(pipe))
    pipe.close()


def test_window_source_reads_store():
    store = CommentStore()
    store.save(SyntheticSource(batch=120)())
    windows = list(
        window_source(store, window=50, limit=30, max_windows=3)
    )
    assert len(windows) == 3
    assert all(len(w) == 30 for w in windows)


def test_empty_store_ends_pipeline():
    store = CommentStore()
    tok = HashingTokenizer(1024)
    src = window_source(store, window=50, limit=30)
    with PrefetchPipeline(src, tok, seq_len=16) as pipe:
        assert list(pipe) == []
