"""io layer: comment store window semantics, scraper loop, chain adapter."""

import numpy as np
import pytest

from svoc_tpu.consensus.state import OracleConsensusContract
from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend, to_hex
from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.scraper import SyntheticSource, catch_up_delay_s, run_scraper


class TestCommentStore:
    def test_schema_roundtrip(self):
        with CommentStore() as s:
            assert s.count() == 0
            assert s.save(["a", "b", "", "c"]) == 3  # empties dropped
            assert s.count() == 3
            assert s.last_timestamp() is not None

    def test_window_advances_before_reading(self):
        """read_window_from_db quirk (oracle_scheduler.py:52): the cursor
        moves by `window` first, so consecutive reads walk the table."""
        with CommentStore() as s:
            s.save([f"c{i}" for i in range(200)])
            comments, dates, pos1 = s.read_window(0, window=50, limit=30)
            assert len(comments) == 30 and len(dates) == 30
            assert pos1 == 50
            _, _, pos2 = s.read_window(pos1, window=50, limit=30)
            assert pos2 == 100

    def test_window_wraps_to_zero(self):
        """position+window >= N resets to 0 (oracle_scheduler.py:53)."""
        with CommentStore() as s:
            s.save([f"c{i}" for i in range(120)])
            _, _, pos = s.read_window(60, window=50, limit=30)
            assert pos == 0

    def test_empty_store(self):
        with CommentStore() as s:
            assert s.read_window(0) == ([], [], 0)

    def test_reference_limit_quirk(self):
        """Window constant 50 but SQL LIMIT 30 (common.py:15 vs
        oracle_scheduler.py:61) — defaults preserve it."""
        with CommentStore() as s:
            s.save([f"c{i}" for i in range(200)])
            comments, _, _ = s.read_window(0)
            assert len(comments) == 30


class TestScraper:
    def test_loop_bounded_rounds(self):
        with CommentStore() as s:
            src = SyntheticSource(batch=7, seed=3)
            slept = []
            n = run_scraper(
                s, src, rate_s=600, max_rounds=3, sleep=slept.append
            )
            assert n == 21 and s.count() == 21
            assert slept == [600, 600]  # no sleep after the last round

    def test_catch_up_delay(self):
        import datetime

        now = 1_000_000.0
        # Naive UTC string, exactly as sqlite CURRENT_TIMESTAMP stores it.
        last = datetime.datetime.fromtimestamp(
            now - 100, tz=datetime.timezone.utc
        ).replace(tzinfo=None).isoformat()
        assert catch_up_delay_s(last, 600, now=now) == pytest.approx(500)
        assert catch_up_delay_s(last, 60, now=now) == 0.0
        assert catch_up_delay_s(None, 600, now=now) == 0.0
        assert catch_up_delay_s("not-a-date", 600, now=now) == 0.0


def make_adapter(dimension=2, constrained=True, max_spread=0.0):
    admins = [0xA0, 0xA1, 0xA2]
    oracles = [0x10 + i for i in range(7)]
    contract = OracleConsensusContract(
        admins=admins,
        oracles=oracles,
        required_majority=2,
        n_failing_oracles=2,
        constrained=constrained,
        unconstrained_max_spread=max_spread,
        dimension=dimension,
    )
    return ChainAdapter(LocalChainBackend(contract)), contract


class TestChainAdapter:
    def test_reads_empty_state(self):
        adapter, _ = make_adapter()
        assert adapter.call_consensus() == [0.0, 0.0]
        assert adapter.call_consensus_active() is False
        assert adapter.call_dimension() == 2
        assert len(adapter.call_oracle_list()) == 7
        assert len(adapter.call_admin_list()) == 3

    def test_update_all_predictions_roundtrip(self):
        """Floats encode to felt calldata, cross the ABI, decode back —
        including the negative-value two's-complement path."""
        adapter, contract = make_adapter(constrained=False, max_spread=10.0)
        rng = np.random.default_rng(0)
        preds = rng.normal([20, -12], 1.0, size=(7, 2))
        assert adapter.update_all_the_predictions(preds) == 7
        assert adapter.call_consensus_active() is True
        consensus = adapter.call_consensus()
        assert consensus[0] == pytest.approx(20, abs=1.5)
        assert consensus[1] == pytest.approx(-12, abs=1.5)  # negative decode
        rel2 = adapter.call_second_pass_consensus_reliability()
        assert 0 < rel2 <= 1

    def test_index_address_resolution(self):
        adapter, _ = make_adapter()
        assert adapter.oracle_index_to_address(3) == 0x13
        assert adapter.address_to_oracle_index(0x13) == 3
        assert adapter.admin_index_to_address(1) == 0xA1
        assert adapter.address_to_admin_index(0xA2) == 2

    def test_vote_flow_through_adapter(self):
        adapter, contract = make_adapter()
        adapter.invoke_update_proposition(0xA0, 6, 0x99)
        assert adapter.call_replacement_propositions()[0] == (6, 0x99)
        adapter.invoke_vote_for_a_proposition(0xA1, 0, True)
        assert adapter.oracle_index_to_address(6) == 0x99

    def test_invoke_proposition_validates_arg_pairing(self):
        adapter, _ = make_adapter()
        with pytest.raises(ValueError):
            adapter.invoke_update_proposition(0xA0, 6, None)

    def test_resume_rehydrates_cache(self):
        adapter, _ = make_adapter()
        state = adapter.resume()
        assert state["consensus_active"] is False
        assert state["dimension"] == 2
        assert state["oracle_list"] == [0x10 + i for i in range(7)]
        assert state["replacement_propositions"] == [None, None, None]

    def test_admin_only_value_list(self):
        adapter, _ = make_adapter()
        with pytest.raises(Exception):
            adapter.call_oracle_value_list(0x10)
        values = adapter.call_oracle_value_list(0xA0)
        assert len(values) == 7

    def test_to_hex(self):
        assert to_hex(255) == "0xff"


class TestRel2Trend:
    """rel₂ trajectory surface (docs/ALGORITHM.md §5 security note: a
    coordinated capture is invisible in the LEVEL of rel₂ — the
    operators' alarm is the slide)."""

    def _adapter(self):
        from svoc_tpu.consensus.state import OracleConsensusContract
        from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend

        return ChainAdapter(
            LocalChainBackend(
                OracleConsensusContract(
                    ["a0"], [f"o{i}" for i in range(7)], dimension=2
                )
            )
        )

    def test_history_accrues_on_reads(self):
        a = self._adapter()
        assert a.rel2_trend()["n"] < 2
        for _ in range(3):
            a.call_second_pass_consensus_reliability()
        t = a.rel2_trend()
        assert t["n"] == 3 and t["falling"] is False and t["delta"] == 0.0

    def test_slide_flags_falling(self, monkeypatch):
        a = self._adapter()
        values = iter([0.9, 0.85, 0.78, 0.7])
        monkeypatch.setattr(
            a.backend, "call", lambda fn: int(next(values) * 1e6)
        )
        for _ in range(4):
            a.call_second_pass_consensus_reliability()
        t = a.rel2_trend()
        assert t["falling"] is True
        assert t["delta"] == pytest.approx(-0.2, abs=1e-6)

    def test_resume_feeds_the_history(self):
        import numpy as np

        a = self._adapter()
        rng = np.random.default_rng(0)
        a.update_all_the_predictions(rng.uniform(0.1, 0.9, (7, 2)))
        a.resume()
        a.resume()
        assert a.rel2_trend()["n"] == 2
