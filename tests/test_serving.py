"""Data-parallel serving path (8-way CPU mesh, conftest-forced)."""

import jax
import jax.numpy as jnp
import numpy as np

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.models.sentiment import scores_to_vectors
from svoc_tpu.parallel.serving import (
    batch_sharding,
    dp_serving_step_fn,
    serving_mesh,
)
from svoc_tpu.sim.oracle import gen_oracle_predictions

LABEL_IDX = (0, 1, 2, 3, 4, 5)


def _setup(n_oracles=16, batch=16, seq=16, window=8):
    cfg = TINY_TEST
    ccfg = ConsensusConfig(n_failing=4, constrained=True)
    mesh = serving_mesh()
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    serve = dp_serving_step_fn(
        mesh,
        cfg,
        ccfg,
        n_oracles,
        window_size=window,
        subset_size=4,
        label_indices=LABEL_IDX,
    )
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(2, 1000, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.int32)
    ids = jax.device_put(ids, batch_sharding(mesh))
    mask = jax.device_put(mask, batch_sharding(mesh))
    return cfg, ccfg, mesh, model, params, serve, ids, mask, window


def test_dp_serving_runs_on_full_mesh():
    cfg, ccfg, mesh, model, params, serve, ids, mask, window = _setup()
    assert mesh.devices.size == 8  # conftest virtual mesh
    out, honest = serve(params, jax.random.PRNGKey(0), ids, mask)
    essence = np.asarray(out.essence)
    assert essence.shape == (6,)
    assert np.all(np.isfinite(essence))
    assert np.asarray(honest).shape == (16,)
    assert np.asarray(honest).sum() == 16 - ccfg.n_failing


def test_dp_serving_matches_single_device_mesh():
    """The 8-way data-parallel serving step must agree with the same
    step on a 1-device mesh (unsharded forward, whole fleet local) —
    the sharding must not change the math."""
    cfg, ccfg, mesh, model, params, serve, ids, mask, window = _setup()
    key = jax.random.PRNGKey(7)
    out, honest = serve(params, key, ids, mask)

    mesh1 = serving_mesh(devices=jax.devices()[:1])
    serve1 = dp_serving_step_fn(
        mesh1,
        cfg,
        ccfg,
        16,
        window_size=window,
        subset_size=4,
        label_indices=LABEL_IDX,
    )
    ids1 = jax.device_put(np.asarray(ids), batch_sharding(mesh1))
    mask1 = jax.device_put(np.asarray(mask), batch_sharding(mesh1))
    out1, honest1 = serve1(params, key, ids1, mask1)

    np.testing.assert_allclose(
        np.asarray(out.essence), np.asarray(out1.essence), atol=1e-5
    )
    np.testing.assert_allclose(
        float(out.reliability_second_pass),
        float(out1.reliability_second_pass),
        atol=1e-5,
    )
    np.testing.assert_array_equal(np.asarray(honest), np.asarray(honest1))
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(out1.reliable)
    )


def test_dp_serving_rejects_indivisible_oracles():
    import pytest

    mesh = serving_mesh()
    with pytest.raises(ValueError, match="not divisible"):
        dp_serving_step_fn(
            mesh, TINY_TEST, ConsensusConfig(n_failing=1), n_oracles=9
        )


def test_packed_serving_matches_unpacked():
    """Packed data-parallel serving must produce the SAME consensus as
    the unpacked dp path on the same texts: the packer preserves input
    order, so the first window_size valid segments = the unpacked
    window."""
    from svoc_tpu.models.packing import pack_tokens, strip_padding
    from svoc_tpu.models.tokenizer import load_tokenizer
    from svoc_tpu.parallel.serving import packed_serving_step_fn

    cfg = TINY_TEST
    ccfg = ConsensusConfig(n_failing=4, constrained=True)
    mesh = serving_mesh()
    window, seq, n_oracles = 8, 16, 16
    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    tok = load_tokenizer(None, cfg.vocab_size, pad_id=cfg.pad_id, max_len=seq)
    texts = [f"short comment number {i} about consensus" for i in range(16)]
    ids, mask = tok(texts, seq)

    serve = dp_serving_step_fn(
        mesh, cfg, ccfg, n_oracles, window_size=window, subset_size=4,
        label_indices=LABEL_IDX,
    )
    key = jax.random.PRNGKey(3)
    d_ids = jax.device_put(jnp.asarray(ids), batch_sharding(mesh))
    d_mask = jax.device_put(jnp.asarray(mask), batch_sharding(mesh))
    ref_out, ref_honest = serve(params, key, d_ids, d_mask)

    lists = strip_padding(ids, mask)
    batch, n = pack_tokens(lists, seq, max_segments=2, pad_id=cfg.pad_id, rows=8)
    assert n == 16  # every comment packed into the 8 rows
    pserve = packed_serving_step_fn(
        mesh, cfg, ccfg, n_oracles, window_size=window, subset_size=4,
        label_indices=LABEL_IDX,
    )
    row = batch_sharding(mesh)
    args = [
        jax.device_put(jnp.asarray(a), row)
        for a in (batch.ids, batch.pos, batch.seg, batch.cls_pos)
    ]
    valid = jax.device_put(jnp.asarray(batch.seg_valid > 0), row)
    out, honest = pserve(params, key, *args, valid)

    np.testing.assert_allclose(
        np.asarray(out.essence), np.asarray(ref_out.essence), atol=2e-4
    )
    np.testing.assert_array_equal(np.asarray(honest), np.asarray(ref_honest))
    np.testing.assert_array_equal(
        np.asarray(out.reliable), np.asarray(ref_out.reliable)
    )


def test_pipelined_packed_serving_is_lossless():
    """The software-pipelined serving twin must reproduce the plain
    packed step exactly, one step later: same windows, same consensus
    per (key, batch) pair, with the drain closing the last batch."""
    from svoc_tpu.models.packing import pack_tokens, strip_padding
    from svoc_tpu.models.tokenizer import load_tokenizer
    from svoc_tpu.parallel.serving import (
        fleet_step_fn,
        packed_serving_pipelined_step_fn,
        packed_serving_step_fn,
    )

    cfg = TINY_TEST
    ccfg = ConsensusConfig(n_failing=4, constrained=True)
    mesh = serving_mesh()
    window, seq, n_oracles = 8, 16, 16
    params = init_params(SentimentEncoder(cfg), seed=0)
    tok = load_tokenizer(None, cfg.vocab_size, pad_id=cfg.pad_id, max_len=seq)
    row = batch_sharding(mesh)

    def packed(seed):
        texts = [f"pipelined comment {seed}-{i} consensus" for i in range(16)]
        ids, mask = tok(texts, seq)
        batch, n = pack_tokens(
            strip_padding(ids, mask), seq, max_segments=2,
            pad_id=cfg.pad_id, rows=8,
        )
        assert n == 16
        args = [
            jax.device_put(jnp.asarray(a), row)
            for a in (batch.ids, batch.pos, batch.seg, batch.cls_pos)
        ]
        return args, jax.device_put(jnp.asarray(batch.seg_valid > 0), row)

    serve = packed_serving_step_fn(
        mesh, cfg, ccfg, n_oracles, window_size=window, subset_size=4,
        label_indices=LABEL_IDX,
    )
    pserve = packed_serving_pipelined_step_fn(
        mesh, cfg, ccfg, n_oracles, window_size=window, subset_size=4,
        label_indices=LABEL_IDX,
    )
    drain = fleet_step_fn(mesh, ccfg, n_oracles, subset_size=4)

    batches = [packed(s) for s in range(3)]
    keys = [jax.random.PRNGKey(50 + s) for s in range(3)]
    ref = [serve(params, k, *a, v) for k, (a, v) in zip(keys, batches)]

    # pipelined: prime with batch 0 (dummy prev window), then each call
    # returns the PREVIOUS batch's consensus; drain the last.
    dim = len(LABEL_IDX)
    prev_window, _, _ = pserve(
        params, keys[0], *batches[0][0], batches[0][1],
        jnp.zeros((window, dim), jnp.float32),
    )
    got = []
    for k_prev, (a, v) in zip(keys, batches[1:]):
        prev_window, out, honest = pserve(params, k_prev, *a, v, prev_window)
        got.append((out, honest))
    got.append(drain(keys[2], prev_window))  # last batch's own key

    assert len(got) == len(ref) == 3
    for (out, honest), (ref_out, ref_honest) in zip(got, ref):
        np.testing.assert_array_equal(
            np.asarray(out.essence), np.asarray(ref_out.essence)
        )
        np.testing.assert_array_equal(np.asarray(honest), np.asarray(ref_honest))


def test_int8_dp_serving_matches_single_device_int8():
    """quant='int8' serving on the 8-way mesh must agree exactly with
    the same int8 step on a 1-device mesh — data sharding cannot change
    the quantized math (activation scales are per-row, so the split is
    invisible)."""
    from svoc_tpu.models.quant import quantize_params

    cfg, ccfg, mesh, model, params, _serve, ids, mask, window = _setup()
    qparams = quantize_params(params, cfg)
    key = jax.random.PRNGKey(9)

    serve8 = dp_serving_step_fn(
        mesh, cfg, ccfg, 16, window_size=window, subset_size=4,
        label_indices=LABEL_IDX, quant="int8",
    )
    out8, honest8 = serve8(qparams, key, ids, mask)

    mesh1 = serving_mesh(devices=jax.devices()[:1])
    serve1 = dp_serving_step_fn(
        mesh1, cfg, ccfg, 16, window_size=window, subset_size=4,
        label_indices=LABEL_IDX, quant="int8",
    )
    ids1 = jax.device_put(np.asarray(ids), batch_sharding(mesh1))
    mask1 = jax.device_put(np.asarray(mask), batch_sharding(mesh1))
    out1, honest1 = serve1(qparams, key, ids1, mask1)

    np.testing.assert_allclose(
        np.asarray(out8.essence), np.asarray(out1.essence), atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(honest8), np.asarray(honest1))
    np.testing.assert_array_equal(
        np.asarray(out8.reliable), np.asarray(out1.reliable)
    )


def test_int8_packed_serving_runs_and_tracks_float():
    """packed × int8 × data-parallel (the highest-throughput serving
    config): same consensus pipeline as the float packed path, within
    quantization tolerance of it on the same texts."""
    from svoc_tpu.models.packing import pack_tokens, strip_padding
    from svoc_tpu.models.quant import quantize_params
    from svoc_tpu.models.tokenizer import load_tokenizer
    from svoc_tpu.parallel.serving import packed_serving_step_fn

    cfg = TINY_TEST
    ccfg = ConsensusConfig(n_failing=4, constrained=True)
    mesh = serving_mesh()
    window, seq, n_oracles = 8, 16, 16
    params = init_params(SentimentEncoder(cfg), seed=0)
    qparams = quantize_params(params, cfg)
    tok = load_tokenizer(None, cfg.vocab_size, pad_id=cfg.pad_id, max_len=seq)
    texts = [f"short comment number {i} about consensus" for i in range(16)]
    ids, mask = tok(texts, seq)
    lists = strip_padding(ids, mask)
    batch, n = pack_tokens(lists, seq, max_segments=2, pad_id=cfg.pad_id, rows=8)
    assert n == 16
    row = batch_sharding(mesh)
    args = [
        jax.device_put(jnp.asarray(a), row)
        for a in (batch.ids, batch.pos, batch.seg, batch.cls_pos)
    ]
    valid = jax.device_put(jnp.asarray(batch.seg_valid > 0), row)
    key = jax.random.PRNGKey(3)

    fserve = packed_serving_step_fn(
        mesh, cfg, ccfg, n_oracles, window_size=window, subset_size=4,
        label_indices=LABEL_IDX,
    )
    fout, fhonest = fserve(params, key, *args, valid)
    qserve = packed_serving_step_fn(
        mesh, cfg, ccfg, n_oracles, window_size=window, subset_size=4,
        label_indices=LABEL_IDX, quant="int8",
    )
    qout, qhonest = qserve(qparams, key, *args, valid)

    # Same honest-mask draw (same key), essence within quant tolerance.
    np.testing.assert_array_equal(np.asarray(qhonest), np.asarray(fhonest))
    np.testing.assert_allclose(
        np.asarray(qout.essence), np.asarray(fout.essence), atol=0.05
    )
    assert np.all(np.isfinite(np.asarray(qout.essence)))


def test_serving_rejects_unknown_quant():
    import pytest

    with pytest.raises(ValueError, match="int8"):
        dp_serving_step_fn(
            serving_mesh(), TINY_TEST, ConsensusConfig(n_failing=1),
            n_oracles=16, quant="fp8",
        )
