"""Encoder + tokenizer + sentiment pipeline unit tests (TINY config)."""

import jax
import jax.numpy as jnp
import numpy as np

from svoc_tpu.models.configs import TINY_TEST
from svoc_tpu.models.encoder import SentimentEncoder, init_params, param_shardings
from svoc_tpu.models.sentiment import (
    GO_EMOTIONS_LABELS,
    TRACKED_INDICES,
    TRACKED_LABELS,
    SentimentPipeline,
    scores_to_vectors,
)
from svoc_tpu.models.tokenizer import HashingTokenizer


def test_label_subset_matches_reference():
    # client/common.py:19-31 — six tracked labels, in dict order.
    assert TRACKED_LABELS == (
        "optimism", "anger", "annoyance", "excitement", "nervousness", "remorse",
    )
    assert len(GO_EMOTIONS_LABELS) == 28
    assert [GO_EMOTIONS_LABELS[i] for i in TRACKED_INDICES] == list(TRACKED_LABELS)


def test_hashing_tokenizer_shapes_and_determinism():
    tok = HashingTokenizer(vocab_size=1024, pad_id=1, max_len=32)
    ids, mask = tok(["Hello, world!", "a b c"], seq_len=16)
    assert ids.shape == (2, 16) and mask.shape == (2, 16)
    ids2, _ = tok(["Hello, world!", "a b c"], seq_len=16)
    np.testing.assert_array_equal(ids, ids2)
    # padding id where mask is 0
    assert (ids[mask == 0] == 1).all()
    # special tokens distinct from pad
    assert ids[0, 0] != 1

def test_encoder_forward_shapes():
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    ids = jnp.ones((3, 24), jnp.int32)
    mask = jnp.concatenate(
        [jnp.ones((3, 12), jnp.int32), jnp.zeros((3, 12), jnp.int32)], axis=1
    )
    logits = model.apply(params, ids, mask)
    assert logits.shape == (3, TINY_TEST.n_labels)
    assert jnp.isfinite(logits).all()


def test_padding_invariance():
    """Extra padding must not change logits (mask correctness)."""
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    tok = HashingTokenizer(TINY_TEST.vocab_size, pad_id=1, max_len=64)
    ids_a, mask_a = tok(["the quick brown fox"], seq_len=16)
    ids_b, mask_b = tok(["the quick brown fox"], seq_len=40)
    la = model.apply(params, jnp.asarray(ids_a), jnp.asarray(mask_a))
    lb = model.apply(params, jnp.asarray(ids_b), jnp.asarray(mask_b))
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-4)


def test_scores_to_vectors_normalized():
    logits = jax.random.normal(jax.random.PRNGKey(0), (5, 28))
    v = scores_to_vectors(logits)
    assert v.shape == (5, 6)
    np.testing.assert_allclose(np.asarray(jnp.sum(v, -1)), np.ones(5), rtol=1e-5)
    assert (np.asarray(v) >= 0).all()


def test_pipeline_end_to_end():
    pipe = SentimentPipeline(
        cfg=TINY_TEST, seq_len=32, batch_size=4, tokenizer_name=None
    )
    texts = [f"comment number {i} is great" for i in range(6)]  # 2 chunks
    vecs = pipe(texts)
    assert vecs.shape == (6, 6)
    np.testing.assert_allclose(vecs.sum(axis=1), np.ones(6), rtol=1e-4)
    # batch padding must not perturb real rows: single-call reference
    pipe2 = SentimentPipeline(
        cfg=TINY_TEST, seq_len=32, batch_size=8, tokenizer_name=None
    )
    vecs2 = pipe2(texts)
    np.testing.assert_allclose(vecs, vecs2, atol=1e-4)


def test_pipeline_rejects_out_of_range_labels():
    import dataclasses

    import pytest

    from svoc_tpu.models.configs import DISTILBERT_SST2

    small = dataclasses.replace(DISTILBERT_SST2, n_layers=1, hidden=64, n_heads=4,
                                intermediate=64, vocab_size=512)
    with pytest.raises(ValueError, match="label_indices"):
        SentimentPipeline(cfg=small, tokenizer_name=None)
    # explicit SST-2 labels work
    pipe = SentimentPipeline(
        cfg=small, tokenizer_name=None, label_indices=(0, 1), seq_len=16,
        batch_size=2,
    )
    assert pipe(["ok"]).shape == (1, 2)


def test_param_shardings_cover_tree():
    from svoc_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(("data", "model"), (4, 2)))
    model = SentimentEncoder(TINY_TEST)
    params = init_params(model)
    shardings = param_shardings(params, mesh)
    flat_p = jax.tree_util.tree_leaves(params)
    flat_s = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    )
    assert len(flat_p) == len(flat_s)
    # at least the FFN kernels must actually be model-sharded
    n_sharded = sum(1 for s in flat_s if any(a == "model" for a in s.spec if a))
    assert n_sharded >= 2 * TINY_TEST.n_layers


def test_flash_attention_encoder_matches_dense():
    """attention="flash" must be logit-equivalent to the dense path
    (same params tree — the attention impl is not a weight change)."""
    import dataclasses

    dense_cfg = dataclasses.replace(TINY_TEST, max_len=64)
    flash_cfg = dataclasses.replace(dense_cfg, attention="flash")
    dense = SentimentEncoder(dense_cfg)
    flash = SentimentEncoder(flash_cfg)
    params = init_params(dense, seed=3)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(2, 1000, (2, 64)), jnp.int32)
    mask = jnp.asarray((rng.random((2, 64)) < 0.8).astype(np.int32))
    mask = mask.at[:, 0].set(1)

    out_dense = dense.apply(params, ids, mask)
    out_flash = flash.apply(params, ids, mask)
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_flash), rtol=1e-4, atol=1e-4
    )


def test_params_dtype_resident_cast():
    pipe = SentimentPipeline(
        cfg=TINY_TEST, seq_len=16, batch_size=2, tokenizer_name=None,
        params_dtype="bfloat16",
    )
    leaves = jax.tree_util.tree_leaves(pipe.params)
    assert all(l.dtype != jnp.float32 for l in leaves)
    vecs = pipe(["some text", "other text"])
    assert vecs.shape == (2, 6)
    np.testing.assert_allclose(vecs.sum(axis=-1), 1.0, rtol=1e-2)


def test_pipeline_data_mesh_matches_single_device():
    """A data-mesh-sharded pipeline must produce the same vectors as the
    unsharded one (same seed → same params; DP is math-invariant)."""
    from svoc_tpu.parallel.serving import serving_mesh

    mesh = serving_mesh()
    assert mesh.devices.size == 8  # conftest virtual mesh
    kw = dict(cfg=TINY_TEST, seq_len=16, batch_size=8, tokenizer_name=None)
    plain = SentimentPipeline(**kw)
    sharded = SentimentPipeline(**kw, data_mesh=mesh)
    texts = [f"comment number {i} about tpus" for i in range(11)]  # 2 chunks
    np.testing.assert_allclose(plain(texts), sharded(texts), atol=1e-5)


def test_pipeline_data_mesh_rejects_indivisible_batch():
    import pytest

    from svoc_tpu.parallel.serving import serving_mesh

    with pytest.raises(ValueError, match="not divisible"):
        SentimentPipeline(
            cfg=TINY_TEST, seq_len=16, batch_size=9, tokenizer_name=None,
            data_mesh=serving_mesh(),
        )
