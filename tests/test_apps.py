"""apps layer: session fetch/commit engine and the command console."""

import numpy as np
import pytest

from svoc_tpu.apps.commands import CommandConsole
from svoc_tpu.apps.session import Session, SessionConfig
from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.scraper import SyntheticSource


from conftest import fake_sentiment_vectorizer as fake_vectorizer  # noqa: E402


def make_session(**cfg_kwargs) -> Session:
    store = CommentStore()
    store.save(SyntheticSource(batch=200)())
    return Session(
        config=SessionConfig(**cfg_kwargs),
        store=store,
        vectorizer=fake_vectorizer,
    )


class TestSession:
    def test_fetch_produces_fleet_predictions(self):
        s = make_session()
        preview = s.fetch()
        assert s.predictions.shape == (7, 6)
        assert preview["n_comments"] == 30
        assert preview["mean"].shape == (6,)
        assert preview["honest"].sum() == 5  # 7 oracles - 2 failing
        # Cursor advanced (circular window semantics).
        assert s.simulation_step == 50

    def test_preview_ranks_match_reference_formula(self):
        """``normalized_ranks`` parity with ``predictions_to_eel_values``
        (``client/oracle_scheduler.py:106-111``): deviation is the L2
        norm from the fleet MEDIAN (not the mean), then rank_array —
        smallest deviation gets normalized rank 1, largest 0."""
        from svoc_tpu.apps.session import _preview_stats

        # Recorded fleet: 5 honest oracles near the simplex center plus
        # 2 adversarial outliers whose deviation-from-median and
        # deviation-from-mean ORDERINGS differ (the mean is dragged
        # toward the outliers; oracle 2 sits exactly on the mean-side).
        values = np.array(
            [
                [0.16, 0.17, 0.16, 0.17, 0.17, 0.17],
                [0.17, 0.16, 0.17, 0.16, 0.17, 0.17],
                [0.30, 0.30, 0.10, 0.10, 0.10, 0.10],
                [0.16, 0.16, 0.17, 0.17, 0.17, 0.17],
                [0.90, 0.02, 0.02, 0.02, 0.02, 0.02],
                [0.02, 0.90, 0.02, 0.02, 0.02, 0.02],
                [0.17, 0.17, 0.17, 0.16, 0.16, 0.17],
            ],
            dtype=np.float32,
        )
        mean, median, normalized = (np.asarray(x) for x in _preview_stats(values))

        # Reference formula, straight numpy re-derivation.
        ref_median = np.median(values, axis=0)
        dev = np.array([np.linalg.norm(p - ref_median) for p in values])
        order = np.argsort(dev)
        ref_ranks = np.zeros(len(order), dtype=int)
        for from_idx, to_idx in enumerate(order):
            ref_ranks[to_idx] = order.size - from_idx - 1
        np.testing.assert_allclose(
            normalized, ref_ranks / (len(values) - 1), atol=1e-6
        )
        np.testing.assert_allclose(median, ref_median, atol=1e-6)
        np.testing.assert_allclose(mean, values.mean(axis=0), atol=1e-6)

        # The adversarial outliers must occupy the two most-deviant
        # slots (normalized rank <= 0.2 colors red in the UI,
        # simulation_graphics.js:97-99) — with MEAN-centered deviation
        # oracle 2's rank would differ, which is the round-1 parity bug.
        assert set(np.argsort(normalized)[:2]) == {4, 5}

    def test_fetch_on_empty_store_raises(self):
        s = Session(config=SessionConfig(), vectorizer=fake_vectorizer)
        with pytest.raises(RuntimeError, match="empty"):
            s.fetch()

    def test_commit_requires_fetch(self):
        s = make_session()
        with pytest.raises(RuntimeError, match="etch"):
            s.commit()

    def test_fetch_commit_activates_consensus(self):
        s = make_session()
        s.fetch()
        assert s.commit() == 7
        assert s.adapter.call_consensus_active() is True
        consensus = s.adapter.call_consensus()
        # Honest oracles average sum-to-one sentiment vectors, so the
        # robust consensus must stay inside the simplex neighborhood.
        assert all(0.0 < x < 1.0 for x in consensus)

    def test_successive_fetches_differ(self):
        s = make_session()
        p1 = dict(s.fetch())
        p2 = s.fetch()
        assert not np.allclose(p1["values"], p2["values"])


class TestCommandConsole:
    def make(self):
        return CommandConsole(make_session())

    def test_help_and_unknown(self):
        c = self.make()
        assert any("Commands" in line for line in c.query("help"))
        assert any("Unknown command" in line for line in c.query("bogus"))
        assert c.query("") == []

    def test_fetch_then_commit_then_resume(self):
        c = self.make()
        out = c.query("fetch")
        assert any("fetched 30 comments" in line for line in out)
        out = c.query("commit")
        assert any("Done (7 transactions)." in line for line in out)
        out = c.query("resume")
        assert any("consensus_active: True" in line for line in out)
        out = c.query("reliability")
        assert any("reliability :" in line for line in out)

    def test_commit_before_fetch(self):
        c = self.make()
        assert c.query("commit") == ["Fetch before!"]

    def test_listing_commands(self):
        c = self.make()
        assert len(c.query("admin_list")) == 4  # header + 3 admins
        assert len(c.query("oracle_list")) == 8  # header + 7 oracles
        assert c.query("dimension") == ["Dimension: 6"]
        assert any(
            "Admin 0 : None" in line
            for line in c.query("replacement_propositions")
        )

    def test_replacement_vote_flow_by_index_and_address(self):
        c = self.make()
        # admin 0 proposes replacing oracle 6 with 0x99.
        out = c.query("update_proposition 0 6 0x99")
        assert out == ["Done."]
        out = c.query("replacement_propositions")
        assert any("6 -> 0x99" in line for line in out)
        # second vote by address reaches majority -> swap.
        addr = hex(c.session.adapter.call_admin_list()[1])
        assert c.query(f"vote_for_a_proposition {addr} 0 yes") == ["Done."]
        assert c.session.adapter.oracle_index_to_address(6) == 0x99
        # propositions reset after replacement.
        out = c.query("replacement_propositions")
        assert all("->" not in line for line in out)

    def test_update_proposition_none_clears(self):
        c = self.make()
        c.query("update_proposition 0 6 0x99")
        assert c.query("update_proposition 0 None") == ["Done."]
        out = c.query("replacement_propositions")
        assert all("->" not in line for line in out)

    def test_vote_rejects_bad_arg(self):
        c = self.make()
        out = c.query("vote_for_a_proposition 0 0 maybe")
        assert out == ["Invalid command: only yes/no accepted"]

    def test_errors_do_not_crash(self):
        c = self.make()
        out = c.query("update_proposition 99 6 0x99")
        assert any(line.startswith("error:") for line in out)

    def test_exit_stops_session(self):
        c = self.make()
        c.query("exit")
        assert c.session.application_on is False

    def test_write_callback_streams(self):
        lines = []
        c = CommandConsole(make_session(), write=lines.append)
        c.query("dimension")
        assert lines == ["Dimension: 6"]

    def test_get_oracle_value_list_default_admin(self):
        c = self.make()
        out = c.query("get_oracle_value_list")
        assert len(out) == 7

    def test_multimodal_requires_fetch(self):
        c = self.make()
        assert c.query("multimodal") == ["No predictions yet — run 'fetch' first."]

    def test_multimodal_analyzes_last_fleet(self):
        c = self.make()
        c.query("fetch")
        out = c.query("multimodal")
        assert any("mixture fit over 7 oracles, K=2" in line for line in out)
        poles = [line for line in out if line.strip().startswith("pole ")]
        assert len(poles) == 2
        # dominant pole listed first (sorted by weight)
        w = [float(line.split("w=")[1].split()[0]) for line in poles]
        assert w == sorted(w, reverse=True)
        assert any(line.startswith("essence (dominant pole)") for line in out)
        assert any(line.startswith("flagged unreliable") for line in out)
        # explicit K and validation (K capped at the 7-oracle fleet size)
        assert any("K=3" in line for line in c.query("multimodal 3"))
        assert c.query("multimodal 0") == ["K must be in [1, 7]."]
        assert c.query("multimodal 8") == ["K must be in [1, 7]."]
        assert c.query("multimodal 1 2") == ["Unexpected number of arguments."]
        # BIC auto-selection reports its pick and runs the analysis
        out = c.query("multimodal auto")
        assert any(line.startswith("BIC selects K=") for line in out)
        assert any("mixture fit over 7 oracles" in line for line in out)


class TestCli:
    def test_cli_smoke(self, monkeypatch, capsys):
        import svoc_tpu.apps.cli as cli

        inputs = iter(["dimension", "exit"])
        monkeypatch.setattr(
            "builtins.input", lambda *_: next(inputs)
        )
        # Avoid the transformer pipeline: startup fetch disabled.
        rc = cli.main(["--disable_startup_fetch", "--seed-comments", "50"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Dimension: 6" in out


class TestCommitFailureSurface:
    def test_commit_command_surfaces_partial_failure(self):
        """An RPC failure mid-commit must print the partial accounting
        (k/N on chain, failing oracle, cause) instead of a traceback."""
        from svoc_tpu.io.chain import ChainCommitError

        s = make_session()
        s.fetch()
        failed = {"n": 0}
        orig = s.adapter.invoke_update_prediction

        def flaky(oracle, prediction):
            if failed["n"] == 2:
                raise ConnectionError("node dropped the request")
            failed["n"] += 1
            return orig(oracle, prediction)

        s.adapter.invoke_update_prediction = flaky
        out = []
        console = CommandConsole(s, write=out.append)
        console.query("commit")
        text = "\n".join(out)
        assert "Commit FAILED after 2/7 transactions" in text
        assert "node dropped the request" in text

    def test_session_records_partial_txs_in_metrics(self):
        from svoc_tpu.io.chain import ChainCommitError
        from svoc_tpu.utils.metrics import registry as metrics

        s = make_session()
        s.fetch()
        s.adapter.invoke_update_prediction = lambda *a: (_ for _ in ()).throw(
            ConnectionError("down")
        )
        before = metrics.counter("chain_transactions").count
        fails_before = metrics.counter("chain_commit_failures").count
        with pytest.raises(ChainCommitError) as exc:
            s.commit()
        assert exc.value.committed == 0
        assert metrics.counter("chain_transactions").count == before
        assert metrics.counter("chain_commit_failures").count == fails_before + 1


class TestConcurrency:
    """The session is shared by the auto_fetch thread, the stdin
    console, and the web UI's ThreadingHTTPServer handlers — the
    reference relied on eel's single event loop for serialization
    (SURVEY.md §5 race-detection notes); here ``session.lock`` must
    provide it."""

    def test_concurrent_commands_serialize_without_corruption(self):
        import threading

        console = CommandConsole(make_session())
        session = console.session
        # Prime predictions so a worker-ordering 'commit' can never hit
        # the legitimate "fetch before commit" error — after this, ANY
        # "error:" line the dispatcher emits is a real concurrency bug
        # (the dispatcher converts exceptions to lines, so collecting
        # raised exceptions alone would be vacuous).
        console.query("fetch")
        errors = []
        n_threads, n_iters = 6, 8

        def worker(i):
            for k in range(n_iters):
                cmd = ["fetch", "commit", "consensus", "oracle_list"][
                    (i + k) % 4
                ]
                for line in console.query(cmd):
                    if line.startswith("error:"):
                        errors.append(f"{cmd}: {line}")

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "worker deadlocked"
        assert not errors
        # Every oracle committed at least once under contention, and the
        # contract went through the activation gate exactly as in the
        # serial flow.
        assert session.adapter.call_consensus_active()
        vals = np.asarray(session.adapter.call_consensus())
        assert vals.shape == (6,) and np.isfinite(vals).all()

    def test_concurrent_fetches_never_share_a_prng_key(self):
        """Two fetches racing must consume distinct PRNG splits — the
        fleet draws of consecutive fetches differ even when issued from
        different threads."""
        import threading

        session = make_session()
        results = []

        def worker():
            results.append(session.fetch()["values"].copy())

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "worker deadlocked"
        assert len(results) == 4
        for i in range(len(results)):
            for j in range(i + 1, len(results)):
                assert not np.array_equal(results[i], results[j]), (
                    "two fetches produced identical fleets — PRNG key "
                    "split raced"
                )

    def test_concurrent_commits_do_not_interleave_transactions(self):
        """Whole-fleet commit atomicity: two racing commits must land as
        two contiguous 7-tx blocks, never a mixed fleet (which would
        reach consensus even though no fetch produced it)."""
        import threading
        import time

        from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend
        from svoc_tpu.apps.session import _default_contract

        cfg = SessionConfig()
        inner = LocalChainBackend(_default_contract(cfg))
        tx_log = []

        class RecordingBackend:
            def call(self, *a):
                return inner.call(*a)

            def call_as(self, *a):
                return inner.call_as(*a)

            def invoke(self, caller, fn, /, **kwargs):
                time.sleep(0.005)  # widen the race window
                tx_log.append((threading.get_ident(), fn))
                return inner.invoke(caller, fn, **kwargs)

        store = CommentStore()
        store.save(SyntheticSource(batch=200)())
        session = Session(
            config=cfg, store=store, vectorizer=fake_vectorizer,
            adapter=ChainAdapter(RecordingBackend()),
        )
        session.fetch()

        threads = [
            threading.Thread(target=session.commit) for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "commit deadlocked"
        assert len(tx_log) == 2 * cfg.n_oracles
        # Contiguity: the thread id must change exactly once.
        owners = [tid for tid, _ in tx_log]
        assert sum(
            1 for a, b in zip(owners, owners[1:]) if a != b
        ) == 1, f"interleaved commits: {owners}"

    def test_racing_first_fetches_build_vectorizer_once(self, monkeypatch):
        import threading

        builds = []

        class CountingPipeline:
            def __init__(self, **kwargs):
                import time

                builds.append(1)
                time.sleep(0.2)  # widen the race window

            def __call__(self, texts):
                rng = np.random.default_rng(42)
                v = rng.uniform(0.05, 0.95, size=(len(texts), 6))
                return v / v.sum(axis=1, keepdims=True)

        import svoc_tpu.models.sentiment as sentiment_mod

        monkeypatch.setattr(sentiment_mod, "SentimentPipeline", CountingPipeline)
        store = CommentStore()
        store.save(SyntheticSource(batch=200)())
        session = Session(config=SessionConfig(), store=store)

        threads = [
            threading.Thread(target=session.fetch) for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert sum(builds) == 1, f"vectorizer built {sum(builds)} times"

    def test_slow_earlier_fetch_does_not_overwrite_later_publish(self):
        """Publish ordering: a fetch that claimed an EARLIER window but
        finishes later must not regress predictions/preview/state
        published by a later-window fetch."""
        import threading
        import time

        release_first = threading.Event()
        call_count = []

        def gated_vectorizer(texts):
            i = len(call_count)
            call_count.append(1)
            if i == 0:  # first (earlier-window) fetch stalls mid-flight
                release_first.wait(30)
            rng = np.random.default_rng(100 + i)
            v = rng.uniform(0.05, 0.95, size=(len(texts), 6))
            return v / v.sum(axis=1, keepdims=True)

        store = CommentStore()
        store.save(SyntheticSource(batch=200)())
        session = Session(
            config=SessionConfig(), store=store, vectorizer=gated_vectorizer
        )
        slow = threading.Thread(target=session.fetch)
        slow.start()
        while not call_count:  # slow fetch has claimed window 1
            time.sleep(0.01)
        later = session.fetch()  # claims window 2, publishes
        version_after_later = session.state_version
        release_first.set()
        slow.join(timeout=60)
        assert not slow.is_alive()
        # The later window's fleet remains the published state, and no
        # extra version bump advertised the stale overwrite.
        np.testing.assert_array_equal(session.predictions, later["values"])
        assert session.last_preview["values"] is later["values"]
        assert session.state_version == version_after_later


class TestInt8Session:
    def test_lazy_vectorizer_receives_quant_and_serves(self, monkeypatch):
        """SessionConfig(quant_inference='int8') must reach the lazy
        vectorizer's SentimentPipeline construction (the REAL property
        path — a hand-injected pipeline would leave the plumb untested)
        and the session must still drive fetch->commit->consensus."""
        import svoc_tpu.models.sentiment as sentiment_mod
        from svoc_tpu.models.configs import TINY_TEST
        from svoc_tpu.models.sentiment import SentimentPipeline

        captured = {}
        real = SentimentPipeline

        def capturing_pipeline(**kwargs):
            captured.update(kwargs)
            # Substitute the tiny config so the test does not build
            # RoBERTa-base; every session-supplied kwarg is kept.
            return real(
                cfg=TINY_TEST, seq_len=32, tokenizer_name=None, **kwargs
            )

        monkeypatch.setattr(
            sentiment_mod, "SentimentPipeline", capturing_pipeline
        )
        store = CommentStore()
        store.save(SyntheticSource(batch=200)())
        session = Session(
            config=SessionConfig(quant_inference="int8"), store=store
        )
        vec = session.vectorizer  # the real lazy property path
        assert captured["quant"] == "int8"
        assert captured["packed"] is True
        from svoc_tpu.models.quant import is_quantized_tree

        assert is_quantized_tree(vec.params)
        session.fetch()
        assert session.commit() == 7
        assert session.adapter.call_consensus_active()

    def test_cli_int8_flag_reaches_session_config(self, monkeypatch):
        """--int8 must land in the constructed Session's config through
        main() itself, not just argparse."""
        import io
        import sys

        import svoc_tpu.apps.cli as cli_mod

        built = {}
        real_session = cli_mod.Session

        def capturing_session(**kwargs):
            s = real_session(**kwargs)
            built["config"] = s.config
            return s

        monkeypatch.setattr(cli_mod, "Session", capturing_session)
        monkeypatch.setattr(sys, "stdin", io.StringIO("exit\n"))
        rc = cli_mod.main(
            ["--int8", "--disable_startup_fetch", "--seed-comments", "5"]
        )
        assert rc == 0
        assert built["config"].quant_inference == "int8"


def test_oracle_dump_renders_exact_wsad_digits():
    """wsad 7000 (0.007000) must print '0.007' — the float round trip
    yields 6999 and would truncate to '0.006' (code-review r4)."""
    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.consensus.state import OracleConsensusContract
    from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend

    contract = OracleConsensusContract(
        [0xA0], [0x10, 0x11, 0x12], constrained=True, dimension=2
    )
    contract.update_prediction(0x10, [7000, 123456], encoding="wsad")
    session = Session(
        config=SessionConfig(n_oracles=3, n_admins=1, dimension=2),
        adapter=ChainAdapter(LocalChainBackend(contract)),
        vectorizer=lambda texts: None,
    )
    out = CommandConsole(session).query("get_oracle_value_list")
    assert "[0.007, 0.123]" in out[0]
