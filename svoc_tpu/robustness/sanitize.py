"""Input-integrity quarantine gate ahead of the consensus kernel.

The on-chain contract refuses malformed predictions transactionally:
``nd_interval_check`` panics the offending tx (``contract.cairo:
589-593``) and the felt codec cannot even represent a NaN.  The TPU
fast path has neither protection — ``consensus_step`` happily folds a
NaN through every reduction, and a single non-finite component poisons
the block's medians, risks and moments.  This gate restores the
contract's refusal semantics at the float boundary:

- **detection** (:func:`quarantine_reasons_jax` /
  :class:`QuarantineGate`): per-oracle masks for non-finite components
  (NaN/Inf), values outside the consensus value domain (``[lo, hi]``
  real units — the contract's interval check for the constrained
  model), and values that cannot survive the wsad/felt codec
  (``|x| * 1e6`` beyond the i128 window — the felt-prime boundary the
  seed's decoder silently wrapped);
- **refusal**: quarantined vectors never reach the kernel
  (:func:`svoc_tpu.consensus.kernel.consensus_step_gated`) nor the
  chain (``Session.commit_resilient`` skips the tx), and each event
  counts against the oracle's health exactly like a commit failure
  (:meth:`FleetHealthSupervisor.record_quarantine`) — a persistent
  garbage emitter is voted out through the same replacement flow as a
  dead signer;
- **observability**: ``oracle_quarantine{reason=}`` counters plus the
  per-slot report in ``Session.resilience_snapshot()`` → ``/api/state``
  and the ``resilience`` console command (docs/OBSERVABILITY.md).

Reason precedence is fixed (nan > inf > range > codec) so a vector
failing several checks reports one stable reason — metrics series and
replay fingerprints must not depend on float comparison quirks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from svoc_tpu.ops.fixedpoint import I128_MAX
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

#: Largest real-unit magnitude the wsad/felt codec can represent
#: (``I128_MAX / 1e6``) — beyond it ``to_wsad`` leaves the i128 window
#: and the encode boundary would manufacture an unsignable felt.
WSAD_LIMIT: float = float(I128_MAX) * 1e-6

#: Quarantine reasons, in precedence order (first match wins).
QUARANTINE_REASONS: Tuple[str, ...] = ("nan", "inf", "range", "codec")


@dataclasses.dataclass(frozen=True)
class SanitizeConfig:
    """Value-domain bounds for the gate.

    ``lo``/``hi`` bound the consensus value domain in real units; the
    codec bound is always enforced on top (it is what the chain itself
    would refuse).  ``None`` disables the corresponding domain check —
    the unconstrained model has no [0,1] interval, only the codec
    window and a practical spread.
    """

    lo: Optional[float] = 0.0
    hi: Optional[float] = 1.0

    def __post_init__(self):
        if self.lo is not None and self.hi is not None and self.lo > self.hi:
            raise ValueError(f"need lo <= hi, got [{self.lo}, {self.hi}]")

    @classmethod
    def for_consensus(cls, constrained: bool):
        """The gate matching a consensus configuration: the contract's
        [0,1] interval for the constrained model; codec-window-only for
        the unconstrained one (``max_spread`` bounds the *estimator*,
        not the value domain — ``contract.cairo:365-368`` — so it plays
        no part in admission)."""
        if constrained:
            return cls(lo=0.0, hi=1.0)
        return cls(lo=None, hi=None)


class QuarantineMasks(NamedTuple):
    """Per-oracle [N] bool masks, one per reason (jit-friendly form)."""

    nan: Any
    inf: Any
    range: Any
    codec: Any

    @property
    def quarantined(self):
        import jax.numpy as jnp

        return jnp.logical_or(
            jnp.logical_or(self.nan, self.inf),
            jnp.logical_or(self.range, self.codec),
        )


def quarantine_reasons_jax(values, lo: Optional[float], hi: Optional[float]):
    """Per-oracle reason masks for ``values [N, M]`` (traceable).

    Comparisons are written so a NaN component can only ever trip the
    ``nan`` mask: ``x < lo`` and ``x > hi`` are False for NaN, and the
    codec check runs on a NaN-neutralized copy.
    """
    import jax.numpy as jnp

    nan = jnp.any(jnp.isnan(values), axis=-1)
    inf = jnp.any(jnp.isinf(values), axis=-1)
    finite = jnp.where(jnp.isfinite(values), values, 0.0)
    out_of_range = jnp.zeros(values.shape[0], dtype=bool)
    if lo is not None:
        out_of_range = jnp.logical_or(
            out_of_range, jnp.any(values < lo, axis=-1)
        )
    if hi is not None:
        out_of_range = jnp.logical_or(
            out_of_range, jnp.any(values > hi, axis=-1)
        )
    codec = jnp.any(jnp.abs(finite) > WSAD_LIMIT, axis=-1)
    # Precedence: a non-finite vector is "nan"/"inf", never "range".
    out_of_range = jnp.logical_and(
        out_of_range, jnp.logical_not(jnp.logical_or(nan, inf))
    )
    codec = jnp.logical_and(
        codec,
        jnp.logical_not(
            jnp.logical_or(jnp.logical_or(nan, inf), out_of_range)
        ),
    )
    return QuarantineMasks(nan=nan, inf=inf, range=out_of_range, codec=codec)


def quarantine_mask_jax(values, lo: Optional[float], hi: Optional[float]):
    """Admission mask ``ok [N]`` (True = clean) — the mask
    :func:`svoc_tpu.consensus.kernel.consensus_step_gated` consumes."""
    import jax.numpy as jnp

    masks = quarantine_reasons_jax(values, lo, hi)
    return jnp.logical_not(masks.quarantined)


def quarantine_mask_claims(values, lo: Optional[float], hi: Optional[float]):
    """Admission masks ``ok [C, N]`` for a claim cube ``[C, N, M]`` —
    the vmapped gate of the multi-claim fabric (docs/FABRIC.md).  One
    traced program inspects every claim's fleet block; the masks feed
    :func:`svoc_tpu.consensus.kernel.consensus_step_gated_claims`
    directly, so gate + consensus fuse into a single dispatch per
    micro-batch.  Identical per claim to :func:`quarantine_mask_jax`
    (the host :class:`QuarantineGate` remains the reason-reporting
    authority — this traced twin only decides admission)."""
    import jax

    return jax.vmap(lambda v: quarantine_mask_jax(v, lo, hi))(values)


@dataclasses.dataclass
class QuarantineReport:
    """One gate pass over a fleet block (host side).

    ``reasons[slot]`` is the precedence-first reason for each
    quarantined fleet slot; ``ok`` the admission mask.
    """

    ok: np.ndarray  # [N] bool, True = admitted
    reasons: Dict[int, str]

    @property
    def quarantined_slots(self) -> List[int]:
        return sorted(self.reasons)

    @property
    def clean(self) -> bool:
        return not self.reasons

    def as_dict(self) -> Dict[str, Any]:
        """JSON-friendly form for ``/api/state`` and soak artifacts."""
        return {
            "quarantined": [
                {"slot": slot, "reason": self.reasons[slot]}
                for slot in self.quarantined_slots
            ],
            "admitted": int(np.sum(self.ok)),
            "total": int(self.ok.shape[0]),
        }


class QuarantinedInputError(RuntimeError):
    """A commit was refused because the gate quarantined fleet slots.

    Raised by the FAITHFUL commit path (``Session.commit``), which has
    no degraded mode: the reference's per-tx loop would stop at the
    first panicking tx anyway, so refusing BEFORE any tx is strictly
    more informative (no partial commit to account for).  The
    resilient path never raises this — it skips the refused slots and
    lets the supervisor own the consequence.
    """

    def __init__(self, report: "QuarantineReport"):
        self.report = report
        detail = ", ".join(
            f"slot {s}: {report.reasons[s]}" for s in report.quarantined_slots
        )
        super().__init__(f"quarantined fleet slots refuse commit ({detail})")


class QuarantineGate:
    """Host-side gate: inspect → report → count (docs/ROBUSTNESS.md).

    Pure numpy (the blocks it sees on the commit path are tiny —
    ``[N, M]`` with N a fleet, not a batch); the device-side twin for
    in-graph gating is :func:`quarantine_reasons_jax`.
    """

    def __init__(
        self,
        config: Optional[SanitizeConfig] = None,
        registry: Optional[MetricsRegistry] = None,
        journal=None,
    ):
        self.config = config or SanitizeConfig()
        self._registry = registry or _default_registry
        #: Event journal (``svoc_tpu.utils.events``): counted
        #: inspections emit one ``quarantine.verdict`` event carrying
        #: the block lineage, so the audit record can answer "which
        #: verdict got this oracle charged".  None = process default.
        self._journal = journal

    def _resolve_journal(self):
        if self._journal is not None:
            return self._journal
        from svoc_tpu.utils.events import journal as default_journal

        return default_journal

    def inspect(
        self,
        values: Sequence,
        *,
        count: bool = True,
        lineage: Optional[str] = None,
    ) -> QuarantineReport:
        """Classify every fleet slot; ``count=True`` (the once-per-fetch
        call) feeds ``oracle_quarantine{reason=}`` — re-inspections of
        the same block (the commit path's recheck of its snapshot) pass
        ``count=False`` so the series stays one-event-one-count.
        Counted inspections also emit the block's
        ``quarantine.verdict`` journal event (tagged ``lineage``) and
        feed ``quarantine_slots_inspected`` (the SLO admission-ratio
        denominator)."""
        arr = np.asarray(values, dtype=np.float64)
        if arr.ndim == 1:
            arr = arr[None, :]
        cfg = self.config
        reasons: Dict[int, str] = {}
        ok = np.ones(arr.shape[0], dtype=bool)
        for slot in range(arr.shape[0]):
            reason = self._classify(arr[slot], cfg)
            if reason is not None:
                reasons[slot] = reason
                ok[slot] = False
                if count:
                    self._registry.counter(
                        "oracle_quarantine", labels={"reason": reason}
                    ).add(1)
        report = QuarantineReport(ok=ok, reasons=reasons)
        if count:
            self._registry.counter("quarantine_slots_inspected").add(
                arr.shape[0]
            )
            self._resolve_journal().emit(
                "quarantine.verdict",
                lineage=lineage,
                admitted=int(np.sum(ok)),
                total=int(arr.shape[0]),
                reasons={str(s): r for s, r in sorted(reasons.items())},
            )
        return report

    @staticmethod
    def _classify(vec: np.ndarray, cfg: SanitizeConfig) -> Optional[str]:
        if np.any(np.isnan(vec)):
            return "nan"
        if np.any(np.isinf(vec)):
            return "inf"
        if cfg.lo is not None and np.any(vec < cfg.lo):
            return "range"
        if cfg.hi is not None and np.any(vec > cfg.hi):
            return "range"
        if np.any(np.abs(vec) > WSAD_LIMIT):
            return "codec"
        return None
