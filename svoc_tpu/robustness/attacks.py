"""Parametric, seeded, jit/vmap-compatible Byzantine oracle strategies.

The reference's "failing" oracle is benign by construction — an
independent ``uniform(0,1)^M`` draw (``client/oracle_scheduler.py:
73-92``) is symmetric about the honest mass and cannot displace a
median.  These strategies model the adversaries that CAN: ``k``
colluders (a traced count, so the colluder-fraction ε axis vmaps) who
see the honest values and coordinate.  Every strategy is a pure
fixed-shape function of ``(key, values, colluder_mask, magnitude,
round_frac)``, dispatched by a traced attack id through
``lax.switch`` — the whole (attack × ε × magnitude) certification grid
of :mod:`svoc_tpu.robustness.certify` therefore evaluates as ONE
batched XLA computation, the vmapped-grid idiom of large-scale TPU
batched linear algebra (arXiv:2112.09017, PAPERS.md).

Threat model (docs/ROBUSTNESS.md): adversaries are omniscient about
the current round's honest values (worst case — they can compute the
honest center exactly) but must emit values the input-integrity gate
admits (finite, in-domain): a NaN bomb is handled by
:mod:`svoc_tpu.robustness.sanitize`, not by the estimator, so the
certified surface is attacks that are *undetectable by syntax*.

The taxonomy:

- ``cluster`` — the whole coalition plants one tight cluster at
  ``center + magnitude·direction`` (maximum pull per colluder; also
  maximally visible to the risk ranking);
- ``shift`` — each colluder keeps its honest-looking draw but adds the
  same coordinated offset toward the target essence (preserves the
  coalition's dispersion — harder to out-rank);
- ``sign_flip`` — colluders mirror their values about the honest
  center (the classic gradient-inversion analogue);
- ``straddle`` — colluders sit AT the reliability-mask boundary: the
  radius of the ``(N - n_failing)``-th ranked honest oracle, half a
  band inside, half outside — engineered to flip which oracles the
  mask drops while staying inside the honest hull's edge;
- ``drift`` — the shift attack scaled by ``round_frac`` ∈ [0,1]: a
  slow coordinated slide across rounds, the attack the rel₂ TREND
  alarm (``ChainAdapter.rel2_trend``) exists to surface.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from svoc_tpu.ops import stats

ATTACK_NAMES: Tuple[str, ...] = (
    "cluster",
    "shift",
    "sign_flip",
    "straddle",
    "drift",
)


def _direction(center: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """Unit vector from the honest center toward the target essence."""
    d = target - center
    return d / jnp.maximum(jnp.linalg.norm(d), 1e-12)


def apply_attack(
    key,
    values: jnp.ndarray,
    colluder_mask: jnp.ndarray,
    attack_id,
    magnitude,
    n_failing: int,
    *,
    target: Optional[jnp.ndarray] = None,
    round_frac=1.0,
    smooth_mode: str = "cairo",
    clip: Optional[Tuple[float, float]] = (0.0, 1.0),
) -> jnp.ndarray:
    """Overwrite the masked slots of an honest fleet with colluder values.

    Args:
      key: PRNG key (intra-coalition jitter — a bit-identical cluster
        would be trivially fingerprintable, and exact value ties would
        leave the outcome to sort tie-order rather than statistics).
      values: ``[N, M]`` honest fleet block (e.g. from
        :mod:`svoc_tpu.sim.generators` with ``n_failing=0``).
      colluder_mask: ``[N]`` bool — True slots are coalition members.
        May encode a TRACED colluder count (``rank < k``), so ε sweeps
        vmap without recompiling.
      attack_id: traced int index into :data:`ATTACK_NAMES`.
      magnitude: attack strength in real units (``cluster``/``shift``/
        ``drift``: offset length along the target direction;
        ``straddle``: relative width of the boundary band).
      n_failing: the defense's static mask budget (the ``straddle``
        geometry needs the cut rank).
      target: ``[M]`` target essence (default: the all-ones corner —
        the constrained domain's extreme point).
      round_frac: ``drift`` progress through its schedule, 0 → 1.
      clip: admission bounds — colluders must emit values the
        quarantine gate admits, so attacks clip into the value domain
        (None for unconstrained fleets).

    Returns the attacked ``[N, M]`` block.
    """
    n, m = values.shape
    if target is None:
        target = jnp.ones((m,), values.dtype)
    honest_mask = jnp.logical_not(colluder_mask)
    # Omniscient adversary: the exact component-wise center of the
    # honest (non-coalition) mass, via the same smooth median the
    # defense uses.
    center = stats.masked_smooth_median(values, honest_mask, smooth_mode)
    direction = _direction(center, jnp.asarray(target, values.dtype))
    # Tiny seeded jitter shared by the strategies (see ``key`` above).
    noise = 1e-3 * jax.random.uniform(key, (n, m), values.dtype, -1.0, 1.0)
    # Colluder rank within the coalition (0, 1, ... for masked slots) —
    # drives the straddle's inside/outside alternation.
    rank = jnp.cumsum(colluder_mask.astype(jnp.int32)) - 1

    def cluster(_):
        point = center[None, :] + magnitude * direction[None, :]
        return point + noise

    def shift(_):
        return values + magnitude * direction[None, :] + noise

    def sign_flip(_):
        return 2.0 * center[None, :] - values + noise

    def straddle(_):
        # The mask keeps the (N - n_failing) lowest-risk oracles; the
        # boundary radius is the honest risk at that cut (computed over
        # the honest slots only, colluders pushed out of the ranking).
        # The cut is clamped INTO the honest subset: with k colluders
        # only n-k finite entries exist, and for k > n_failing the
        # all-slots rank would index the +inf tail — the isfinite
        # fallback would then park the whole coalition at the center
        # (a no-op attack) and the certificate rows above the design
        # budget would be vacuous.
        qr = stats.quadratic_risk(values, center)
        qr_ranked = jnp.where(honest_mask, qr, jnp.inf)
        n_honest = jnp.sum(honest_mask.astype(jnp.int32))
        cut = jnp.clip(n - n_failing - 1, 0, jnp.maximum(n_honest - 1, 0))
        r_cut = jnp.sqrt(jnp.sort(qr_ranked)[cut])
        r_cut = jnp.where(jnp.isfinite(r_cut), r_cut, 0.0)
        # Alternate just inside / just outside the boundary band.
        side = jnp.where(rank % 2 == 0, -1.0, 1.0)
        radius = r_cut * (1.0 + side * magnitude)
        return center[None, :] + radius[:, None] * direction[None, :] + noise

    def drift(_):
        return values + round_frac * magnitude * direction[None, :] + noise

    colluder_vals = jax.lax.switch(
        jnp.asarray(attack_id, jnp.int32),
        [cluster, shift, sign_flip, straddle, drift],
        operand=None,
    )
    if clip is not None:
        colluder_vals = jnp.clip(colluder_vals, clip[0], clip[1])
    return jnp.where(colluder_mask[:, None], colluder_vals, values)
