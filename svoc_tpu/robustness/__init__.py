"""Byzantine-oracle hardening: attacks, breakdown certification, and
the input-integrity quarantine gate (docs/ROBUSTNESS.md).

PR 3's resilience layer hardened the I/O plane (faults, retries,
breakers, supervision); this package is its data-plane twin:

- :mod:`svoc_tpu.robustness.attacks` — parametric, seeded,
  jit/vmap-compatible Byzantine oracle strategies layered onto the
  simulator's fleets;
- :mod:`svoc_tpu.robustness.certify` — the empirical breakdown-point
  sweep (one batched pass over the attack × ε × magnitude grid) behind
  ``make robustness-cert`` / ``ROBUSTNESS_CERT.json``;
- :mod:`svoc_tpu.robustness.sanitize` — the quarantine gate ahead of
  the consensus kernel and the chain commit path: NaN/Inf detection,
  wsad-range / felt-boundary checks, per-oracle quarantine masks that
  feed :class:`~svoc_tpu.resilience.supervisor.FleetHealthSupervisor`
  health exactly like commit failures.
"""

from svoc_tpu.robustness.attacks import (  # noqa: F401
    ATTACK_NAMES,
    apply_attack,
)
from svoc_tpu.robustness.certify import (  # noqa: F401
    BreakdownCell,
    breakdown_sweep,
    certificate,
)
from svoc_tpu.robustness.sanitize import (  # noqa: F401
    QUARANTINE_REASONS,
    QuarantinedInputError,
    QuarantineGate,
    QuarantineReport,
    SanitizeConfig,
    quarantine_reasons_jax,
    quarantine_mask_jax,
)
