"""Empirical breakdown-point certification of the consensus estimator.

``docs/ALGORITHM.md`` §5 argues the two-pass estimator's breakdown
point from theory (≈ N/2 for any median rule) and
:func:`svoc_tpu.sim.montecarlo.fleet_breakdown_curve` measures one
attack (the biased corner band).  This module certifies the claim the
paper actually makes — *bounded essence deviation under up to
``n_failing`` coordinated adversaries* — empirically, for EVERY
implemented attack strategy:

1. draw ``T`` honest fleets and their attack-free consensus (the
   reference essence);
2. evaluate the full (attack × colluder-count × magnitude) grid in a
   **single batched pass**: every cell's ``T`` attacked blocks run
   through the vmapped two-pass kernel inside one jit — the TPU-native
   sweep idiom (arXiv:2112.09017), ~a thousand consensus blocks per
   dispatch instead of a Python loop;
3. calibrate the tolerance per colluder count with a *benign
   replacement control* (the same slots overwritten by independent
   honest draws): the deviation bound is
   ``max(bound_abs, bound_ratio · benign_deviation)``, so the
   certificate never mistakes subset-resampling noise for an attack
   effect (and never certifies against a bound the honest fleet itself
   could not meet);
4. emit the certificate: per attack, the largest *prefix-monotone*
   tolerated colluder count (every count up to it passes at every
   magnitude), its fraction of N, plus the deviation / capture tables
   — ``ROBUSTNESS_CERT.json`` via ``tools/robustness_cert.py``.

Capture is reported alongside deviation: the mean fraction of
colluders the reliability mask *admits* (a captured colluder sits
inside the reliable set and pulls the second pass directly) — the
straddle attack exists to maximize exactly this number.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step_batched
from svoc_tpu.robustness.attacks import ATTACK_NAMES, apply_attack
from svoc_tpu.sim.generators import (
    generate_beta_oracles,
    generate_gaussian_oracles,
)


@dataclasses.dataclass(frozen=True)
class BreakdownCell:
    """One (attack, colluder-count, magnitude) grid cell, reduced over
    trials."""

    attack: str
    colluders: int
    fraction: float
    magnitude: float
    mean_deviation: float
    max_deviation: float
    mean_capture: float
    valid_fraction: float


@partial(
    jax.jit,
    static_argnames=("cfg", "n_failing", "clip", "target"),
)
def _grid_eval(
    attack_keys,  # [C, T] PRNG keys
    honest,  # [T, N, M] honest fleet blocks
    benign,  # [T, N, M] independent honest blocks (the control)
    positions,  # [T, N] int32 — per-trial colluder slot order
    attack_ids,  # [C] int32 (index into ATTACK_NAMES; -1 = benign control)
    counts,  # [C] int32 colluder counts
    magnitudes,  # [C] float
    *,
    cfg: ConsensusConfig,
    n_failing: int,
    clip: Optional[Tuple[float, float]],
    target: Optional[Tuple[float, ...]],
):
    """All grid cells in one fused computation: ``[C]`` reductions."""
    t, n, m = honest.shape
    ref = consensus_step_batched(honest, cfg)
    essence_ref = ref.essence  # [T, M]
    tgt = None if target is None else jnp.asarray(target, honest.dtype)

    drift_id = ATTACK_NAMES.index("drift")

    def one_cell(aid, k, mag, keys):
        def one_trial(key, vals, control, pos, idx):
            cmask = pos < k
            # Drift is certified along its WHOLE schedule: trial ``idx``
            # evaluates round_frac (idx+1)/T, so the cell's mean
            # deviation covers the gradual slide (the thing the rel₂
            # trend alarm watches) and the max still includes the
            # endpoint.  Every other attack is single-round — full
            # strength on every trial.
            frac = jnp.where(aid == drift_id, (idx + 1.0) / t, 1.0)
            attacked = apply_attack(
                key,
                vals,
                cmask,
                jnp.maximum(aid, 0),
                mag,
                n_failing,
                target=tgt,
                round_frac=frac,
                clip=clip,
            )
            # aid < 0: the benign replacement control — same slots,
            # independent honest draws (the calibration cell).
            attacked = jnp.where(
                aid >= 0,
                attacked,
                jnp.where(cmask[:, None], control, vals),
            )
            return attacked, cmask

        attacked, cmask = jax.vmap(one_trial)(
            keys, honest, benign, positions, jnp.arange(t, dtype=honest.dtype)
        )
        out = consensus_step_batched(attacked, cfg)
        dev = jnp.linalg.norm(
            out.essence - essence_ref, axis=-1
        ) / (m ** 0.5)
        captured = jnp.sum(
            jnp.logical_and(out.reliable, cmask), axis=-1
        ) / jnp.maximum(k, 1)
        return (
            jnp.mean(dev),
            jnp.max(dev),
            jnp.mean(captured.astype(dev.dtype)),
            jnp.mean(out.interval_valid.astype(dev.dtype)),
        )

    return jax.vmap(one_cell)(attack_ids, counts, magnitudes, attack_keys)


def breakdown_sweep(
    key,
    cfg: ConsensusConfig,
    *,
    n_oracles: int,
    colluder_counts: Sequence[int],
    magnitudes: Sequence[float],
    attacks: Sequence[str] = ATTACK_NAMES,
    n_trials: int = 64,
    dim: int = 6,
    beta_a: float = 20.0,
    beta_b: float = 20.0,
    gauss_mu: Optional[Sequence[float]] = None,
    gauss_sigma: float = 3.0,
) -> Dict[str, Any]:
    """Run the (attack × count × magnitude) grid for one consensus
    config; returns cells plus the per-count benign control rows.

    Constrained fleets are Beta(a, b) on [0,1]^M with target essence at
    the all-ones corner; unconstrained fleets are Gaussian around
    ``gauss_mu`` with the target pushed ``max_spread`` along the
    diagonal (the estimator's own saturation scale).

    The ``drift`` attack is evaluated along its whole schedule — trial
    ``i`` runs at ``round_frac=(i+1)/n_trials`` — so its cells bound
    the deviation of the gradual slide itself rather than collapsing
    to the ``shift`` endpoint.
    """
    for a in attacks:
        if a not in ATTACK_NAMES:
            raise ValueError(f"unknown attack {a!r} (have {ATTACK_NAMES})")
    counts = [int(c) for c in colluder_counts]
    if any(c < 0 or c >= n_oracles for c in counts):
        raise ValueError(f"colluder counts {counts} outside [0, {n_oracles})")

    k_fleet, k_benign, k_slots, k_attack = jax.random.split(key, 4)
    trial_keys = jax.random.split(k_fleet, n_trials)
    benign_keys = jax.random.split(k_benign, n_trials)
    if cfg.constrained:
        gen = lambda ks: jax.vmap(  # noqa: E731 — tiny local closure
            lambda k: generate_beta_oracles(
                k, n_oracles, 0, beta_a, beta_b, dim=dim
            )[0]
        )(ks)
        clip: Optional[Tuple[float, float]] = (0.0, 1.0)
        target: Optional[Tuple[float, ...]] = tuple([1.0] * dim)
    else:
        mu = (
            np.asarray(gauss_mu, np.float32)
            if gauss_mu is not None
            else np.full((dim,), 10.0, np.float32)
        )
        gen = lambda ks: jax.vmap(  # noqa: E731
            lambda k: generate_gaussian_oracles(
                k, n_oracles, 0, mu, np.full((dim,), gauss_sigma, np.float32)
            )[0]
        )(ks)
        clip = None
        target = tuple(
            float(x) for x in (mu + cfg.max_spread / np.sqrt(dim))
        )
    honest = gen(trial_keys)
    benign = gen(benign_keys)
    # Per-trial colluder slot order (shared across cells so ε rows of
    # one trial nest: the ε=k coalition is the ε=k-1 coalition plus one).
    perms = jax.vmap(
        lambda k: jax.random.permutation(k, n_oracles)
    )(jax.random.split(k_slots, n_trials))
    positions = jnp.argsort(perms, axis=-1).astype(jnp.int32)

    # Grid: attacks × counts × magnitudes, plus one benign control row
    # per count (attack id -1, magnitude 0).
    ids, cts, mags = [], [], []
    for a in attacks:
        for c in counts:
            for g in magnitudes:
                # GLOBAL taxonomy index: ``lax.switch`` dispatches over
                # ATTACK_NAMES order, so a caller's attack SUBSET must
                # not be indexed by its own position.
                ids.append(ATTACK_NAMES.index(a))
                cts.append(c)
                mags.append(float(g))
    for c in counts:
        ids.append(-1)
        cts.append(c)
        mags.append(0.0)
    n_cells = len(ids)
    attack_keys = jax.vmap(
        lambda i: jax.random.split(jax.random.fold_in(k_attack, i), n_trials)
    )(jnp.arange(n_cells))

    mean_dev, max_dev, capture, valid = _grid_eval(
        attack_keys,
        honest,
        benign,
        positions,
        jnp.asarray(ids, jnp.int32),
        jnp.asarray(cts, jnp.int32),
        jnp.asarray(mags, jnp.float32),
        cfg=cfg,
        n_failing=cfg.n_failing,
        clip=clip,
        target=target,
    )
    mean_dev = np.asarray(mean_dev, np.float64)
    max_dev = np.asarray(max_dev, np.float64)
    capture = np.asarray(capture, np.float64)
    valid = np.asarray(valid, np.float64)

    cells = []
    i = 0
    for _ai, a in enumerate(attacks):
        for c in counts:
            for g in magnitudes:
                cells.append(
                    BreakdownCell(
                        attack=a,
                        colluders=c,
                        fraction=c / n_oracles,
                        magnitude=float(g),
                        mean_deviation=float(mean_dev[i]),
                        max_deviation=float(max_dev[i]),
                        mean_capture=float(capture[i]),
                        valid_fraction=float(valid[i]),
                    )
                )
                i += 1
    benign_rows = {}
    for c in counts:
        benign_rows[c] = float(mean_dev[i])
        i += 1
    return {
        "n_oracles": n_oracles,
        "n_trials": n_trials,
        "dim": dim,
        "config": {
            "n_failing": cfg.n_failing,
            "constrained": cfg.constrained,
            "max_spread": cfg.max_spread,
            "smooth_mode": cfg.smooth_mode,
        },
        "colluder_counts": counts,
        "magnitudes": [float(g) for g in magnitudes],
        "attacks": list(attacks),
        "cells": cells,
        "benign_deviation": benign_rows,
    }


def certificate(
    sweep: Dict[str, Any],
    *,
    bound_abs: float = 0.05,
    bound_ratio: float = 3.0,
) -> Dict[str, Any]:
    """Reduce a sweep to the certificate: per attack, the largest
    prefix-monotone tolerated colluder count under the calibrated
    deviation bound (module docstring, step 3/4)."""
    counts = sweep["colluder_counts"]
    n = sweep["n_oracles"]
    benign = sweep["benign_deviation"]
    bounds = {
        c: max(bound_abs, bound_ratio * benign[c]) for c in counts
    }
    by_attack: Dict[str, Dict[int, list]] = {}
    for cell in sweep["cells"]:
        by_attack.setdefault(cell.attack, {}).setdefault(
            cell.colluders, []
        ).append(cell)
    attacks_out = {}
    for attack, rows in by_attack.items():
        tolerated = 0
        for c in sorted(rows):
            if all(r.mean_deviation <= bounds[c] for r in rows[c]):
                tolerated = c
            else:
                break  # prefix-monotone: a gap ends the certificate
        worst_capture = max(
            r.mean_capture for cells in rows.values() for r in cells
        )
        attacks_out[attack] = {
            "tolerated_colluders": tolerated,
            "tolerated_fraction": tolerated / n,
            "worst_mean_capture": worst_capture,
            "table": [
                dataclasses.asdict(r)
                for c in sorted(rows)
                for r in rows[c]
            ],
        }
    return {
        "n_oracles": n,
        "n_failing": sweep["config"]["n_failing"],
        "constrained": sweep["config"]["constrained"],
        "design_fraction": sweep["config"]["n_failing"] / n,
        "bound_abs": bound_abs,
        "bound_ratio": bound_ratio,
        "bounds": {str(c): bounds[c] for c in counts},
        "benign_deviation": {str(c): benign[c] for c in counts},
        "attacks": attacks_out,
        "certified": all(
            a["tolerated_fraction"]
            >= sweep["config"]["n_failing"] / n
            for a in attacks_out.values()
        ),
    }
