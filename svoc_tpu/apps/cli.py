"""CLI entry point (``client/main.py`` parity).

``python -m svoc_tpu.apps.cli [--dimension N] [--scraper] [--rate R]
[--live_mode] [--disable_startup_fetch] [--seed-comments N]``

Flags mirror ``client/main.py:15-24``; ``--disable_sepolia`` is implied
(the local chain simulator is the default backend — pass
``--contract-info`` + ``--accounts`` for the Sepolia path once
``starknet.py`` is available).  Instead of the eel web UI, commands are
read from stdin (same command language, ``help`` to list).
"""

from __future__ import annotations

import argparse
import sys

from svoc_tpu.apps.commands import CommandConsole
from svoc_tpu.apps.session import Session, SessionConfig
from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.scraper import SyntheticSource


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="svoc",
        description="TPU-native stochastic vector oracle consensus client",
    )
    p.add_argument("--dimension", type=int, default=6)
    p.add_argument("--n-oracles", type=int, default=7)
    p.add_argument("--n-failing", type=int, default=2)
    p.add_argument("--scraper", action="store_true",
                   help="run the ingest loop in the background")
    p.add_argument("--rate", type=float, default=600.0,
                   help="scraper period in seconds (main.py:23)")
    p.add_argument("--refresh", type=float, default=5.0,
                   help="auto_fetch period in seconds (common.py:11)")
    p.add_argument("--live-scraper", action="store_true",
                   help="scrape HN via Selenium when available")
    p.add_argument("--int8", action="store_true",
                   help="serve sentiment through the W8A8 dynamic-PTQ "
                        "forward (2x the bf16 MXU rate on v5e)")
    p.add_argument("--live_mode", action="store_true")
    p.add_argument("--disable_startup_fetch", action="store_true")
    p.add_argument("--db", default=":memory:",
                   help="comment store path (reference: data/comments.db)")
    p.add_argument("--seed-comments", type=int, default=200,
                   help="pre-seed an empty store with N synthetic comments")
    p.add_argument("--contract-info", default=None,
                   help="data/contract_info.json (rpc + deployed address) — "
                        "with --accounts, commits go to Sepolia instead of "
                        "the local simulator")
    p.add_argument("--accounts", default=None,
                   help="data/sepolia.json with admin/oracle keys "
                        "(client/README.md:38-77 layout)")
    return p


def build_adapter(args):
    """The chain backend for parsed CLI args: Sepolia when both
    ``--contract-info`` and ``--accounts`` are given (reference
    ``retrieve_account_data`` + RPC path), else the local simulator
    (``None`` → Session default)."""
    if bool(args.contract_info) != bool(args.accounts):
        raise SystemExit(
            "--contract-info and --accounts must be given together"
        )
    if not args.contract_info:
        return None
    from svoc_tpu.io.chain import ChainAdapter, starknet_backend_from_files

    return ChainAdapter(
        starknet_backend_from_files(args.contract_info, args.accounts)
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    store = CommentStore(args.db)
    if store.count() == 0 and args.seed_comments:
        store.save(SyntheticSource(batch=args.seed_comments)())

    session = Session(
        config=SessionConfig(
            n_oracles=args.n_oracles,
            n_failing=args.n_failing,
            dimension=args.dimension,
            refresh_rate_s=args.refresh,
            scraper_rate_s=args.rate,
            live_scraper=args.live_scraper,
            quant_inference="int8" if args.int8 else None,
        ),
        store=store,
        adapter=build_adapter(args),
    )
    console = CommandConsole(session, write=print)

    if args.scraper:
        console.query("scraper on")
    if args.live_mode:
        console.query("live_mode on")
    if not args.disable_startup_fetch:
        # main.py:51-54 boots with resume + fetch.
        console.query("resume")
        console.query("fetch")

    print("svoc console — 'help' for commands, 'exit' to quit")
    while session.application_on:
        try:
            line = input("> ")
        except EOFError:
            break
        console.query(line)
    console.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
