"""Application layer: the session state, command API, and CLI.

Reproduces the reference client's user surface — the ``eel`` command
language of ``client/web_interface.py:14-55`` and the process entry of
``client/main.py`` — over the TPU-native stack: the fetch path runs the
jitted sentiment + fleet + consensus graphs, the chain path goes through
:mod:`svoc_tpu.io.chain` (local simulator by default, Sepolia when
configured).
"""

from svoc_tpu.apps.session import Session, SessionConfig
from svoc_tpu.apps.commands import CommandConsole

__all__ = ["Session", "SessionConfig", "CommandConsole"]
